"""Load-plane smoke: a real two-replica inference fleet under a synthetic
10k-client open-loop sweep, with a SIGKILL of one replica mid-sweep — the
CPU-scale proof of ISSUE 12's acceptance bar:

- two ``replica_main`` processes (continuous batching, ver-keyed swaps fed
  by a live model PUB publishing rising versions) serve the checked
  ``inference_base_port`` range;
- ``run_loadgen`` sweeps three offered-load plateaus from 2 driver
  processes standing in for >= 10k synthetic clients, grading each stage
  through a fresh SLO engine and writing ``<result-dir>/loadgen.json``;
- one replica is SIGKILL'd mid-sweep: hedged retries absorb the loss,
  overall success must stay >= 99.9%, and the per-stage version floor must
  never decrease (the fleet's monotonic-weights guarantee under churn);
- the sub-saturation first stage must grade GREEN on
  ``p99:inference-rtt``.

Exits nonzero on any failure — this is the ``make loadgen-smoke`` CI gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/loadgen_smoke.py \
      [--clients 12000] [--base-port 31400] [--kill-at 8]
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLO_SPEC = "p99:inference-rtt<250ms@window=60s"


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=12_000)
    p.add_argument("--base-port", type=int, default=31400)
    p.add_argument("--rates", default="100,250,600",
                   help="aggregate offered rps per stage")
    p.add_argument("--duration", type=float, default=6.0)
    p.add_argument("--kill-at", type=float, default=8.0,
                   help="seconds into the sweep the replica-1 SIGKILL fires")
    p.add_argument("--result-dir", default=None)
    args = p.parse_args()

    import jax

    from tpu_rl.config import Config
    from tpu_rl.fleet import replica_main
    from tpu_rl.loadgen import probe_ready, run_loadgen
    from tpu_rl.models.families import build_family
    from tpu_rl.runtime.protocol import Protocol
    from tpu_rl.runtime.transport import MODEL_HWM, Pub

    model_port = args.base_port + 10
    cfg = Config.from_dict(dict(
        algo="IMPALA", obs_shape=(4,), action_space=2, hidden_size=32,
        worker_num_envs=1, act_mode="remote",
        inference_replicas=2, inference_base_port=args.base_port,
        inference_batch=16, inference_flush_us=500,
        inference_timeout_ms=1500, inference_hedge_ms=150,
        inference_retries=1,
    ))
    ports = [args.base_port, args.base_port + 1]
    endpoints = [("127.0.0.1", prt) for prt in ports]
    result_dir = args.result_dir or tempfile.mkdtemp(prefix="loadgen-smoke-")
    out_path = os.path.join(result_dir, "loadgen.json")
    rates = [float(r) for r in args.rates.split(",")]

    # The stand-in learner: a live model PUB bumping the policy version
    # every second, so the sweep exercises the replicas' ver-keyed swaps
    # and the drivers' floor ratchet with real rollout churn.
    family = build_family(cfg)
    params = family.init_params(jax.random.key(0), seq_len=cfg.seq_len)
    actor_host = jax.device_get(params["actor"])
    pub = Pub("*", model_port, bind=True, hwm=MODEL_HWM)
    stop_pub = threading.Event()

    def _publish() -> None:
        ver = 0
        while not stop_pub.is_set():
            ver += 1
            pub.send(Protocol.Model, {"actor": actor_host, "ver": ver})
            stop_pub.wait(2.0)

    ctx = mp.get_context("spawn")
    replicas = [
        ctx.Process(
            target=replica_main,
            args=(cfg, i, ports[i], "127.0.0.1", model_port,
                  cfg.telemetry_port or args.base_port + 11, None, None),
            kwargs={"seed": 0},
            daemon=True,
        )
        for i in range(2)
    ]
    killer = None
    try:
        for proc in replicas:
            proc.start()
        print(f"[loadgen] fleet booting on {ports} ...", flush=True)
        if not probe_ready(endpoints, cfg, timeout_s=180.0):
            print("[loadgen] FAIL: fleet never became ready", flush=True)
            return 1
        threading.Thread(target=_publish, daemon=True).start()

        # The chaos leg: replica 1 dies -9 mid-sweep (stage 2 at the
        # defaults). No respawn — the surviving replica must carry the
        # offered load through hedged failover.
        killer = threading.Timer(args.kill_at, replicas[1].kill)
        killer.daemon = True
        killer.start()

        print(
            f"[loadgen] sweep: {args.clients} clients, rates {rates} rps, "
            f"kill replica-1 at t+{args.kill_at}s", flush=True,
        )
        doc = run_loadgen(
            cfg, endpoints, n_clients=args.clients, rates=rates,
            duration_s=args.duration, out_path=out_path, n_procs=2,
            rows=1, slo_spec=SLO_SPEC,
        )
    finally:
        if killer is not None:
            killer.cancel()
        stop_pub.set()
        pub.close()
        for proc in replicas:
            if proc.is_alive():
                proc.kill()
            proc.join(timeout=10)

    for stage in doc["stages"]:
        print(json.dumps(stage), flush=True)

    failures = []
    if not os.path.exists(out_path):
        failures.append(f"{out_path} was never written")
    if len(doc["stages"]) != len(rates):
        failures.append(
            f"expected {len(rates)} stages, got {len(doc['stages'])}"
        )
    success = doc["overall"]["success_rate"]
    if success < 0.999:
        failures.append(
            f"overall success {success} < 0.999 — the kill was not absorbed"
        )
    floors = [s["version_floor"] for s in doc["stages"]]
    if any(b < a for a, b in zip(floors, floors[1:])):
        failures.append(f"version floor regressed across stages: {floors}")
    if floors and floors[-1] < 1:
        failures.append(
            f"floor never rose ({floors}) — the model broadcast never landed"
        )
    first_slo = doc["stages"][0].get("slo") if doc["stages"] else None
    if not (first_slo and first_slo["ok"]):
        failures.append(
            f"sub-saturation stage SLO not green: {first_slo}"
        )
    absorbed = sum(
        s["hedges"] + s["failovers"] for s in doc["stages"][1:]
    )
    if absorbed == 0:
        failures.append(
            "no hedges/failovers after the kill — the chaos leg never bit"
        )

    if failures:
        for f in failures:
            print(f"[loadgen] FAIL: {f}", flush=True)
        return 1
    print(
        f"[loadgen] OK: {doc['overall']['ok']}/{doc['overall']['sent']} "
        f"ok ({success:.4%}), floors {floors}, "
        f"{absorbed} hedged/failed-over after the kill, "
        f"curve at {out_path}", flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
