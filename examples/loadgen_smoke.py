"""Load-plane smoke: a real two-replica inference fleet under a synthetic
10k-client open-loop sweep, with a SIGKILL of one replica mid-sweep — the
CPU-scale proof of ISSUE 12's acceptance bar:

- two ``replica_main`` processes (continuous batching, ver-keyed swaps fed
  by a live model PUB publishing rising versions) serve the checked
  ``inference_base_port`` range;
- ``run_loadgen`` sweeps three offered-load plateaus from 2 driver
  processes standing in for >= 10k synthetic clients, grading each stage
  through a fresh SLO engine and writing ``<result-dir>/loadgen.json``;
- one replica is SIGKILL'd mid-sweep: hedged retries absorb the loss,
  overall success must stay >= 99.9%, and the per-stage version floor must
  never decrease (the fleet's monotonic-weights guarantee under churn);
- the sub-saturation first stage must grade GREEN on
  ``p99:inference-rtt``;
- the replicas serve through a BUCKET LADDER (``inference_buckets=8``) with
  telemetry on, and every stage is graded against the replicas' live stat
  snapshots on ``counter:inference-xla-recompiles==0`` — the PR 11
  recompile ratchet as an SLO: all bucket programs compile before the
  socket binds, so a sweep across flush sizes must never hit XLA again.
  Each stage's verdict must be a hard GREEN (``ok is True``), never
  no-data.

Exits nonzero on any failure — this is the ``make loadgen-smoke`` CI gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/loadgen_smoke.py \
      [--clients 12000] [--base-port 31400] [--kill-at 8]
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLO_SPEC = (
    "p99:inference-rtt<250ms@window=60s,"
    "counter:inference-xla-recompiles==0"
)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=12_000)
    p.add_argument("--base-port", type=int, default=31400)
    p.add_argument("--rates", default="100,250,600",
                   help="aggregate offered rps per stage")
    p.add_argument("--duration", type=float, default=6.0)
    p.add_argument("--kill-at", type=float, default=8.0,
                   help="seconds into the sweep the replica-1 SIGKILL fires")
    p.add_argument("--result-dir", default=None)
    args = p.parse_args()

    import jax

    from tpu_rl.config import Config
    from tpu_rl.fleet import replica_main
    from tpu_rl.loadgen import probe_ready, run_loadgen
    from tpu_rl.models.families import build_family
    from tpu_rl.runtime.protocol import Protocol
    from tpu_rl.runtime.transport import MODEL_HWM, Pub, Sub

    model_port = args.base_port + 10
    stat_port = args.base_port + 11
    result_dir = args.result_dir or tempfile.mkdtemp(prefix="loadgen-smoke-")
    cfg = Config.from_dict(dict(
        algo="IMPALA", obs_shape=(4,), action_space=2, hidden_size=32,
        worker_num_envs=1, act_mode="remote",
        inference_replicas=2, inference_base_port=args.base_port,
        inference_batch=16, inference_flush_us=500,
        inference_timeout_ms=1500, inference_hedge_ms=150,
        inference_retries=1,
        # Bucket-ladder sweep (ladder [8, 16]) with telemetry on
        # (result_dir flips telemetry_enabled): the recompile-ratchet SLO
        # below grades the replicas' own counters live.
        inference_buckets=8, result_dir=result_dir,
        telemetry_interval_s=1.0,
    ))
    ports = [args.base_port, args.base_port + 1]
    endpoints = [("127.0.0.1", prt) for prt in ports]
    out_path = os.path.join(result_dir, "loadgen.json")
    rates = [float(r) for r in args.rates.split(",")]

    # The stand-in learner: a live model PUB bumping the policy version
    # every second, so the sweep exercises the replicas' ver-keyed swaps
    # and the drivers' floor ratchet with real rollout churn.
    family = build_family(cfg)
    params = family.init_params(jax.random.key(0), seq_len=cfg.seq_len)
    actor_host = jax.device_get(params["actor"])
    pub = Pub("*", model_port, bind=True, hwm=MODEL_HWM)
    stop_pub = threading.Event()

    def _publish() -> None:
        ver = 0
        while not stop_pub.is_set():
            ver += 1
            pub.send(Protocol.Model, {"actor": actor_host, "ver": ver})
            stop_pub.wait(2.0)

    ctx = mp.get_context("spawn")
    replicas = [
        ctx.Process(
            target=replica_main,
            args=(cfg, i, ports[i], "127.0.0.1", model_port,
                  stat_port, None, None),
            kwargs={"seed": 0},
            daemon=True,
        )
        for i in range(2)
    ]

    # Server-side telemetry tap: the replicas' stat PUBs connect out to
    # learner_ip:stat_port, so the smoke binds the SUB end and keeps each
    # replica's LATEST snapshot — the extra grading input for the
    # recompile-ratchet SLO (a killed replica's last snapshot keeps
    # counting: its pre-kill recompiles stay in the fleet sum).
    stat_sub = Sub("*", stat_port, bind=True)
    latest: dict[int, dict] = {}
    stop_stats = threading.Event()

    def _collect_stats() -> None:
        while not stop_stats.is_set():
            for proto, snap in stat_sub.drain(max_msgs=256):
                if proto == Protocol.Telemetry and isinstance(snap, dict):
                    latest[int(snap.get("rid", -1))] = snap
            stop_stats.wait(0.1)

    killer = None
    try:
        for proc in replicas:
            proc.start()
        print(f"[loadgen] fleet booting on {ports} ...", flush=True)
        if not probe_ready(endpoints, cfg, timeout_s=180.0):
            print("[loadgen] FAIL: fleet never became ready", flush=True)
            return 1
        threading.Thread(target=_publish, daemon=True).start()
        threading.Thread(target=_collect_stats, daemon=True).start()
        # First replica snapshots must land before grading starts, so the
        # recompile rule can never grade no-data on stage 0.
        t_wait = time.monotonic() + 30.0
        while len(latest) < 2 and time.monotonic() < t_wait:
            time.sleep(0.2)
        if len(latest) < 2:
            print("[loadgen] FAIL: replica telemetry never arrived",
                  flush=True)
            return 1

        # The chaos leg: replica 1 dies -9 mid-sweep (stage 2 at the
        # defaults). No respawn — the surviving replica must carry the
        # offered load through hedged failover.
        killer = threading.Timer(args.kill_at, replicas[1].kill)
        killer.daemon = True
        killer.start()

        print(
            f"[loadgen] sweep: {args.clients} clients, rates {rates} rps, "
            f"kill replica-1 at t+{args.kill_at}s", flush=True,
        )
        doc = run_loadgen(
            cfg, endpoints, n_clients=args.clients, rates=rates,
            duration_s=args.duration, out_path=out_path, n_procs=2,
            rows=1, slo_spec=SLO_SPEC,
            extra_snapshots=lambda: list(latest.values()),
        )
    finally:
        if killer is not None:
            killer.cancel()
        stop_pub.set()
        stop_stats.set()
        pub.close()
        stat_sub.close()
        for proc in replicas:
            if proc.is_alive():
                proc.kill()
            proc.join(timeout=10)

    for stage in doc["stages"]:
        print(json.dumps(stage), flush=True)

    failures = []
    if not os.path.exists(out_path):
        failures.append(f"{out_path} was never written")
    if len(doc["stages"]) != len(rates):
        failures.append(
            f"expected {len(rates)} stages, got {len(doc['stages'])}"
        )
    success = doc["overall"]["success_rate"]
    if success < 0.999:
        failures.append(
            f"overall success {success} < 0.999 — the kill was not absorbed"
        )
    floors = [s["version_floor"] for s in doc["stages"]]
    if any(b < a for a, b in zip(floors, floors[1:])):
        failures.append(f"version floor regressed across stages: {floors}")
    if floors and floors[-1] < 1:
        failures.append(
            f"floor never rose ({floors}) — the model broadcast never landed"
        )
    first_slo = doc["stages"][0].get("slo") if doc["stages"] else None
    if not (first_slo and first_slo["ok"]):
        failures.append(
            f"sub-saturation stage SLO not green: {first_slo}"
        )
    # Recompile ratchet across the bucket-ladder sweep: EVERY stage's
    # counter:inference-xla-recompiles==0 rule must grade a hard GREEN.
    # ok=None (no-data) is a failure too — it would mean the replicas'
    # snapshots never reached the grading set and the ratchet was not
    # actually checked.
    for i, stage in enumerate(doc["stages"]):
        rules = (stage.get("slo") or {}).get("rules", [])
        rule = next(
            (r for r in rules if r["metric"] == "inference-xla-recompiles"),
            None,
        )
        if rule is None or rule["ok"] is not True:
            failures.append(
                f"stage {i}: recompile ratchet not green: {rule}"
            )
    absorbed = sum(
        s["hedges"] + s["failovers"] for s in doc["stages"][1:]
    )
    if absorbed == 0:
        failures.append(
            "no hedges/failovers after the kill — the chaos leg never bit"
        )

    if failures:
        for f in failures:
            print(f"[loadgen] FAIL: {f}", flush=True)
        return 1
    print(
        f"[loadgen] OK: {doc['overall']['ok']}/{doc['overall']['sent']} "
        f"ok ({success:.4%}), floors {floors}, "
        f"{absorbed} hedged/failed-over after the kill, "
        f"curve at {out_path}", flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
