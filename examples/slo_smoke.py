"""SLO-plane smoke: run the smallest real cluster twice under declarative
SLO rules (``Config.slo_spec``). Phase 1 carries a three-rule spec the run
can meet — ``/slo`` must report passing and storage must exit 0. Phase 2
adds an impossible rule with ``slo_fail_run`` armed — ``/slo`` must report
failing (HTTP 503) and storage must exit NONZERO. Exits nonzero on any
failure — this is the ``make slo-smoke`` CI gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/slo_smoke.py \
      [--updates 6] [--base-port 30600] [--telemetry-port 30660]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Six rules over metrics every distributed run produces: a tail-latency
# bound (staleness histogram, in updates), a worst-case resource gauge, a
# fleet-summed failure rate (the ISSUE's example rule — no corruption is
# injected here, so the rate must hold at 0/s), an exact invariant —
# the in-jit update guards are on by default, so a clean run must apply
# every update (any skipped-nonfinite update is a violation, not a budget) —
# and two training-health rules over the learning-dynamics plane
# (``learn_diag``, on by default): a discrete policy that has not collapsed
# keeps positive entropy, and a trust-region-clipped PPO update keeps
# approx-KL well under 1 nat.
PASSING_SPEC = (
    "p99:policy-staleness-updates<10000,"
    "gauge:storage-rss-bytes>0,"
    "rate:transport-rejected-frames<1/s,"
    "counter:learner-nonfinite-updates==0,"
    "gauge:learner-diag-entropy>0,"
    "gauge:learner-diag-approx-kl<1.0"
)
# A live storage process can never hold under one byte of RSS.
IMPOSSIBLE_RULE = "gauge:storage-rss-bytes<1"


def _get_slo(port: int, timeout: float = 3.0):
    """GET /slo -> (status, parsed doc) — 503 carries the failing verdict."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/slo", timeout=timeout
        ) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except ValueError:
            return e.code, None
    except (urllib.error.URLError, ConnectionError, OSError, ValueError):
        return None, None


def run_phase(
    name: str,
    spec: str,
    fail_run: bool,
    base_port: int,
    telemetry_port: int,
    updates: int,
    timeout: float,
):
    """One cluster run under `spec`; returns (slo scrapes, storage exitcode,
    final slo.json doc or None, failure strings)."""
    from tests.conftest import small_config
    from tpu_rl.config import MachinesConfig, WorkerMachine
    from tpu_rl.runtime.runner import local_cluster

    run_dir = tempfile.mkdtemp(prefix=f"slo_smoke_{name}_")
    cfg = small_config(
        env="CartPole-v1",
        algo="PPO",
        worker_step_sleep=0.0,
        learner_device="cpu",
        rollout_lag_sec=30.0,
        time_horizon=100,
        loss_log_interval=2,
        result_dir=run_dir,
        telemetry_port=telemetry_port,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,
        slo_spec=spec,
        slo_fail_run=fail_run,
    )
    machines = MachinesConfig(
        learner_ip="127.0.0.1",
        learner_port=base_port,
        workers=[WorkerMachine(
            num_p=2, manager_ip="127.0.0.1", ip="127.0.0.1",
            port=base_port + 5,
        )],
    )
    failures: list[str] = []
    scrapes: list = []
    print(f"[slo-smoke] {name}: cluster up; run_dir={run_dir}", flush=True)
    sup = local_cluster(cfg, machines, max_updates=updates)
    try:
        learner = next(c for c in sup.children if c.name == "learner")
        deadline = time.time() + timeout
        # Scrape /slo until every rule has data (or the learner finishes) —
        # the verdict must come from the engine, not from rule silence.
        while time.time() < deadline:
            status, doc = _get_slo(telemetry_port)
            if status in (200, 503) and doc is not None:
                scrapes.append((status, doc))
                if doc.get("no_data", 0) == 0 and doc.get("rules"):
                    break
            if not learner.proc.is_alive():
                break
            time.sleep(0.5)
        while time.time() < deadline and learner.proc.is_alive():
            time.sleep(0.5)
        if learner.proc.is_alive() or learner.proc.exitcode != 0:
            failures.append(
                f"{name}: learner did not complete cleanly "
                f"(alive={learner.proc.is_alive()}, "
                f"exitcode={learner.proc.exitcode})"
            )
    finally:
        sup.stop()

    storage = next(c for c in sup.children if c.name == "storage")
    exitcode = storage.proc.exitcode
    final_doc = None
    try:
        with open(os.path.join(run_dir, "slo.json")) as f:
            final_doc = json.load(f)
    except (OSError, ValueError) as e:
        failures.append(f"{name}: slo.json invalid: {type(e).__name__}: {e}")
    if not scrapes:
        failures.append(f"{name}: /slo never answered with a verdict")
    return scrapes, exitcode, final_doc, failures


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=6)
    p.add_argument("--base-port", type=int, default=30600)
    p.add_argument("--telemetry-port", type=int, default=30660)
    p.add_argument("--timeout", type=float, default=240.0)
    args = p.parse_args()
    failures: list[str] = []

    # ---- phase 1: meetable spec, /slo green, clean exit ----------------
    scrapes, exitcode, final_doc, errs = run_phase(
        "pass", PASSING_SPEC, fail_run=True,
        base_port=args.base_port, telemetry_port=args.telemetry_port,
        updates=args.updates, timeout=args.timeout,
    )
    failures += errs
    if scrapes:
        status, doc = scrapes[-1]
        print(
            f"[slo-smoke] pass: /slo {status} ok={doc.get('ok')} "
            f"failing={doc.get('failing')} no_data={doc.get('no_data')}",
            flush=True,
        )
        if status != 200 or doc.get("ok") is not True or doc.get("failing"):
            failures.append(f"pass: /slo not green: {status} {doc}")
    if exitcode != 0:
        failures.append(f"pass: storage exitcode {exitcode}, expected 0")
    if final_doc is not None and final_doc.get("ok") is not True:
        failures.append(f"pass: final slo.json not ok: {final_doc}")

    # ---- phase 2: impossible rule + fail_run gate, nonzero exit --------
    scrapes, exitcode, final_doc, errs = run_phase(
        "fail", f"{PASSING_SPEC},{IMPOSSIBLE_RULE}", fail_run=True,
        base_port=args.base_port + 20,
        telemetry_port=args.telemetry_port + 20,
        updates=args.updates, timeout=args.timeout,
    )
    failures += errs
    if scrapes:
        status, doc = scrapes[-1]
        print(
            f"[slo-smoke] fail: /slo {status} ok={doc.get('ok')} "
            f"failing={doc.get('failing')}",
            flush=True,
        )
        if status != 503 or doc.get("ok") is not False:
            failures.append(f"fail: /slo did not report failing: {status} {doc}")
    if exitcode == 0:
        failures.append("fail: storage exited 0 despite a violated SLO")
    else:
        print(f"[slo-smoke] fail: storage exitcode {exitcode} (gate fired)",
              flush=True)
    if final_doc is not None and final_doc.get("ok") is not False:
        failures.append(f"fail: final slo.json not failing: {final_doc}")

    if failures:
        for f in failures:
            print(f"[slo-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print("[slo-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
