#!/bin/sh
# On-chip measurement backlog — run on the TPU host the moment the
# accelerator is reachable (probe first, everything below hangs otherwise).
# Step 1 (bench matrix) WAS completed in round 4's 03:45-04:10 UTC tunnel
# window (RUN_TPU_r04.md); steps 2-3 remain pending — the tunnel died again
# before they ran. Note the tunnel's per-dispatch RTT when it returned was
# ~3-5 ms (vs ~0.5 ms round 3): bench.py's @ref rows now chain 16 updates
# per dispatch to amortize it; bench_lstm_kernel.py timings below are
# per-dispatch and will carry that RTT as a constant additive floor on both
# kernel and scan rows (ratios stay meaningful).
#
#   timeout 90 python -c "import jax; print(jax.devices())"
#
# Each step writes its committed artifact; nothing here overwrites an
# on-chip record with fallback numbers (bench.py routes CPU runs to
# bench_results.cpu.json by itself).
set -ex
cd "$(dirname "$0")/.."

# 1. Full learner matrix -> bench_results.json. Run 4 of round 4 added the
#    PPO-transformer@longctx-flash row (Pallas TPU fused-attention kernel,
#    NEVER yet executed on a real chip — the CPU tests only pin its masking
#    spec); if it errors, the row records the error without aborting the
#    matrix, and the committed table keeps the other rows.
python bench.py

# 2. LSTM kernel-vs-scan -> bench_lstm_kernel.json. The dispatch is now
#    measured-win-only; verify no row has auto_regression > 1.0 (the
#    "force" mode times the raw kernel, including the fused backward at
#    multi-tile shapes, which the old bench silently measured as
#    kernel-fwd + scan-bwd).
PYTHONPATH=. python examples/bench_lstm_kernel.py

# 3. Long-context transformer profile (VERDICT r3 #6): step-level trace to
#    attribute the remaining gap to attention vs FF vs data movement.
#    View with tensorboard/xprof; summarize findings in README.
PYTHONPATH=. python - <<'EOF'
import jax
import bench
row = bench.bench_one(
    "PPO-transformer@longctx-blockwise",
    dict(
        algo="PPO", model="transformer", compute_dtype="bfloat16",
        attention_impl="blockwise", batch_size=16, seq_len=2048,
        hidden_size=512, n_heads=8, n_layers=4, obs_shape=(64,),
        action_space=8, profile_dir="/tmp/tpu_rl_longctx_trace",
    ),
    3, 20,
)
print(row)
EOF
