#!/bin/sh
# On-chip measurement backlog — run on the TPU host the moment the
# accelerator is reachable (probe first, everything below hangs otherwise):
#
#   timeout 90 python -c "import jax; print(jax.devices())"
#
# Round-4 tunnel windows so far: 03:45-04:10 UTC (full matrix, chained @ref
# methodology) and 16:10-16:21 UTC (matrix re-run at lower RTT — IMPALA@ref
# 5.32M t/s — plus the LSTM kernel re-record and the flash-attention
# BlockSizes sweep, bench_flash.json). The tunnel died before the items
# below ran. Each step writes its committed artifact; nothing here
# overwrites an on-chip record with fallback numbers (bench.py routes CPU
# runs to bench_results.*.json variants by itself).
set -ex
cd "$(dirname "$0")/.."

# 1. Re-measure the longctx-flash train-step row with the TUNED BlockSizes
#    (gcd(512,T) uniform tiles, tpu_rl/parallel/sequence.py): the op-level
#    sweep has fwd+bwd 3.1x faster than the library-default tiles that made
#    the committed matrix row lose to blockwise (190.7 vs 136.2 ms/step).
#    Update the row in bench_results.json if it confirms.
PYTHONPATH=/root/repo:/root/.axon_site python - <<'EOF'
import json
import bench
row = bench.bench_one(
    "PPO-transformer@longctx-flash",
    dict(algo="PPO", model="transformer", compute_dtype="bfloat16",
         attention_impl="flash", batch_size=16, seq_len=2048,
         hidden_size=512, n_heads=8, n_layers=4, obs_shape=(64,),
         action_space=8),
    3, 20,
)
print(json.dumps(row))
EOF

# 2. Re-record bench_flash.json: the committed sweep's "full" fwd_ms row is
#    warmup-contaminated (annotated in the artifact); the script now forces
#    a post-warmup sync. (Keep /root/.axon_site on PYTHONPATH or the TPU
#    plugin never registers and the row silently re-records on CPU.)
PYTHONPATH=/root/repo:/root/.axon_site python examples/bench_flash_attention.py

# 3. Long-context transformer profile (VERDICT r3 #6): step-level trace to
#    attribute the remaining gap to attention vs FF vs data movement.
#    bench_one pops profile_dir and wraps the timed loop in
#    jax.profiler.start_trace/stop_trace. View with tensorboard/xprof;
#    summarize findings in README.
PYTHONPATH=/root/repo:/root/.axon_site python - <<'EOF'
import json
import bench
row = bench.bench_one(
    "PPO-transformer@longctx-flash-profiled",
    dict(algo="PPO", model="transformer", compute_dtype="bfloat16",
         attention_impl="flash", batch_size=16, seq_len=2048,
         hidden_size=512, n_heads=8, n_layers=4, obs_shape=(64,),
         action_space=8, profile_dir="/tmp/tpu_rl_longctx_trace"),
    3, 10,
)
print(json.dumps(row))
EOF

# 4. V-MPO anomaly: 1.20 ms/update chained vs 0.12-0.26 for every sibling
#    algorithm at the same quantum (16:10 window matrix). TPU-specific:
#    on CPU the same chained programs measure V-MPO at only 1.4x IMPALA
#    (8.3 vs 6.0 ms/update), and the CPU HLO census shows no sort (top_k
#    lowers clean) — so suspects are the TPU lowerings of top_k and
#    take_along_axis (gather), which the trace will name directly.
PYTHONPATH=/root/repo:/root/.axon_site python - <<'EOF'
import json
import bench
row = bench.bench_one(
    "V-MPO@ref-profiled",
    dict(algo="V-MPO", obs_shape=(4,), action_space=2, batch_size=128,
         seq_len=5, hidden_size=64, profile_dir="/tmp/tpu_rl_vmpo_trace"),
    5, 20, 16,
)
print(json.dumps(row))
EOF

# --- round-5 additions ---

# 4b. V-MPO re-measure AFTER the round-5 mask rewrite (top_k+gather ->
#     threshold mask, tpu_rl/algos/vmpo.py top_half_mask): the @ref row
#     should now land within ~2x of IMPALA@ref (was 10x). If it does, item
#     4's trace is confirmation; if not, the trace names what remains.
PYTHONPATH=/root/repo:/root/.axon_site python - <<'EOF'
import json
import bench
row = bench.bench_one(
    "V-MPO@ref",
    dict(algo="V-MPO", obs_shape=(4,), action_space=2, batch_size=128,
         seq_len=5, hidden_size=64),
    5, 50, 16,
)
print(json.dumps(row))
EOF

# 5. END-TO-END learner FPS through the real shm feed with the production
#    chained dispatch (Config.learner_chain; VERDICT r4 weak #6 — all prior
#    on-chip numbers are synthetic-batch rows). Reports both the chip rate
#    and the host feed rate; feed_blocked_ratio ~1 = chip-bound.
PYTHONPATH=/root/repo:/root/.axon_site python examples/run_tpu_e2e_learner.py \
    --updates 2048 --chain 16 --out bench_e2e_learner.json

# 6. Wide-LSTM MFU attribution (VERDICT r4 weak #5 / next #5): profile the
#    22%-MFU f32 and bf16 rows; attribute recurrent-matmul serialization vs
#    gate VPU vs HBM from the trace (examples/trace_top_ops.py summarizes),
#    then either extend the Pallas kernel or write the roofline note.
PYTHONPATH=/root/repo:/root/.axon_site python - <<'EOF'
import json
import bench
for dtype in ("float32", "bfloat16"):
    row = bench.bench_one(
        f"IMPALA@wide-lstm-{dtype}-profiled",
        dict(algo="IMPALA", batch_size=1024, seq_len=16, hidden_size=1024,
             obs_shape=(64,), action_space=8, compute_dtype=dtype,
             profile_dir=f"/tmp/tpu_rl_widelstm_{dtype}_trace"),
        5, 15,
    )
    print(json.dumps(row))
EOF
PYTHONPATH=/root/repo:/root/.axon_site python examples/trace_top_ops.py /tmp/tpu_rl_widelstm_float32_trace || true
PYTHONPATH=/root/repo:/root/.axon_site python examples/trace_top_ops.py /tmp/tpu_rl_widelstm_bfloat16_trace || true
