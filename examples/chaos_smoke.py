"""Chaos smoke: boot the smallest real cluster under a deterministic fault
plan — a worker SIGKILL, probabilistic rollout corruption, and relay send
delays — and assert the run STILL completes and every injected fault is
accounted for:

- the learner reaches ``max_updates`` and exits cleanly,
- the supervisor restarted at least one child (the chaos kill),
- every injected corruption shows up in the fleet's rejected-frame
  counters (injected == rejected, exactly — the chaos plane corrupts at
  the consuming edge, so nothing is lost between injection and the CRC
  reject),
- at least one relay send was chaos-delayed.

Exits nonzero on any failure — this is the ``make chaos-smoke`` CI gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/chaos_smoke.py \
      [--updates 8] [--base-port 28400]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# kill fires once the fleet is warming up (t0 = first supervisor poll);
# corrupt targets the rollout channel at the storage edge; the delay rides
# ~20% of the manager's forward sends. Probabilities are low enough that
# the learner still converges on its data budget.
DEFAULT_SPEC = (
    "kill:worker-0-1@t+6s,corrupt:rollout@p=0.02,delay:manager@10ms@p=0.2"
)


def _counter(source: dict, name: str) -> float:
    return sum(
        v for n, _labels, v in source.get("counters", ()) if n == name
    )


def _role_total(tele: dict, role: str, name: str) -> float:
    return sum(
        _counter(s, name) for s in tele["sources"] if s.get("role") == role
    )


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=8)
    p.add_argument("--base-port", type=int, default=28400)
    p.add_argument("--chaos-spec", default=DEFAULT_SPEC)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args()

    from tpu_rl.config import MachinesConfig, WorkerMachine
    from tpu_rl.runtime.runner import local_cluster
    from tests.conftest import small_config  # the CI-sized Config recipe

    run_dir = tempfile.mkdtemp(prefix="chaos_smoke_")
    cfg = small_config(
        env="CartPole-v1",
        algo="PPO",
        worker_step_sleep=0.0,
        learner_device="cpu",
        rollout_lag_sec=30.0,
        time_horizon=100,
        loss_log_interval=2,
        result_dir=run_dir,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,
        supervise_poll_s=0.5,
        chaos_spec=args.chaos_spec,
        chaos_seed=7,
    )
    machines = MachinesConfig(
        learner_ip="127.0.0.1",
        learner_port=args.base_port,
        workers=[WorkerMachine(
            num_p=2, manager_ip="127.0.0.1", ip="127.0.0.1",
            port=args.base_port + 5,
        )],
    )
    print(
        f"[chaos-smoke] cluster up; run_dir={run_dir} "
        f"spec={args.chaos_spec!r}", flush=True,
    )
    sup = local_cluster(cfg, machines, max_updates=args.updates)
    failures: list[str] = []
    # loop() owns supervision: chaos injection, restart-on-death, telemetry.
    # It sets stop_event itself once the learner exits cleanly.
    loop_thread = threading.Thread(target=sup.loop, daemon=True)
    loop_thread.start()
    try:
        if not sup.stop_event.wait(args.timeout):
            failures.append(
                f"fleet did not complete within {args.timeout:.0f}s"
            )
        loop_thread.join(10.0)
        learner = next(c for c in sup.children if c.name == "learner")
        learner.proc.join(30.0)
        if learner.proc.is_alive() or learner.proc.exitcode != 0:
            failures.append(
                f"learner did not complete cleanly under chaos "
                f"(alive={learner.proc.is_alive()}, "
                f"exitcode={learner.proc.exitcode})"
            )
        restarts = sum(c.restarts for c in sup.children)
        if restarts < 1:
            failures.append(
                "no supervised restart happened — the chaos kill never "
                "landed or the supervisor missed it"
            )
        else:
            print(
                f"[chaos-smoke] supervised restarts: {restarts}", flush=True
            )
    finally:
        sup.stop()

    tele_path = os.path.join(run_dir, "telemetry.json")
    try:
        tele = json.loads(open(tele_path).read())
    except (OSError, ValueError) as e:
        failures.append(f"telemetry.json invalid: {type(e).__name__}: {e}")
        tele = {"sources": []}

    kills = _role_total(tele, "supervisor", "chaos-process-kills")
    sup_restarts = _role_total(tele, "supervisor", "supervisor-restarts")
    if kills < 1:
        failures.append(f"chaos-process-kills={kills}, expected >= 1")
    if sup_restarts < 1:
        failures.append(
            f"supervisor-restarts={sup_restarts} in telemetry, expected >= 1"
        )

    # Fault accounting: the chaos plane corrupts rollout frames at the
    # storage edge, where the decode CRC rejects them in the SAME recv call
    # — so the fleet-wide rejected total must equal the injected count
    # exactly (no other source of corruption exists in a healthy run).
    corrupted = _role_total(tele, "storage", "chaos-corrupted-frames")
    rejected = sum(
        _role_total(tele, role, f"{role}-rejected-frames")
        for role in ("worker", "manager", "storage")
    )
    if corrupted < 1:
        failures.append(
            "chaos corrupted zero frames — the injection shim never fired"
        )
    if corrupted != rejected:
        failures.append(
            f"fault accounting mismatch: injected {corrupted} corruptions "
            f"but the fleet rejected {rejected} frames"
        )
    else:
        print(
            f"[chaos-smoke] fault accounting: {corrupted:.0f} injected == "
            f"{rejected:.0f} rejected", flush=True,
        )
    delayed = _role_total(tele, "manager", "chaos-delayed-frames")
    if delayed < 1:
        failures.append(f"chaos-delayed-frames={delayed}, expected >= 1")
    else:
        print(f"[chaos-smoke] delayed sends: {delayed:.0f}", flush=True)

    if failures:
        for f in failures:
            print(f"[chaos-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print("[chaos-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
