"""Pod-scale colocated smoke — the `make ci` gate for ISSUE 18.

Three checks, all on the CPU backend with subprocess "virtual hosts"
(``XLA_FLAGS=--xla_force_host_platform_device_count`` per child, gloo
collectives via ``jax.distributed``):

1. POD LEARNING + DURABILITY: a 2-host pod-Anakin CartPole run (the
   ``colocated_smoke`` recipe sharded over the global data axis) must
   survive a SIGKILL of the non-chief host after the first committed
   checkpoint — the relaunched pod resumes from the newest committed
   index at a bumped run epoch — and still train to best-window mean
   return >= 60 within the update budget.
2. CHECKPOINT READABLE: after the run, the final committed checkpoint
   restores through the standard reader (``restore_actor_params``) and
   its marker records the bumped epoch.
3. SEBULBA SPLIT: the split actor/learner loop (2+2 devices, bounded
   queue) must complete with the overlap signature — compute attributed
   on BOTH lane ledgers in the same window, queue-wait > 0 somewhere,
   and the queue high-watermark bounded by the configured depth.

Usage:
    JAX_PLATFORMS=cpu PYTHONPATH=. python examples/sebulba_smoke.py
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RETURN_THRESHOLD = 60.0  # same bar as colocated_smoke (random policy ~22)
SAVE_INTERVAL = 100
PORT = 29980


# --------------------------------------------------------------- child bodies
def pod_child(pid: int, nprocs: int, workdir: str, updates: int) -> None:
    """One virtual pod host running the fused pod-Anakin loop."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_rl.config import Config
    from tpu_rl.runtime.colocated import ColocatedLoop

    mh = {
        "coordinator": f"127.0.0.1:{PORT}",
        "num_processes": nprocs,
        "process_id": pid,
    }
    cfg = Config(
        env="CartPole-v1", env_mode="colocated", algo="PPO",
        batch_size=32, buffer_size=32, seq_len=5,
        lr=3e-4, entropy_coef=0.001, reward_scale=0.1,
        time_horizon=500, loss_log_interval=200,
        mesh_data=nprocs, multihost=mh,
        model_dir=os.path.join(workdir, "ckpt"),
        model_save_interval=SAVE_INTERVAL,
    )
    loop = ColocatedLoop(cfg, seed=0, max_updates=updates)
    out = loop.run()
    if jax.process_index() == 0:
        print("SMOKE_RESULT " + json.dumps({
            "updates": out["updates"],
            "episodes": out["episodes"],
            "best_window": out["mean_return_best_window"],
            "start_it": loop._start_it,
            "epoch": loop.run_epoch,
        }), flush=True)


def sebulba_child(workdir: str, updates: int) -> None:
    """Single-process sebulba split: 2 actor + 2 learner devices."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_rl.config import Config
    from tpu_rl.runtime.sebulba import SebulbaLoop

    cfg = Config(
        env="CartPole-v1", env_mode="colocated", algo="PPO",
        batch_size=32, buffer_size=32, seq_len=5,
        lr=3e-4, entropy_coef=0.001, reward_scale=0.1,
        time_horizon=500, loss_log_interval=20,
        sebulba_split=2, sebulba_queue=2,
        result_dir=os.path.join(workdir, "sebulba"),
    )
    loop = SebulbaLoop(cfg, seed=0, max_updates=updates)
    out = loop.run(log=False)
    roles = {led.role: led.snapshot() for led in loop._ledgers()}
    print("SEBULBA_RESULT " + json.dumps({
        "updates": out["updates"],
        "episodes": out["episodes"],
        "queue_peak": out["queue_peak_depth"],
        "queue_depth": cfg.sebulba_queue,
        "actor_compute_s": roles["sebulba-actor"]["buckets"]["compute"],
        "learner_compute_s": roles["sebulba-learner"]["buckets"]["compute"],
        "actor_compute_ratio": roles["sebulba-actor"]["ratios"]["compute"],
        "learner_compute_ratio":
            roles["sebulba-learner"]["ratios"]["compute"],
        "queue_wait_s": (
            roles["sebulba-actor"]["buckets"]["queue-wait"]
            + roles["sebulba-learner"]["buckets"]["queue-wait"]
        ),
    }), flush=True)


# ------------------------------------------------------------- orchestration
def _spawn_pod(pid: int, nprocs: int, workdir: str, updates: int):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--pod-child", str(pid),
         "--nprocs", str(nprocs), "--workdir", workdir,
         "--updates", str(updates)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )


def _result_line(out: str, tag: str) -> dict:
    line = next(ln for ln in out.splitlines() if ln.startswith(tag))
    return json.loads(line[len(tag):])


def check_pod(updates: int, threshold: float, failures: list[str],
              workdir: str) -> None:
    ckpt_dir = os.path.join(workdir, "ckpt")
    t0 = time.time()

    # Phase A: launch the pod, then SIGKILL the non-chief host right after
    # the first two-phase commit lands.
    procs = [_spawn_pod(pid, 2, workdir, updates) for pid in range(2)]
    deadline = time.time() + 300
    while time.time() < deadline:
        if glob.glob(os.path.join(ckpt_dir, "*", "COMMITTED")):
            break
        if any(p.poll() is not None for p in procs):
            break
        time.sleep(0.25)
    if not glob.glob(os.path.join(ckpt_dir, "*", "COMMITTED")):
        for p in procs:
            p.kill()
        outs = [p.communicate()[0] for p in procs]
        failures.append(
            "no committed checkpoint before kill:\n"
            + "\n".join(o[-1500:] for o in outs)
        )
        return
    procs[1].send_signal(signal.SIGKILL)
    try:
        procs[0].wait(timeout=120)
    except subprocess.TimeoutExpired:
        procs[0].kill()
    for p in procs:
        p.communicate()
    print(
        f"[sebulba-smoke] pod host 1 SIGKILLed after first commit "
        f"({time.time() - t0:.1f}s); relaunching pod", flush=True,
    )

    # Phase B: the pod rejoins and finishes the budget.
    procs = [_spawn_pod(pid, 2, workdir, updates) for pid in range(2)]
    outs = []
    for pid, p in enumerate(procs):
        out, _ = p.communicate(timeout=900)
        outs.append(out)
        if p.returncode != 0:
            failures.append(f"rejoined host {pid} rc={p.returncode}\n"
                            f"{out[-1500:]}")
    if failures:
        return
    res = _result_line(outs[0], "SMOKE_RESULT ")
    print(
        f"[sebulba-smoke] pod: {res['updates']} updates, "
        f"{res['episodes']} episodes, best-window mean return "
        f"{res['best_window']:.1f} (threshold {threshold}), resumed from "
        f"idx {res['start_it']} at run epoch {res['epoch']}, "
        f"{time.time() - t0:.1f}s total", flush=True,
    )
    if res["best_window"] < threshold:
        failures.append(
            f"pod did not learn: best-window {res['best_window']:.1f} "
            f"< {threshold}"
        )
    if res["start_it"] < SAVE_INTERVAL:
        failures.append(f"rejoin did not resume: start_it={res['start_it']}")
    if res["epoch"] != 1:
        failures.append(f"run epoch not bumped on rejoin: {res['epoch']}")
    if res["updates"] != updates:
        failures.append(
            f"update index not monotonic to budget: {res['updates']}"
        )

    # Final committed checkpoint must be readable through the standard
    # reader, and its marker must carry the bumped epoch.
    from tpu_rl.checkpoint import (
        latest_committed,
        read_meta,
        restore_actor_params,
    )

    newest = latest_committed(ckpt_dir, "PPO")
    if newest is None or newest[0] != updates:
        failures.append(f"final commit missing or wrong idx: {newest}")
        return
    if read_meta(newest[1]).get("epoch") != 1:
        failures.append(f"final marker epoch: {read_meta(newest[1])}")
    params = restore_actor_params(ckpt_dir, "PPO")
    if params is None or "actor" not in params:
        failures.append("committed checkpoint unreadable via "
                        "restore_actor_params")


def check_sebulba(updates: int, failures: list[str], workdir: str) -> None:
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    t0 = time.time()
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--sebulba-child",
         "--workdir", workdir, "--updates", str(updates)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    out, _ = proc.communicate(timeout=600)
    if proc.returncode != 0:
        failures.append(f"sebulba child rc={proc.returncode}\n{out[-1500:]}")
        return
    res = _result_line(out, "SEBULBA_RESULT ")
    print(
        f"[sebulba-smoke] split: {res['updates']} updates, "
        f"{res['episodes']} episodes, queue peak "
        f"{res['queue_peak']}/{res['queue_depth']}, actor compute "
        f"{res['actor_compute_ratio']:.0%} / learner compute "
        f"{res['learner_compute_ratio']:.0%}, queue-wait "
        f"{res['queue_wait_s']:.2f}s, {time.time() - t0:.1f}s", flush=True,
    )
    if res["updates"] != updates:
        failures.append(f"sebulba stopped early: {res['updates']}")
    # The overlap acceptance signal: both lanes burned compute in the SAME
    # ledger window (one window spans the whole run here).
    if not (res["actor_compute_s"] > 0 and res["learner_compute_s"] > 0):
        failures.append(f"no actor/learner overlap: {res}")
    if res["queue_wait_s"] <= 0:
        failures.append("no backpressure attributed to queue-wait")
    if not 1 <= res["queue_peak"] <= res["queue_depth"]:
        failures.append(f"queue depth not bounded: {res}")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pod-child", type=int, default=None, metavar="PID")
    p.add_argument("--sebulba-child", action="store_true")
    p.add_argument("--nprocs", type=int, default=2)
    p.add_argument("--workdir", default=None)
    p.add_argument("--updates", type=int, default=None)
    p.add_argument("--threshold", type=float, default=RETURN_THRESHOLD)
    args = p.parse_args()

    if args.pod_child is not None:
        pod_child(args.pod_child, args.nprocs, args.workdir,
                  args.updates or 1800)
        return 0
    if args.sebulba_child:
        sebulba_child(args.workdir, args.updates or 120)
        return 0

    failures: list[str] = []
    with tempfile.TemporaryDirectory(prefix="sebulba_smoke_") as workdir:
        check_pod(args.updates or 1800, args.threshold, failures, workdir)
        check_sebulba(120, failures, workdir)

    if failures:
        for f in failures:
            print(f"[sebulba-smoke] FAIL: {f}", flush=True)
        return 1
    print("[sebulba-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
