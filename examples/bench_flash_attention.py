"""Attention-impl microbench at the long-context workload shape.

Round-4 on-chip bench showed the stock-default flash row LOSING to both
full attention and blockwise at (B16, T2048, H8, D64):

    full 72.0 ms/step, blockwise 136.2, flash 190.7   (whole train step)

This isolates the attention op itself (fwd and fwd+grad) and sweeps the
Pallas kernel's BlockSizes — the defaults are 128-everywhere with
block_b=1 (`BlockSizes.get_default`, annotated "TODO: select better
parameters"), which at this shape means a 128x16x16 grid of tiny tiles.
The result decides the dispatch policy in
`tpu_rl/parallel/sequence.flash_attention_tpu` (measured-win-only, the
same lesson as the LSTM kernel: VERDICT r3 #5).

Run ON the TPU (keep /root/.axon_site on PYTHONPATH):

    PYTHONPATH=/root/repo:/root/.axon_site python examples/bench_flash_attention.py

Writes bench_flash.json next to the repo root.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpu_rl.parallel import sequence as seqlib

B, T, H, D = 16, 2048, 8, 64
DTYPE = jnp.bfloat16
WARMUP, ITERS = 3, 20


def _inputs():
    rng = np.random.default_rng(0)
    shape = (B, T, H, D)
    q = jnp.asarray(rng.normal(size=shape), DTYPE) * 0.1
    k = jnp.asarray(rng.normal(size=shape), DTYPE) * 0.1
    v = jnp.asarray(rng.normal(size=shape), DTYPE) * 0.1
    # Two episode segments per row, seam mid-sequence — exercises the
    # segment mask the real workload always carries.
    firsts = np.zeros((B, T, 1), np.float32)
    firsts[:, 0] = 1.0
    firsts[:, T // 2] = 1.0
    seg = seqlib.segment_ids_from_firsts(jnp.asarray(firsts))
    q_pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    return q, k, v, q_pos, seg


def _force_done(out) -> None:
    # device_get a scalar through the tunnel to force true completion
    # (block_until_ready can return early over axon; see bench.py _sync).
    s = jax.tree.map(lambda x: jnp.sum(x.astype(jnp.float32)), out)
    float(np.asarray(jax.device_get(jax.tree.leaves(s)[0])))


def _time(fn, *args) -> float:
    out = None
    for _ in range(WARMUP):
        out = fn(*args)
    # Same forced sync as the timed region: block_until_ready alone let the
    # first recorded row absorb still-draining warmup/compile work (the
    # original bench_flash.json "full" row's physically impossible
    # fwd_ms=670 vs fwdbwd_ms=31).
    _force_done(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    _force_done(out)
    return (time.perf_counter() - t0) / ITERS * 1e3


def _flash_fn(block: int | None):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds,
        flash_attention,
    )

    from tpu_rl.parallel.sequence import _uniform_block_sizes

    bs = None if block is None else _uniform_block_sizes(min(block, T))

    def fn(q, k, v, q_pos, seg):
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        seg32 = seg.astype(jnp.int32)
        o = flash_attention(
            qt, kt, vt, segment_ids=SegmentIds(q=seg32, kv=seg32),
            causal=True, sm_scale=float(1.0 / np.sqrt(D)), block_sizes=bs,
        )
        return o.transpose(0, 2, 1, 3)

    return fn


def main() -> None:
    q, k, v, q_pos, seg = _inputs()
    impls: dict[str, object] = {
        "full": functools.partial(seqlib.full_attention, causal=True),
        "blockwise": functools.partial(seqlib.blockwise_attention, causal=True),
        "flash@128(default)": _flash_fn(None),
        "flash@256": _flash_fn(256),
        "flash@512": _flash_fn(512),
        "flash@1024": _flash_fn(1024),
        "flash@2048": _flash_fn(2048),
    }
    rows = []
    for name, fn in impls.items():
        row = {"name": name, "shape": [B, T, H, D], "dtype": "bfloat16"}
        try:
            fwd = jax.jit(fn)
            row["fwd_ms"] = round(_time(fwd, q, k, v, q_pos, seg), 3)

            def loss(q_, k_, v_):
                return jnp.sum(fn(q_, k_, v_, q_pos, seg).astype(jnp.float32))

            grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            row["fwdbwd_ms"] = round(_time(grad, q, k, v), 3)
        except Exception as e:  # noqa: BLE001 — record the failure, keep rows
            row["error"] = f"{type(e).__name__}: {e}"[:300]
        rows.append(row)
        print(json.dumps(row), flush=True)

    out = {
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "warmup": WARMUP,
        "iters": ITERS,
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(__file__), "..", "bench_flash.json")
    if jax.default_backend() != "tpu":
        path = path.replace(".json", ".cpu.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", os.path.normpath(path))


if __name__ == "__main__":
    main()
