"""Local vs remote (SEED-style centralized) acting throughput.

The harness lives in ``bench.run_act_compare`` (shared with the
``TPU_RL_BENCH_ACT=1 python bench.py`` mode); this wrapper adds the CLI. It
drives the production ``InferenceService`` (learner-device padded-batch
jitted act behind a ZMQ ROUTER) with N real ``InferenceClient`` DEALER
threads, against the same model acting locally, and reports acts/sec plus
the ``inference-rtt`` / ``inference-batch-size`` / ``inference-step-time``
timer breakdown.

Run (CPU host or TPU host — the service compiles for whatever backend jax
resolves):
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/bench_remote_acting.py \
      [--clients 4] [--envs 16] [--acts 150] [--port 29920] \
      [--out bench_act.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--clients", type=int, default=None,
                   help="concurrent worker clients (default 4)")
    p.add_argument("--envs", type=int, default=None,
                   help="envs (= obs rows) per client per tick (default 16)")
    p.add_argument("--acts", type=int, default=None,
                   help="timed acting ticks per client "
                        "(default 150 on CPU, 600 on an accelerator)")
    p.add_argument("--port", type=int, default=29920)
    p.add_argument("--out", default=None,
                   help="result JSON path (default bench_act[.cpu].json)")
    args = p.parse_args()

    from bench import run_act_compare

    result = run_act_compare(
        clients=args.clients,
        envs_per_client=args.envs,
        acts=args.acts,
        port=args.port,
        out_path=args.out,
    )
    print(json.dumps(result, indent=1))


if __name__ == "__main__":
    main()
