"""On-chip END-TO-END learner FPS: the production LearnerService fed through
the REAL shared-memory path (OnPolicyStore put -> consume -> _assemble ->
chained dispatch), not a synthetic pre-placed device batch.

This is the honest counterpart to bench.py's @ref rows (which time the
compiled step on a device-resident batch): here every update's batch crosses
host shm -> device, exactly like a deployment. If the host feed cannot keep
the chip busy, that gap IS the result — both rates are reported.

The reference's corresponding instrument is the learner-throughput timer
around its sample+update loop (``/root/reference/utils/utils.py:167-189``).

Run on the TPU host (learner owns the chip; feeders are host threads):
  PYTHONPATH=/root/repo:/root/.axon_site python examples/run_tpu_e2e_learner.py \
      [--updates 2048] [--chain 16] [--feeders 4] [--out bench_e2e_learner.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=2048)
    p.add_argument("--chain", type=int, default=16)
    p.add_argument("--feeders", type=int, default=4)
    p.add_argument("--publish-interval", type=int, default=256)
    p.add_argument("--out", default="bench_e2e_learner.json")
    args = p.parse_args()

    from tpu_rl.config import Config
    from tpu_rl.data.layout import BatchLayout
    from tpu_rl.data.shm_ring import OnPolicyStore, alloc_handles
    from tpu_rl.runtime.learner_service import LearnerService
    from tpu_rl.types import BATCH_FIELDS

    cfg = Config.from_dict(
        dict(
            algo="IMPALA", batch_size=128, seq_len=5, hidden_size=64,
            obs_shape=(4,), action_space=2, learner_chain=args.chain,
            loss_log_interval=10**9,
        )
    )
    layout = BatchLayout.from_config(cfg)
    handles = alloc_handles(layout, capacity=cfg.batch_size)

    # Pre-generate a pool of synthetic windows (field -> (seq, width)); the
    # feeders only memcpy, so the feed rate measures the shm path, not RNG.
    rng = np.random.default_rng(0)
    pool = []
    for j in range(64):
        w = {}
        for f in BATCH_FIELDS:
            shape = (layout.seq_len, layout.width(f))
            if f == "act":
                w[f] = rng.integers(0, 2, size=shape).astype(np.float32)
            elif f == "is_fir":
                a = np.zeros(shape, np.float32)
                a[0] = 1.0
                w[f] = a
            elif f == "log_prob":
                w[f] = np.full(shape, -0.7, np.float32)
            else:
                w[f] = rng.standard_normal(shape).astype(np.float32) * 0.1
        pool.append(w)

    stop = threading.Event()
    puts = [0] * args.feeders
    put_blocked = [0] * args.feeders
    # OnPolicyStore.put is single-writer (slot reserve and slot write are
    # separate critical sections); serialize feeders so N threads emulate N
    # producers funneling through one writer, never a torn/lost window.
    put_lock = threading.Lock()

    def feed(k: int) -> None:
        store = OnPolicyStore(handles, layout)  # per-thread views
        i = k
        while not stop.is_set():
            with put_lock:
                ok = store.put(pool[i % len(pool)])
            if ok:
                puts[k] += 1
                i += 1
            else:
                put_blocked[k] += 1
                time.sleep(0)  # store full: learner is the bottleneck

    threads = [
        threading.Thread(target=feed, args=(k,), daemon=True)
        for k in range(args.feeders)
    ]
    for t in threads:
        t.start()

    svc = LearnerService(
        cfg,
        handles,
        model_port=29890,
        stop_event=stop,
        max_updates=args.updates,
        publish_interval=args.publish_interval,
    )
    t0 = time.perf_counter()
    svc.run()
    elapsed = time.perf_counter() - t0
    stop.set()
    for t in threads:
        t.join(timeout=10)

    import jax

    updates = args.updates // max(1, args.chain) * max(1, args.chain)
    transitions = updates * cfg.batch_size * cfg.seq_len
    total_puts = sum(puts)
    # Steady-state rate from the service's own windowed timer (last 100
    # dispatches; excludes idle polls, dilutes first-dispatch compile).
    steady = svc.timer.mean_throughput("learner-throughput")
    row = dict(
        device_kind=jax.devices()[0].device_kind,
        algo=cfg.algo, batch=cfg.batch_size, seq=cfg.seq_len,
        hidden=cfg.hidden_size, chain=args.chain, feeders=args.feeders,
        updates=updates, seconds=round(elapsed, 2),
        e2e_learner_tps=round(transitions / elapsed, 1),
        e2e_learner_tps_steady=(
            round(steady, 1) if steady is not None else None
        ),
        feed_windows_per_s=round(total_puts / elapsed, 1),
        feed_tps=round(total_puts * cfg.seq_len / elapsed, 1),
        feed_blocked_ratio=round(
            sum(put_blocked) / max(1, sum(put_blocked) + total_puts), 3
        ),
        note=(
            "e2e through the real shm feed (put->consume->_assemble->chained "
            "dispatch); feed_blocked_ratio ~1 means the chip outran the host "
            "feed's spare capacity, ~0 means the feed was the bottleneck"
        ),
    )
    print(json.dumps(row), flush=True)
    with open(args.out, "w") as f:
        json.dump(row, f, indent=1)
    print(f"wrote {args.out}", flush=True)


if __name__ == "__main__":
    main()
