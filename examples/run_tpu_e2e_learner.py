"""On-chip END-TO-END learner FPS: the production LearnerService fed through
the REAL shared-memory path (OnPolicyStore put -> consume -> assemble ->
chained dispatch), not a synthetic pre-placed device batch.

This is the honest counterpart to bench.py's @ref rows (which time the
compiled step on a device-resident batch): here every update's batch crosses
host shm -> device, exactly like a deployment. If the host feed cannot keep
the chip busy, that gap IS the result — both rates are reported.

The harness itself lives in ``bench.e2e_learner_row`` (shared with the
``TPU_RL_BENCH_E2E`` A/B mode); this wrapper adds the CLI. ``--feed``
selects the data plane: ``prefetch`` (pipelined feeder thread,
``Config.learner_prefetch`` depth), ``sync`` (the serial baseline,
``learner_prefetch=0``), or ``both`` (run each and report the speedup —
the overlap A/B on real hardware).

The reference's corresponding instrument is the learner-throughput timer
around its sample+update loop (``/root/reference/utils/utils.py:167-189``).

Run on the TPU host (learner owns the chip; feeders are host threads):
  PYTHONPATH=/root/repo:/root/.axon_site python examples/run_tpu_e2e_learner.py \
      [--updates 2048] [--chain 16] [--feeders 4] [--feed both] \
      [--prefetch-depth 2] [--out bench_e2e_learner.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=2048)
    p.add_argument("--chain", type=int, default=16)
    p.add_argument("--feeders", type=int, default=4)
    p.add_argument("--publish-interval", type=int, default=256)
    p.add_argument(
        "--feed", choices=("prefetch", "sync", "both"), default="prefetch",
        help="data plane: pipelined feed, serial baseline, or A/B both",
    )
    p.add_argument("--prefetch-depth", type=int, default=2)
    p.add_argument("--out", default="bench_e2e_learner.json")
    args = p.parse_args()

    from bench import e2e_learner_row, run_e2e_compare

    if args.feed == "both":
        result = run_e2e_compare(
            updates=args.updates, chain=args.chain, feeders=args.feeders,
            out_path=args.out,
        )
        print(json.dumps(result), flush=True)
        print(f"wrote {args.out}", flush=True)
        return

    prefetch = args.prefetch_depth if args.feed == "prefetch" else 0
    row = e2e_learner_row(
        updates=args.updates, chain=args.chain, feeders=args.feeders,
        publish_interval=args.publish_interval, prefetch=prefetch,
    )
    row["note"] = (
        "e2e through the real shm feed (put->consume->assemble->chained "
        "dispatch); feed_blocked_ratio ~1 means the chip outran the host "
        "feed's spare capacity, ~0 means the feed was the bottleneck"
    )
    print(json.dumps(row), flush=True)
    with open(args.out, "w") as f:
        json.dump(row, f, indent=1)
    print(f"wrote {args.out}", flush=True)


if __name__ == "__main__":
    main()
