"""Colocated (Anakin) A/B: fused on-device loop vs distributed feed.

The harness lives in ``bench.run_colocated_compare`` (shared with the
``TPU_RL_BENCH_COLOCATED=1 python bench.py`` mode); this wrapper adds the
CLI. Both sides run the reference learner workload (IMPALA, batch x seq 5,
hidden 64, obs 4 / act 2):

- distributed: ``bench.e2e_learner_row`` — feeder threads memcpy windows
  into the real shm OnPolicyStore while the production LearnerService
  consumes and train-steps them (prefetched feed, the data plane's best
  configuration). This is the storage->learner transitions/s the
  acceptance bar compares against.
- colocated: ``runtime/colocated.py``'s fused program — ``family.act`` ->
  jittable CartPole step -> window assembly -> ``train_step`` as ONE jitted
  dispatch, envs resident on device. Measured at the same 128-env quantum
  (headline speedup) plus larger env batches (scale rows).

Run on CPU (acceptance: speedup >= 2x) or on an accelerator:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/bench_colocated.py \
      [--updates 200] [--env-batches 128,1024] [--out bench_colocated.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--updates", type=int, default=None,
                   help="timed fused iterations per env-batch row "
                        "(default 200 on CPU, 2048 on chip)")
    p.add_argument("--env-batches", default=None,
                   help="comma-separated env-batch sizes, e.g. 128,1024 "
                        "(default 128,1024 on CPU; 128,1024,4096 on chip)")
    p.add_argument("--out", default=None,
                   help="result JSON path (default bench_colocated[.cpu].json)")
    args = p.parse_args()

    from bench import run_colocated_compare

    env_batches = (
        tuple(int(s) for s in args.env_batches.split(","))
        if args.env_batches else None
    )
    result = run_colocated_compare(
        updates=args.updates,
        env_batches=env_batches,
        out_path=args.out,
    )
    print(json.dumps(result, indent=1))


if __name__ == "__main__":
    main()
