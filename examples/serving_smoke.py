"""Serving fast-path smoke: a real two-replica fleet serving QUANTIZED
(bf16) params through the shape-bucketed batching path, proving the ISSUE 16
composition end to end on CPU:

- two ``replica_main`` processes boot with ``inference_dtype="bf16"`` and
  ``inference_buckets=8`` — every bucket program compiles BEFORE the socket
  binds, and the post-warm recompile count must stay exactly 0 across a
  flush-size sweep (the PR 11 ratchet through the quantized+bucketed path);
- a live model PUB bumps the policy version mid-run, so the sweep crosses
  ver-keyed re-quantizing swaps;
- client threads drive mixed-width requests (1..12 rows) through real
  DEALER sockets: zero failures allowed;
- LIVE PARITY SPOT-CHECK: a fresh client sends ``first=1`` (zero carry) and
  the reply's logits are compared against the local f32 reference act on
  the same observations — argmax must agree on every row and the logits
  must match within bf16 tolerance, proving the quantized serving path
  answers with the same policy, not just quickly.

Exits nonzero on any failure — this is the ``make serving-smoke`` CI gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/serving_smoke.py \
      [--base-port 31300]
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--base-port", type=int, default=31300)
    p.add_argument("--acts", type=int, default=60,
                   help="timed acts per client thread")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_rl.config import Config
    from tpu_rl.fleet import replica_main
    from tpu_rl.loadgen import probe_ready
    from tpu_rl.models.families import build_family
    from tpu_rl.runtime.inference_service import InferenceClient
    from tpu_rl.runtime.protocol import Protocol
    from tpu_rl.runtime.transport import MODEL_HWM, Pub, Sub

    model_port = args.base_port + 10
    stat_port = args.base_port + 11
    result_dir = tempfile.mkdtemp(prefix="serving-smoke-")
    cfg = Config.from_dict(dict(
        algo="IMPALA", obs_shape=(4,), action_space=2, hidden_size=32,
        worker_num_envs=16, act_mode="remote",
        inference_replicas=2, inference_base_port=args.base_port,
        inference_batch=16, inference_flush_us=500,
        inference_timeout_ms=3000, inference_hedge_ms=500,
        inference_retries=1,
        # The fast path under test: bf16 serving params + bucket ladder
        # [8, 16]; telemetry installs the per-bucket recompile watches.
        inference_dtype="bf16", inference_buckets=8,
        result_dir=result_dir, telemetry_interval_s=0.5,
    ))
    ports = [args.base_port, args.base_port + 1]
    endpoints = [("127.0.0.1", prt) for prt in ports]

    family = build_family(cfg)
    params = family.init_params(jax.random.key(0), seq_len=cfg.seq_len)
    actor_host = jax.device_get(params["actor"])
    pub = Pub("*", model_port, bind=True, hwm=MODEL_HWM)
    stop_pub = threading.Event()

    def _publish() -> None:
        ver = 0
        while not stop_pub.is_set():
            ver += 1
            pub.send(Protocol.Model, {"actor": actor_host, "ver": ver})
            stop_pub.wait(1.0)

    # Stat tap: bind the SUB end of the replicas' stat PUBs and keep each
    # replica's latest snapshot — the recompile ratchet's evidence.
    stat_sub = Sub("*", stat_port, bind=True)
    latest: dict[int, dict] = {}
    stop_stats = threading.Event()

    def _collect_stats() -> None:
        while not stop_stats.is_set():
            for proto, snap in stat_sub.drain(max_msgs=256):
                if proto == Protocol.Telemetry and isinstance(snap, dict):
                    latest[int(snap.get("rid", -1))] = snap
            stop_stats.wait(0.1)

    ctx = mp.get_context("spawn")
    replicas = [
        ctx.Process(
            target=replica_main,
            args=(cfg, i, ports[i], "127.0.0.1", model_port,
                  stat_port, None, None),
            kwargs={"seed": 0},
            daemon=True,
        )
        for i in range(2)
    ]

    failures: list[str] = []
    try:
        for proc in replicas:
            proc.start()
        print(f"[serving] fleet booting on {ports} (bf16 + buckets [8, 16])",
              flush=True)
        if not probe_ready(endpoints, cfg, timeout_s=180.0):
            print("[serving] FAIL: fleet never became ready", flush=True)
            return 1
        threading.Thread(target=_publish, daemon=True).start()
        threading.Thread(target=_collect_stats, daemon=True).start()

        # ---- mixed-width sweep: both replicas, every bucket program
        fail_counts = [0, 0]

        def drive(k: int) -> None:
            cl = InferenceClient(cfg, "127.0.0.1", ports[k % 2], wid=k)
            try:
                rng = np.random.default_rng(k)
                widths = [1, 2, 4, 7, 9, 12]
                for i in range(args.acts):
                    n = widths[i % len(widths)]
                    obs = rng.standard_normal((n, 4)).astype(np.float32)
                    first = (
                        np.ones(n, np.float32) if i == 0
                        else np.zeros(n, np.float32)
                    )
                    if cl.act(obs, first) is None:
                        fail_counts[k % 2] += 1
            finally:
                cl.close()

        threads = [
            threading.Thread(target=drive, args=(k,), daemon=True)
            for k in range(4)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        n_acts = 4 * args.acts
        print(f"[serving] sweep: {n_acts} mixed-width acts in {dt:.1f}s, "
              f"failures {sum(fail_counts)}", flush=True)
        if sum(fail_counts):
            failures.append(f"{sum(fail_counts)} client acts failed")

        # ---- live parity spot-check against the local f32 reference
        rng = np.random.default_rng(1234)
        obs = rng.standard_normal((8, 4)).astype(np.float32)
        cl = InferenceClient(cfg, "127.0.0.1", ports[0], wid=99)
        try:
            reply = cl.act(obs, np.ones(8, np.float32))  # first=1: zero carry
        finally:
            cl.close()
        if reply is None:
            failures.append("parity probe got no reply")
        else:
            if int(reply.get("ver", -1)) < 1:
                failures.append(
                    f"parity reply served pre-broadcast weights "
                    f"(ver {reply.get('ver')})"
                )
            hw, cw = family.carry_widths
            _a, ref_logits, _lp, _h2, _c2 = family.act(
                params, jnp.asarray(obs), jnp.zeros((8, hw)),
                jnp.zeros((8, cw)), jax.random.key(0),
            )
            ref = np.asarray(ref_logits)
            got = np.asarray(reply["logits"])
            maxdiff = float(np.abs(got - ref).max())
            agree = float(np.mean(got.argmax(-1) == ref.argmax(-1)))
            print(f"[serving] parity: logits maxdiff {maxdiff:.2e}, "
                  f"argmax agreement {agree:.0%}, ver {reply['ver']}",
                  flush=True)
            if maxdiff > 5e-2:
                failures.append(f"bf16 logits drifted {maxdiff} > 5e-2")
            if agree < 1.0:
                failures.append(f"argmax disagreement ({agree:.0%})")

        # ---- the ratchet: both replicas' live counters must report 0
        t_wait = time.monotonic() + 30.0
        while len(latest) < 2 and time.monotonic() < t_wait:
            time.sleep(0.2)
        if len(latest) < 2:
            failures.append("replica telemetry never arrived")
        for rid, snap in sorted(latest.items()):
            val = next(
                (v for name, _lbls, v in snap.get("counters", [])
                 if name == "inference-xla-recompiles"),
                None,
            )
            print(f"[serving] replica {rid}: recompiles {val}", flush=True)
            if val is None:
                failures.append(f"replica {rid} published no recompile count")
            elif val != 0:
                failures.append(f"replica {rid} recompiled {val}x post-warm")
    finally:
        stop_pub.set()
        stop_stats.set()
        pub.close()
        stat_sub.close()
        for proc in replicas:
            if proc.is_alive():
                proc.kill()
            proc.join(timeout=10)

    if failures:
        for f in failures:
            print(f"[serving] FAIL: {f}", flush=True)
        return 1
    print("[serving] OK: bf16+bucketed fleet served every flush shape with "
          "0 recompiles, 0 failures, and live f32 parity", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
