"""PBT smoke: K=4 colocated CartPole variants under the population
controller, with one deliberately poisoned variant — the ``make pbt-smoke``
CI gate for the population plane (seeded sampling, telemetry scraping,
truncation selection, exploit/explore checkpoint adoption, kill-resumability).

Sequence:

1. boot ``PopulationController`` over K=4 colocated members with a seeded
   lr/entropy search space; member 0's lr is overridden to ~100x the
   known-good value (a variant PBT must weed out);
2. the controller evals every ``interval`` member updates: the poisoned
   member must show up as a truncation loser and be exploit-replaced
   (winner checkpoint copied, hyperparameters adopted + mutated, epoch
   bumped, member restarted);
3. the harness SIGKILLs the first exploited member right after its exploit
   restart — mid-adoption, before it has produced anything of its own. The
   supervisor must respawn it and the respawn must resume from the COPIED
   committed checkpoint (two-phase commit preserved across the copy);
4. assert the final leaderboard's best fitness clears the CartPole bar,
   the audit trail has the expected spawn/eval/exploit/respawn events,
   every surviving checkpoint dir is committed, and the run exits 0.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/pbt_smoke.py \
      [--updates 1500] [--timeout 600]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POISON_LR = 0.03  # ~100x the known-good 3e-4: reliably cripples PPO CartPole
FITNESS_BAR = 60.0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=1500)
    p.add_argument("--timeout", type=float, default=600.0)
    args = p.parse_args()

    from tpu_rl.checkpoint import COMMIT_MARKER, _ckpt_dirs, is_committed
    from tpu_rl.config import Config
    from tpu_rl.population import PopulationController

    run_dir = tempfile.mkdtemp(prefix="pbt_smoke_")
    cfg = Config(
        env="CartPole-v1",
        env_mode="colocated",
        algo="PPO",
        batch_size=32,
        buffer_size=32,
        seq_len=5,
        lr=3e-4,
        entropy_coef=0.001,
        reward_scale=0.1,
        time_horizon=500,
        loss_log_interval=100,
        model_save_interval=100,
        ckpt_keep=3,
        learner_device="cpu",
        result_dir=run_dir,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,
        supervise_poll_s=0.25,
        startup_grace_s=180.0,
        heartbeat_timeout_s=90.0,
        # Search space centered on the known-good colocated CartPole recipe;
        # eval every 300 member updates -> ~4 generations in a 1500-update
        # budget, first eval after every member has committed checkpoints.
        pop_spec=(
            "lr:log[1e-4,1e-3] entropy_coef:lin[0.0005,0.002] "
            "perturb=1.2,0.8 interval=300u quantile=0.25 k=4"
        ),
        pop_seed=7,
    )

    # The SIGKILL-mid-exploit probe: the 'exploit' audit event carries the
    # restarted member's fresh pid — kill it on the spot, before it has
    # resumed, and let the supervisor's ordinary crash respawn prove the
    # copied checkpoint is whole and adoptable.
    probe = {"killed_member": None, "exploited": []}

    def on_event(ev: dict) -> None:
        if ev.get("ev") != "exploit":
            return
        probe["exploited"].append(ev)
        if probe["killed_member"] is None:
            probe["killed_member"] = ev["loser"]
            print(
                f"[pbt-smoke] SIGKILL member-{ev['loser']} mid-exploit "
                f"(pid {ev['pid']})", flush=True,
            )
            os.kill(ev["pid"], signal.SIGKILL)

    ctrl = PopulationController(
        cfg,
        max_updates=args.updates,
        initial_values={0: {"lr": POISON_LR}},
        on_event=on_event,
    )
    print(
        f"[pbt-smoke] population up; run_dir={run_dir} "
        f"poisoned member-0 lr={POISON_LR}", flush=True,
    )
    # Watchdog: a hung population must fail the gate, not wedge CI.
    watchdog = threading.Timer(args.timeout, ctrl.sup.stop_event.set)
    watchdog.daemon = True
    watchdog.start()
    t0 = time.monotonic()
    doc = ctrl.run()
    watchdog.cancel()
    print(
        f"[pbt-smoke] run finished in {time.monotonic() - t0:.0f}s "
        f"ok={doc['ok']} counts={doc['counts']}", flush=True,
    )

    failures: list[str] = []
    if not doc["ok"]:
        failures.append(
            "population run did not complete cleanly (timeout, exhausted "
            "restart budget, or external stop)"
        )

    # ---- audit trail: the poisoned member was weeded out ----
    events = []
    try:
        with open(os.path.join(run_dir, "population.jsonl")) as f:
            events = [json.loads(line) for line in f if line.strip()]
    except (OSError, ValueError) as e:
        failures.append(f"population.jsonl unreadable: {type(e).__name__}: {e}")
    by_ev: dict[str, int] = {}
    for ev in events:
        by_ev[ev.get("ev", "?")] = by_ev.get(ev.get("ev", "?"), 0) + 1
    print(f"[pbt-smoke] audit events: {by_ev}", flush=True)
    exploits = [ev for ev in events if ev.get("ev") == "exploit"]
    if not exploits:
        failures.append("no exploit event: truncation selection never fired")
    elif not any(ev["loser"] == 0 for ev in exploits):
        failures.append(
            "poisoned member-0 was never truncation-replaced "
            f"(losers: {sorted({ev['loser'] for ev in exploits})})"
        )
    if by_ev.get("eval", 0) < 1:
        failures.append("no eval event: generation boundary never reached")

    # ---- kill-resumability: the SIGKILLed member came back and resumed ----
    killed = probe["killed_member"]
    if killed is None:
        failures.append("SIGKILL probe never armed (no exploit happened)")
    else:
        respawns = [
            ev for ev in events
            if ev.get("ev") == "respawn" and ev.get("member") == f"member-{killed}"
        ]
        if not respawns:
            failures.append(
                f"supervisor never respawned SIGKILLed member-{killed}"
            )
        resume_path = os.path.join(
            run_dir, f"member-{killed}", "learner_resume.jsonl"
        )
        try:
            with open(resume_path) as f:
                recs = [json.loads(line) for line in f if line.strip()]
        except (OSError, ValueError):
            recs = []
        if not recs:
            failures.append(
                f"member-{killed} wrote no resume record after the "
                "mid-exploit SIGKILL — the copied checkpoint was not adopted"
            )
        else:
            last = recs[-1]
            if int(last["epoch"]) < 1:
                failures.append(
                    f"member-{killed} resumed without an epoch bump: {last}"
                )
            print(
                f"[pbt-smoke] member-{killed} resumed at idx {last['idx']}, "
                f"run epoch {last['epoch']} ({len(recs)} resume(s))",
                flush=True,
            )

    # ---- leaderboard: someone actually solved the task ----
    try:
        final = json.loads(
            open(os.path.join(run_dir, "population.json")).read()
        )
    except (OSError, ValueError) as e:
        failures.append(f"population.json invalid: {type(e).__name__}: {e}")
        final = {"leaderboard": []}
    board = final.get("leaderboard", [])
    if board != sorted(
        board,
        key=lambda r: -(r["best_fitness"] if r["best_fitness"] is not None
                        else float("-inf")),
    ):
        failures.append("leaderboard is not sorted best-first")
    best = board[0] if board else None
    if best is None or best["best_fitness"] is None:
        failures.append("empty leaderboard / no fitness readings")
    elif best["best_fitness"] < FITNESS_BAR:
        failures.append(
            f"best fitness {best['best_fitness']:.1f} < {FITNESS_BAR:.0f} — "
            "the population never solved CartPole"
        )
    else:
        print(
            f"[pbt-smoke] best member-{best['member']} "
            f"fitness {best['best_fitness']:.1f} values {best['values']}",
            flush=True,
        )

    # ---- durability: every surviving checkpoint dir is committed ----
    for k in range(ctrl.spec.k):
        models = os.path.join(run_dir, f"member-{k}", "models")
        if not os.path.isdir(models):
            failures.append(f"member-{k} has no models dir")
            continue
        for _idx, path in _ckpt_dirs(models, "PPO"):
            if not is_committed(path):
                failures.append(
                    f"uncommitted checkpoint survived: {path} (no "
                    f"{COMMIT_MARKER} marker)"
                )

    if failures:
        for f in failures:
            print(f"[pbt-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print("[pbt-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
