"""Summarize a jax.profiler trace: top ops by device/host SELF-time (inclusive minus nested children).

Reads the newest ``*.trace.json.gz`` (Chrome trace format) under the given
profile dir (the layout ``jax.profiler.start_trace`` writes:
``<dir>/plugins/profile/<ts>/<host>.trace.json.gz``) and prints the top-N
event names by summed duration, per process ("pid") group — device streams
and host threads come out as separate groups, so the device table directly
answers "which op dominates the step" (the attribution VERDICT r3 #6 asks
for on the long-context transformer).

Usage:
    python examples/trace_top_ops.py /tmp/tpu_rl_longctx_trace [N]
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import sys


def newest_trace(profile_dir: str) -> str:
    pats = os.path.join(profile_dir, "**", "*.trace.json.gz")
    files = sorted(glob.glob(pats, recursive=True), key=os.path.getmtime)
    if not files:
        raise SystemExit(f"no *.trace.json.gz under {profile_dir}")
    return files[-1]


def _self_times(events: list) -> list:
    """(event, self_dur) for complete ('X') events: inclusive duration minus
    the duration of nested children. Chrome-trace events within one
    (pid, tid) track are properly nested, so a stack sweep in start-time
    order (ties: longer event first = parent first) attributes every
    microsecond exactly once — without this, a wrapper TraceMe would
    double-count and could eclipse the real dominant op."""
    by_track: dict = collections.defaultdict(list)
    for e in events:
        if e.get("ph") == "X" and "dur" in e and "ts" in e:
            by_track[(e.get("pid"), e.get("tid"))].append(e)
    out = []
    for track in by_track.values():
        track.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list = []  # [end_ts, child_accum, event]
        for e in track:
            while stack and e["ts"] >= stack[-1][0]:
                end, child, parent = stack.pop()
                out.append((parent, parent["dur"] - child))
            if stack:
                stack[-1][1] += e["dur"]
            stack.append([e["ts"] + e["dur"], 0, e])
        while stack:
            end, child, parent = stack.pop()
            out.append((parent, parent["dur"] - child))
    return out


def summarize(path: str, top_n: int = 20) -> dict:
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    # pid -> process name (trace metadata)
    pnames: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pnames[e.get("pid")] = e.get("args", {}).get("name", str(e.get("pid")))
    groups: dict = collections.defaultdict(lambda: collections.Counter())
    counts: dict = collections.defaultdict(lambda: collections.Counter())
    for e, self_dur in _self_times(events):
        key = pnames.get(e.get("pid"), str(e.get("pid")))
        groups[key][e["name"]] += self_dur
        counts[key][e["name"]] += 1
    out = {}
    for proc, ctr in groups.items():
        total = sum(ctr.values())
        rows = [
            {
                "name": name[:120],
                "total_us": dur,
                "pct": round(100.0 * dur / total, 1) if total else 0.0,
                "count": counts[proc][name],
            }
            for name, dur in ctr.most_common(top_n)
        ]
        out[proc] = {"total_us": total, "top": rows}
    return out


def main() -> None:
    profile_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_rl_longctx_trace"
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    path = newest_trace(profile_dir)
    print(f"# {path}")
    for proc, summary in summarize(path, top_n).items():
        print(f"\n== {proc}  (total {summary['total_us']/1e3:.1f} ms across events)")
        for r in summary["top"]:
            print(
                f"  {r['pct']:5.1f}%  {r['total_us']/1e3:9.3f} ms  "
                f"x{r['count']:<5} {r['name']}"
            )


if __name__ == "__main__":
    main()
