"""Actor-side throughput: env-steps/s of ONE worker process as a function of
``worker_num_envs`` (vectorized acting), against the reference's
by-construction per-process ceiling.

The reference worker steps one env per process with a per-step forward and a
hard 0.05 s sleep (``/root/reference/agents/worker.py:131``) — ~20 env-steps/s
per process, ~600/s for the configured 30-process fleet (BASELINE.md). Here
one process steps N envs with a single batched jitted forward per tick; this
script measures the real end-to-end loop (gymnasium stepping + batched act +
ZMQ publish into a draining SUB) with the throttle off.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/bench_worker_throughput.py \
      [--envs 1 8 32] [--seconds 20] [--out bench_worker.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(num_envs: int, seconds: float, base_port: int) -> dict:
    from tpu_rl.config import Config
    from tpu_rl.runtime.protocol import Protocol
    from tpu_rl.runtime.transport import Pub, Sub
    from tpu_rl.runtime.worker import Worker

    cfg = Config.from_dict(
        dict(
            env="CartPole-v1",
            algo="PPO",
            hidden_size=64,  # reference model size
            obs_shape=(4,),
            action_space=2,
            worker_step_sleep=0.0,
            worker_num_envs=num_envs,
            time_horizon=500,
        )
    )
    relay = Sub("127.0.0.1", base_port, bind=True)
    model_pub = Pub("127.0.0.1", base_port + 1, bind=True)
    stop = threading.Event()
    w = Worker(
        cfg, worker_id=0, manager_ip="127.0.0.1", manager_port=base_port,
        learner_ip="127.0.0.1", model_port=base_port + 1, stop_event=stop,
    )
    t = threading.Thread(target=w.run, daemon=True)
    t.start()

    # Warmup gates on RECEIVED TRAFFIC, not wall-clock: wait for the first
    # rollout frame (jit compile + ZMQ slow-join complete), then drain a
    # short settle window. A fixed sleep understates throughput whenever
    # compile bleeds into the timed region on a slow/loaded host.
    warmup_deadline = time.time() + 120.0
    while time.time() < warmup_deadline:
        got = relay.recv(timeout_ms=100)
        if got is not None and got[0] == Protocol.RolloutBatch:
            break
    else:
        raise RuntimeError(
            "worker produced no RolloutBatch frame within 120 s warmup"
        )
    settle = time.time() + 1.0
    while time.time() < settle:
        relay.recv(timeout_ms=50)
    n_steps = 0
    t0 = time.time()
    deadline = t0 + seconds
    while time.time() < deadline:
        got = relay.recv(timeout_ms=100)
        if got is not None and got[0] == Protocol.RolloutBatch:
            # one frame per tick = num_envs env-steps
            n_steps += len(got[1]["id"])
    elapsed = time.time() - t0
    stop.set()
    t.join(timeout=30)
    relay.close()
    model_pub.close()
    sps = n_steps / elapsed
    return dict(
        num_envs=num_envs,
        env_steps_per_s=round(sps, 1),
        per_env_steps_per_s=round(sps / num_envs, 1),
        seconds=round(elapsed, 1),
        vs_reference_per_process=round(sps / 20.0, 1),
    )


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--envs", type=int, nargs="+", default=[1, 8, 32])
    p.add_argument("--seconds", type=float, default=20.0)
    p.add_argument("--out", default="bench_worker.json")
    args = p.parse_args()

    rows = []
    for i, n in enumerate(args.envs):
        row = measure(n, args.seconds, 29800 + 4 * i)
        rows.append(row)
        print(json.dumps(row), flush=True)
    with open(args.out, "w") as f:
        json.dump(
            dict(
                note=(
                    "one worker process, CartPole-v1, hidden 64, throttle off; "
                    "reference per-process ceiling is ~20 env-steps/s "
                    "(0.05 s sleep, /root/reference/agents/worker.py:131)"
                ),
                rows=rows,
            ),
            f,
            indent=1,
        )
    print(f"wrote {args.out}", flush=True)


if __name__ == "__main__":
    main()
