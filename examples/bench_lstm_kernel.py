"""On-chip kernel-vs-scan benchmark for the fused Pallas LSTM.

Times the LSTM sequence unroll (forward and forward+grad) with the Pallas
kernel (``set_pallas_mode("auto")``) against the ``lax.scan`` path
(``"off"``), at the reference batch quantum and at MXU-loading widths —
including shapes whose batch is grid-tiled over VMEM (``batch_tile``).

Run on the TPU (no JAX_PLATFORMS override):
  PYTHONPATH=/root/repo python examples/bench_lstm_kernel.py

Writes ``bench_lstm_kernel.json`` and prints one row per (shape, pass).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tpu_rl.models import cells
from tpu_rl.models.cells import LSTMCell
from tpu_rl.ops.pallas_lstm import batch_tile, bwd_batch_tile

SHAPES = [
    # (B, S, IN, H, iters) — reference quantum, mid, wide (grid-tiled)
    (128, 5, 4, 64, 300),
    (256, 16, 64, 256, 100),
    (1024, 16, 64, 1024, 30),
]


def _run(cell, params, x, firsts, carry0, mode: str, grad: bool, iters: int):
    def fwd(params, x):
        cells.set_pallas_mode(mode)
        try:
            (hN, cN), hs = cell.apply(
                params, x, carry0, firsts, True, method=LSTMCell.unroll
            )
        finally:
            cells.set_pallas_mode("auto")
        return (hs**2).mean() + (hN + cN).mean()

    fn = jax.jit(jax.grad(fwd) if grad else fwd)
    out = fn(params, x)  # compile
    jax.block_until_ready(out)
    # device_get forces true chain completion (see bench.py _sync note)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(params, x)
    np.asarray(
        jax.device_get(jax.tree_util.tree_leaves(out)[0])
    ).ravel()[:1]
    return (time.perf_counter() - t0) / iters


def main() -> None:
    rows = []
    for B, S, IN, H, iters in SHAPES:
        rng = np.random.default_rng(0)
        cell = LSTMCell(H)
        x = jnp.asarray(rng.normal(size=(B, S, IN)).astype(np.float32))
        firsts = np.zeros((B, S, 1), np.float32)
        firsts[:, 0] = 1.0
        firsts = jnp.asarray(firsts)
        carry0 = (jnp.zeros((B, H)), jnp.zeros((B, H)))
        params = cell.init(jax.random.key(0), (carry0[0], carry0[1]), x[:, 0])
        for grad in (False, True):
            # "force" runs the REAL kernel wherever a tiling fits (auto now
            # dispatches by measured win, so auto's fwd-only path is the
            # scan — forcing is the only way to keep timing the kernel).
            t_scan = _run(cell, params, x, firsts, carry0, "off", grad, iters)
            t_kern = _run(cell, params, x, firsts, carry0, "force", grad, iters)
            # What auto-dispatch picks at this (shape, pass): the kernel only
            # under AD at whole-batch-single-tile shapes (cells._use_pallas +
            # the lstm_unroll primal's scan body).
            single_tile = (
                batch_tile(B, S, H) == B and bwd_batch_tile(B, S, H) == B
            )
            chosen = "kernel" if (grad and single_tile) else "scan"
            chosen_ms = t_kern if chosen == "kernel" else t_scan
            row = {
                "shape": f"B{B} S{S} H{H}",
                "pass": "fwd+grad" if grad else "fwd",
                "batch_tile": batch_tile(B, S, H),
                "scan_ms": round(t_scan * 1e3, 3),
                "kernel_ms": round(t_kern * 1e3, 3),
                "speedup": round(t_scan / t_kern, 2),
                "tokens_per_s_kernel": round(B * S / t_kern, 1),
                "auto_chooses": chosen,
                "auto_regression": round(
                    chosen_ms / min(t_scan, t_kern), 3
                ),  # 1.0 = auto picked the measured-fastest path
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
    out = {
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "rows": rows,
    }
    with open("bench_lstm_kernel.json", "w") as f:
        json.dump(out, f, indent=1)
    print("wrote bench_lstm_kernel.json", flush=True)


if __name__ == "__main__":
    main()
