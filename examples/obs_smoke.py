"""Observability smoke: boot the smallest real cluster with the telemetry
plane on, scrape ``/metrics`` and ``/healthz`` mid-run, and validate the
rolling ``telemetry.json`` + Chrome trace artifacts. Exits nonzero on any
failure — this is the ``make obs-smoke`` CI gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/obs_smoke.py \
      [--updates 6] [--base-port 30400] [--telemetry-port 30460]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_ROLES = ("worker", "manager", "storage", "learner")
_STALENESS_COUNT = re.compile(
    r"^policy_staleness_updates_count\{[^}]*\} (\d+)$", re.M
)


def _get(url: str, timeout: float = 3.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except (urllib.error.URLError, ConnectionError, OSError):
        return None, ""


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=6)
    p.add_argument("--base-port", type=int, default=30400)
    p.add_argument("--telemetry-port", type=int, default=30460)
    p.add_argument("--timeout", type=float, default=240.0)
    args = p.parse_args()

    from tpu_rl.config import MachinesConfig, WorkerMachine
    from tpu_rl.runtime.runner import local_cluster
    from tests.conftest import small_config  # the CI-sized Config recipe

    run_dir = tempfile.mkdtemp(prefix="obs_smoke_")
    cfg = small_config(
        env="CartPole-v1",
        algo="PPO",
        worker_step_sleep=0.0,
        learner_device="cpu",
        rollout_lag_sec=30.0,
        time_horizon=100,
        loss_log_interval=2,
        result_dir=run_dir,
        telemetry_port=args.telemetry_port,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,
    )
    machines = MachinesConfig(
        learner_ip="127.0.0.1",
        learner_port=args.base_port,
        workers=[WorkerMachine(
            num_p=2, manager_ip="127.0.0.1", ip="127.0.0.1",
            port=args.base_port + 5,
        )],
    )
    print(f"[obs-smoke] cluster up; run_dir={run_dir}", flush=True)
    sup = local_cluster(cfg, machines, max_updates=args.updates)
    metrics_url = f"http://127.0.0.1:{args.telemetry_port}/metrics"
    failures: list[str] = []
    try:
        learner = next(c for c in sup.children if c.name == "learner")
        deadline = time.time() + args.timeout
        text = ""
        while time.time() < deadline:
            _, text = _get(metrics_url)
            counts = [int(m) for m in _STALENESS_COUNT.findall(text)]
            if (
                all(f'role="{r}"' in text for r in REQUIRED_ROLES)
                and any(c > 0 for c in counts)
            ):
                break
            time.sleep(0.5)
        else:
            failures.append(
                "per-role /metrics samples (incl. nonzero staleness) never "
                f"converged; last scrape was {len(text)} bytes"
            )
        missing = [r for r in REQUIRED_ROLES if f'role="{r}"' not in text]
        if missing:
            failures.append(f"/metrics missing roles: {missing}")
        else:
            print(
                f"[obs-smoke] /metrics: {len(text.splitlines())} lines, "
                f"all of {REQUIRED_ROLES} present", flush=True,
            )

        status, body = _get(f"http://127.0.0.1:{args.telemetry_port}/healthz")
        if status not in (200, 503):
            failures.append(f"/healthz unreachable (status={status})")
        else:
            doc = json.loads(body)
            print(
                f"[obs-smoke] /healthz {status}: "
                f"{sorted(doc['roles'])}", flush=True,
            )

        while time.time() < deadline and learner.proc.is_alive():
            time.sleep(1.0)
        if learner.proc.is_alive() or learner.proc.exitcode != 0:
            failures.append(
                f"learner did not complete cleanly "
                f"(alive={learner.proc.is_alive()}, "
                f"exitcode={learner.proc.exitcode})"
            )
    finally:
        sup.stop()

    tele_path = os.path.join(run_dir, "telemetry.json")
    try:
        tele = json.loads(open(tele_path).read())
        roles = {s["role"] for s in tele["sources"]}
        print(f"[obs-smoke] telemetry.json roles: {sorted(roles)}", flush=True)
        if not {"worker", "storage", "learner"} <= roles:
            failures.append(f"telemetry.json missing roles: {roles}")
    except (OSError, ValueError, KeyError) as e:
        failures.append(f"telemetry.json invalid: {type(e).__name__}: {e}")
    trace_path = os.path.join(run_dir, "trace.json")
    try:
        trace = json.loads(open(trace_path).read())
        spans = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
        print(f"[obs-smoke] trace.json spans: {sorted(spans)}", flush=True)
        if "train-step" not in spans:
            failures.append(f"trace.json has no train-step span: {spans}")
    except (OSError, ValueError, KeyError) as e:
        failures.append(f"trace.json invalid: {type(e).__name__}: {e}")

    if failures:
        for f in failures:
            print(f"[obs-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print("[obs-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
