"""Distributed-tracing smoke: boot the smallest real cluster with rollout
lineage sampling on (``trace_sample_n``), let the storage edge auto-merge the
per-role trace dumps at shutdown, then re-merge and validate the fleet trace:
all four roles on one clock-corrected timeline, and at least one sampled
rollout chained worker -> manager -> storage -> learner by Chrome flow
events. Exits nonzero on any failure — this is the ``make trace-smoke`` CI
gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/trace_smoke.py \
      [--updates 6] [--base-port 30500] [--telemetry-port 30560]

Open the resulting ``fleet_trace.json`` in https://ui.perfetto.dev to see the
lineage arrows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_ROLES = {"worker", "manager", "storage", "learner"}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=6)
    p.add_argument("--base-port", type=int, default=30500)
    p.add_argument("--telemetry-port", type=int, default=30560)
    p.add_argument("--timeout", type=float, default=240.0)
    p.add_argument("--sample-n", type=int, default=2)
    args = p.parse_args()

    from tpu_rl.config import MachinesConfig, WorkerMachine
    from tpu_rl.obs import merge_result_dir
    from tpu_rl.obs.merge import MERGED_NAME
    from tpu_rl.runtime.runner import local_cluster
    from tests.conftest import small_config  # the CI-sized Config recipe

    run_dir = tempfile.mkdtemp(prefix="trace_smoke_")
    cfg = small_config(
        env="CartPole-v1",
        algo="PPO",
        worker_step_sleep=0.0,
        learner_device="cpu",
        rollout_lag_sec=30.0,
        time_horizon=100,
        loss_log_interval=2,
        result_dir=run_dir,
        telemetry_port=args.telemetry_port,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,
        trace_sample_n=args.sample_n,
    )
    machines = MachinesConfig(
        learner_ip="127.0.0.1",
        learner_port=args.base_port,
        workers=[WorkerMachine(
            num_p=2, manager_ip="127.0.0.1", ip="127.0.0.1",
            port=args.base_port + 5,
        )],
    )
    print(f"[trace-smoke] cluster up; run_dir={run_dir}", flush=True)
    sup = local_cluster(cfg, machines, max_updates=args.updates)
    failures: list[str] = []
    try:
        learner = next(c for c in sup.children if c.name == "learner")
        deadline = time.time() + args.timeout
        while time.time() < deadline and learner.proc.is_alive():
            time.sleep(1.0)
        if learner.proc.is_alive() or learner.proc.exitcode != 0:
            failures.append(
                f"learner did not complete cleanly "
                f"(alive={learner.proc.is_alive()}, "
                f"exitcode={learner.proc.exitcode})"
            )
    finally:
        sup.stop()

    merged_path = os.path.join(run_dir, MERGED_NAME)
    if not os.path.exists(merged_path):
        failures.append("storage edge did not auto-merge fleet_trace.json")
    # Re-merge now that every role has joined and flushed its final dump —
    # the authoritative artifact the assertions below run against.
    summary = merge_result_dir(run_dir)
    print(
        f"[trace-smoke] merged {summary['n_files']} dump(s): "
        f"{summary['n_events']} events, {summary['flows']} flow(s), "
        f"roles={summary['roles']}", flush=True,
    )
    try:
        fleet = json.loads(open(merged_path).read())  # valid JSON on disk
    except (OSError, ValueError) as e:
        failures.append(f"fleet trace invalid: {type(e).__name__}: {e}")
        fleet = {"traceEvents": [], "meta": {"roles": [], "clock": {}}}

    missing = REQUIRED_ROLES - set(fleet["meta"]["roles"])
    if missing:
        failures.append(f"fleet trace missing roles: {sorted(missing)}")
    chains: dict[str, list[str]] = {}
    for ev in fleet["traceEvents"]:
        if ev.get("cat") == "lineage":
            chains.setdefault(ev["id"], []).append(ev["args"]["hop"])
    linked = [
        tid for tid, hops in chains.items()
        if {"worker-tick", "storage-ingest", "train-step"} <= set(hops)
        and ("relay-in" in hops or "relay-out" in hops)
    ]
    print(
        f"[trace-smoke] {len(chains)} lineage chain(s), "
        f"{len(linked)} fully linked worker->manager->storage->learner",
        flush=True,
    )
    if not linked:
        failures.append(
            f"no fully-linked rollout chain; partial chains: "
            f"{dict(list(chains.items())[:5])}"
        )
    if not any(k.startswith("worker") for k in fleet["meta"]["clock"]):
        failures.append(
            f"clock sync never estimated a worker offset: "
            f"{fleet['meta']['clock']}"
        )

    if failures:
        for f in failures:
            print(f"[trace-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print(f"[trace-smoke] OK — open {merged_path} in ui.perfetto.dev",
          flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
