#!/bin/sh
# Round-5 on-chip drain — run the MOMENT the tunnel probe succeeds.
# Priority-ordered for short windows (rounds 3-4 saw 11-25 min windows
# between multi-hour outages); every step is timeout-bounded so a dying
# tunnel kills the step, not the chain. Probe first:
#
#   timeout 90 python -c "import jax; print(jax.devices())"
#
# Never clobber PYTHONPATH without /root/.axon_site (the TPU plugin
# registers there); bench.py routes CPU fallbacks away from the committed
# on-chip artifacts by itself.
set -x
cd "$(dirname "$0")/.."

# 1. FULL matrix with the round-5 code. One run covers three debts at once:
#    the V-MPO@ref row after the mask rewrite (was 1.198 ms/update, 10x its
#    siblings, from the topk+gather lowering), the longctx-flash row with
#    the tuned gcd(512,T) tiles now in the dispatch (committed matrix still
#    shows the library-default 190.7 ms), and a fresh bench_results.json
#    (with recorded_at) for the outage-proof headline to embed.
timeout 1800 env PYTHONPATH=/root/repo:/root/.axon_site python bench.py

# 2. End-to-end learner FPS through the real shm feed with the production
#    chained dispatch (VERDICT r4 weak #6 — every prior on-chip number is a
#    synthetic-batch row).
timeout 600 env PYTHONPATH=/root/repo:/root/.axon_site \
    python examples/run_tpu_e2e_learner.py \
    --updates 2048 --chain 16 --out bench_e2e_learner.json

# 3. Wide-LSTM MFU attribution (22% ceiling, bf16 buying nothing): profiled
#    f32 + bf16 rows, then the trace top-op summaries that name the
#    bottleneck (recurrent matmul vs gate VPU vs HBM).
timeout 900 env PYTHONPATH=/root/repo:/root/.axon_site python - <<'EOF'
import json
import bench
for dtype in ("float32", "bfloat16"):
    row = bench.bench_one(
        f"IMPALA@wide-lstm-{dtype}-profiled",
        dict(algo="IMPALA", batch_size=1024, seq_len=16, hidden_size=1024,
             obs_shape=(64,), action_space=8, compute_dtype=dtype,
             profile_dir=f"/tmp/tpu_rl_widelstm_{dtype}_trace"),
        5, 15,
    )
    print(json.dumps(row))
EOF
timeout 300 env PYTHONPATH=/root/repo:/root/.axon_site \
    python examples/trace_top_ops.py /tmp/tpu_rl_widelstm_float32_trace || true
timeout 300 env PYTHONPATH=/root/repo:/root/.axon_site \
    python examples/trace_top_ops.py /tmp/tpu_rl_widelstm_bfloat16_trace || true

# 4. Flash-attention op-level sweep re-record (round-4 item 2: the
#    committed sweep's "full" fwd row is warmup-contaminated).
timeout 900 env PYTHONPATH=/root/repo:/root/.axon_site \
    python examples/bench_flash_attention.py

# 5. Long-context train-step trace (round-4 item 3) — only reached in a
#    long window; attributes the remaining flash-row gap.
timeout 600 env PYTHONPATH=/root/repo:/root/.axon_site python - <<'EOF'
import json
import bench
row = bench.bench_one(
    "PPO-transformer@longctx-flash-profiled",
    dict(algo="PPO", model="transformer", compute_dtype="bfloat16",
         attention_impl="flash", batch_size=16, seq_len=2048,
         hidden_size=512, n_heads=8, n_layers=4, obs_shape=(64,),
         action_space=8, profile_dir="/tmp/tpu_rl_longctx_trace"),
    3, 10,
)
print(json.dumps(row))
EOF
timeout 300 env PYTHONPATH=/root/repo:/root/.axon_site \
    python examples/trace_top_ops.py /tmp/tpu_rl_longctx_trace || true

# 6. V-MPO step trace — only if step 1 shows the row still anomalous.
timeout 600 env PYTHONPATH=/root/repo:/root/.axon_site python - <<'EOF'
import json
import bench
row = bench.bench_one(
    "V-MPO@ref-profiled",
    dict(algo="V-MPO", obs_shape=(4,), action_space=2, batch_size=128,
         seq_len=5, hidden_size=64, profile_dir="/tmp/tpu_rl_vmpo_trace"),
    5, 20, 16,
)
print(json.dumps(row))
EOF
timeout 300 env PYTHONPATH=/root/repo:/root/.axon_site \
    python examples/trace_top_ops.py /tmp/tpu_rl_vmpo_trace || true
