"""Single-process end-to-end slice: PPO on CartPole-v1 (regression anchor).

Thin wrapper over the general ``examples/train_inline.py`` (any algo, any
env). Kept under this name as the canonical smoke check.

Run: JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/train_cartpole_inline.py
"""

from __future__ import annotations

from examples.train_inline import main as _main


def main(updates: int = 250, algo: str = "PPO", seed: int = 0) -> float:
    return _main(updates=updates, algo=algo, env_name="CartPole-v1", seed=seed)


if __name__ == "__main__":
    final = main()
    print(f"final 50-game mean episode reward: {final:.1f}")
    assert final > 40.0, "PPO failed to improve on CartPole"
