"""Single-process end-to-end slice: PPO on CartPole-v1 through the public API.

This is SURVEY.md §7 step 3 — env loop + seq-5 assembly + jitted train step in
one process, no ZMQ — and the regression anchor for the distributed runtime.

Run: JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/train_cartpole_inline.py
"""

from __future__ import annotations

import collections
import time

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from tpu_rl.algos.registry import get_algo
from tpu_rl.config import Config
from tpu_rl.types import BATCH_FIELDS, Batch


def main(updates: int = 250, algo: str = "PPO", seed: int = 0) -> float:
    cfg = Config.from_dict(
        dict(
            algo=algo,
            obs_shape=(4,),
            action_space=2,
            batch_size=32,
            seq_len=5,
            lr=3e-4,
            entropy_coef=0.001,
            reward_scale=0.1,
            time_horizon=500,
        )
    )
    family, state, train_step = get_algo(cfg.algo).build(cfg, jax.random.key(seed))
    train_step = jax.jit(train_step)
    act = jax.jit(family.act)

    env = gym.make(cfg.env)
    key = jax.random.key(seed + 1)
    obs, _ = env.reset(seed=seed)
    h = jnp.zeros((1, cfg.hidden_size))
    c = jnp.zeros((1, cfg.hidden_size))
    is_fir = 1.0
    epi_rew, epi_steps = 0.0, 0
    rewards = collections.deque(maxlen=50)

    seq: list[dict] = []
    ready: list[dict] = []
    t0 = time.time()

    for update in range(updates):
        # ---- collect batch_size seq-5 windows on-policy ----
        while len(ready) < cfg.batch_size:
            key, sub = jax.random.split(key)
            ob = jnp.asarray(obs, jnp.float32)[None]
            a, logits, log_prob, h2, c2 = act(state.params, ob, h, c, sub)
            a_env = int(a[0, 0])
            nobs, rew, term, trunc, _ = env.step(a_env)
            done = term or trunc
            epi_rew += float(rew)
            epi_steps += 1
            seq.append(
                dict(
                    obs=np.asarray(ob[0]),
                    act=np.asarray(a[0]),
                    rew=np.array([float(rew) * cfg.reward_scale], np.float32),
                    logits=np.asarray(logits[0]),
                    log_prob=np.asarray(log_prob[0]),
                    is_fir=np.array([is_fir], np.float32),
                    hx=np.asarray(h[0]),
                    cx=np.asarray(c[0]),
                )
            )
            if len(seq) == cfg.seq_len:
                ready.append(
                    {k: np.stack([s[k] for s in seq]) for k in BATCH_FIELDS}
                )
                seq = []
            is_fir = 0.0
            obs, h, c = nobs, h2, c2
            if done or epi_steps >= cfg.time_horizon:
                rewards.append(epi_rew)
                obs, _ = env.reset()
                h = jnp.zeros_like(h)
                c = jnp.zeros_like(c)
                is_fir, epi_rew, epi_steps = 1.0, 0.0, 0

        batch = Batch.from_mapping(
            {k: np.stack([t[k] for t in ready]) for k in BATCH_FIELDS}
        )
        ready = []
        key, sub = jax.random.split(key)
        state, metrics = train_step(state, batch, sub)
        if (update + 1) % 25 == 0:
            mean_rew = float(np.mean(rewards)) if rewards else float("nan")
            print(
                f"update {update+1:4d}  loss {float(metrics['loss']):+.4f}  "
                f"mean-epi-rew {mean_rew:7.2f}  elapsed {time.time()-t0:5.1f}s"
            )
    return float(np.mean(rewards)) if rewards else 0.0


if __name__ == "__main__":
    final = main()
    print(f"final 50-game mean episode reward: {final:.1f}")
    assert final > 40.0, "PPO failed to improve on CartPole"
