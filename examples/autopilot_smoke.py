"""Autopilot smoke: the closed control loop end to end, on one host — the
CPU-scale proof of ISSUE 17's acceptance bar.

One ``AutopilotController`` (``manage_all=True``: it owns the whole
replica range) supervises an elastic inference fleet while a diurnal
loadgen schedule sweeps offered load 20 -> 205 -> 20 rps (>= 10x up and
back down):

- every replica stalls 40ms per batch flush (``stall:inference@40ms``
  service chaos), pinning single-replica capacity near ``batch/stall``
  ~190 rps — so the peak stage saturates one replica deterministically
  and the valleys never do;
- a probe client + 1 Hz SLO engine grade ``p99:inference-rtt`` over a
  sliding window of the probe's own RTT histogram; the controller
  scrapes that ``/slo`` (plus ``/metrics``) off a smoke-local telemetry
  server and must scale OUT to >= 2 replicas under the peak and back IN
  when the valley returns;
- ``kill:inference-1@t+5s`` stays armed until the first scaled-out
  replica exists, then SIGKILLs it — the controller's supervision pass
  must respawn it without burning the run;
- the drivers start with lanes planned for the FULL capacity range, so
  scale-out adoption happens through the lane re-probe backoff (this
  PR's FleetClient satellite): the stage rows must show ``reprobes``;
- acceptance: zero failed client requests overall, version floor never
  decreases, both 20 rps valley stages grade GREEN on
  ``p99:inference-rtt``, every ``autopilot.jsonl`` action record
  validates against the documented schema, and the live dashboard frame
  renders an AUTOPILOT panel.

Exits nonzero on any failure — this is the ``make autopilot-smoke`` CI
gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/autopilot_smoke.py \
      [--clients 6000] [--base-port 31500]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from collections import deque

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The controller's policy: scale out on sustained p99 burn, back in when
# the burn window is clean; bounds [1, 3] replicas. Scale-in demands a
# much longer clean streak (25s at the 0.5s poll) than scale-out's 2s:
# burn saturates at 0 whenever capacity is comfortable, so an impatient
# scale-in would hunt the floor even under the peak.
AUTOPILOT_SPEC = (
    "scale_out:replicas?burn:inference-rtt>0.5"
    "@sustain=4@cooldown=6s@max=3,"
    "scale_in:replicas?burn:inference-rtt<0.02"
    "@sustain=50@cooldown=6s@min=1,"
    "limit=12/60s"
)
# The live engine the autopilot scrapes (1 Hz over the probe's sliding
# window) and the per-stage grading rule for the loadgen document.
LIVE_SLO = "p99:inference-rtt<500ms@window=15s"
STAGE_SLO = "p99:inference-rtt<500ms@window=600s"
# Diurnal ramp (aggregate rps, dwell seconds): 20 -> 205 -> 20 is >= 10x
# up and back; the 120 shoulders stay under one replica's ~190 rps
# capacity, the 205 peak saturates it.
SCHEDULE = [(20, 20), (120, 12), (205, 80), (120, 12), (20, 55)]

# Every `ev: action` line in autopilot.jsonl must carry exactly these
# typed fields (ARCHITECTURE.md section Autopilot documents the schema).
ACTION_SCHEMA = {
    "action": str,
    "target": str,
    "rule": str,
    "signal": str,
    "value": (int, float),
    "reason": str,
    "step": int,
    "from": int,
    "to": int,
    "replicas": int,
    "workers": int,
    "t": (int, float),
}


def validate_action(rec: dict) -> str | None:
    """None when the record matches ACTION_SCHEMA, else the complaint."""
    for key, typ in ACTION_SCHEMA.items():
        if key not in rec:
            return f"missing key {key!r}"
        if not isinstance(rec[key], typ) or isinstance(rec[key], bool):
            return f"key {key!r} has {type(rec[key]).__name__}"
    if rec["action"] not in ("scale_out", "scale_in", "respawn"):
        return f"unknown action {rec['action']!r}"
    return None


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=6_000)
    p.add_argument("--base-port", type=int, default=31500)
    p.add_argument("--result-dir", default=None)
    args = p.parse_args()

    import jax

    from tpu_rl.autopilot import AutopilotController
    from tpu_rl.config import Config, MachinesConfig
    from tpu_rl.fleet import FleetClient
    from tpu_rl.loadgen import probe_ready, run_loadgen
    from tpu_rl.models.families import build_family
    from tpu_rl.obs import (
        MetricsRegistry,
        TelemetryAggregator,
        TelemetryHTTPServer,
    )
    from tpu_rl.obs.registry import diff_snapshots
    from tpu_rl.obs.slo import SloEngine
    from tpu_rl.obs.top import build_frame, fetch_json
    from tpu_rl.runtime.protocol import Protocol
    from tpu_rl.runtime.transport import MODEL_HWM, Pub, Sub

    stat_port = args.base_port + 10  # machines.learner_port: the stat SUB
    http_port = args.base_port + 12  # smoke-local telemetry server
    result_dir = args.result_dir or tempfile.mkdtemp(prefix="autopilot-smoke-")
    machines = MachinesConfig(learner_ip="127.0.0.1", learner_port=stat_port)
    cfg = Config.from_dict(dict(
        algo="IMPALA", obs_shape=(4,), action_space=2, hidden_size=32,
        worker_num_envs=1, act_mode="remote", learner_device="cpu",
        inference_replicas=1, inference_base_port=args.base_port,
        inference_batch=8, inference_flush_us=2000, inference_buckets=8,
        # Generous timeout: the open-loop peak briefly queues multi-second
        # waits while the scaled-out replica compiles; hedges stay a
        # recovery tool (killed-lane failover), not a load amplifier.
        inference_timeout_ms=15_000, inference_hedge_ms=3_000,
        inference_retries=1,
        # Fast lane re-probe so clients adopt a scaled-out replica within
        # seconds of it binding.
        inference_reprobe_s=0.5, inference_reprobe_max_s=4.0,
        autopilot_spec=AUTOPILOT_SPEC, autopilot_poll_s=0.5,
        autopilot_drain_s=0.3,
        # stall: the deterministic saturation lever. kill: armed from the
        # start, fires as soon as the first scaled-out replica exists.
        chaos_spec="stall:inference@40ms,kill:inference-1@t+5s",
        result_dir=result_dir, telemetry_interval_s=0.5,
    ))
    capacity_ports = machines.inference_ports(
        cfg.replace(inference_replicas=3)
    )
    endpoints = [("127.0.0.1", prt) for prt in capacity_ports]
    out_path = os.path.join(result_dir, "loadgen.json")

    # Stand-in learner: rising-version model PUB (the replicas' ver-keyed
    # swap + the clients' floor ratchet need live broadcasts).
    family = build_family(cfg)
    params = family.init_params(jax.random.key(0), seq_len=cfg.seq_len)
    actor_host = jax.device_get(params["actor"])
    pub = Pub("*", machines.model_port, bind=True, hwm=MODEL_HWM)
    stop = threading.Event()

    def _publish() -> None:
        ver = 0
        while not stop.is_set():
            ver += 1
            pub.send(Protocol.Model, {"actor": actor_host, "ver": ver})
            stop.wait(2.0)

    # Stat plane: replica + controller registries PUB here; the aggregator
    # behind /metrics is what the autopilot's own scraper reads back.
    stat_sub = Sub("*", stat_port, bind=True)
    agg = TelemetryAggregator()

    def _collect_stats() -> None:
        while not stop.is_set():
            for proto, snap in stat_sub.drain(max_msgs=256):
                if proto == Protocol.Telemetry and isinstance(snap, dict):
                    agg.ingest(snap)
            stop.wait(0.1)

    # Probe plane: a closed-loop client across the FULL planned range
    # records real RTTs; the 1 Hz engine grades a sliding window of them
    # (cumulative histograms would never recover after the peak).
    probe_reg = MetricsRegistry(role="autopilot-probe")
    rtt_hist = probe_reg.histogram("inference-rtt")
    engine = SloEngine(LIVE_SLO)
    # Short probe hedge: a probe that picks a lane the autopilot JUST
    # retired must be rescued well under the 500ms live threshold —
    # otherwise every scale-in pollutes the very burn signal that decided
    # it and the controller flaps out/in forever.
    probe_cl = FleetClient(
        cfg.replace(inference_hedge_ms=300), endpoints, wid=7
    )

    def _probe() -> None:
        obs = np.zeros((1, 4), np.float32)
        first = np.ones((1,), np.float32)
        while not stop.is_set():
            t0 = time.perf_counter()
            got = probe_cl.act(obs, first, retries=0)
            rtt = time.perf_counter() - t0
            # A timed-out probe is a violation at the timeout bound, not
            # a missing sample.
            rtt_hist.observe(rtt if got is not None else rtt + 1.0)
            stop.wait(0.1)

    def _grade() -> None:
        ring: deque = deque()  # (t, cumulative snapshot)
        while not stop.is_set():
            now = time.monotonic()
            snap = probe_reg.snapshot()
            ring.append((now, snap))
            while ring and now - ring[0][0] > 16.0:
                ring.popleft()
            win = (
                diff_snapshots(snap, ring[0][1]) if len(ring) > 1 else snap
            )
            engine.evaluate([win], now=now)
            stop.wait(1.0)

    ctrl = AutopilotController(
        cfg, machines=machines, manage_all=True,
        scrape_url=f"http://127.0.0.1:{http_port}", http_port=0, seed=0,
    )
    server = TelemetryHTTPServer(
        agg, http_port, slo=engine.report, autopilot=ctrl.status_doc
    )
    result: dict = {}

    def _run_ctrl() -> None:
        result["autopilot"] = ctrl.run()

    ap_live = None
    probe_thread = None
    frame: list = []
    try:
        threading.Thread(target=_publish, daemon=True).start()
        threading.Thread(target=_collect_stats, daemon=True).start()
        ctrl_thread = threading.Thread(target=_run_ctrl, daemon=True)
        ctrl_thread.start()
        print(
            f"[autopilot-smoke] booting replica 0 on {capacity_ports[0]} "
            f"(capacity range {capacity_ports}) ...", flush=True,
        )
        t_boot = time.monotonic()
        if not probe_ready(endpoints[:1], cfg, timeout_s=240.0):
            print("[autopilot-smoke] FAIL: replica 0 never became ready",
                  flush=True)
            return 1
        print(
            f"[autopilot-smoke] replica 0 ready in "
            f"{time.monotonic() - t_boot:.1f}s", flush=True,
        )
        # Probe + grading only start against a ready fleet: boot-time
        # timeouts must not pre-burn the scale-out rule before any load.
        probe_thread = threading.Thread(target=_probe, daemon=True)
        probe_thread.start()
        threading.Thread(target=_grade, daemon=True).start()
        time.sleep(2.0)

        print(
            f"[autopilot-smoke] diurnal sweep {SCHEDULE} rps "
            f"({args.clients} clients)", flush=True,
        )
        doc = run_loadgen(
            cfg, endpoints, n_clients=args.clients, schedule=SCHEDULE,
            out_path=out_path, n_procs=2, rows=1, slo_spec=STAGE_SLO,
        )

        # Dashboard leg while the controller is still live: the frame the
        # operator would see must carry the AUTOPILOT panel.
        ap_live = fetch_json(f"http://127.0.0.1:{http_port}/autopilot", 3.0)
        if isinstance(ap_live, dict) and "error" in ap_live:
            ap_live = None
        frame = build_frame([], None, None, autopilot_doc=ap_live)
    finally:
        ctrl.sup.stop_event.set()
        time.sleep(0.1)
        stop.set()
    ctrl_thread.join(timeout=60.0)
    server.close()
    # The probe thread may be mid-act: let it notice `stop` before its
    # client's sockets go away under it.
    if probe_thread is not None:
        probe_thread.join(timeout=20.0)
    probe_cl.close()
    pub.close()
    stat_sub.close()

    for stage in doc["stages"]:
        print(json.dumps(stage), flush=True)

    events = []
    audit_path = os.path.join(result_dir, "autopilot.jsonl")
    if os.path.exists(audit_path):
        with open(audit_path) as f:
            events = [json.loads(line) for line in f if line.strip()]
    actions = [e for e in events if e.get("ev") == "action"]
    ap_doc = result.get("autopilot") or {}

    failures = []
    if ctrl_thread.is_alive():
        failures.append("controller never stopped")
    if not ap_doc.get("ok"):
        failures.append(f"autopilot run not ok: {ap_doc}")
    if len(doc["stages"]) != len(SCHEDULE):
        failures.append(
            f"expected {len(SCHEDULE)} stages, got {len(doc['stages'])}"
        )
    success = doc["overall"]["success_rate"]
    if success < 1.0:
        failures.append(
            f"overall success {success} < 1.0 — "
            f"{doc['overall']['sent'] - doc['overall']['ok']} requests failed"
        )
    floors = [s["version_floor"] for s in doc["stages"]]
    if any(b < a for a, b in zip(floors, floors[1:])):
        failures.append(f"version floor regressed across stages: {floors}")
    if floors and floors[-1] < 1:
        failures.append(f"floor never rose ({floors})")
    for idx in (0, len(doc["stages"]) - 1):
        slo = doc["stages"][idx].get("slo") if doc["stages"] else None
        if not (slo and slo["ok"]):
            failures.append(f"valley stage {idx} SLO not green: {slo}")
    # The closed loop itself: out under the peak, back in after it.
    outs = [a for a in actions if a["action"] == "scale_out"]
    ins = [a for a in actions if a["action"] == "scale_in"]
    peak = max((a["replicas"] for a in actions), default=1)
    final = ap_doc.get("replicas", peak)
    if not outs or peak < 2:
        failures.append(f"never scaled out (peak {peak}): {actions}")
    if not ins:
        failures.append("never scaled back in")
    if final >= peak:
        failures.append(f"final replicas {final} not below peak {peak}")
    for a in actions:
        complaint = validate_action(a)
        if complaint:
            failures.append(f"action record {a}: {complaint}")
    kills = [
        e for e in events
        if e.get("ev") == "chaos" and e.get("action") == "kill"
    ]
    respawns = [e for e in events if e.get("ev") == "respawn"]
    if not kills:
        failures.append("chaos kill never fired")
    if not respawns:
        failures.append("killed replica was never respawned")
    reprobes = sum(s.get("reprobes", 0) for s in doc["stages"])
    if reprobes < 1:
        failures.append(
            "drivers never re-probed a lane — scale-out adoption untested"
        )
    if not any("AUTOPILOT" in line for line in frame):
        failures.append(f"dashboard frame has no AUTOPILOT panel: {ap_live}")

    if failures:
        for f in failures:
            print(f"[autopilot-smoke] FAIL: {f}", flush=True)
        return 1
    print(
        f"[autopilot-smoke] OK: success {success:.4%}, floors {floors}, "
        f"replicas peaked at {peak} and settled at {final} "
        f"({len(outs)} out / {len(ins)} in, {len(kills)} chaos kill "
        f"absorbed, {reprobes} driver reprobes), audit at {audit_path}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
