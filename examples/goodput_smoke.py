"""Goodput-plane smoke: run the smallest real cluster (3 workers) with the
ledger plane on and a SIGSTOP chaos fault on one worker, then assert the
PR's live invariants end to end:

1. every role's published ledger is exhaustive — bucket ratios sum to 1
   with ``overcommit_ratio`` <= 1% (nothing double-counted), and learner /
   storage / manager / worker all show NONZERO goodput;
2. ``gauge:learner-goodput-ratio>0.0`` is accepted and evaluated by the
   SLO engine (``/slo`` green, the rule present with data);
3. the SIGSTOP'd worker surfaces as the TOP straggler in ``GET /goodput``
   (report-only: frame rate collapses to 0 against a healthy fleet);
4. ``python -m tpu_rl.obs.top --once`` renders one dashboard frame against
   the live fleet and exits 0.

Exits nonzero on any failure — this is the ``make goodput-smoke`` CI gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/goodput_smoke.py \
      [--base-port 30700] [--telemetry-port 30760]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STOPPED_WID = 1  # chaos stops worker-0-1 — wid 1 on the single machine
GOODPUT_ROLES = ("learner", "storage", "manager", "worker")


def _get_json(url: str, timeout: float = 3.0):
    """GET -> (status, parsed doc); HTTPError bodies (503 /slo) count."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except ValueError:
            return e.code, None
    except (urllib.error.URLError, ConnectionError, OSError, ValueError):
        return None, None


def _ledger_problems(doc: dict) -> list[str]:
    """The exhaustiveness invariant over every published breakdown: ratios
    sum to 1 within 1% and overcommit <= 1%."""
    problems = []
    entries = dict(doc.get("roles") or {})
    storage_snap = doc.get("storage")
    if storage_snap is not None:
        entries["storage/self"] = {
            "goodput": storage_snap.get("goodput"),
            "ratios": storage_snap.get("ratios") or {},
            "overcommit_ratio": storage_snap.get("overcommit_ratio"),
        }
    for key, e in entries.items():
        total = sum((e.get("ratios") or {}).values())
        if not 0.99 <= total <= 1.01:
            problems.append(f"{key}: bucket ratios sum {total:.4f} not ~1")
        over = e.get("overcommit_ratio")
        if over is not None and over > 0.01:
            problems.append(f"{key}: overcommit_ratio {over:.4f} > 1%")
    return problems


def _coverage_gaps(doc: dict) -> list[str]:
    """Nonzero goodput on every role the smoke deploys."""
    gaps = []
    entries = doc.get("roles") or {}
    storage_snap = doc.get("storage") or {}
    for role in GOODPUT_ROLES:
        if role == "storage":
            vals = [storage_snap.get("goodput") or 0.0]
        else:
            vals = [
                e.get("goodput") or 0.0
                for key, e in entries.items()
                if key.startswith(role + "/")
            ]
        if not vals or max(vals) <= 0.0:
            gaps.append(f"{role}: no source with goodput > 0")
    return gaps


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--base-port", type=int, default=30700)
    p.add_argument("--telemetry-port", type=int, default=30760)
    p.add_argument("--timeout", type=float, default=180.0)
    args = p.parse_args()

    from tests.conftest import small_config
    from tpu_rl.config import MachinesConfig, WorkerMachine
    from tpu_rl.runtime.runner import local_cluster

    run_dir = tempfile.mkdtemp(prefix="goodput_smoke_")
    cfg = small_config(
        env="CartPole-v1",
        algo="PPO",
        worker_step_sleep=0.0,
        learner_device="cpu",
        rollout_lag_sec=30.0,
        time_horizon=100,
        loss_log_interval=1000,
        result_dir=run_dir,
        telemetry_port=args.telemetry_port,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,
        slo_spec="gauge:learner-goodput-ratio>0.0",
        # SIGSTOP one of three workers shortly after launch: silent to the
        # heartbeat plane, so a huge timeout keeps the supervisor from
        # healing it — the straggler report, not quarantine, must find it.
        chaos_spec=f"stop:worker-0-{STOPPED_WID}@t+2s",
        heartbeat_timeout_s=600.0,
    )
    machines = MachinesConfig(
        learner_ip="127.0.0.1",
        learner_port=args.base_port,
        workers=[WorkerMachine(
            num_p=3, manager_ip="127.0.0.1", ip="127.0.0.1",
            port=args.base_port + 5,
        )],
    )
    base = f"http://127.0.0.1:{args.telemetry_port}"
    failures: list[str] = []
    print(f"[goodput-smoke] cluster up; run_dir={run_dir}", flush=True)
    # Generous budget: the smoke stops the fleet itself once every live
    # assertion has been observed (or the deadline passes).
    sup = local_cluster(cfg, machines, max_updates=2000)
    last: dict = {}
    try:
        deadline = time.time() + args.timeout
        pending = {"ledger", "coverage", "slo", "straggler"}
        fleet_warm = False
        while time.time() < deadline and pending:
            time.sleep(1.0)
            if fleet_warm and sup.chaos is not None:
                # The smoke is the supervision loop here: chaos one-shots
                # fire from this poll (Supervisor.loop is not running). The
                # first poll resolves the plan's t+2s, so holding it until
                # the fleet is warm guarantees the stopped worker has
                # frames on record to collapse from.
                for action, name in sup.chaos.poll(sup.children):
                    print(f"[goodput-smoke] chaos {action} -> {name}",
                          flush=True)
            status, doc = _get_json(base + "/goodput")
            if status != 200 or doc is None:
                continue
            last = doc
            if not fleet_warm:
                rates = doc.get("rates") or {}
                if len(rates) >= 3 and all(v > 0 for v in rates.values()):
                    fleet_warm = True
                    print(
                        f"[goodput-smoke] fleet warm (3 wids producing); "
                        f"arming chaos stop of wid {STOPPED_WID}",
                        flush=True,
                    )
            if "ledger" in pending and not _ledger_problems(doc):
                pending.discard("ledger")
                print("[goodput-smoke] ledger sums ok (overcommit <= 1%)",
                      flush=True)
            if "coverage" in pending and not _coverage_gaps(doc):
                pending.discard("coverage")
                print("[goodput-smoke] nonzero goodput on every role",
                      flush=True)
            if "straggler" in pending and sup.chaos is not None and (
                sup.chaos.n_stops > 0
            ):
                # Only a truly stopped worker has a COLLAPSED windowed frame
                # rate — a startup staleness transient cannot fake this.
                top = doc.get("stragglers") or []
                rate = (top[0].get("signals") or {}).get(
                    "frame-rate"
                ) if top else None
                if (
                    top
                    and top[0].get("wid") == STOPPED_WID
                    and top[0].get("score", 0.0) > 2.0
                    and rate is not None
                    and rate < 1.0
                ):
                    pending.discard("straggler")
                    print(
                        f"[goodput-smoke] SIGSTOP'd wid {STOPPED_WID} is the "
                        f"top straggler (score {top[0]['score']}, "
                        f"rate {rate}/s)",
                        flush=True,
                    )
            if "slo" in pending:
                s_status, s_doc = _get_json(base + "/slo")
                if s_status == 200 and s_doc and s_doc.get("ok") is True:
                    rules = s_doc.get("rules") or []
                    hit = [
                        r for r in rules
                        if "learner-goodput-ratio" in str(
                            r.get("rule") or r.get("spec") or ""
                        )
                    ]
                    if hit and hit[0].get("ok") is True:
                        pending.discard("slo")
                        print(
                            "[goodput-smoke] SLO accepts "
                            "gauge:learner-goodput-ratio>0.0 (green)",
                            flush=True,
                        )
        for what in sorted(pending):
            detail = ""
            if what == "ledger":
                detail = f": {_ledger_problems(last)}" if last else ""
            elif what == "coverage":
                detail = f": {_coverage_gaps(last)}" if last else ""
            elif what == "straggler":
                detail = f": top={last.get('stragglers')}" if last else ""
            failures.append(f"never observed live invariant '{what}'{detail}")

        # Dashboard renders one frame against the LIVE fleet, no tty.
        proc = subprocess.run(
            [
                sys.executable, "-m", "tpu_rl.obs.top",
                "--once", "--url", base + "/metrics",
            ],
            capture_output=True, text=True, timeout=60,
            env={
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))
                ),
            },
        )
        if proc.returncode != 0 or "GOODPUT" not in proc.stdout:
            failures.append(
                f"top --once failed: rc={proc.returncode} "
                f"stdout={proc.stdout[:400]!r} stderr={proc.stderr[:400]!r}"
            )
        else:
            print("[goodput-smoke] dashboard frame:", flush=True)
            print(proc.stdout, flush=True)
    finally:
        sup.stop()

    # The offline twin: storage appends ledger snapshots on the exporter
    # cadence; at least one line must have landed and parse back.
    try:
        with open(os.path.join(run_dir, "goodput.jsonl")) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        if not lines or "storage" not in lines[-1]:
            failures.append(f"goodput.jsonl malformed: {lines[-1:]}")
    except (OSError, ValueError) as e:
        failures.append(f"goodput.jsonl missing/invalid: {e}")

    if failures:
        for f in failures:
            print(f"[goodput-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print("[goodput-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
