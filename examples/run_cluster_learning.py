"""Demonstrate that the REAL distributed deployment learns — not just that it
completes updates.

Spawns the full local cluster (learner + storage + manager + vectorized
workers as separate processes over ZMQ + shm, the reference's
``main.py:301-414`` topology) on IMPALA/CartPole-v1 for a bounded number of
updates, then reads the learner's tensorboard event file and reports the
``50-game-mean-stat-of-epi-rew`` fleet-reward curve (the reference's own
env-performance scalar, ``agents/manager.py:62-79`` ->
``agents/learner.py:136-148``).

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/run_cluster_learning.py \
      [--updates 3000] [--out CLUSTER_LEARNING.md]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=3000)
    p.add_argument("--algo", default="IMPALA")
    p.add_argument("--env", default="CartPole-v1")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--num-envs", type=int, default=8)
    p.add_argument("--out", default=None, help="markdown run-record path")
    p.add_argument("--run-dir", default="runs/cluster_learning")
    p.add_argument("--base-port", type=int, default=30100)
    # Standard V-trace truncation is rho_bar=1 (no floor); the defaults keep
    # the reference's [0.1, 0.8] clip (compute_loss.py:29-43) for parity.
    p.add_argument("--rho-bar", type=float, default=0.8)
    p.add_argument("--rho-min", type=float, default=0.1)
    # Hyperparameters default to the inline-solved IMPALA recipe
    # (examples/run_baselines.py): hot exploration phase then a
    # near-deterministic tail. The round-3 run held entropy_coef=0.01
    # forever, which pins policy entropy ~0.58 — a CartPole policy that
    # flips actions ~28% of the time cannot balance 500 steps, so the fleet
    # mean was capped near 50 independent of any lag effect.
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--entropy-coef", type=float, default=1e-3)
    p.add_argument("--anneal-coef", type=float, default=5e-5)
    p.add_argument("--anneal-lr", type=float, default=1e-4)
    p.add_argument("--anneal-frac", type=float, default=0.4)
    p.add_argument(
        "--anneal-at", type=int, default=None,
        help="absolute switch update (overrides --anneal-frac); with "
        "--resume-from past this index the cold phase resumes immediately",
    )
    p.add_argument("--no-anneal", action="store_true")
    p.add_argument("--worker-step-sleep", type=float, default=0.02)
    p.add_argument(
        "--learner-chain", type=int, default=1,
        help="updates per dispatched learner program (Config.learner_chain); "
        "the learner accumulates K consumed batches per dispatch",
    )
    p.add_argument(
        "--k-epoch", type=int, default=1,
        help="optimizer epochs per batch (Config.K_epoch); V-MPO's inline "
        "recipe needs 4 — its KL Lagrange constraint is inactive at 1 "
        "(behavior == target at the only epoch, examples/run_baselines.py)",
    )
    p.add_argument(
        "--keep-window-carry", action="store_true",
        help="train from the actor-stored recurrent carries "
        "(Config.zero_window_carry=False, reference parity) instead of the "
        "R2D2-style zero-init that the IMPALA lag diagnosis made default "
        "here",
    )
    p.add_argument(
        "--value-clip", type=float, nargs=2, default=None,
        metavar=("LO", "HI"),
        help="bounded-return V-trace value clamp (Config.value_target_clip); "
        "CartPole at reward_scale 0.1 / gamma 0.99: 0 10",
    )
    p.add_argument("--target", type=float, default=475.0,
                   help="stop early when the fleet 50-game mean reaches this")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--max-hours", type=float, default=2.0,
        help="hard wallclock cap on the whole run",
    )
    p.add_argument(
        "--resume-from", default=None,
        help="models dir of a previous run: the learner restores the newest "
        "checkpoint (params + optimizer + update counter) and the workers "
        "warm-start from it — the SURVEY §5.4 resume path, exercised on the "
        "real topology. With an absolute anneal switch ('at') already "
        "passed, the resumed learner re-enters the cold phase immediately.",
    )
    args = p.parse_args()

    from tpu_rl.config import Config, MachinesConfig, WorkerMachine
    from tpu_rl.runtime.runner import local_cluster

    # Fresh timestamped subdir per invocation: stale event files from a
    # previous run would otherwise merge into the reward curve.
    run_dir = os.path.abspath(
        os.path.join(args.run_dir, time.strftime("%Y%m%d-%H%M%S"))
    )
    os.makedirs(run_dir, exist_ok=True)
    cfg = Config.from_dict(
        dict(
            env=args.env,
            algo=args.algo,
            batch_size=32,
            seq_len=5,
            hidden_size=64,
            lr=args.lr,
            entropy_coef=args.entropy_coef,
            entropy_anneal=(
                None
                if args.no_anneal
                else {
                    "coef": args.anneal_coef,
                    "lr": args.anneal_lr,
                    **(
                        {"at": args.anneal_at}
                        if args.anneal_at is not None
                        else {"frac": args.anneal_frac}
                    ),
                }
            ),
            stop_at_reward=args.target,
            value_target_clip=(
                tuple(args.value_clip) if args.value_clip else None
            ),
            # Decisive for async learning (measured): without zero-init the
            # stale actor-stored carries drive bootstrapped value
            # hallucination (mean V > discounted cap) -> persistent negative
            # advantages -> entropy ratchets to exactly 0 regardless of the
            # entropy bonus (collapse observed at coef 0.001, 0.01 AND 0.05).
            zero_window_carry=not args.keep_window_carry,
            rho_bar=args.rho_bar,
            rho_min=args.rho_min,
            # Throttle the fleet to just above the learner's consumption
            # rate (~500 transitions/s at 3 updates/s): on a single shared
            # core, unthrottled workers flood the relay queues and data ages
            # in flight — measured V-trace ratios fell to ~0.5 (heavy lag),
            # where the rho-clipped corrections are too weak to keep the
            # value function honest (mean V drifted past the discounted
            # cap). Near-empty queues keep the behavior policy fresh.
            worker_step_sleep=args.worker_step_sleep,
            worker_num_envs=args.num_envs,
            learner_chain=args.learner_chain,
            K_epoch=args.k_epoch,
            learner_device="cpu",  # deterministic on shared hosts; the
            # real-TPU topology is separately recorded in RUN_LOCAL_TPU_r03.md
            rollout_lag_sec=5.0,
            time_horizon=500,
            result_dir=run_dir,
            model_dir=(
                os.path.abspath(args.resume_from)
                if args.resume_from
                else os.path.join(run_dir, "models")
            ),
            model_save_interval=500,
            loss_log_interval=100,
        )
    )
    machines = MachinesConfig(
        learner_ip="127.0.0.1",
        learner_port=args.base_port,
        workers=[
            WorkerMachine(
                num_p=args.workers, manager_ip="127.0.0.1", ip="127.0.0.1",
                port=args.base_port + 2,
            )
        ],
    )
    t0 = time.time()
    deadline = t0 + args.max_hours * 3600.0  # hard cap: never spin forever
    sup = local_cluster(cfg, machines, max_updates=args.updates, seed=args.seed)
    try:
        learner = next(c for c in sup.children if c.name == "learner")
        while learner.proc.is_alive() and time.time() < deadline:
            sup.check()  # restart-on-silence supervision for the other roles
            time.sleep(2.0)
        rc = learner.proc.exitcode if not learner.proc.is_alive() else None
    finally:
        sup.stop()
    wallclock = time.time() - t0

    # ---- read the fleet-reward curve back from tensorboard events
    from tensorboard.backend.event_processing.event_accumulator import (
        EventAccumulator,
    )

    curve = []
    for ev_file in sorted(glob.glob(os.path.join(run_dir, "events.*"))):
        acc = EventAccumulator(ev_file)
        acc.Reload()
        if "50-game-mean-stat-of-epi-rew" in acc.Tags().get("scalars", []):
            curve += [
                (s.step, s.value)
                for s in acc.Scalars("50-game-mean-stat-of-epi-rew")
            ]
    curve.sort()
    fleet_max = max((v for _, v in curve), default=None)
    result = dict(
        algo=cfg.algo,
        env=cfg.env,
        updates=args.updates,
        learner_exit=rc,
        wallclock_s=round(wallclock, 1),
        workers=args.workers,
        num_envs_per_worker=args.num_envs,
        learner_chain=args.learner_chain,
        k_epoch=args.k_epoch,
        zero_window_carry=not args.keep_window_carry,
        seed=args.seed,
        target=args.target,
        solved=(fleet_max is not None and fleet_max >= args.target),
        fleet_reward_first=curve[0][1] if curve else None,
        fleet_reward_last=curve[-1][1] if curve else None,
        fleet_reward_max=fleet_max,
        n_stat_points=len(curve),
    )
    print(json.dumps(result), flush=True)
    if args.out:
        lines = [
            "# Cluster learning run record",
            "",
            "Full multi-process deployment (learner + storage + manager + "
            f"{args.workers} workers x {args.num_envs} envs over ZMQ + shm) — "
            "the reference `main.py:301-414` topology — learning "
            f"{cfg.env} with {cfg.algo}.",
            "",
            "```bash",
            "JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python "
            f"examples/run_cluster_learning.py --updates {args.updates}",
            "```",
            "",
            f"- learner exit code: **{rc}** after {args.updates} updates "
            f"in {round(wallclock, 1)} s",
            "- fleet 50-game mean episode reward "
            "(`50-game-mean-stat-of-epi-rew`, worker -> manager window -> "
            "storage stat mailbox -> learner tensorboard):",
            "",
            "| game count | mean reward |",
            "|---|---|",
        ]
        step = max(1, len(curve) // 12)
        for s, v in curve[::step]:
            lines.append(f"| {s} | {v:.1f} |")
        if curve and curve[-1] not in curve[::step]:
            lines.append(f"| {curve[-1][0]} | {curve[-1][1]:.1f} |")
        with open(args.out, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"wrote {args.out}", flush=True)


if __name__ == "__main__":
    main()
