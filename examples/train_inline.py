"""Single-process end-to-end slice for ANY of the six algorithms, through the
public API: EnvAdapter env loop + seq-window assembly + jitted train step, no
ZMQ. Works for discrete (CartPole) and continuous (Pendulum/MountainCarContinuous)
envs — the reference's two showcase settings (``/root/reference/README.md``).

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/train_inline.py \
      [--algo PPO] [--env CartPole-v1] [--updates 250]
"""

from __future__ import annotations

import argparse
import collections
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_rl.algos.registry import get_algo
from tpu_rl.config import Config
from tpu_rl.runtime.env import EnvAdapter, probe_spaces
from tpu_rl.types import BATCH_FIELDS, Batch


def act_params(state):
    """Acting parameter tree for either state flavor (SACState keeps the
    actor separate; TrainState nests it under "actor")."""
    if hasattr(state, "actor_params"):
        return {"actor": state.actor_params}
    return {"actor": state.params["actor"]}


def main(
    updates: int = 250,
    algo: str = "PPO",
    env_name: str = "CartPole-v1",
    seed: int = 0,
    batch_size: int = 32,
    log_every: int = 25,
) -> float:
    cfg = probe_spaces(
        Config.from_dict(
            dict(
                algo=algo,
                env=env_name,
                batch_size=batch_size,
                seq_len=5,
                lr=3e-4,
                entropy_coef=0.001,
                reward_scale=0.1,
                time_horizon=500,
            )
        )
    )
    family, state, train_step = get_algo(cfg.algo).build(cfg, jax.random.key(seed))
    train_step = jax.jit(train_step)
    act = jax.jit(family.act)

    env = EnvAdapter(cfg, seed=seed)
    key = jax.random.key(seed + 1)
    obs = env.reset()
    hw, cw = family.carry_widths
    h = jnp.zeros((1, hw))
    c = jnp.zeros((1, cw))
    is_fir = 1.0
    epi_rew, epi_steps = 0.0, 0
    rewards = collections.deque(maxlen=50)

    seq: list[dict] = []
    ready: list[dict] = []
    t0 = time.time()

    for update in range(updates):
        while len(ready) < cfg.batch_size:
            key, sub = jax.random.split(key)
            ob = jnp.asarray(obs, jnp.float32)[None]
            a, logits, log_prob, h2, c2 = act(act_params(state), ob, h, c, sub)
            next_obs, rew, done = env.step(np.asarray(a[0]))
            epi_rew += rew
            epi_steps += 1
            seq.append(
                dict(
                    obs=np.asarray(ob[0]),
                    act=np.asarray(a[0]),
                    rew=np.array([rew * cfg.reward_scale], np.float32),
                    logits=np.asarray(logits[0]),
                    log_prob=np.asarray(log_prob[0]),
                    is_fir=np.array([is_fir], np.float32),
                    hx=np.asarray(h[0]),
                    cx=np.asarray(c[0]),
                )
            )
            if len(seq) == cfg.seq_len:
                ready.append(
                    {k: np.stack([s[k] for s in seq]) for k in BATCH_FIELDS}
                )
                seq = []
            is_fir = 0.0
            obs, h, c = next_obs, h2, c2
            if done or epi_steps >= cfg.time_horizon:
                rewards.append(epi_rew)
                obs = env.reset()
                h = jnp.zeros_like(h)
                c = jnp.zeros_like(c)
                is_fir, epi_rew, epi_steps = 1.0, 0.0, 0

        batch = Batch.from_mapping(
            {k: np.stack([t[k] for t in ready]) for k in BATCH_FIELDS}
        )
        ready = []
        key, sub = jax.random.split(key)
        state, metrics = train_step(state, batch, sub)
        if (update + 1) % log_every == 0:
            mean_rew = float(np.mean(rewards)) if rewards else float("nan")
            print(
                f"update {update+1:4d}  loss {float(metrics['loss']):+.4f}  "
                f"mean-epi-rew {mean_rew:8.2f}  elapsed {time.time()-t0:5.1f}s"
            )
    env.close()
    return float(np.mean(rewards)) if rewards else 0.0


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--algo", default="PPO")
    p.add_argument("--env", default="CartPole-v1")
    p.add_argument("--updates", type=int, default=250)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    final = main(args.updates, args.algo, args.env, args.seed)
    print(f"final 50-game mean episode reward: {final:.1f}")
