"""Single-process end-to-end slice for ANY of the six algorithms, through the
public API: EnvAdapter env loop + seq-window assembly + jitted train step, no
ZMQ. Works for discrete (CartPole) and continuous (Pendulum/MountainCarContinuous)
envs — the reference's two showcase settings (``/root/reference/README.md``).

On-policy algos consume each assembled batch once; off-policy algos (SAC*)
accumulate sequence windows in a uniform replay buffer and sample from it —
the inline equivalent of the reference's shared-memory replay path
(``/root/reference/agents/learner.py:369-400``).

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/train_inline.py \
      [--algo PPO] [--env CartPole-v1] [--updates 250] [--target 500]
"""

from __future__ import annotations

import argparse
import collections
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_rl.algos.registry import get_algo
from tpu_rl.config import Config, is_off_policy
from tpu_rl.runtime.env import EnvAdapter, probe_spaces
from tpu_rl.types import BATCH_FIELDS, Batch, maybe_zero_carry


def act_params(state):
    """Acting parameter tree for either state flavor (SACState keeps the
    actor separate; TrainState nests it under "actor")."""
    if hasattr(state, "actor_params"):
        return {"actor": state.actor_params}
    return {"actor": state.params["actor"]}


def run(
    updates: int = 250,
    algo: str = "PPO",
    env_name: str = "CartPole-v1",
    seed: int = 0,
    batch_size: int = 32,
    log_every: int = 25,
    target: float | None = None,
    overrides: dict | None = None,
) -> dict:
    """Train and return a stats dict.

    ``target``: stop early once the 50-game mean episode reward reaches it
    (the reference's success criterion is expressed this way — CartPole-v1
    return 500 = the ``time_horizon`` cap, ``/root/reference/utils/
    parameters.json:2,11``; its tensorboard scalar is the 50-game mean,
    ``agents/manager.py:62-79``).
    """
    cfg_dict = dict(
        algo=algo,
        env=env_name,
        batch_size=batch_size,
        seq_len=5,
        lr=3e-4,
        entropy_coef=0.001,
        reward_scale=0.1,
        time_horizon=500,
    )
    overrides = dict(overrides or {})
    # Two-phase schedule: {"coef": final_entropy, "lr": final_lr, "frac": 0.5}
    # switches the entropy bonus (and optionally the learning rate) after
    # ``frac`` of the update budget — high early exploration, then a
    # near-deterministic low-variance tail so capped-return targets
    # (CartPole 500 = every step of every episode) are reachable without the
    # late policy collapse a hot lr + cold entropy invites. One extra jit
    # compile at the boundary; the optimizer state carries over (the
    # on-policy families use rmsprop, whose accumulator is lr-independent).
    anneal = overrides.pop("entropy_anneal", None)
    # Random-action warmup (off-policy exploration aid): for the first N env
    # steps act from a scripted random policy instead of the learned one.
    # Continuous envs use STICKY bang-bang actions (a held +/-1 that flips
    # sign with small probability, plus jitter): on MountainCarContinuous,
    # iid uniform actions average to no net force and measured 0/20 episodes
    # ever reach the goal, while sticky bang-bang pumps the resonant swing
    # and reaches it 20/20 — the replay buffer actually gets goal rewards.
    # Discrete envs keep iid uniform. SAC recomputes log-probs from the
    # current policy (off-policy), so behavior actions need no importance
    # correction.
    warmup_steps = int(overrides.pop("warmup_steps", 0))
    warmup_flip_p = float(overrides.pop("warmup_flip_p", 0.1))
    cfg_dict.update(overrides)
    cfg = probe_spaces(Config.from_dict(cfg_dict))
    off_policy = is_off_policy(cfg.algo)
    if warmup_steps and not off_policy:
        # On-policy algos (PPO/IMPALA/V-MPO) compute importance ratios from
        # the stored behavior log-probs; warmup actions are NOT drawn from the
        # policy, so those ratios would silently be garbage.
        raise ValueError(
            "warmup_steps requires an off-policy algorithm (SAC/SAC-Continuous)"
        )
    spec = get_algo(cfg.algo)
    family, state, train_step = spec.build(cfg, jax.random.key(seed))
    train_step = jax.jit(train_step)
    switch_at = None
    if anneal:
        # "at" absolute / "frac" relative — same contract as the cluster
        # learner (Config.entropy_anneal); inline runs have no resume, so
        # absolute and relative coincide here.
        switch_at = max(
            1,
            int(anneal["at"]) if "at" in anneal
            else int(anneal["frac"] * updates),
        )
    act = jax.jit(family.act)

    env = EnvAdapter(cfg, seed=seed)
    key = jax.random.key(seed + 1)
    obs = env.reset()
    hw, cw = family.carry_widths
    h = jnp.zeros((1, hw))
    c = jnp.zeros((1, cw))
    is_fir = 1.0
    epi_rew, epi_steps = 0.0, 0
    rewards = collections.deque(maxlen=50)
    best_epi_rew = -float("inf")  # exploration probe: did ANY episode succeed?
    rng = np.random.default_rng(seed)

    warm_sign = float(rng.choice([-1.0, 1.0]))  # sticky bang-bang warmup state
    seq: list[dict] = []
    ready: list[dict] = []
    # Off-policy replay of sequence windows (capacity in windows, matching the
    # reference's trajectory-count capacity, ``utils/parameters.json:26``).
    replay: collections.deque = collections.deque(maxlen=cfg.buffer_size)
    env_steps = 0
    update = 0
    time_to_target = None
    hit = False
    t0 = time.time()

    def mean50() -> float:
        return float(np.mean(rewards)) if rewards else float("nan")

    while update < updates and not hit:
        # ---- collect: one fresh window per update (off-policy) or a full
        # batch of windows (on-policy).
        need = 1 if (off_policy and len(replay) >= cfg.batch_size) else cfg.batch_size
        while len(ready) < need:
            key, sub = jax.random.split(key)
            ob = jnp.asarray(obs, jnp.float32)[None]
            a, logits, log_prob, h2, c2 = act(act_params(state), ob, h, c, sub)
            if env_steps < warmup_steps:
                # keep the policy carry (h2, c2) consistent with what the
                # policy *saw*, but override the executed/stored action.
                # The stored log_prob/logits then describe the POLICY'S
                # sampled action, not the executed one — poison them with NaN
                # so any future consumer fails loudly instead of silently
                # importance-weighting with garbage (warmup is gated to SAC,
                # which recomputes log-probs from the current policy and
                # never reads these fields).
                log_prob = jnp.full_like(log_prob, jnp.nan)
                logits = jnp.full_like(logits, jnp.nan)
                if family.continuous:
                    if rng.random() < warmup_flip_p:
                        warm_sign = -warm_sign
                    a = jnp.asarray(
                        np.clip(
                            warm_sign + 0.25 * rng.normal(size=a.shape),
                            -1.0, 1.0,
                        ),
                        jnp.float32,
                    )
                else:
                    a = jnp.asarray(
                        rng.integers(0, cfg.action_space, size=a.shape),
                        a.dtype,
                    )
            next_obs, rew, done = env.step(np.asarray(a[0]))
            epi_rew += rew
            epi_steps += 1
            env_steps += 1
            seq.append(
                dict(
                    obs=np.asarray(ob[0]),
                    act=np.asarray(a[0]),
                    rew=np.array([rew * cfg.reward_scale], np.float32),
                    logits=np.asarray(logits[0]),
                    log_prob=np.asarray(log_prob[0]),
                    is_fir=np.array([is_fir], np.float32),
                    hx=np.asarray(h[0]),
                    cx=np.asarray(c[0]),
                )
            )
            if len(seq) == cfg.seq_len:
                ready.append(
                    {k: np.stack([s[k] for s in seq]) for k in BATCH_FIELDS}
                )
                seq = []
            is_fir = 0.0
            obs, h, c = next_obs, h2, c2
            if done or epi_steps >= cfg.time_horizon:
                rewards.append(epi_rew)
                best_epi_rew = max(best_epi_rew, epi_rew)
                if (
                    target is not None
                    and len(rewards) == rewards.maxlen
                    and mean50() >= target
                ):
                    time_to_target = time.time() - t0
                    hit = True
                obs = env.reset()
                h = jnp.zeros_like(h)
                c = jnp.zeros_like(c)
                is_fir, epi_rew, epi_steps = 1.0, 0.0, 0

        # ---- train
        if off_policy:
            replay.extend(ready)
            ready = []
            if len(replay) < cfg.batch_size:
                continue
            idx = rng.integers(0, len(replay), size=cfg.batch_size)
            picked = [replay[int(i)] for i in idx]
        else:
            picked, ready = ready, []
        batch = Batch.from_mapping(
            maybe_zero_carry(
                cfg, {k: np.stack([t[k] for t in picked]) for k in BATCH_FIELDS}
            )
        )
        key, sub = jax.random.split(key)
        state, metrics = train_step(state, batch, sub)
        update += 1
        if switch_at is not None and update == switch_at:
            cfg = cfg.replace(
                entropy_coef=float(anneal.get("coef", cfg.entropy_coef)),
                lr=float(anneal.get("lr", cfg.lr)),
                std_floor=float(anneal.get("std_floor", cfg.std_floor)),
                # SAC: release (or move) the temperature floor — hot phase
                # guarantees exploration while the critic consolidates, cold
                # phase lets the controller converge the policy.
                alpha_min=float(anneal.get("alpha_min", cfg.alpha_min)),
            )
            if "std_floor" in anneal:
                # std_floor is a static module attribute, not a parameter:
                # rebuild the family (params carry over unchanged) so acting
                # and training both use the new floored distribution.
                from tpu_rl.models.families import build_family

                family = build_family(cfg)
                act = jax.jit(family.act)
            train_step = jax.jit(spec.make_train_step(cfg, family))
            print(
                f"update {update}: entropy_coef -> {cfg.entropy_coef}, "
                f"lr -> {cfg.lr}, std_floor -> {cfg.std_floor}, "
                f"alpha_min -> {cfg.alpha_min}",
                flush=True,
            )
        if update % log_every == 0:
            # SAC runs surface the temperature and critic loss: the two
            # scalars that localize a rise-then-collapse (alpha undershoot
            # vs critic divergence).
            extra = ""
            if "alpha" in metrics:
                extra = (
                    f"  alpha {float(metrics['alpha']):.4f}"
                    f"  q-loss {float(metrics['value-loss']):+.4f}"
                )
            print(
                f"update {update:5d}  loss {float(metrics['loss']):+.4f}  "
                f"mean-epi-rew {mean50():8.2f}  "
                f"best {best_epi_rew:8.2f}  env-steps {env_steps:7d}  "
                f"elapsed {time.time()-t0:6.1f}s{extra}",
                flush=True,
            )
    wallclock = time.time() - t0

    # Greedy evaluation (discrete policies): act by argmax instead of
    # sampling. Training mean-50 is measured under the stochastic behavior
    # policy, whose residual exploration caps it below the CartPole 500
    # ceiling; the greedy policy is what "reaches return 500" (the reference's
    # implicit success criterion = its time_horizon cap) actually means at
    # deployment. The LSTM/transformer carry depends only on observations,
    # so the same jitted act drives both.
    eval_mean = None
    greedy_act = (
        jax.jit(family.act_greedy) if family.act_greedy is not None else None
    )
    if not family.continuous or greedy_act is not None:
        returns = []
        for ep in range(20):
            obs = env.reset()
            h = jnp.zeros((1, hw))
            c = jnp.zeros((1, cw))
            total, steps, done = 0.0, 0, False
            while not done and steps < cfg.time_horizon:
                ob = jnp.asarray(obs, jnp.float32)[None]
                if family.continuous:
                    a, h, c = greedy_act(act_params(state), ob, h, c)
                    greedy = np.asarray(a[0])
                else:
                    _a, logits, _lp, h, c = act(
                        act_params(state), ob, h, c,
                        jax.random.key(ep * 1000 + steps),
                    )
                    greedy = np.asarray(
                        [float(np.argmax(np.asarray(logits[0])))]
                    )
                obs, rew, done = env.step(greedy)
                total += rew
                steps += 1
            returns.append(total)
        eval_mean = float(np.mean(returns))
    env.close()
    return {
        "algo": cfg.algo,
        "env": cfg.env,
        "final_mean_50": mean50(),
        "best_epi_rew": (
            round(best_epi_rew, 1) if np.isfinite(best_epi_rew) else None
        ),
        "target": target,
        "reached_target": hit,
        "time_to_target_s": (
            round(time_to_target, 1) if time_to_target is not None else None
        ),
        "greedy_eval_mean_20": eval_mean,
        "updates": update,
        "env_steps": env_steps,
        "wallclock_s": round(wallclock, 1),
        "env_steps_per_s": round(env_steps / max(wallclock, 1e-9), 1),
        "seed": seed,
    }


def main(
    updates: int = 250,
    algo: str = "PPO",
    env_name: str = "CartPole-v1",
    seed: int = 0,
    batch_size: int = 32,
    log_every: int = 25,
) -> float:
    """Back-compat wrapper: returns the final 50-game mean episode reward."""
    stats = run(updates, algo, env_name, seed, batch_size, log_every)
    mean = stats["final_mean_50"]
    return mean if np.isfinite(mean) else 0.0


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--algo", default="PPO")
    p.add_argument("--env", default="CartPole-v1")
    p.add_argument("--updates", type=int, default=250)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--target", type=float, default=None)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args()
    stats = run(
        args.updates, args.algo, args.env, args.seed,
        batch_size=args.batch_size, target=args.target,
    )
    import json

    print(json.dumps(stats))
