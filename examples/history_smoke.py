"""History smoke: boot the smallest real cluster with the run-history
plane on (and a chaos worker kill mid-run), then drive every read surface
the plane ships end-to-end:

- the store exists under ``result_dir/history`` and rotated chunks on the
  configured cadence;
- the ``/query`` contract (the exact ``HistoryReader.http_query`` code
  the HTTP server serves) lists series, returns raw points showing run
  progress, and downsamples with ``step``;
- the chaos kill is audited to ``chaos.jsonl`` inside the history span,
  and ``python -m tpu_rl.obs.report`` renders it as an event overlay in
  all three artifacts;
- ``python -m tpu_rl.obs.compare`` run-vs-itself is green (exit 0), a
  candidate doctored to DROP a recorded channel is red (exit 1 — no-data
  gates, never silent-passes), and a candidate doctored 20x slower on
  detectable throughput channels is flagged as regressed.

Exits nonzero on any failure — this is the ``make history-smoke`` CI gate.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/history_smoke.py \
      [--updates 8] [--base-port 28600]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rewrite_history(src: str, dst: str, transform) -> None:
    """Copy a history dir chunk-by-chunk, mapping every row's sample dict
    through ``transform`` (in place). series.json is copied verbatim —
    an index entry whose points vanished is exactly the no-data shape
    the compare gate must catch."""
    os.makedirs(dst, exist_ok=True)
    for fname in os.listdir(src):
        s = os.path.join(src, fname)
        if not fname.endswith(".jsonl"):
            shutil.copy(s, os.path.join(dst, fname))
            continue
        with open(s) as f, open(os.path.join(dst, fname), "w") as out:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                transform(row.get("s") or {})
                out.write(json.dumps(row, separators=(",", ":")) + "\n")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=8)
    p.add_argument("--base-port", type=int, default=28600)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args()

    from tpu_rl.config import MachinesConfig, WorkerMachine
    from tpu_rl.obs import compare, report
    from tpu_rl.obs.history import HistoryReader
    from tpu_rl.runtime.runner import local_cluster
    from tests.conftest import small_config  # the CI-sized Config recipe

    run_dir = tempfile.mkdtemp(prefix="history_smoke_")
    cfg = small_config(
        env="CartPole-v1",
        algo="PPO",
        worker_step_sleep=0.0,
        learner_device="cpu",
        rollout_lag_sec=30.0,
        time_horizon=100,
        loss_log_interval=2,
        result_dir=run_dir,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,
        supervise_poll_s=0.5,
        history_chunk_s=5.0,
        history_retention_s=600.0,
        # fires once the fleet is warm — late enough that storage (slow
        # jax import) has opened its first history chunk on most boxes
        chaos_spec="kill:worker-0-1@t+12s",
        chaos_seed=7,
    )
    machines = MachinesConfig(
        learner_ip="127.0.0.1",
        learner_port=args.base_port,
        workers=[WorkerMachine(
            num_p=2, manager_ip="127.0.0.1", ip="127.0.0.1",
            port=args.base_port + 5,
        )],
    )
    print(f"[history-smoke] cluster up; run_dir={run_dir}", flush=True)
    sup = local_cluster(cfg, machines, max_updates=args.updates)
    failures: list[str] = []
    loop_thread = threading.Thread(target=sup.loop, daemon=True)
    loop_thread.start()
    try:
        if not sup.stop_event.wait(args.timeout):
            failures.append(
                f"fleet did not complete within {args.timeout:.0f}s"
            )
        loop_thread.join(10.0)
    finally:
        sup.stop()

    # ------------------------------------------------------- store + /query
    hdir = os.path.join(run_dir, "history")
    reader = HistoryReader(hdir)
    if not reader.exists():
        failures.append(f"no history store materialized under {hdir}")
        for f in failures:
            print(f"[history-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    n_chunks = len(reader._chunks())
    print(f"[history-smoke] history chunks: {n_chunks}", flush=True)
    if n_chunks < 1:
        failures.append("history dir exists but holds no chunks")

    status, listing = reader.http_query({})
    series = [row["name"] for row in listing.get("series", ())]
    if status != 200 or not series:
        failures.append(f"/query series listing empty (status {status})")
    ch = "learner/learner-update-index"
    if ch not in series:
        failures.append(f"{ch} missing from /query series listing")
    status, doc = reader.http_query({"metric": ch})
    pts = doc.get("points") or []
    if status != 200 or len(pts) < 2:
        failures.append(f"/query {ch}: {len(pts)} points, expected >= 2")
    else:
        values = [v for _t, v in pts]
        if not (min(values) < max(values) and max(values) >= args.updates):
            failures.append(
                f"/query {ch} shows no run progress: {values[:8]}..."
            )
        else:
            print(
                f"[history-smoke] /query {ch}: {len(pts)} points, "
                f"last={values[-1]:.0f}", flush=True,
            )
    status, down = reader.http_query({"metric": ch, "step": "2"})
    if status != 200 or not down.get("buckets"):
        failures.append("/query step=2 downsampling returned no buckets")

    # ------------------------------------------------- chaos event + report
    chaos_path = os.path.join(run_dir, "chaos.jsonl")
    try:
        chaos_events = [
            json.loads(ln) for ln in open(chaos_path).read().splitlines()
        ]
    except OSError:
        chaos_events = []
    span = reader.span()
    if not chaos_events:
        failures.append("chaos.jsonl empty — the kill was never audited")
    elif span is None or not (
        # The supervisor's clock starts before storage finishes its (slow)
        # boot, so the kill may precede the first recorded row by the boot
        # latency — but it must land within the run, never after it.
        span[0] - 60.0
        <= chaos_events[0]["t"]
        <= span[1] + cfg.history_chunk_s
    ):
        failures.append(
            f"chaos event t={chaos_events[0]['t']:.1f} outside history "
            f"span {span}"
        )
    else:
        print(
            "[history-smoke] chaos kill audited inside history span",
            flush=True,
        )

    rc = report.main([run_dir])
    if rc != 0:
        failures.append(f"report CLI exited {rc}")
    else:
        md = open(os.path.join(run_dir, "report.md")).read()
        html_text = open(os.path.join(run_dir, "report.html")).read()
        rep = json.loads(open(os.path.join(run_dir, "report.json")).read())
        if not any(ev["kind"] == "chaos" for ev in rep["events"]):
            failures.append("report.json events carry no chaos event")
        if "chaos" not in md or "chaos" not in html_text:
            failures.append("chaos event not rendered in report.md/html")
        if not rep["channels"]:
            failures.append("report charted zero channels")

    # -------------------------------------------------------------- compare
    rc = compare.main([run_dir, run_dir])
    if rc != 0:
        failures.append(f"self-compare exited {rc}, expected 0 (green)")

    # Doctored candidate 1: drop the recorded update-index channel
    # entirely. Missing data must gate — exit 1, never a silent pass.
    dropped = os.path.join(run_dir, "doctored_dropped")
    _rewrite_history(hdir, dropped, lambda s: s.pop(ch, None))
    rc = compare.main([run_dir, dropped])
    if rc != 1:
        failures.append(
            f"compare vs channel-dropped candidate exited {rc}, expected 1"
        )
    else:
        print("[history-smoke] dropped-channel candidate gated red", flush=True)

    # Doctored candidate 2: 20x slower on every direction-ful channel
    # whose baseline is stable enough for the MAD band to resolve a 95%
    # drop (a genuinely noisy micro-run channel widening its own band is
    # the tool working as specified, not a miss).
    detectable = []
    for name in series:
        if compare.direction(name) != "up":
            continue
        vals = compare.trim_warmup(reader.points(name))
        if len(vals) < compare.MIN_SAMPLES:
            continue
        med, sigma = compare.robust_stats(vals)
        band = max(compare.MAD_K * sigma, compare.REL_TOL * abs(med))
        if med > 0 and band < 0.9 * med:
            detectable.append(name)
    if detectable:
        slow = os.path.join(run_dir, "doctored_slow")

        def _slowdown(s):
            for name in detectable:
                if name in s:
                    s[name] = s[name] * 0.05

        _rewrite_history(hdir, slow, _slowdown)
        doc = compare.compare_runs(hdir, slow)
        regressed = [
            r["channel"] for r in doc["rows"] if r["verdict"] == "regressed"
        ]
        if doc["ok"] or not regressed:
            failures.append(
                f"slow candidate not flagged: detectable={detectable} "
                f"counts={doc['counts']}"
            )
        else:
            print(
                f"[history-smoke] slow candidate regressed on "
                f"{len(regressed)}/{len(detectable)} channels", flush=True,
            )
    else:
        print(
            "[history-smoke] no band-resolvable throughput channel this "
            "run; slow-doctor check skipped (dropped-channel gate above "
            "still pins red)", flush=True,
        )

    if failures:
        for f in failures:
            print(f"[history-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print("[history-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
