"""Colocated-mode smoke: the fused on-device loop learns, and the A/B bench
row emits — the `make ci` gate for ISSUE 7 (Anakin-mode colocated envs).

Two checks, both on the CPU backend:

1. LEARNING: a short colocated PPO run on jittable CartPole (the
   ``train_inline`` recipe: lr 3e-4, entropy 1e-3, reward_scale 0.1) must
   lift the completed-episode mean return well above the random-policy
   baseline (~22) within a small update budget. This exercises the whole
   fused path end to end: act -> on-device env step -> window assembly ->
   train_step under one jit, auto-reset, carry zeroing, on-device episode
   stats.
2. BENCH ROW: ``bench.run_colocated_compare`` in light mode (short windows,
   no result file) must emit the colocated-vs-distributed row with the
   expected schema and a direction-consistent speedup (the light mode
   hard-asserts colocated >= distributed internally).

Usage:
    JAX_PLATFORMS=cpu PYTHONPATH=. python examples/colocated_smoke.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RETURN_THRESHOLD = 60.0  # best-window mean; random ~22, seed-0 run peaks >130


def check_learning(updates: int, threshold: float, failures: list[str]) -> None:
    from tpu_rl.config import Config
    from tpu_rl.runtime.colocated import ColocatedLoop

    cfg = Config(
        env="CartPole-v1", env_mode="colocated", algo="PPO",
        batch_size=32, buffer_size=32, seq_len=5,
        lr=3e-4, entropy_coef=0.001, reward_scale=0.1,
        time_horizon=500, loss_log_interval=200,
    )
    t0 = time.time()
    loop = ColocatedLoop(cfg, seed=0, max_updates=updates)
    out = loop.run(log=False)
    print(
        f"[colocated-smoke] learning: {out['updates']} updates, "
        f"{out['episodes']} episodes, best-window mean return "
        f"{out['mean_return_best_window']:.1f} "
        f"(threshold {threshold}), {time.time() - t0:.1f}s",
        flush=True,
    )
    if out["mean_return_best_window"] < threshold:
        failures.append(
            f"no learning: best-window mean return "
            f"{out['mean_return_best_window']:.1f} < {threshold}"
        )
    if out["episodes"] < 100:
        failures.append(f"too few episodes completed: {out['episodes']}")


def check_bench_row(failures: list[str]) -> None:
    os.environ["TPU_RL_BENCH_COLOCATED_LIGHT"] = "1"
    from bench import run_colocated_compare

    try:
        result = run_colocated_compare()
    except AssertionError as e:
        failures.append(f"bench direction assert failed: {e}")
        return
    print(
        "[colocated-smoke] bench row: "
        + json.dumps({k: result[k] for k in (
            "speedup", "colocated_tps", "distributed_tps_steady")}),
        flush=True,
    )
    for key in (
        "metric", "device_kind", "speedup", "colocated_tps",
        "colocated_tps_best", "distributed_tps_steady", "rows",
    ):
        if key not in result:
            failures.append(f"bench row missing key: {key}")
    rows = result.get("rows", {})
    if not rows.get("colocated") or "colocated_tps" not in rows["colocated"][0]:
        failures.append(f"malformed colocated rows: {rows.get('colocated')}")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--updates", type=int, default=1800,
                   help="learning-check update budget (default 1800)")
    p.add_argument("--threshold", type=float, default=RETURN_THRESHOLD,
                   help="best-window mean-return bar (default 60)")
    p.add_argument("--skip-bench", action="store_true",
                   help="learning check only")
    args = p.parse_args()

    failures: list[str] = []
    check_learning(args.updates, args.threshold, failures)
    if not args.skip_bench:
        check_bench_row(failures)

    if failures:
        for f in failures:
            print(f"[colocated-smoke] FAIL: {f}", flush=True)
        return 1
    print("[colocated-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
