"""Rollout fan-in A/B: zero-copy raw relay vs decode baseline.

The harness lives in ``bench.run_relay_compare`` (shared with the
``TPU_RL_BENCH_RELAY=1 python bench.py`` mode); this wrapper adds the CLI.
Both legs of the ISSUE-3 A/B run per mode:

- relay: a producer PUB floods pre-encoded 32-env RolloutBatch frames at a
  REAL Manager over real ZMQ; a sink SUB (bound where storage binds) counts
  forwarded frames/s. Raw mode peeks the header and forwards the wire parts
  verbatim; decode mode pays the full decode + re-encode per frame.
- ingest: the REAL LearnerStorage path, no sockets — columnar
  ``push_tick`` + ``put_many`` (raw) vs ``split_rollout_batch`` + per-step
  ``push`` + per-window ``put`` (decode), in env-steps/s.

ISSUE-8 rows ride along: an shm-transport relay leg (same Manager, the
storage hop over shared-memory rings), an isolated manager→storage hop A/B
(tcp vs shm, no manager in the loop), and a native-vs-python frame
validation micro A/B at peek and CRC grade.

Host-side benchmark (manager and storage never touch the accelerator):
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/bench_relay.py \
      [--duration 4.0] [--ticks 3000] [--envs 32] [--port 29940] \
      [--out bench_relay.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--duration", type=float, default=None,
                   help="timed relay window per mode, seconds (default 4)")
    p.add_argument("--ticks", type=int, default=None,
                   help="timed ingest ticks per mode (default 3000)")
    p.add_argument("--envs", type=int, default=32,
                   help="envs per tick frame (default 32, the reference "
                        "tick shape the acceptance bar is specified at)")
    p.add_argument("--port", type=int, default=29940)
    p.add_argument("--out", default=None,
                   help="result JSON path (default bench_relay[.cpu].json)")
    args = p.parse_args()

    from bench import run_relay_compare

    result = run_relay_compare(
        duration=args.duration,
        ingest_ticks=args.ticks,
        n_envs=args.envs,
        base_port=args.port,
        out_path=args.out,
    )
    print(json.dumps(result, indent=1))


if __name__ == "__main__":
    main()
