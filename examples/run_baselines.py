"""Run the five BASELINE.json benchmark configs end-to-end and record
learning results (final/curve returns + wallclock + env-steps), writing
``BASELINE_RESULTS.json`` rows.

Configs (BASELINE.md "Benchmark configs to reproduce"):
  1. PPO discrete        — CartPole-v1, target return 500
  2. IMPALA discrete     — CartPole-v1 (V-trace), target return 500
  3. PPO-Continuous      — MountainCarContinuous-v0, solved = 50-game mean >= 90
  4. SAC-Continuous      — MountainCarContinuous-v0 (off-policy replay path)
  5. V-MPO discrete      — CartPole-v1

Targets: CartPole-v1 return 500 is the reference's implicit success criterion
(= its ``time_horizon`` cap, ``/root/reference/utils/parameters.json:2,11``);
MountainCarContinuous "solved" is gymnasium's documented reward threshold 90
(the reference README's claim is "solved", ``/root/reference/README.md:20-21``).

Run (single config):
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/run_baselines.py \
      --only IMPALA --updates 6000
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.train_inline import run  # noqa: E402

# CartPole-v1 target: 475 is the environment's OFFICIAL reward_threshold
# (gymnasium registers CartPole-v1 with reward_threshold=475.0) measured on
# the stochastic behavior policy's 50-game mean; the 500 cap itself (the
# reference's implicit criterion) is demonstrated by the greedy evaluation
# train_inline.run performs after training (residual exploration entropy
# makes a SAMPLED 50-game mean of exactly 500 a measure-zero event).
CONFIGS: dict[str, dict] = {
    "PPO": dict(
        algo="PPO", env_name="CartPole-v1", target=475.0,
        # PPO reuses each batch K_epoch times behind its clipped surrogate
        # (the reference defaults to K_epoch=1, which wastes PPO's defining
        # sample-reuse property — V-trace already covers that regime via the
        # IMPALA config); hold the hot lr longer before the low-variance tail.
        overrides=dict(
            K_epoch=3,
            eps_clip=0.2,
            entropy_coef=0.001,
            entropy_anneal={"coef": 1e-4, "lr": 1.5e-4, "frac": 0.6},
        ),
    ),
    "IMPALA": dict(
        algo="IMPALA", env_name="CartPole-v1", target=475.0,
        overrides=dict(
            entropy_coef=0.001,
            entropy_anneal={"coef": 5e-5, "lr": 1e-4, "frac": 0.4},
        ),
    ),
    "V-MPO": dict(
        algo="V-MPO", env_name="CartPole-v1", target=475.0,
        # V-MPO is built for sample reuse under its KL Lagrange constraint:
        # with K_epoch=1 on fresh on-policy data the KL term is identically
        # zero (behavior == target at the only epoch) and the temperature
        # dual barely moves (measured: eta 5.0 -> 4.0 over 600 updates, so
        # the psi-weights stay near-uniform). K_epoch=4 activates the
        # constraint and lets eta anneal itself (5.0 -> 2.5 over the same
        # budget, no collapse) — no external entropy/lr schedule needed.
        overrides=dict(K_epoch=4, lr=3e-4),
    ),
    "PPO-Continuous": dict(
        algo="PPO-Continuous", env_name="MountainCarContinuous-v0",
        target=90.0,
        # Sparse-goal exploration env. An entropy bonus alone is not enough:
        # measured, entropy_coef=0.05 still collapsed into the do-nothing
        # local optimum (mean-50 -7.5, greedy -1.0 after 6k updates) — the
        # -0.1*a^2 action penalty pays the policy to shrink its std before
        # the goal is ever found. std_floor keeps the sampling distribution
        # wide (exactly on-policy: acting and training share the floored
        # std), gamma ~1 carries the +100 terminal reward through ~999-step
        # episodes, and the anneal drops the floor + entropy once the goal
        # is being exploited so the sampled mean-50 can clear 90.
        # action_repeat=8 is the decisive piece (measured): iid Gaussian
        # noise NEVER reaches the goal (0/20 episodes) because zero-mean
        # per-step forces cancel; the same noise held 8 steps pumps the
        # resonant swing (16/20). It also shrinks the decision horizon to
        # ~125 policy steps, so gamma 0.99 suffices and each 320-step batch
        # covers ~2.5 whole episodes.
        overrides=dict(
            action_repeat=8,
            std_floor=0.3,
            entropy_coef=0.005,
            gamma=0.99,
            batch_size=64,
            time_horizon=999,
            reward_scale=0.1,
            entropy_anneal={
                "coef": 1e-4, "lr": 1.5e-4, "std_floor": 0.05, "frac": 0.5,
            },
        ),
    ),
    "SAC": dict(
        algo="SAC", env_name="CartPole-v1", target=475.0,
        # Discrete SAC (the reference's sixth algorithm,
        # /root/reference/agents/learner_module/sac/learning.py:13-163, run
        # on CartPole per its README). The auto temperature rule
        # (0.98*log|A| = 0.679 of the 0.693 max) pins the policy near
        # maximum entropy — right for exploration-hard envs, fatal for a
        # capped-return env where 475/500 needs near-determinism (the same
        # measured effect as the cluster run's fixed entropy bonus:
        # entropy ~0.58 caps the mean near 50). A LOW explicit
        # target_entropy lets alpha anneal itself down as the critics
        # sharpen; iid-uniform warmup fills the replay with diverse states
        # first.
        overrides=dict(
            lr=3e-4, target_entropy=0.05, warmup_steps=2000,
            buffer_size=8192, reward_scale=0.1, time_horizon=500,
        ),
    ),
    "SAC-Continuous": dict(
        algo="SAC-Continuous", env_name="MountainCarContinuous-v0",
        target=90.0,
        # Sparse-goal exploration: the tanh-Gaussian's zero-mean noise
        # averages to no net force, so a pure-policy SAC never escapes the
        # valley (measured: mean-50 stuck near -33 after 10k updates), and
        # iid-uniform warmup is no better (measured 0/20 random episodes
        # reach the goal; recorded run ended at greedy -0.38). STICKY
        # bang-bang warmup (train_inline) pumps the resonant swing — 20/20
        # scripted episodes reach the goal — so the replay actually contains
        # goal (+100) rewards; gamma ~1 carries that signal back through the
        # ~999-step episodes.
        # buffer_size must hold the goal-rich warmup windows for the WHOLE
        # run: with 8192 windows (~41k steps) the warmup data was evicted
        # ~30k post-warmup steps in, and a seed that hadn't locked on by
        # then (seed 1) never recovered; 32768 windows (~164k steps) out-
        # lives the 150k-step budget.
        # Seed variance on the warmup-only recipe was measured EXHAUSTIVELY
        # in round 4 (five instrumented reruns with alpha in the log line):
        # without action_repeat the run is a RACE between policy-mean
        # consolidation and the decay of goal visits, and roughly half the
        # seeds lose it (alpha decays 0.117 -> 0.008 while the mean falls
        # 64.5 -> -33 in lockstep; alpha floors, floor release schedules, a
        # 10x slower temperature controller, and 5x warmup all failed
        # measurably — temperature-side knobs either can't re-reach the
        # goal once the mean migrates, or block the winning seeds'
        # convergence too).
        # action_repeat=8 — the SAME lever that is decisive for
        # PPO-Continuous above — dissolves the race: each exploration
        # decision (and its reparameterized noise) is HELD 8 env steps, so
        # post-warmup exploration pumps the resonant swing and can always
        # re-reach the goal (16/20 held vs 0/20 iid). The hardest seed
        # (2: 0/5 failed attempts under every temperature-side recipe)
        # solves in 52 s / ~2.1k updates; the decision horizon shrinks to
        # ~125 so gamma 0.99 suffices.
        overrides=dict(
            action_repeat=8, time_horizon=999, reward_scale=0.1, lr=3e-4,
            buffer_size=32768, gamma=0.99, warmup_steps=10_000,
        ),
    ),
}


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--only", default=None, help="run a single config by name")
    p.add_argument("--updates", type=int, default=6000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="BASELINE_RESULTS.json")
    args = p.parse_args()

    names = [args.only] if args.only else list(CONFIGS)
    rows = []
    for name in names:
        spec = CONFIGS[name]
        print(f"=== {name}: {spec['algo']} on {spec['env_name']} "
              f"(target {spec['target']}) ===", flush=True)
        stats = run(
            updates=args.updates,
            algo=spec["algo"],
            env_name=spec["env_name"],
            seed=args.seed,
            target=spec["target"],
            overrides=spec.get("overrides"),
        )
        rows.append(stats)
        print(json.dumps(stats), flush=True)

    # merge with any existing rows (one file accumulates the matrix)
    existing: list = []
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                existing = json.load(f)
        except Exception:
            existing = []
    by_key = {(r["algo"], r.get("seed", 0)): r for r in existing}
    for r in rows:
        by_key[(r["algo"], r.get("seed", 0))] = r
    merged = list(by_key.values())
    with open(args.out, "w") as f:
        json.dump(merged, f, indent=1)
    print(f"wrote {args.out}", flush=True)
    # companion markdown table (committed alongside the JSON)
    md = [
        "| algo | env | seed | target | reached | time-to-target (s) | "
        "50-game mean | greedy eval | updates | env steps | steps/s |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in sorted(merged, key=lambda r: (r["algo"], r.get("seed", 0))):
        md.append(
            "| {algo} | {env} | {seed} | {target} | {reached_target} | "
            "{time_to_target_s} | {final_mean_50:.1f} | {ge} | {updates} | "
            "{env_steps} | {env_steps_per_s} |".format(
                ge=(
                    f"{r['greedy_eval_mean_20']:.1f}"
                    if r.get("greedy_eval_mean_20") is not None
                    else "—"
                ),
                seed=r.get("seed", 0),  # legacy rows predate the seed field
                **{k: v for k, v in r.items() if k != "seed"},
            )
        )
    with open(os.path.splitext(args.out)[0] + ".md", "w") as f:
        f.write("\n".join(md) + "\n")


if __name__ == "__main__":
    main()
