"""Resume smoke: SIGKILL the learner AND the storage mid-run and assert the
fleet survives with its full run state intact — the ``make resume-smoke``
CI gate for the durability plane (checkpoint atomicity, full-run resume,
run-epoch fencing, membership).

Sequence (driven from this harness so the kills land deterministically
relative to checkpoint progress, unlike a wall-clock chaos spec):

1. boot the smallest real cluster with a TORN checkpoint fixture planted in
   the model dir (an orbax-shaped dir with no COMMITTED marker — a crash
   mid-save) and probabilistic rollout corruption from the chaos plane;
2. wait for the first COMMITTED checkpoint, then SIGKILL storage and the
   learner back-to-back;
3. assert the supervisor respawned both, the learner resumed from the
   newest committed index at a bumped run epoch (``learner_resume.jsonl``),
   and the run completed cleanly with the final update index past the
   resume point (monotonic resume, never a restart from 0);
4. assert the respawned storage fenced stale-epoch frames from the
   pre-crash incarnation (counted, separate from corruption rejects), that
   every worker re-registered in the membership table, and that chaos
   fault accounting still balances exactly (injected == rejected);
5. assert the torn fixture was never restored and is swept from disk.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/resume_smoke.py \
      [--updates 24] [--base-port 28700]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TORN_IDX = 999_999  # planted torn dir: newer than any real index


def _counter(source: dict, name: str) -> float:
    return sum(
        v for n, _labels, v in source.get("counters", ()) if n == name
    )


def _role_total(tele: dict, role: str, name: str) -> float:
    return sum(
        _counter(s, name) for s in tele["sources"] if s.get("role") == role
    )


def _gauge_max(tele: dict, role: str, name: str) -> float:
    vals = [
        v
        for s in tele["sources"]
        if s.get("role") == role
        for n, _labels, v in s.get("gauges", ())
        if n == name
    ]
    return max(vals) if vals else float("-inf")


def _child(sup, name: str):
    return next(c for c in sup.children if c.name == name)


def _wait(pred, timeout: float, poll: float = 0.2) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return pred()


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=24)
    p.add_argument("--base-port", type=int, default=28700)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args()

    from tests.conftest import small_config  # the CI-sized Config recipe
    from tpu_rl.checkpoint import latest_committed
    from tpu_rl.config import MachinesConfig, WorkerMachine
    from tpu_rl.runtime.runner import local_cluster

    run_dir = tempfile.mkdtemp(prefix="resume_smoke_")
    model_dir = os.path.join(run_dir, "models")
    # Torn-save fixture: an uncommitted dir with a HIGHER index than the run
    # will ever reach. If the marker protocol leaks anywhere, the worker
    # warm-start or the learner resume would pick it and crash/corrupt.
    torn = os.path.join(model_dir, f"PPO_{TORN_IDX}")
    os.makedirs(torn)
    with open(os.path.join(torn, "checkpoint"), "w") as f:
        f.write("torn mid-write by a previous incarnation")

    cfg = small_config(
        env="CartPole-v1",
        algo="PPO",
        # Pace rollout generation so the run is data-bound: the kills land
        # mid-run with headroom instead of racing a millisecond-fast loop.
        worker_step_sleep=0.005,
        learner_device="cpu",
        rollout_lag_sec=30.0,
        time_horizon=100,
        loss_log_interval=4,
        result_dir=run_dir,
        model_dir=model_dir,
        model_save_interval=2,
        ckpt_keep=3,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,
        supervise_poll_s=0.25,
        chaos_spec="corrupt:rollout@p=0.03",
        chaos_seed=11,
    )
    machines = MachinesConfig(
        learner_ip="127.0.0.1",
        learner_port=args.base_port,
        workers=[WorkerMachine(
            num_p=2, manager_ip="127.0.0.1", ip="127.0.0.1",
            port=args.base_port + 5,
        )],
    )
    print(f"[resume-smoke] cluster up; run_dir={run_dir}", flush=True)
    sup = local_cluster(cfg, machines, max_updates=args.updates)
    failures: list[str] = []
    resume_path = os.path.join(run_dir, "learner_resume.jsonl")
    loop_thread = threading.Thread(target=sup.loop, daemon=True)
    loop_thread.start()
    try:
        # ---- phase 1: first committed checkpoint, then the double kill ----
        if not _wait(
            lambda: latest_committed(model_dir, "PPO") is not None,
            args.timeout * 0.6,
        ):
            failures.append("no committed checkpoint appeared before kill")
        elif sup.stop_event.is_set():
            failures.append("fleet finished before the mid-run kill landed")
        else:
            committed_idx = latest_committed(model_dir, "PPO")[0]
            print(
                f"[resume-smoke] first commit at idx {committed_idx}; "
                "SIGKILL storage + learner", flush=True,
            )
            # Storage first, learner immediately after: both die inside one
            # supervision window, so the respawned storage (fence restored
            # from the cross-respawn mailbox) is live while the workers are
            # still acting on the pre-crash epoch — the stale frames it
            # fences are the ones this smoke asserts on.
            for name in ("storage", "learner"):
                os.kill(_child(sup, name).proc.pid, signal.SIGKILL)
            if not _wait(
                lambda: _child(sup, "storage").restarts >= 1
                and _child(sup, "learner").restarts >= 1,
                60.0,
            ):
                failures.append("supervisor did not respawn both children")
            if not _wait(lambda: os.path.exists(resume_path), 120.0):
                failures.append(
                    "respawned learner wrote no resume record "
                    "(learner_resume.jsonl missing)"
                )
        # ---- phase 2: the resumed run completes ----
        if not sup.stop_event.wait(args.timeout):
            failures.append(f"fleet did not complete within {args.timeout:.0f}s")
        loop_thread.join(10.0)
        learner = _child(sup, "learner")
        learner.proc.join(30.0)
        if learner.proc.is_alive() or learner.proc.exitcode != 0:
            failures.append(
                f"resumed learner did not exit cleanly "
                f"(alive={learner.proc.is_alive()}, "
                f"exitcode={learner.proc.exitcode})"
            )
    finally:
        sup.stop()

    # ---- resume audit: monotonic continuation, epoch bump ----
    resumed_idx = resumed_epoch = None
    try:
        with open(resume_path) as f:
            rec = [json.loads(line) for line in f if line.strip()][-1]
        resumed_idx, resumed_epoch = int(rec["idx"]), int(rec["epoch"])
    except (OSError, ValueError, IndexError, KeyError) as e:
        failures.append(f"resume record unreadable: {type(e).__name__}: {e}")
    if resumed_idx is not None:
        if resumed_idx < 1 or resumed_idx >= TORN_IDX:
            failures.append(
                f"resumed from idx {resumed_idx} — expected a real committed "
                f"index (>= 1, never the torn fixture {TORN_IDX})"
            )
        if resumed_epoch is None or resumed_epoch < 1:
            failures.append(
                f"resume did not bump the run epoch (epoch={resumed_epoch})"
            )
        print(
            f"[resume-smoke] resumed at idx {resumed_idx}, "
            f"run epoch {resumed_epoch}", flush=True,
        )
    if os.path.isdir(torn):
        failures.append("torn checkpoint fixture survived the learner sweep")

    tele_path = os.path.join(run_dir, "telemetry.json")
    try:
        tele = json.loads(open(tele_path).read())
    except (OSError, ValueError) as e:
        failures.append(f"telemetry.json invalid: {type(e).__name__}: {e}")
        tele = {"sources": []}

    final_idx = _gauge_max(tele, "learner", "learner-update-index")
    if resumed_idx is not None and final_idx <= resumed_idx:
        failures.append(
            f"update index did not advance past the resume point "
            f"({final_idx} <= {resumed_idx}) — the run restarted, not resumed"
        )
    epoch_seen = _gauge_max(tele, "learner", "learner-run-epoch")
    if epoch_seen < 1:
        failures.append(
            f"learner-run-epoch={epoch_seen} in telemetry, expected >= 1"
        )
    stale = _role_total(tele, "storage", "storage-stale-epoch-frames")
    if stale < 1:
        failures.append(
            "storage fenced zero stale-epoch frames — the pre-crash "
            "incarnation's rollouts were admitted into the resumed run"
        )
    else:
        print(f"[resume-smoke] stale frames fenced: {stale:.0f}", flush=True)
    joined = _role_total(tele, "storage", "storage-members-joined")
    if joined < 2:
        failures.append(
            f"storage-members-joined={joined:.0f} after respawn, expected "
            "both workers to re-register"
        )
    pushes = _role_total(tele, "learner", "learner-join-pushes")
    if pushes < 1:
        failures.append(
            f"learner-join-pushes={pushes:.0f}, expected >= 1 (the join "
            "flag never reached the learner)"
        )
    # Fault accounting parity must survive the respawns: the corrupting shim
    # and the CRC reject both live in the storage process, so they reset
    # together and the fleet-wide totals still balance exactly. Stale-epoch
    # drops are counted separately and must NOT leak into this ledger.
    corrupted = _role_total(tele, "storage", "chaos-corrupted-frames")
    rejected = sum(
        _role_total(tele, role, f"{role}-rejected-frames")
        for role in ("worker", "manager", "storage")
    )
    if corrupted != rejected:
        failures.append(
            f"fault accounting mismatch across respawn: injected "
            f"{corrupted:.0f} corruptions but the fleet rejected "
            f"{rejected:.0f} frames"
        )
    else:
        print(
            f"[resume-smoke] fault accounting: {corrupted:.0f} injected == "
            f"{rejected:.0f} rejected", flush=True,
        )

    if failures:
        for f in failures:
            print(f"[resume-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print("[resume-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
