"""Multi-leg cluster-replication driver.

Runs ``run_cluster_learning.py`` legs back-to-back with the phase schedule
that solved seed 0 (CLUSTER_SOLVED.md): one fresh hot->cold leg, then
alternating cold (lr 1e-4 — the phase that produces 400+ breakout cycles)
and cool (lr 3e-5, entropy 2e-5 — the phase that consolidates a breakout
into a monotone climb) resume legs, all sharing one models dir, until the
fleet 50-game mean reaches the target or the wallclock budget runs out.
The round-4 seed-1 attempt established that the cool phase alone cannot
break out of the 140-250 band (CLUSTER_SOLVED.md "Seed-1 replication") —
the alternation is the recipe, automated here so a full replication needs
no operator in the loop.

Each leg's JSON result line (printed by run_cluster_learning) is parsed
for ``solved``; per-leg records land in ``<dir>/leg<i>.md`` +
``<dir>/chain.jsonl``. Reference topology being exercised:
``/root/reference/main.py:301-414``; success criterion
``/root/reference/README.md:18-21``.

Usage (background, one shared CPU core — keep the host quiet):
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo nohup python \
      examples/run_cluster_seed_chain.py --seed 1 --budget-hours 8 &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def run_leg(script: str, leg_args: list[str], out_path: str) -> dict | None:
    """Run one leg; return its parsed JSON result line (None if missing)."""
    cmd = [sys.executable, script] + leg_args + ["--out", out_path]
    print(f"[chain] leg: {' '.join(cmd)}", flush=True)
    proc = subprocess.run(cmd, capture_output=True, text=True)
    result = None
    for line in (proc.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                pass
    if proc.returncode != 0:
        tail = (proc.stderr or "")[-2000:]
        print(f"[chain] leg rc={proc.returncode}\n{tail}", flush=True)
    return result


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--target", type=float, default=475.0)
    p.add_argument("--budget-hours", type=float, default=8.0)
    p.add_argument("--leg-hours", type=float, default=2.0)
    p.add_argument("--dir", default=None, help="chain dir (runs/seed<N>_chain)")
    p.add_argument("--base-port", type=int, default=30400)
    p.add_argument(
        "--resume-from", default=None,
        help="existing models dir: skip the fresh leg and start the "
        "cold/cool alternation from this checkpoint",
    )
    args = p.parse_args()

    chain_dir = os.path.abspath(args.dir or f"runs/seed{args.seed}_chain")
    os.makedirs(chain_dir, exist_ok=True)
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "run_cluster_learning.py")
    log = open(os.path.join(chain_dir, "chain.jsonl"), "a")

    common = [
        "--seed", str(args.seed),
        "--target", str(args.target),
        "--value-clip", "0", "10",
        "--base-port", str(args.base_port),
        "--run-dir", chain_dir,
        "--updates", "40000",  # per-leg incremental cap; wallclock governs
    ]
    deadline = time.time() + args.budget_hours * 3600.0

    def hours_left() -> float:
        return (deadline - time.time()) / 3600.0

    models_dir = args.resume_from and os.path.abspath(args.resume_from)
    leg_i = 0
    solved = False
    # Don't start a leg with less than ~12 min (or one leg-length) left —
    # too short to learn anything, long enough to corrupt nothing.
    min_leg = min(0.2, args.leg_hours)
    while not solved and hours_left() > min_leg:
        leg_i += 1
        leg_h = min(args.leg_hours, hours_left())
        out = os.path.join(chain_dir, f"leg{leg_i}.md")
        if models_dir is None:
            # fresh hot->cold leg (seed-0 leg-1 recipe)
            leg = common + [
                "--anneal-at", "3200", "--max-hours", f"{leg_h:.3f}",
            ]
        elif leg_i % 2 == 0:
            # cold cycling leg: default anneal (entropy 5e-5, lr 1e-4)
            leg = common + [
                "--anneal-at", "0", "--max-hours", f"{leg_h:.3f}",
                "--resume-from", models_dir,
            ]
        else:
            # cool consolidation leg
            leg = common + [
                "--anneal-at", "0", "--anneal-coef", "2e-5",
                "--anneal-lr", "3e-5", "--max-hours", f"{leg_h:.3f}",
                "--resume-from", models_dir,
            ]
        result = run_leg(script, leg, out)
        if result is None:
            print("[chain] leg produced no result line; stopping", flush=True)
            break
        result["leg"] = leg_i
        result["phase"] = (
            "fresh" if "--resume-from" not in leg
            else ("cold" if leg_i % 2 == 0 else "cool")
        )
        print(f"[chain] leg {leg_i}: {json.dumps(result)}", flush=True)
        log.write(json.dumps(result) + "\n")
        log.flush()
        if models_dir is None:
            # all later legs resume the first leg's models dir
            run_subdirs = sorted(
                d for d in os.listdir(chain_dir)
                if os.path.isdir(os.path.join(chain_dir, d, "models"))
            )
            if run_subdirs:
                models_dir = os.path.join(chain_dir, run_subdirs[0], "models")
        solved = bool(result.get("solved"))
    print(f"[chain] done: solved={solved} after {leg_i} legs", flush=True)
    sys.exit(0 if solved else 3)


if __name__ == "__main__":
    main()
