"""Self-healing smoke: prove the heal plane (tpu_rl.heal) end to end.

Three phases, exits nonzero on any failure — the ``make heal-smoke`` CI
gate:

1. **In-process guard math** — with clean data, guard-on training is
   bit-identical to guard-off (the ``lax.cond`` true branch runs exactly
   the pre-guard update); with a NaN in the batch, guard-on leaves params
   bitwise untouched and counts every skipped sub-update.
2. **NaN chaos run** — the smallest real cluster under a data-fault plan
   that poisons one worker's rollout values (``nan:``/``spike:`` on obs/
   rew, contained at the storage ingress edge) and the OTHER worker's
   log_prob column (deliberately NOT ingress-checked — it rides into
   training and must be contained by the in-jit guards, then tripped on
   by the watchdog).
   Asserts: the learner rolled back to a committed checkpoint at least
   once and bumped the run epoch (``learner_rollback.jsonl``), the
   poisoned worker was quarantined AND later un-quarantined on clean
   re-probe, every rollout-channel injection is accounted
   (injected == storage-poisoned-frames, exactly), the guards skipped at
   least one nonfinite update, the fleet kept producing episodes, and the
   run still completed cleanly.
3. **Clean run** — same healing config, no chaos: zero rollbacks, zero
   quarantines, zero poisoned frames, zero nonfinite updates.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python examples/heal_smoke.py \
      [--updates 10] [--base-port 29200]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The rollout-value faults target ONLY wid=1 (worker-0-1): their NaN/spike
# obs+rew are caught at the storage ingress edge and quarantine that worker.
# The window closes mid-run (for=6s) so wid 1's clean re-probe
# un-quarantines it and its final chaos counters are exported well before
# shutdown (exact injected==poisoned accounting). The logp fault rides
# wid=0 — the worker that STAYS in the fleet — because quarantine drops
# every frame from wid 1, poisoned or not; a logp fault there would never
# reach the learner. On wid 0 it passes ingress (log_prob is deliberately
# unvalidated) and must be contained by the in-jit guards; the long window
# keeps poison flowing while the learner is past its first-compile stall.
DEFAULT_SPEC = (
    "nan:rollout@p=0.4@t+4s@for=6s@wid=1,"
    "spike:rollout@p=0.2@t+4s@for=6s@wid=1,"
    "nan:logp@p=0.5@t+2s@for=25s@wid=0"
)


def _counter(source: dict, name: str) -> float:
    return sum(
        v for n, _labels, v in source.get("counters", ()) if n == name
    )


def _role_total(tele: dict, role: str, name: str) -> float:
    return sum(
        _counter(s, name) for s in tele["sources"] if s.get("role") == role
    )


def _tree_equal(a, b) -> bool:
    import jax
    import numpy as np

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def check_guard_math() -> list[str]:
    """Phase 1: in-jit guard semantics, no cluster needed."""
    import jax
    import jax.numpy as jnp

    from tests.conftest import small_config
    from tests.test_algos import make_batch
    from tpu_rl.algos.registry import get_algo

    failures: list[str] = []
    cfg_on = small_config(algo="PPO", update_guard=True)
    cfg_off = small_config(algo="PPO", update_guard=False)
    fam, s_on, step_on = get_algo("PPO").build(cfg_on, jax.random.PRNGKey(0))
    _, s_off, step_off = get_algo("PPO").build(cfg_off, jax.random.PRNGKey(0))
    batch = make_batch(cfg_on, fam)
    k = jax.random.PRNGKey(1)
    s_on1, m_on = jax.jit(step_on)(s_on, batch, k)
    s_off1, _ = jax.jit(step_off)(s_off, batch, k)
    if not _tree_equal(s_on1.params, s_off1.params):
        failures.append("guard-on clean step is not bit-identical to guard-off")
    if float(m_on["nonfinite-updates"]) != 0.0:
        failures.append(
            f"clean step counted {float(m_on['nonfinite-updates'])} "
            "nonfinite updates, expected 0"
        )

    # Poison log_prob (what nan:logp injects): every K_epoch sub-update
    # must be skipped, params bitwise untouched.
    bad = batch.replace(log_prob=batch.log_prob.at[0, 0, 0].set(jnp.nan))
    s_bad, m_bad = jax.jit(step_on)(s_on, bad, k)
    if not _tree_equal(s_bad.params, s_on.params):
        failures.append("guard let a NaN update touch params")
    if float(m_bad["nonfinite-updates"]) != float(cfg_on.K_epoch):
        failures.append(
            f"NaN step counted {float(m_bad['nonfinite-updates'])} skips, "
            f"expected K_epoch={cfg_on.K_epoch}"
        )
    if not failures:
        print("[heal-smoke] guard math: bit-identical clean, contained NaN",
              flush=True)
    return failures


def run_phase(
    name: str,
    chaos_spec: str | None,
    base_port: int,
    updates: int,
    timeout: float,
):
    """One cluster run with the healing plane armed; returns
    (telemetry dict, rollback records, storage exitcode, failures)."""
    from tests.conftest import small_config
    from tpu_rl.config import MachinesConfig, WorkerMachine
    from tpu_rl.runtime.runner import local_cluster

    run_dir = tempfile.mkdtemp(prefix=f"heal_smoke_{name}_")
    cfg = small_config(
        env="CartPole-v1",
        algo="PPO",
        worker_step_sleep=0.0,
        learner_device="cpu",
        rollout_lag_sec=30.0,
        time_horizon=100,
        loss_log_interval=2,
        result_dir=run_dir,
        model_dir=os.path.join(run_dir, "ckpt"),
        model_save_interval=2,
        ckpt_keep=4,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,
        supervise_poll_s=0.5,
        # The healing plane under test:
        update_guard=True,
        watchdog_enabled=True,
        watchdog_nonfinite=2,
        max_rollbacks=10,
        rollback_window_s=600.0,
        ingress_validate=True,
        quarantine_strikes=3,
        quarantine_clear_s=2.0,
        chaos_spec=chaos_spec,
        chaos_seed=11,
    )
    machines = MachinesConfig(
        learner_ip="127.0.0.1",
        learner_port=base_port,
        workers=[WorkerMachine(
            num_p=2, manager_ip="127.0.0.1", ip="127.0.0.1",
            port=base_port + 5,
        )],
    )
    failures: list[str] = []
    print(
        f"[heal-smoke] {name}: cluster up; run_dir={run_dir} "
        f"spec={chaos_spec!r}", flush=True,
    )
    sup = local_cluster(cfg, machines, max_updates=updates)
    loop_thread = threading.Thread(target=sup.loop, daemon=True)
    loop_thread.start()
    try:
        if not sup.stop_event.wait(timeout):
            failures.append(
                f"{name}: fleet did not complete within {timeout:.0f}s"
            )
        loop_thread.join(10.0)
        learner = next(c for c in sup.children if c.name == "learner")
        learner.proc.join(30.0)
        if learner.proc.is_alive() or learner.proc.exitcode != 0:
            failures.append(
                f"{name}: learner did not complete cleanly "
                f"(alive={learner.proc.is_alive()}, "
                f"exitcode={learner.proc.exitcode})"
            )
    finally:
        sup.stop()

    storage = next(c for c in sup.children if c.name == "storage")
    tele = {"sources": []}
    try:
        tele = json.loads(open(os.path.join(run_dir, "telemetry.json")).read())
    except (OSError, ValueError) as e:
        failures.append(
            f"{name}: telemetry.json invalid: {type(e).__name__}: {e}"
        )
    rollbacks: list[dict] = []
    rb_path = os.path.join(run_dir, "learner_rollback.jsonl")
    if os.path.exists(rb_path):
        try:
            with open(rb_path) as f:
                rollbacks = [json.loads(line) for line in f if line.strip()]
        except (OSError, ValueError) as e:
            failures.append(
                f"{name}: learner_rollback.jsonl invalid: "
                f"{type(e).__name__}: {e}"
            )
    return tele, rollbacks, storage.proc.exitcode, failures


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--updates", type=int, default=10)
    p.add_argument("--base-port", type=int, default=29200)
    p.add_argument("--chaos-spec", default=DEFAULT_SPEC)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args()
    failures: list[str] = []

    # ---- phase 1: in-jit guard semantics --------------------------------
    failures += check_guard_math()

    # ---- phase 2: NaN chaos — contain, roll back, quarantine, recover ---
    tele, rollbacks, _exit, errs = run_phase(
        "chaos", args.chaos_spec, args.base_port, args.updates, args.timeout
    )
    failures += errs

    if not rollbacks:
        failures.append("chaos: no rollback recorded — the watchdog never "
                        "tripped (or no committed checkpoint existed)")
    else:
        epochs = [r.get("epoch", 0) for r in rollbacks]
        print(
            f"[heal-smoke] chaos: {len(rollbacks)} rollback(s), run epoch "
            f"-> {max(epochs)}", flush=True,
        )
        if max(epochs) < 1:
            failures.append(
                f"chaos: rollback never bumped the run epoch: {epochs}"
            )
    n_rb = _role_total(tele, "learner", "learner-rollbacks")
    if n_rb < 1:
        failures.append(f"chaos: learner-rollbacks={n_rb}, expected >= 1")
    nf = _role_total(tele, "learner", "learner-nonfinite-updates")
    if nf < 1:
        failures.append(
            f"chaos: learner-nonfinite-updates={nf}, expected >= 1 — the "
            "logp poison never reached (or never tripped) the in-jit guards"
        )

    # Fault accounting: DataChaos injects at most one rollout-channel fault
    # per frame and ingress classifies BEFORE the epoch fence, so the
    # worker-side injection counters must equal storage's poisoned-frame
    # drops exactly (logp injections are a separate, unvalidated channel).
    injected = _role_total(tele, "worker", "chaos-nan-injected") + _role_total(
        tele, "worker", "chaos-spike-injected"
    )
    poisoned = _role_total(tele, "storage", "storage-poisoned-frames")
    if injected < 1:
        failures.append("chaos: zero rollout-value injections — the data "
                        "fault plan never fired")
    if injected != poisoned:
        failures.append(
            f"chaos: fault accounting mismatch: injected {injected} "
            f"rollout-value faults but storage poisoned {poisoned}"
        )
    else:
        print(
            f"[heal-smoke] chaos: {injected:.0f} injected == "
            f"{poisoned:.0f} poisoned", flush=True,
        )
    if _role_total(tele, "worker", "chaos-logp-nan-injected") < 1:
        failures.append("chaos: zero logp injections — the guard-channel "
                        "fault never fired")

    nq = _role_total(tele, "storage", "storage-quarantines")
    nuq = _role_total(tele, "storage", "storage-unquarantines")
    if nq < 1:
        failures.append(f"chaos: storage-quarantines={nq}, expected >= 1")
    if nuq < 1:
        failures.append(
            f"chaos: storage-unquarantines={nuq}, expected >= 1 — the "
            "poisoned worker never cleared on clean re-probe"
        )
    if nq >= 1 and nuq >= 1:
        print(
            f"[heal-smoke] chaos: quarantines={nq:.0f} "
            f"unquarantines={nuq:.0f} "
            f"dropped-clean={_role_total(tele, 'storage', 'storage-quarantined-frames'):.0f}",
            flush=True,
        )
    # Loose learning bar: the fleet kept producing episodes throughout
    # (logp poison skews training, not acting; quarantine drops frames,
    # not the worker's env loop).
    episodes = _role_total(tele, "worker", "worker-episodes")
    if episodes < 1:
        failures.append(f"chaos: worker-episodes={episodes}, fleet starved")

    # ---- phase 3: clean run — the healing plane must be invisible -------
    tele, rollbacks, _exit, errs = run_phase(
        "clean", None, args.base_port + 20, max(4, args.updates // 2),
        args.timeout,
    )
    failures += errs
    for metric, role in (
        ("learner-rollbacks", "learner"),
        ("learner-nonfinite-updates", "learner"),
        ("storage-poisoned-frames", "storage"),
        ("storage-quarantines", "storage"),
        ("storage-quarantined-frames", "storage"),
    ):
        v = _role_total(tele, role, metric)
        if v != 0:
            failures.append(f"clean: {metric}={v}, expected 0")
    if rollbacks:
        failures.append(f"clean: {len(rollbacks)} rollback(s) recorded")

    if failures:
        for f in failures:
            print(f"[heal-smoke] FAIL: {f}", file=sys.stderr, flush=True)
        return 1
    print("[heal-smoke] OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
