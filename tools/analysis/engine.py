"""Shared machinery for the checker suite: findings, AST helpers, baseline.

A ``Finding`` identifies one violation; the baseline (``baseline.toml``)
waives findings by (check, code, path, symbol) — never by line number, so a
waiver survives unrelated edits above it but dies with the symbol it names.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
from pathlib import Path
from typing import Iterator

try:  # Python 3.11+
    import tomllib
except ModuleNotFoundError:  # pragma: no cover - 3.10 fallback
    import tomli as tomllib  # type: ignore[no-redef]

REPO_ROOT = Path(__file__).resolve().parents[2]
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.toml"

# Hard cap on committed waivers: past this the baseline is hiding debt, not
# recording it — fix the findings instead.
MAX_WAIVERS = 10


@dataclasses.dataclass(frozen=True)
class Finding:
    """One checker violation, addressable for waiving and for tests."""

    check: str  # checker name ("hotpath", "jit", ...)
    code: str  # stable rule id ("HP001", ...)
    path: str  # repo-relative posix path
    line: int  # 1-based
    symbol: str  # dotted qualname of the offending function, or "<module>"
    message: str

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.code} [{self.check}] "
            f"{self.symbol}: {self.message}"
        )


@dataclasses.dataclass(frozen=True)
class Waiver:
    """One baseline entry. ``path`` may be an fnmatch glob; ``symbol`` may
    be ``*`` to waive the rule for the whole file."""

    check: str
    code: str
    path: str
    symbol: str
    reason: str

    def matches(self, f: Finding) -> bool:
        return (
            f.check == self.check
            and f.code == self.code
            and fnmatch.fnmatch(f.path, self.path)
            and self.symbol in ("*", f.symbol)
        )


def load_baseline(path: str | Path = BASELINE_PATH) -> list[Waiver]:
    """Parse and validate the waiver baseline; raises ValueError on an
    unjustified entry or on more than MAX_WAIVERS entries."""
    data = tomllib.loads(Path(path).read_text())
    waivers: list[Waiver] = []
    for i, entry in enumerate(data.get("waiver", [])):
        reason = str(entry.get("reason", "")).strip()
        if not reason:
            raise ValueError(f"baseline waiver #{i + 1} has no reason: {entry}")
        for key in ("check", "code", "path"):
            if not entry.get(key):
                raise ValueError(f"baseline waiver #{i + 1} missing {key!r}")
        waivers.append(
            Waiver(
                check=str(entry["check"]),
                code=str(entry["code"]),
                path=str(entry["path"]),
                symbol=str(entry.get("symbol", "*")),
                reason=reason,
            )
        )
    if len(waivers) > MAX_WAIVERS:
        raise ValueError(
            f"baseline holds {len(waivers)} waivers, cap is {MAX_WAIVERS}: "
            "fix findings instead of waiving them"
        )
    return waivers


def apply_baseline(
    findings: list[Finding], waivers: list[Waiver]
) -> tuple[list[Finding], list[Finding], list[Waiver]]:
    """-> (kept, waived, stale_waivers). A waiver that matched nothing is
    stale — reported so the baseline shrinks as findings get fixed."""
    kept: list[Finding] = []
    waived: list[Finding] = []
    used: set[int] = set()
    for f in findings:
        hit = None
        for i, w in enumerate(waivers):
            if w.matches(f):
                hit = i
                break
        if hit is None:
            kept.append(f)
        else:
            used.add(hit)
            waived.append(f)
    stale = [w for i, w in enumerate(waivers) if i not in used]
    return kept, waived, stale


def parse_file(path: str | Path) -> ast.Module:
    return ast.parse(Path(path).read_text(), filename=str(path))


def rel(path: str | Path, root: str | Path = REPO_ROOT) -> str:
    return Path(path).resolve().relative_to(Path(root).resolve()).as_posix()


def iter_functions(
    tree: ast.Module,
) -> Iterator[tuple[str, ast.FunctionDef | ast.AsyncFunctionDef]]:
    """Yield (dotted qualname, node) for every def, including those nested
    inside classes and other defs ("Outer.__init__.Handler.do_GET")."""

    def walk(node: ast.AST, prefix: str) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def terminal_name(node: ast.expr) -> str | None:
    """The last identifier of a Name or dotted Attribute chain
    (``jax.lax.scan`` -> "scan"), else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.expr) -> str | None:
    """Full dotted form of a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
