"""CLI: ``python -m tools.analysis [--check NAME ...] [--no-baseline]``.

Exit codes: 0 = clean (waived findings and stale waivers are reported but
don't fail), 1 = unwaived findings, 2 = a checker or the baseline itself is
broken.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.analysis.checks import ALL_CHECKS
from tools.analysis.engine import (
    BASELINE_PATH,
    REPO_ROOT,
    apply_baseline,
    load_baseline,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="python -m tools.analysis")
    p.add_argument(
        "--check", action="append", choices=sorted(ALL_CHECKS),
        help="run only this checker (repeatable; default: all)",
    )
    p.add_argument("--root", default=str(REPO_ROOT), help="repo root to scan")
    p.add_argument(
        "--baseline", default=str(BASELINE_PATH),
        help="waiver baseline toml (default: tools/analysis/baseline.toml)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, waived or not",
    )
    args = p.parse_args(argv)

    root = Path(args.root)
    names = args.check or sorted(ALL_CHECKS)
    findings = []
    for name in names:
        try:
            findings.extend(ALL_CHECKS[name].run(root))
        except Exception as e:  # a broken checker must fail loudly, not pass
            print(f"error: checker {name!r} crashed: {e!r}", file=sys.stderr)
            return 2

    if args.no_baseline:
        kept, waived, stale = findings, [], []
    else:
        try:
            waivers = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"error: bad baseline {args.baseline}: {e}", file=sys.stderr)
            return 2
        kept, waived, stale = apply_baseline(findings, waivers)

    for f in sorted(kept, key=lambda f: (f.path, f.line, f.code)):
        print(f.render())
    for w in stale:
        print(
            f"warning: stale waiver ({w.check}/{w.code} {w.path} {w.symbol}) "
            "matched nothing — remove it from baseline.toml",
            file=sys.stderr,
        )
    checked = ", ".join(names)
    print(
        f"tools.analysis: {len(kept)} finding(s), {len(waived)} waived, "
        f"{len(stale)} stale waiver(s) [{checked}]",
        file=sys.stderr,
    )
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
