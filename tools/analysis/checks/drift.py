"""Metric and config drift.

Metrics: every name registered in code (``registry.counter/gauge/histogram``
with a literal name, ``timer.record``/``record_gauge``, and the ``*_GAUGE`` /
``*_HIST`` string constants in ``tpu_rl/obs``) must appear in one of
ARCHITECTURE.md's metric tables, and every documented name must exist in
code. Doc rows may use ``fnmatch`` wildcards (the ``learner-*`` family row);
a wildcard that matches nothing is itself drift. Registry names must not be
registered under two different kinds (timer-plane mirrors of fleet counters
are exempt: the learner re-exports mailbox aggregates as timer gauges by
design — see ``_log_fleet_stat``).

Config: every ``Config`` field is either read inside ``Config.validate`` or
listed in ``CONFIG_VALIDATE_EXEMPT`` with a reason. The CLI override map in
``__main__.load_config`` may only assign keys that are real Config fields,
and every ``--flag``/``args.X`` pair must line up both ways.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

import fnmatch

from tools.analysis.engine import Finding, REPO_ROOT, parse_file, rel

NAME = "drift"

DOC_FILE = "docs/ARCHITECTURE.md"
CODE_DIR = "tpu_rl"
CONFIG_FILE = "tpu_rl/config.py"
MAIN_FILE = "tpu_rl/__main__.py"

_REGISTRY_KINDS = {"counter": "counter", "gauge": "gauge", "histogram": "histogram"}
_TIMER_METHODS = {"record", "record_gauge"}
_CONST_SUFFIX_KINDS = {"_GAUGE": "gauge", "_HIST": "histogram", "_METRIC": "counter"}
_DOC_HEADER = re.compile(r"^\|\s*Name\s*\|\s*Kind\s*\|")
_METRIC_NAME = re.compile(r"^[a-z0-9*]+(-[a-z0-9*]+)+$")

# Config fields deliberately outside ``validate`` — every entry carries the
# why. Adding a field without either a validate read or a row here is DR010.
CONFIG_VALIDATE_EXEMPT: dict[str, str] = {
    "result_dir": "free-form output path; None = no artifacts",
    "model_dir": "free-form checkpoint path; None = derived from result_dir",
    "profile_dir": "free-form XLA trace path; None = profiler off",
    "history_dir": "free-form history-store path; None = result_dir/history",
    "is_gray": "boolean; both values valid",
    "ckpt_async": "boolean A/B switch; both values valid",
    "resume_force": "boolean escape hatch; both values valid",
    "reset_carry_on_first": "boolean parity switch; both values valid",
    "stop_at_reward": "any float is a legal stop bar; None = run full budget",
    "policy_loss_coef": "any float is a legal loss weight (0 disables the term)",
    "value_loss_coef": "any float is a legal loss weight (0 disables the term)",
    "entropy_coef": "any float is a legal loss weight (0 disables the term)",
    "v_mpo_lagrange_multiplier_init": "algo-specific init; positivity enforced by softplus in algos/vmpo.py",
    "coef_alpha_upper": "V-MPO dual lr; any positive-ish float, consumed by optax",
    "coef_alpha_below": "V-MPO dual lr; any positive-ish float, consumed by optax",
    "chaos_seed": "any int seeds the per-site RNG streams",
    "ingress_validate": "boolean plane switch; both values valid",
    "slo_fail_run": "boolean exit gate; both values valid",
    "obs_shape": "runtime-derived by probe_spaces, never user-set",
    "action_space": "runtime-derived by probe_spaces, never user-set",
}


# ------------------------------------------------------------------ metrics
def extract_code_metrics(
    paths: list[Path], root: Path
) -> list[tuple[str, str, str, int]]:
    """-> [(name, kind, rel_path, line)]; kind in counter/gauge/histogram/timer."""
    out: list[tuple[str, str, str, int]] = []
    for p in paths:
        rel_path = rel(p, root)
        tree = parse_file(p)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                kind = _REGISTRY_KINDS.get(attr)
                if kind is None and attr in _TIMER_METHODS:
                    kind = "timer"
                if kind is None or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    out.append((arg.value, kind, rel_path, node.lineno))
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                name = node.targets[0].id
                for suffix, kind in _CONST_SUFFIX_KINDS.items():
                    if name.endswith(suffix):
                        out.append((node.value.value, kind, rel_path, node.lineno))
                        break
    return out


def extract_doc_metrics(path: str | Path) -> list[tuple[str, int]]:
    """Metric names from every ``| Name | Kind | ... |`` table -> [(name, line)]."""
    out: list[tuple[str, int]] = []
    in_table = False
    for lineno, line in enumerate(Path(path).read_text().splitlines(), 1):
        stripped = line.strip()
        if _DOC_HEADER.match(stripped):
            in_table = True
            continue
        if in_table:
            if not stripped.startswith("|"):
                in_table = False
                continue
            first_cell = stripped.strip("|").split("|", 1)[0]
            for token in re.findall(r"`([^`]+)`", first_cell):
                if _METRIC_NAME.match(token):
                    out.append((token, lineno))
    return out


def compare_metrics(
    code: list[tuple[str, str, str, int]],
    doc: list[tuple[str, int]],
    doc_rel: str = DOC_FILE,
) -> list[Finding]:
    findings: list[Finding] = []
    doc_exact = {n for n, _ in doc if "*" not in n}
    doc_globs = [(n, ln) for n, ln in doc if "*" in n]
    code_names = {n for n, _, _, _ in code}

    seen: set[str] = set()
    for name, kind, path, line in code:
        if name in seen:
            continue
        seen.add(name)
        if name in doc_exact or any(
            fnmatch.fnmatch(name, g) for g, _ in doc_globs
        ):
            continue
        findings.append(
            Finding(
                NAME, "DR001", path, line, name,
                f"metric {name!r} ({kind}) is not documented in "
                f"{doc_rel}'s metric tables",
            )
        )
    for name, line in doc:
        if "*" in name:
            if not any(fnmatch.fnmatch(c, name) for c in code_names):
                findings.append(
                    Finding(
                        NAME, "DR002", doc_rel, line, name,
                        f"documented metric family {name!r} matches nothing in code",
                    )
                )
        elif name not in code_names:
            findings.append(
                Finding(
                    NAME, "DR002", doc_rel, line, name,
                    f"documented metric {name!r} does not exist in code "
                    "(renamed or removed?)",
                )
            )

    # Kind collisions among registry metrics (timer mirrors exempt).
    kinds: dict[str, set[str]] = {}
    first_site: dict[str, tuple[str, int]] = {}
    for name, kind, path, line in code:
        if kind == "timer":
            continue
        kinds.setdefault(name, set()).add(kind)
        first_site.setdefault(name, (path, line))
    for name, ks in sorted(kinds.items()):
        if len(ks) > 1:
            path, line = first_site[name]
            findings.append(
                Finding(
                    NAME, "DR003", path, line, name,
                    f"metric {name!r} is registered as {sorted(ks)} — one "
                    "name, one kind",
                )
            )
    return findings


# ------------------------------------------------------------------- config
def check_config(
    path: str | Path, rel_path: str, exempt: dict[str, str] = CONFIG_VALIDATE_EXEMPT
) -> list[Finding]:
    tree = parse_file(path)
    findings: list[Finding] = []
    cfg_class = next(
        (
            n
            for n in tree.body
            if isinstance(n, ast.ClassDef) and n.name == "Config"
        ),
        None,
    )
    if cfg_class is None:
        return [Finding(NAME, "DR010", rel_path, 1, "Config", "Config class not found")]
    fields: dict[str, int] = {}
    validate_fn = None
    for stmt in cfg_class.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            fields[stmt.target.id] = stmt.lineno
        elif isinstance(stmt, ast.FunctionDef) and stmt.name == "validate":
            validate_fn = stmt
    if validate_fn is None:
        return [
            Finding(NAME, "DR010", rel_path, cfg_class.lineno, "Config",
                    "Config.validate not found")
        ]
    covered = {
        n.attr
        for n in ast.walk(validate_fn)
        if isinstance(n, ast.Attribute)
        and isinstance(n.value, ast.Name)
        and n.value.id == "self"
    }
    for field, line in sorted(fields.items()):
        if field in covered or field in exempt:
            continue
        findings.append(
            Finding(
                NAME, "DR010", rel_path, line, f"Config.{field}",
                f"field {field!r} is neither read in Config.validate nor "
                "exempted in CONFIG_VALIDATE_EXEMPT (checks/drift.py)",
            )
        )
    for field in sorted(exempt):
        if field not in fields:
            findings.append(
                Finding(
                    NAME, "DR010", rel_path, 1, f"Config.{field}",
                    f"CONFIG_VALIDATE_EXEMPT names {field!r}, which is not a "
                    "Config field (stale exemption)",
                )
            )
    return findings


def check_cli(
    path: str | Path, rel_path: str, config_fields: set[str]
) -> list[Finding]:
    tree = parse_file(path)
    findings: list[Finding] = []
    flag_dests: set[str] = set()
    args_used: dict[str, int] = {}
    override_keys: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                dest = next(
                    (
                        kw.value.value
                        for kw in node.keywords
                        if kw.arg == "dest"
                        and isinstance(kw.value, ast.Constant)
                    ),
                    node.args[0].value.lstrip("-").replace("-", "_"),
                )
                flag_dests.add(dest)
        elif (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "args"
        ):
            args_used.setdefault(node.attr, node.lineno)
        elif (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Subscript)
            and isinstance(node.targets[0].value, ast.Name)
            and node.targets[0].value.id == "overrides"
            and isinstance(node.targets[0].slice, ast.Constant)
            and isinstance(node.targets[0].slice.value, str)
        ):
            override_keys.setdefault(node.targets[0].slice.value, node.lineno)

    for attr, line in sorted(args_used.items()):
        if attr not in flag_dests:
            findings.append(
                Finding(
                    NAME, "DR011", rel_path, line, f"args.{attr}",
                    f"args.{attr} is read but no add_argument declares that "
                    "dest — the CLI would crash on access",
                )
            )
    for dest in sorted(flag_dests):
        if dest not in args_used:
            findings.append(
                Finding(
                    NAME, "DR012", rel_path, 1, f"--{dest.replace('_', '-')}",
                    f"flag dest {dest!r} is declared but never read from args "
                    "(dead flag)",
                )
            )
    for key, line in sorted(override_keys.items()):
        if key not in config_fields:
            findings.append(
                Finding(
                    NAME, "DR013", rel_path, line, key,
                    f"CLI override targets {key!r}, which is not a Config "
                    "field — the override would be silently dropped by "
                    "Config.replace",
                )
            )
    return findings


def _config_fields(path: Path) -> set[str]:
    tree = parse_file(path)
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return {
                s.target.id
                for s in node.body
                if isinstance(s, ast.AnnAssign) and isinstance(s.target, ast.Name)
            }
    return set()


def run(root: Path = REPO_ROOT) -> list[Finding]:
    code_files = sorted((root / CODE_DIR).rglob("*.py"))
    code_metrics = extract_code_metrics(code_files, root)
    doc_metrics = extract_doc_metrics(root / DOC_FILE)
    findings = compare_metrics(code_metrics, doc_metrics)
    findings.extend(check_config(root / CONFIG_FILE, CONFIG_FILE))
    findings.extend(
        check_cli(root / MAIN_FILE, MAIN_FILE, _config_fields(root / CONFIG_FILE))
    )
    return findings
