"""Thread discipline: the INVENTORY below declares every background-thread
entry point in the repo (the target function handed to ``threading.Thread``
or an in-thread request handler). Inside an entry function, any attribute
write (``self.x = ...``, ``obj.x += ...``) is a cross-thread publication and
must be either:

- lexically inside a ``with`` block whose context expression names a lock or
  condition (identifier containing "lock" or "cond"), or
- an attribute named in the entry's allowlist, each justified inline below.

Scope is the entry function itself (including nested defs/lambdas) — the
same single-function scope the seqlock and mailbox comments reason about.
Helpers called from the thread are owned by it and reviewed at their call
sites; widening to whole-call-graph analysis would drown the signal in
thread-owned state.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.analysis.engine import Finding, REPO_ROOT, iter_functions, parse_file

NAME = "threads"

# file -> {entry qualname -> allowed attribute names}.
INVENTORY: dict[str, dict[str, frozenset[str]]] = {
    "tpu_rl/runtime/learner_service.py": {
        # _error: single-writer slot; publish() re-raises it from the update
        # loop after join(), so the GIL-atomic store needs no lock.
        "AsyncPublisher._run": frozenset({"_error"}),
    },
    "tpu_rl/data/prefetch.py": {
        # _error: single-writer slot drained by the consumer after the
        # sentinel; queue handoff orders the publication.
        "PrefetchPipeline._run": frozenset({"_error"}),
    },
    "tpu_rl/checkpoint.py": {
        # Every shared write happens under self._cond by construction.
        "Checkpointer._run": frozenset(),
    },
    "tpu_rl/runtime/sebulba.py": {
        # Actor lane: publication is the BoundedPipe plus the params/stats
        # slots, and every slot write sits under self._lane_lock.
        "SebulbaLoop._actor_loop": frozenset(),
    },
    "tpu_rl/runtime/inference_service.py": {
        # _jnp: imported once at thread start, read-only afterwards.
        # error: single-writer slot; the runner reads it post-join.
        # n_flush_full/n_flush_deadline: serve-thread-owned monotonic
        # counters; the learner loop reads them for telemetry only, where a
        # torn read is a one-snapshot off-by-one, not a correctness hazard.
        # perf: GIL-atomic reference store at thread start (None until the
        # PerfTracker exists); the learner's telemetry emit only reads it,
        # and a pre-capture sighting just exports zero FLOPs for one tick.
        # buckets: the resolved bucket ladder, stored once before warmup
        # (GIL-atomic list reference, never mutated after); telemetry emits
        # read it to label per-bucket counters, and a pre-store sighting
        # sees the empty placeholder — zero rows for one tick, not a race.
        "InferenceService._serve": frozenset(
            {"_jnp", "error", "n_flush_full", "n_flush_deadline", "perf",
             "buckets"}
        ),
    },
    "tpu_rl/obs/exporters.py": {
        # Stdlib-threaded request handler; it must stay read-only over the
        # aggregator, hence the empty allowlist.
        "TelemetryHTTPServer.__init__.Handler.do_GET": frozenset(),
    },
}

_LOCKISH = ("lock", "cond", "mutex")


def _lock_guarded(with_node: ast.With) -> bool:
    for item in with_node.items:
        for sub in ast.walk(item.context_expr):
            name = None
            if isinstance(sub, ast.Attribute):
                name = sub.attr
            elif isinstance(sub, ast.Name):
                name = sub.id
            if name is not None and any(t in name.lower() for t in _LOCKISH):
                return True
    return False


def _attr_write_targets(node: ast.stmt) -> list[ast.Attribute]:
    targets: list[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    out: list[ast.Attribute] = []
    for t in targets:
        if isinstance(t, ast.Attribute):
            out.append(t)
        elif isinstance(t, (ast.Tuple, ast.List)):
            out.extend(e for e in t.elts if isinstance(e, ast.Attribute))
    return out


def _visit(
    fn: ast.AST, allowed: frozenset[str], qualname: str, path: str
) -> list[Finding]:
    findings: list[Finding] = []

    def walk(node: ast.AST, guarded: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_guarded = guarded
            if isinstance(child, ast.With):
                child_guarded = guarded or _lock_guarded(child)
            if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for attr in _attr_write_targets(child):
                    if attr.attr in allowed or guarded:
                        continue
                    findings.append(
                        Finding(
                            NAME, "TH001", path, child.lineno, qualname,
                            f"attribute write .{attr.attr} on a thread entry "
                            "path without a lock/cond guard or an inventory "
                            "allowlist entry (checks/threads.py)",
                        )
                    )
            walk(child, child_guarded)

    walk(fn, False)
    return findings


def scan_file(
    path: str | Path, inventory: dict[str, frozenset[str]], rel_path: str
) -> list[Finding]:
    tree = parse_file(path)
    fns = dict(iter_functions(tree))
    findings: list[Finding] = []
    for qualname, allowed in sorted(inventory.items()):
        fn = fns.get(qualname)
        if fn is None:
            findings.append(
                Finding(
                    NAME, "TH000", rel_path, 1, qualname,
                    "thread-inventory entry not found in file (renamed? "
                    "update INVENTORY in checks/threads.py)",
                )
            )
            continue
        findings.extend(_visit(fn, allowed, qualname, rel_path))
    return findings


def run(root: Path = REPO_ROOT) -> list[Finding]:
    findings: list[Finding] = []
    for rel_path, inventory in INVENTORY.items():
        findings.extend(scan_file(root / rel_path, inventory, rel_path))
    return findings
