"""Checker registry: every module here exposes ``NAME`` and ``run(root)``."""

from __future__ import annotations

from tools.analysis.checks import (
    drift,
    hotpath,
    jit_boundary,
    protocol_check,
    threads,
)

ALL_CHECKS = {
    m.NAME: m for m in (hotpath, jit_boundary, protocol_check, drift, threads)
}
