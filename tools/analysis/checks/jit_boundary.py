"""Jit-boundary discipline: functions handed to ``jax.jit`` or used as a
``lax.scan`` body must stay traceable — no host syncs (``float()``,
``.item()``, ``np.asarray``/``np.array`` on traced values) and no untraced
side effects (``print``, ``time.*``). Any of these either crashes at trace
time on an abstract value or, worse, silently runs once at trace time and
never again.

Traced-function discovery is syntactic, matching how this repo spells it:

- ``jax.jit(f, ...)`` / ``jax.jit(self._body, ...)`` call form,
- ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorators,
- ``lax.scan(f, ...)``, including ``lax.scan(lambda c, x: self._tick(...))``
  where the names called inside the lambda are traced too.

Collected names resolve to same-module defs by their last qualname segment.
``int()``/``bool()`` are deliberately not flagged: they appear in static
shape math on concrete Python values throughout the parallel layers.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.analysis.engine import (
    Finding,
    REPO_ROOT,
    iter_functions,
    parse_file,
    rel,
    terminal_name,
)

NAME = "jit"

# Files/dirs holding jit or scan bodies (repo-relative).
TARGETS = (
    "tpu_rl/runtime/colocated.py",
    "tpu_rl/runtime/sebulba.py",
    "tpu_rl/runtime/inference_service.py",
    "tpu_rl/runtime/learner_service.py",
    "tpu_rl/runtime/worker.py",
    "tpu_rl/parallel",
    "tpu_rl/algos",
    "tpu_rl/ops",
    # The learning-dynamics plane's jitted fold (make_accumulate ->
    # jax.jit(accumulate)) and the in-jit bucket math it closes over.
    "tpu_rl/obs/learn.py",
)

_HOST_SYNC_CALLS = {
    "float": ("JB005", "float() forces a host sync on a traced value"),
    "item": ("JB003", ".item() forces a host sync on a traced value"),
    "asarray": ("JB004", "np.asarray materializes a traced value on host"),
    "array": ("JB004", "np.array materializes a traced value on host"),
}


def _collect_traced_names(tree: ast.Module) -> set[str]:
    """Bare names of functions this module traces via jit or scan."""
    traced: set[str] = set()

    def note(arg: ast.expr) -> None:
        t = terminal_name(arg)
        if t is not None:
            traced.add(t)
        elif isinstance(arg, ast.Lambda):
            # scan(lambda c, x: self._tick(...)): the lambda body is inline
            # — trace every function it calls by name.
            for sub in ast.walk(arg.body):
                if isinstance(sub, ast.Call):
                    st = terminal_name(sub.func)
                    if st is not None:
                        traced.add(st)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            t = terminal_name(node.func)
            if t == "jit" and node.args:
                note(node.args[0])
            elif t == "scan" and node.args:
                note(node.args[0])
            elif t == "partial" and node.args:
                # partial(jax.jit, ...) used as a decorator factory
                if terminal_name(node.args[0]) == "jit" and len(node.args) > 1:
                    note(node.args[1])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                base = dec.func if isinstance(dec, ast.Call) else dec
                if terminal_name(base) == "jit":
                    traced.add(node.name)
                elif (
                    isinstance(dec, ast.Call)
                    and terminal_name(dec.func) == "partial"
                    and dec.args
                    and terminal_name(dec.args[0]) == "jit"
                ):
                    traced.add(node.name)
    traced.discard("jit")
    traced.discard("scan")
    return traced


def _visit(fn: ast.AST, qualname: str, path: str) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        t = terminal_name(node.func)
        if t == "print":
            findings.append(
                Finding(
                    NAME, "JB001", path, node.lineno, qualname,
                    "print inside a traced body runs at trace time only",
                )
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time"
        ):
            findings.append(
                Finding(
                    NAME, "JB002", path, node.lineno, qualname,
                    f"time.{t}() inside a traced body is evaluated once at "
                    "trace time, not per step",
                )
            )
        elif t in _HOST_SYNC_CALLS:
            # np.asarray/np.array only when spelled through np/numpy;
            # bare float()/.item() always.
            if t in ("asarray", "array"):
                if not (
                    isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("np", "numpy")
                ):
                    continue
            code, msg = _HOST_SYNC_CALLS[t]
            findings.append(Finding(NAME, code, path, node.lineno, qualname, msg))
    return findings


def scan_file(path: str | Path, rel_path: str) -> list[Finding]:
    tree = parse_file(path)
    traced = _collect_traced_names(tree)
    if not traced:
        return []
    findings: list[Finding] = []
    for qualname, fn in iter_functions(tree):
        if qualname.rsplit(".", 1)[-1] in traced:
            findings.extend(_visit(fn, qualname, rel_path))
    return findings


def run(root: Path = REPO_ROOT) -> list[Finding]:
    findings: list[Finding] = []
    for target in TARGETS:
        p = root / target
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            findings.extend(scan_file(f, rel(f, root)))
    return findings
