"""Protocol/mailbox consistency.

Wire side (``tpu_rl/runtime/protocol.py``):

- PC001: every ``struct.Struct`` named in STRUCT_DECLS must have a declared
  ``*_BYTES`` constant equal to ``struct.calcsize`` of its format — the
  static twin of the import-time asserts, so the mismatch is also visible
  without importing (and the constant can't be deleted).
- PC002: every ``Protocol.X`` named in the ``TRACE_KINDS`` allowlist must be
  a member of the ``Protocol`` enum (peek's accepted set is the enum itself,
  so this pins the allowlist inside what peek accepts).
- PC003: ``Protocol`` enum values must be unique and contiguous from 0 —
  ``TRACE_KINDS_MASK`` and the native validator index bitmask tables by
  proto byte.

Mailbox side (``tpu_rl/runtime/mailbox.py`` + every reader/writer):

- PC010: ``SLOT_*`` values unique and contiguous from 0, ``STAT_SLOTS`` ==
  slot count.
- PC011: no bare integer index into the stat mailbox array — readers and
  writers must spell the named constant, the whole point of the module.
- PC012: every ``SLOT_*`` constant is referenced (as a name, not an import)
  in at least two modules outside mailbox.py — one writer side and one
  reader side. A deleted reference that orphans a slot to a single side
  fails here.
"""

from __future__ import annotations

import ast
import struct
from pathlib import Path

from tools.analysis.engine import Finding, REPO_ROOT, parse_file, rel

NAME = "protocol"

PROTOCOL_FILE = "tpu_rl/runtime/protocol.py"
# struct.Struct assign name -> declared byte-count constant name.
STRUCT_DECLS = {"_HEADER": "HEADER_BYTES", "_TRAILER": "TRAILER_BYTES"}
ENUM_NAME = "Protocol"
ALLOWLIST_NAME = "TRACE_KINDS"

MAILBOX_FILE = "tpu_rl/runtime/mailbox.py"
SLOT_PREFIX = "SLOT_"
SLOT_TOTAL = "STAT_SLOTS"
# Names the stat mailbox array travels under at read/write sites.
MAILBOX_ARRAY_NAMES = frozenset({"sa", "stat_array"})
# Modules scanned for bare indices and slot cross-references.
SLOT_USER_DIR = "tpu_rl"
# Slots written and read through one shared helper each side still need two
# distinct modules touching them; mailbox.py itself never counts.
MIN_SLOT_MODULES = 2


def _const_int_assigns(tree: ast.Module, prefix: str | None = None) -> dict[str, tuple[int, int]]:
    """Module-level ``NAME = <int literal>`` -> (value, lineno)."""
    out: dict[str, tuple[int, int]] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, int)
            and not isinstance(node.value.value, bool)
        ):
            name = node.targets[0].id
            if prefix is None or name.startswith(prefix) or name == SLOT_TOTAL:
                out[name] = (node.value.value, node.lineno)
    return out


def check_protocol_file(
    path: str | Path,
    rel_path: str,
    struct_decls: dict[str, str] = STRUCT_DECLS,
) -> list[Finding]:
    tree = parse_file(path)
    findings: list[Finding] = []

    # name -> (format string, lineno) for X = struct.Struct("...") assigns.
    structs: dict[str, tuple[str, int]] = {}
    consts = _const_int_assigns(tree)
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr == "Struct"
            and node.value.args
            and isinstance(node.value.args[0], ast.Constant)
            and isinstance(node.value.args[0].value, str)
        ):
            structs[node.targets[0].id] = (node.value.args[0].value, node.lineno)

    for sname, cname in sorted(struct_decls.items()):
        if sname not in structs:
            findings.append(
                Finding(
                    NAME, "PC001", rel_path, 1, sname,
                    f"expected wire struct {sname} = struct.Struct(...) not found",
                )
            )
            continue
        fmt, line = structs[sname]
        if cname not in consts:
            findings.append(
                Finding(
                    NAME, "PC001", rel_path, line, sname,
                    f"declared byte constant {cname} for {sname} is missing",
                )
            )
            continue
        declared, _ = consts[cname]
        actual = struct.calcsize(fmt)
        if actual != declared:
            findings.append(
                Finding(
                    NAME, "PC001", rel_path, line, sname,
                    f"struct.calcsize({fmt!r}) == {actual} but {cname} == "
                    f"{declared}: format and declared size drifted",
                )
            )

    # Protocol enum members.
    members: dict[str, tuple[int, int]] = {}
    enum_line = 1
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == ENUM_NAME:
            enum_line = node.lineno
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, int)
                ):
                    members[stmt.targets[0].id] = (stmt.value.value, stmt.lineno)
    if not members:
        findings.append(
            Finding(
                NAME, "PC003", rel_path, enum_line, ENUM_NAME,
                f"enum {ENUM_NAME} with integer members not found",
            )
        )
    else:
        values = sorted(v for v, _ in members.values())
        if values != list(range(len(values))):
            findings.append(
                Finding(
                    NAME, "PC003", rel_path, enum_line, ENUM_NAME,
                    f"{ENUM_NAME} values {values} are not unique+contiguous "
                    "from 0 (proto-byte-indexed tables would misroute)",
                )
            )

    # TRACE_KINDS allowlist members must exist on the enum.
    saw_allowlist = False
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == ALLOWLIST_NAME
        ):
            saw_allowlist = True
            for sub in ast.walk(node.value):
                if (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == ENUM_NAME
                    and sub.attr not in members
                ):
                    findings.append(
                        Finding(
                            NAME, "PC002", rel_path, sub.lineno, ALLOWLIST_NAME,
                            f"{ALLOWLIST_NAME} names {ENUM_NAME}.{sub.attr}, "
                            f"which is not a member of {ENUM_NAME}",
                        )
                    )
    if not saw_allowlist:
        findings.append(
            Finding(
                NAME, "PC002", rel_path, 1, ALLOWLIST_NAME,
                f"trace allowlist {ALLOWLIST_NAME} not found",
            )
        )
    return findings


def check_mailbox_file(path: str | Path, rel_path: str) -> list[Finding]:
    tree = parse_file(path)
    findings: list[Finding] = []
    consts = _const_int_assigns(tree, prefix=SLOT_PREFIX)
    slots = {k: v for k, v in consts.items() if k.startswith(SLOT_PREFIX)}
    total = consts.get(SLOT_TOTAL)
    if not slots:
        return [
            Finding(NAME, "PC010", rel_path, 1, SLOT_PREFIX + "*", "no slot constants found")
        ]
    values = [v for v, _ in slots.values()]
    if sorted(values) != list(range(len(values))):
        findings.append(
            Finding(
                NAME, "PC010", rel_path, min(l for _, l in slots.values()),
                SLOT_PREFIX + "*",
                f"slot values {sorted(values)} are not unique+contiguous from 0",
            )
        )
    if total is None:
        findings.append(
            Finding(NAME, "PC010", rel_path, 1, SLOT_TOTAL, f"{SLOT_TOTAL} missing")
        )
    elif total[0] != len(slots):
        findings.append(
            Finding(
                NAME, "PC010", rel_path, total[1], SLOT_TOTAL,
                f"{SLOT_TOTAL} == {total[0]} but {len(slots)} slots are declared",
            )
        )
    return findings


def scan_slot_usage(path: str | Path, rel_path: str) -> list[Finding]:
    """PC011: bare integer subscripts on the stat mailbox array."""
    tree = parse_file(path)
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Subscript):
            continue
        base = node.value
        named = (isinstance(base, ast.Name) and base.id in MAILBOX_ARRAY_NAMES) or (
            isinstance(base, ast.Attribute) and base.attr in MAILBOX_ARRAY_NAMES
        )
        if not named:
            continue
        idx = node.slice
        if isinstance(idx, ast.Constant) and isinstance(idx.value, int):
            findings.append(
                Finding(
                    NAME, "PC011", rel_path, node.lineno, "<module>",
                    f"bare index [{idx.value}] into the stat mailbox — use the "
                    "SLOT_* constant from tpu_rl.runtime.mailbox",
                )
            )
    return findings


def _slot_refs(tree: ast.Module, slot_names: set[str]) -> set[str]:
    """Slot constants referenced as load names (imports don't count —
    an unused import is not a reader/writer)."""
    refs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in slot_names:
            refs.add(node.id)
    return refs


def run(root: Path = REPO_ROOT) -> list[Finding]:
    findings = check_protocol_file(root / PROTOCOL_FILE, PROTOCOL_FILE)
    mailbox_path = root / MAILBOX_FILE
    findings.extend(check_mailbox_file(mailbox_path, MAILBOX_FILE))

    slots = {
        k
        for k in _const_int_assigns(parse_file(mailbox_path), prefix=SLOT_PREFIX)
        if k.startswith(SLOT_PREFIX)
    }
    ref_modules: dict[str, set[str]] = {s: set() for s in slots}
    for f in sorted((root / SLOT_USER_DIR).rglob("*.py")):
        rel_path = rel(f, root)
        if rel_path == MAILBOX_FILE:
            continue
        tree = parse_file(f)
        findings.extend(scan_slot_usage(f, rel_path))
        for s in _slot_refs(tree, slots):
            ref_modules[s].add(rel_path)
    for s in sorted(slots):
        mods = ref_modules[s]
        if len(mods) < MIN_SLOT_MODULES:
            findings.append(
                Finding(
                    NAME, "PC012", MAILBOX_FILE, 1, s,
                    f"{s} is referenced in {sorted(mods) or 'no modules'} — a "
                    f"mailbox slot needs both its writer and its reader "
                    f"(>= {MIN_SLOT_MODULES} modules) or it is dead/drifted",
                )
            )
    return findings
