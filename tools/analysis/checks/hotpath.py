"""Hot-path purity: the manifest below names the per-frame functions the
tracemalloc tests pin (tests/test_chaos.py, tests/test_obs.py). Two tiers:

- ``strict``: no string formatting, no logging/print, no comprehensions,
  no non-empty container displays, no known-allocating helpers. These run
  per frame/record at relay rate; a stray f-string is a measured regression.
- ``fmt``: formatting/logging only (f-strings, ``.format``, ``%``-format,
  ``print``, logger calls). For the worker tick, whose JOB is building the
  per-tick payload dict — container allocation is intrinsic there, but
  string rendering belongs in the cold fault helpers.

Empty displays (``parts = []``) are allowed in both tiers: they are the
idiomatic zero-cost accumulator init, not a per-element allocation.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.analysis.engine import (
    Finding,
    REPO_ROOT,
    iter_functions,
    parse_file,
    terminal_name,
)

NAME = "hotpath"

STRICT = "strict"
FMT = "fmt"

# qualname -> tier, per file. Keep in lockstep with the tracemalloc pins:
# adding a pin without a manifest entry leaves the path unchecked statically.
MANIFEST: dict[str, dict[str, str]] = {
    "tpu_rl/runtime/transport.py": {
        "Pub.send_raw": STRICT,
        "Sub.recv_raw": STRICT,
        "_RingWriter.write": STRICT,
        "_RingReader.read": STRICT,
        "ShmPub.send_raw": STRICT,
        "ShmConsumer.drain_frames": STRICT,
    },
    "tpu_rl/runtime/manager.py": {
        "Manager._pump": STRICT,
        "Manager._ingest": STRICT,
    },
    "tpu_rl/runtime/storage.py": {
        "LearnerStorage._ingest": STRICT,
        "LearnerStorage._epoch_admit": STRICT,
        "LearnerStorage._touch_member": STRICT,
        "LearnerStorage._poll_epoch": STRICT,
        "LearnerStorage._ingress_admit": STRICT,
        "MembershipTable.strike": STRICT,
        "MembershipTable.is_quarantined": STRICT,
        "MembershipTable.probe_clear": STRICT,
    },
    "tpu_rl/heal/ingress.py": {
        "IngressGuard.tick_clean": STRICT,
    },
    "tpu_rl/chaos/inject.py": {
        "DataChaos.on_tick": STRICT,
    },
    "tpu_rl/data/assembler.py": {
        "RolloutAssembler.push_tick": STRICT,
    },
    "tpu_rl/obs/goodput.py": {
        # The ledger tick rides every role's main loop (storage: per
        # recv/ingest pass): one float add, no allocation.
        "GoodputLedger.add": STRICT,
    },
    "tpu_rl/runtime/worker.py": {
        "Worker.run": FMT,
    },
    "tpu_rl/runtime/sebulba.py": {
        # The lane seam: both sides cross it once per produced batch, and
        # any blocking inside is *measured* (queue-wait) — allocation here
        # would pollute the backpressure signal itself.
        "BoundedPipe.put": STRICT,
        "BoundedPipe.get": STRICT,
    },
    "tpu_rl/obs/learn.py": {
        # The learning-dynamics fold rides every learner dispatch (one
        # extra device program, zero syncs — the whole plane's overhead
        # contract, bench_diag.cpu.json); the host-side wrapper must stay
        # allocation-free so the cost is the device fold alone. drain() is
        # cold (log cadence) and deliberately NOT pinned.
        "DiagAccumulator.add": STRICT,
    },
}

# Helpers whose call is an allocation/serialization bomb regardless of tier.
ALLOCATING_HELPERS = frozenset({"deepcopy", "dumps", "format_map", "getLogger"})

# Receivers whose method calls are logging, not data flow.
_LOGGER_NAMES = frozenset({"logging", "logger", "log"})


def _visit(fn: ast.AST, tier: str, qualname: str, path: str) -> list[Finding]:
    findings: list[Finding] = []

    def add(code: str, node: ast.AST, msg: str) -> None:
        findings.append(
            Finding(NAME, code, path, getattr(node, "lineno", 0), qualname, msg)
        )

    for node in ast.walk(fn):
        if isinstance(node, ast.JoinedStr):
            add("HP001", node, "f-string allocates per call on a hot path")
        elif isinstance(node, ast.Call):
            t = terminal_name(node.func)
            if t == "format" and isinstance(node.func, ast.Attribute):
                add("HP002", node, "str.format allocates per call on a hot path")
            elif t == "print":
                add("HP006", node, "print on a hot path (I/O + formatting)")
            elif (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in _LOGGER_NAMES
            ):
                add("HP006", node, f"logging call {node.func.value.id}.{t} on a hot path")
            elif t in ALLOCATING_HELPERS:
                add("HP007", node, f"known-allocating helper {t}() on a hot path")
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            if isinstance(node.left, ast.Constant) and isinstance(node.left.value, str):
                add("HP003", node, "%-format allocates per call on a hot path")
        elif tier == STRICT:
            if isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                add("HP004", node, "comprehension allocates per call on a hot path")
            elif isinstance(node, (ast.List, ast.Set)) and node.elts:
                add("HP005", node, "non-empty container literal on a hot path")
            elif isinstance(node, ast.Dict) and node.keys:
                add("HP005", node, "non-empty dict literal on a hot path")
    return findings


def scan_file(
    path: str | Path, manifest: dict[str, str], rel_path: str
) -> list[Finding]:
    """Check the manifest entries of one file. A missing qualname is itself
    a finding (HP000): a rename must not silently drop coverage."""
    tree = parse_file(path)
    fns = dict(iter_functions(tree))
    findings: list[Finding] = []
    for qualname, tier in sorted(manifest.items()):
        fn = fns.get(qualname)
        if fn is None:
            findings.append(
                Finding(
                    NAME, "HP000", rel_path, 1, qualname,
                    "hot-path manifest entry not found in file "
                    "(renamed? update the manifest in checks/hotpath.py)",
                )
            )
            continue
        findings.extend(_visit(fn, tier, qualname, rel_path))
    return findings


def run(root: Path = REPO_ROOT) -> list[Finding]:
    findings: list[Finding] = []
    for rel_path, manifest in MANIFEST.items():
        findings.extend(scan_file(root / rel_path, manifest, rel_path))
    return findings
