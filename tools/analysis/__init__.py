"""Repo-native static analysis plane (``python -m tools.analysis``).

Five AST-based checkers enforce the conventions the runtime tests can only
observe dynamically:

- ``hotpath``:  allocation/logging discipline on the tracemalloc-pinned
  relay/ingest paths (see ``checks/hotpath.py`` for the manifest).
- ``jit``:      no host syncs or untraced side effects inside ``jax.jit`` /
  ``lax.scan`` bodies.
- ``protocol``: wire-struct sizes match declared byte constants, TRACE_KINDS
  stays inside the Protocol enum, mailbox SLOT_* constants are unique and
  contiguous, and no code indexes the stat mailbox with a bare number.
- ``drift``:    metric names in code and in ARCHITECTURE.md's tables agree
  both ways; Config fields are validated or explicitly exempted; the CLI
  override map only names real Config fields.
- ``threads``:  declared background threads only write shared attributes
  under a lock/condition or through the per-thread allowlist.

Waivers live in ``baseline.toml`` (max 10, every entry carries a reason);
fixture-driven tests for each checker are in ``tests/test_analysis.py``.
"""
