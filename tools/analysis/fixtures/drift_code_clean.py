"""Clean drift code fixture: both metrics appear in drift_doc_clean.md."""


class M:
    def go(self, reg):
        reg.counter("relay-frames")
        reg.gauge("queue-depth")
