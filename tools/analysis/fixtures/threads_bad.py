"""Seeded thread fixture: two unguarded writes around one guarded write."""


class W:
    def _run(self):
        self.count = 0
        with self._lock:
            self.ok = True
        self.count += 1
