"""Seeded jit fixture: one host-sync violation per code, lines pinned."""
import time

import jax
import numpy as np


def _body(x):
    print(x)
    t = time.time()
    v = x.item()
    a = np.asarray(x)
    f = float(v)
    return a, t, f


step = jax.jit(_body)
