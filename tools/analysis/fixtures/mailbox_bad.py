"""Seeded mailbox fixture: slot gap plus wrong STAT_SLOTS."""
SLOT_A = 0
SLOT_B = 2
STAT_SLOTS = 3
