"""Clean jit fixture: pure traced body, zero findings expected."""
import jax


def _body(x):
    return x * 2


step = jax.jit(_body)
