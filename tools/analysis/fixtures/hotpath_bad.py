"""Seeded hot-path fixture: one violation per code, lines pinned by tests."""
import json


class Ring:
    def hot_send(self, buf, parts):
        name = f"ring-{len(parts)}"
        name2 = "ring-{}".format(len(parts))
        name3 = "ring-%d" % len(parts)
        lens = [len(p) for p in parts]
        meta = {"n": len(parts)}
        print(name, name2, name3, lens, meta)
        return json.dumps(meta)
