"""Seeded protocol fixture: size drift, enum gap, ghost allowlist member."""
import struct

_HEADER = struct.Struct("<HBBII")
HEADER_BYTES = 10


class Protocol:
    Model = 0
    Rollout = 1
    Batch = 3


TRACE_KINDS = frozenset({Protocol.Rollout, Protocol.Ghost})
