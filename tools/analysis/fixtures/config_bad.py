"""Seeded config fixture: ``batch`` is neither validated nor exempted."""


class Config:
    lr: float = 1e-3
    batch: int = 32

    def validate(self):
        assert self.lr > 0
