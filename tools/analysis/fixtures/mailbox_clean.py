"""Clean mailbox fixture: contiguous slots, matching total."""
SLOT_A = 0
SLOT_B = 1
STAT_SLOTS = 2
