"""Clean hot-path fixture: strict tier, zero findings expected."""
import struct

_U32 = struct.Struct("<I")


class Ring:
    def hot_send(self, buf, parts):
        total = 0
        out = []
        for p in parts:
            total += len(p)
            out.append(p)
        _U32.pack_into(buf, 0, total)
        return total
