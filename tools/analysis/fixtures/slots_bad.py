"""Seeded slot-usage fixture: bare integer indices into the stat mailbox."""


def f(sa):
    sa[3] = 1.0
    return sa[0]
