"""Clean protocol fixture: struct/const, enum, allowlist all consistent."""
import struct

_HEADER = struct.Struct("<HBBII")
HEADER_BYTES = 12


class Protocol:
    Model = 0
    Rollout = 1


TRACE_KINDS = frozenset({Protocol.Rollout})
