"""Seeded drift code fixture: undocumented metric plus a kind collision."""


class M:
    def go(self, reg):
        reg.counter("relay-frames")
        reg.counter("orphan-metric")
        reg.gauge("relay-frames")
