"""Seeded CLI fixture: phantom args read, dead flag, non-Config override."""


def main(parser, args, overrides):
    parser.add_argument("--lr")
    parser.add_argument("--dead-flag")
    overrides["lr"] = args.lr
    overrides["ghost_field"] = args.batch
