"""Clean thread fixture: the only shared write happens under the lock."""


class W:
    def _run(self):
        with self._lock:
            self.done = True
