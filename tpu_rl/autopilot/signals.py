"""Autopilot signal plane: poll the fleet's read-only HTTP endpoints into
a windowed store.

Zero new member-side protocol (the PR 14 controller discipline): every
signal the decision engine consumes already exists on the storage (or
smoke-local) telemetry server —

- ``GET /slo`` — per-rule verdicts with burn rates *and* the burn-rate
  history the engine's sustain windows align with (satellite of this PR);
- ``GET /goodput`` — per-role goodput ratios + the straggler top-k;
- ``GET /metrics`` — the raw Prometheus exposition for any gauge/counter
  a rule names directly.

The scraper flattens one poll into the flat ``{"kind:name": value}``
signal dict :meth:`~tpu_rl.autopilot.policy.DecisionEngine.decide`
takes, and appends every sample into a :class:`SignalStore` ring so the
controller's status document (and the dashboard) can show short series,
not just the latest point. Prometheus sanitizes the repo's dash-named
metrics to underscores; the scraper maps them back (``_`` -> ``-``) so
rules are written in the same dash convention as every spec grammar in
the repo.

stdlib-only (urllib via :mod:`tpu_rl.obs.top` helpers), injectable
clock, and a fetch function injection point so tests drive it with
canned documents instead of sockets.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable

from tpu_rl.obs.top import fetch, fetch_json, parse_prometheus


class SignalStore:
    """Windowed per-signal sample ring: ``{key: deque[(t, value)]}``."""

    def __init__(
        self,
        window_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.window_s = float(window_s)
        self._clock = clock
        self._series: dict[str, deque] = {}

    def put(self, key: str, value: float, t: float | None = None) -> None:
        t = self._clock() if t is None else t
        ring = self._series.setdefault(key, deque())
        if ring and t <= ring[-1][0]:
            return  # replayed history (e.g. /slo burn_history): keep monotonic
        ring.append((t, float(value)))
        while ring and t - ring[0][0] > self.window_s:
            ring.popleft()

    def latest(self, key: str) -> float | None:
        ring = self._series.get(key)
        return ring[-1][1] if ring else None

    def series(self, key: str) -> list:
        return list(self._series.get(key, ()))

    def snapshot(self) -> dict:
        """Latest value per signal — the status-doc view."""
        return {k: ring[-1][1] for k, ring in self._series.items() if ring}


class SignalScraper:
    """One poll = three GETs -> (signals dict, meta dict).

    Partial availability is normal (a 404 ``/goodput`` on a fleet without
    the ledger, a brief connection refusal while the server binds): each
    endpoint contributes what it has and silence never fabricates a
    value — the engine holds streaks on missing signals.
    """

    def __init__(
        self,
        base_url: str,
        store: SignalStore | None = None,
        timeout_s: float = 2.0,
        fetch_fn: Callable = fetch,
        fetch_json_fn: Callable = fetch_json,
    ):
        self.base_url = base_url.rstrip("/")
        self.store = store if store is not None else SignalStore()
        self.timeout_s = float(timeout_s)
        self._fetch = fetch_fn
        self._fetch_json = fetch_json_fn
        self.n_polls = 0
        self.n_errors = 0

    def poll(self, now: float | None = None) -> tuple[dict, dict]:
        now = self.store._clock() if now is None else now
        self.n_polls += 1
        signals: dict = {}
        meta: dict = {}
        self._poll_slo(signals, now)
        self._poll_goodput(signals, meta, now)
        self._poll_metrics(signals, now)
        for key, value in signals.items():
            self.store.put(key, value, t=now)
        return signals, meta

    # ------------------------------------------------------------ endpoints
    def _poll_slo(self, signals: dict, now: float) -> None:
        doc = self._fetch_json(self.base_url + "/slo", self.timeout_s)
        if not isinstance(doc, dict) or "rules" not in doc:
            self.n_errors += 1
            return
        for row in doc.get("rules", ()):
            if not isinstance(row, dict):
                continue
            metric, burn = row.get("metric"), row.get("burn_rate")
            if metric is None or burn is None:
                continue
            key = f"burn:{metric}"
            # Several rules may watch one metric: the worst burn governs.
            signals[key] = max(float(burn), signals.get(key, 0.0))
            # Replay the server-side history so the store's series matches
            # what the engine's sustain window actually saw — same data,
            # one source of truth (the satellite-3 /slo payload).
            for point in row.get("burn_history", ()) or ():
                try:
                    t_hist, b_hist = float(point[0]), float(point[1])
                except (TypeError, ValueError, IndexError):
                    continue
                self.store.put(key, b_hist, t=t_hist)

    def _poll_goodput(self, signals: dict, meta: dict, now: float) -> None:
        doc = self._fetch_json(self.base_url + "/goodput", self.timeout_s)
        if not isinstance(doc, dict):
            return  # 404 (no ledger) is a normal fleet shape, not an error
        by_role: dict[str, list] = {}
        for key, row in (doc.get("roles") or {}).items():
            goodput = (row or {}).get("goodput")
            if goodput is None:
                continue
            role = str(key).partition("/")[0]
            by_role.setdefault(role, []).append(float(goodput))
        for role, values in by_role.items():
            signals[f"goodput:{role}"] = sum(values) / len(values)
        stragglers = doc.get("stragglers") or []
        if stragglers and isinstance(stragglers[0], dict):
            top = stragglers[0]
            score = top.get("score")
            if score is not None:
                signals["straggler:score"] = float(score)
                if top.get("wid") is not None:
                    meta["straggler_wid"] = top["wid"]

    def _poll_metrics(self, signals: dict, now: float) -> None:
        status, body = self._fetch(self.base_url + "/metrics", self.timeout_s)
        if status != 200:
            self.n_errors += 1
            return
        gauges: dict[str, float] = {}
        counters: dict[str, float] = {}
        for name, _labels, value in parse_prometheus(body):
            # Histogram series (_bucket/_sum/_count/_p99) keep their
            # suffixes and never collide with gauge/counter family names.
            key = name.replace("_", "-")
            gauges[key] = max(gauges.get(key, float("-inf")), value)
            counters[key] = counters.get(key, 0.0) + value
        kinds = _family_kinds(body)
        for key in gauges:
            fam = kinds.get(key)
            if fam == "gauge":
                signals[f"gauge:{key}"] = gauges[key]
            elif fam == "counter":
                signals[f"counter:{key}"] = counters[key]


# Channel-name prefix under which the controller persists every scraped
# signal sample into its run-history store (one ``signals/<key>`` channel
# per SignalStore key, all kinds: burn/goodput/straggler/gauge/counter).
SIGNAL_CHANNEL_PREFIX = "signals/"


def signal_channels(store: SignalStore) -> dict[str, float]:
    """The store's latest values as history channels — what the controller
    hands to ``TimeSeriesStore.record(extra=...)`` each exporter tick."""
    return {
        SIGNAL_CHANNEL_PREFIX + key: value
        for key, value in store.snapshot().items()
    }


def rehydrate_signals(
    store: SignalStore,
    reader,
    now_wall: float | None = None,
    now_mono: float | None = None,
) -> int:
    """Refill a :class:`SignalStore`'s windows from a history store after
    a controller restart, so sustain streaks resume where the dead
    controller left off instead of restarting from empty — for EVERY
    signal kind (goodput/straggler/gauge/counter/burn), not just the
    ``/slo`` ``burn_history`` replay.

    ``reader`` is a :class:`tpu_rl.obs.history.HistoryReader` (duck-typed:
    ``series()`` + ``points()``). History timestamps are wall-clock; the
    store's rings are monotonic — samples are converted through the
    current wall-to-monotonic offset, and anything that would land in the
    monotonic future (cross-boot history, clock steps) is dropped so the
    ring's monotonic guard never rejects future LIVE samples. Returns the
    number of samples restored."""
    now_wall = time.time() if now_wall is None else now_wall
    now_mono = store._clock() if now_mono is None else now_mono
    offset = now_wall - now_mono  # t_wall = t_mono + offset
    horizon = now_wall - store.window_s
    n = 0
    for ch in sorted(reader.series()):
        if not ch.startswith(SIGNAL_CHANNEL_PREFIX):
            continue
        key = ch[len(SIGNAL_CHANNEL_PREFIX):]
        for t_wall, value in reader.points(ch, start=horizon):
            t_mono = t_wall - offset
            if t_mono > now_mono:
                continue
            store.put(key, value, t=t_mono)
            n += 1
    return n


def _family_kinds(body: str) -> dict:
    """``# TYPE`` lines -> {dash-name: kind} (histogram families skipped)."""
    kinds: dict[str, str] = {}
    for line in body.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4 and parts[3] in ("gauge", "counter"):
                kinds[parts[2].replace("_", "-")] = parts[3]
    return kinds
