"""AutopilotController: the closed-loop elastic-capacity orchestrator.

The controller is the orchestrator process itself (the ``autopilot`` CLI
role runs it in the main process, exactly as ``population`` runs the PBT
controller): it owns one :class:`~tpu_rl.runtime.runner.Supervisor`
whose children are the elastic fleet members it manages —
``inference-<i>`` replicas on the portplan's pre-planned port range, and
(optionally) extra workers — plus the autopilot's own telemetry
registry, audit log and status document.

Control flow per poll tick (single-threaded — no new threads beyond the
telemetry HTTP server; the members are processes and the signal scrape
is HTTP against endpoints that already exist):

1. chaos poll + supervision pass (crash/silence respawns — a chaos
   ``kill:inference-*`` mid-scale is absorbed by the same machinery),
2. scrape ``/slo`` + ``/goodput`` + ``/metrics`` into the windowed
   signal store (:mod:`tpu_rl.autopilot.signals`),
3. run the decision engine (:mod:`tpu_rl.autopilot.policy`) over the
   latest signals and current member counts,
4. actuate each decision: spawn the next planned replica index, drain +
   retire the highest, or evict-and-respawn a pegged straggler worker
   (the deliberate-restart pattern — no restart budget burned),
5. publish ``autopilot-*`` gauges/counters and refresh the status doc.

Scaling stays inside the pre-planned port range, so ``FleetClient``
discovery (lane re-probe, this PR) and the version floor work
unchanged: a scaled-out replica self-announces on the stat channel,
leases into the ReplicaTable, and receives the learner's join-push of
current weights — the floor never decreases across any action.

Every decision appends one line to ``result_dir/autopilot.jsonl``
(:mod:`tpu_rl.obs.audit`); the final summary is written
crash-atomically to ``result_dir/autopilot.json``.
"""

from __future__ import annotations

import functools
import json
import os
import time
from collections import deque
from typing import Any, Callable

from tpu_rl.autopilot.policy import AutopilotSpec, DecisionEngine
from tpu_rl.autopilot.signals import SignalScraper, SignalStore
from tpu_rl.config import Config, MachinesConfig

# Status doc keeps the last N actions for the dashboard panel.
RECENT_ACTIONS = 20


class ReplicaSet:
    """The inference-replica actuator arm: spawn/retire ``inference-<i>``
    children through the controller's supervisor, always inside the
    pre-planned port range.

    ``static`` replicas (indices ``0..static-1``) are owned elsewhere —
    the learner's in-process replica 0 and ``learner_role``'s children —
    and are never touched; the autopilot manages ``static..capacity-1``.
    A standalone deployment (the smoke) sets ``static=0`` and the
    autopilot owns the whole range.
    """

    def __init__(
        self,
        sup,
        cfg: Config,
        machines: MachinesConfig,
        capacity: int,
        static: int = 0,
        seed: int = 0,
    ):
        assert 0 <= static <= capacity, (static, capacity)
        self.sup = sup
        self.cfg = cfg
        self.machines = machines
        self.capacity = capacity
        self.static = static
        self.seed = seed
        # Plan the FULL range once: scale-outs reuse pre-checked ports, so
        # a scaled-out replica lands exactly where FleetClient's planned
        # lane list (and its re-probe backoff) already points.
        self.ports = machines.inference_ports(
            cfg.replace(inference_replicas=capacity)
        )
        self._children: dict[int, Any] = {}  # managed index -> runner.Child

    @property
    def count(self) -> int:
        """Total fleet replica count (static members + managed children,
        retired ones excluded)."""
        return self.static + len(self._children)

    def spawn_index(self, i: int):
        from tpu_rl.fleet import replica_main

        child = self.sup.spawn(
            f"inference-{i}",
            functools.partial(replica_main, seed=self.seed),
            self.cfg,
            i,
            self.ports[i],
            self.machines.learner_ip,
            self.machines.model_port,
            self.machines.learner_port,
            cpu_only=(self.cfg.learner_device == "cpu"),
        )
        self._children[i] = child
        return child

    def retire_index(self, i: int, drain_s: float) -> None:
        """Drain then kill: in-flight requests are ms-scale, so a bounded
        grace before the SIGTERM lets them complete; clients absorb the
        tail through hedging and re-probe the lane when (if) the index
        returns. The retired Child must leave ``sup.children`` — the
        supervisor would otherwise read the nonzero exit as a crash and
        respawn what the autopilot just scaled in."""
        child = self._children.pop(i)
        if drain_s > 0:
            time.sleep(drain_s)
        self.sup._ensure_dead(child)
        self.sup.children.remove(child)

    def scale_to(self, target: int) -> list[dict]:
        """Move the TOTAL count to ``target`` (clamped to
        [static, capacity]); returns one audit record per member moved."""
        target = max(self.static, min(target, self.capacity))
        events = []
        while self.count < target:
            i = next(
                j for j in range(self.static, self.capacity)
                if j not in self._children
            )
            self.spawn_index(i)
            events.append(
                {"ev": "spawn", "kind": "replica", "index": i,
                 "port": self.ports[i]}
            )
        while self.count > target:
            i = max(self._children)
            port = self.ports[i]
            self.retire_index(i, drain_s=self.cfg.autopilot_drain_s)
            events.append(
                {"ev": "retire", "kind": "replica", "index": i, "port": port,
                 "drain_s": self.cfg.autopilot_drain_s}
            )
        return events


class AutopilotController:
    """Close the loop from fleet health signals to fleet shape. See the
    module docstring for the tick structure."""

    def __init__(
        self,
        cfg: Config,
        machines: MachinesConfig | None = None,
        manage_all: bool = False,
        scrape_url: str | None = None,
        http_port: int | None = None,
        worker_spawn: Callable[[Any, int], Any] | None = None,
        seed: int = 0,
        log: bool = True,
        on_event: Callable[[dict], None] | None = None,
    ):
        assert cfg.autopilot_spec, "autopilot role needs Config.autopilot_spec"
        assert cfg.result_dir, (
            "autopilot role needs result_dir: decisions audit to "
            "result_dir/autopilot.jsonl"
        )
        self.spec = AutopilotSpec.parse(cfg.autopilot_spec)
        self.base = cfg
        self.machines = machines or MachinesConfig()
        self.log = log
        self.on_event = on_event
        self.worker_spawn = worker_spawn

        from tpu_rl.runtime.runner import Supervisor

        self.sup = Supervisor.from_config(cfg)
        self.engine = DecisionEngine(self.spec)
        self.store = SignalStore()
        url = scrape_url or (
            f"http://{self.machines.learner_ip}:{cfg.telemetry_port}"
        )
        self.scraper = SignalScraper(url, store=self.store)

        hi_bounds = [
            r.hi for r in self.spec.rules
            if r.target == "replicas" and r.hi is not None
        ]
        capacity = max([cfg.inference_replicas, *hi_bounds])
        # manage_all: standalone fleets (the smoke) where the autopilot IS
        # the replica owner from index 0; otherwise the statically
        # provisioned members (learner-owned 0..N-1) are off-limits and
        # the autopilot manages only the elastic tail.
        static = 0 if manage_all else cfg.inference_replicas
        self.replicas = ReplicaSet(
            self.sup, cfg, self.machines, capacity=capacity, static=static,
            seed=seed,
        )
        self._initial = cfg.inference_replicas if manage_all else 0

        self._next_worker_idx = 1000  # autopilot-spawned worker name suffix
        self.counts = {
            "actions": 0, "scale_out": 0, "scale_in": 0, "respawns": 0,
            "straggler_respawns": 0, "chaos": 0, "skipped": 0,
        }
        self._recent: deque = deque(maxlen=RECENT_ACTIONS)

        self.aggregator = None
        self.registry = None
        self._http = None
        self._json_exp = None
        self._telem_pub = None
        self._emitter = None
        self._history = None
        self._http_port = (
            http_port if http_port is not None
            else (cfg.telemetry_port + 1 if cfg.telemetry_port > 0 else 0)
        )
        self._setup_telemetry()
        # Restart rehydration (run-history plane): a respawned controller
        # inherits the dead one's signal windows — ALL kinds, so sustain
        # streaks resume instead of restarting from empty.
        self.n_rehydrated = 0
        if self._history is not None:
            from tpu_rl.autopilot.signals import rehydrate_signals

            self.n_rehydrated = rehydrate_signals(self.store, self._history)

    # ------------------------------------------------------------- telemetry
    def _setup_telemetry(self) -> None:
        cfg = self.base
        if not cfg.telemetry_enabled:
            return
        from tpu_rl.obs import (
            JsonExporter,
            MetricsRegistry,
            PeriodicSnapshot,
            TelemetryAggregator,
            TelemetryHTTPServer,
            maybe_history,
        )
        from tpu_rl.runtime.protocol import Protocol
        from tpu_rl.runtime.transport import make_data_pub

        self.registry = MetricsRegistry(role="autopilot")
        self.aggregator = TelemetryAggregator(
            registry=self.registry, stale_after_s=cfg.telemetry_stale_s
        )
        # The autopilot-* registry rides the fleet's stat channel (the
        # storage SUB on the learner host) so the gauges land on the SAME
        # /metrics page every other role reports to.
        self._telem_pub = make_data_pub(
            cfg, self.machines.learner_ip, self.machines.learner_port,
            bind=False,
        )
        pub = self._telem_pub
        self._emitter = PeriodicSnapshot(
            self.registry,
            lambda snap: pub.send(Protocol.Telemetry, snap),
            interval_s=cfg.telemetry_interval_s,
        )
        # Self-served history store (the controller is its own storage
        # side): autopilot-* metrics plus every scraped signal window, fed
        # on the exporter cadence, queryable live and rehydrated on restart.
        self._history = maybe_history(cfg)
        if self._http_port > 0:
            self._http = TelemetryHTTPServer(
                self.aggregator, self._http_port, autopilot=self.status_doc,
                query=(
                    self._history.http_query
                    if self._history is not None else None
                ),
            )
        self._json_exp = JsonExporter(
            self.aggregator,
            os.path.join(cfg.result_dir, "telemetry.json"),
            interval_s=cfg.telemetry_interval_s,
        )

    def _tick_metrics(self) -> None:
        if self.registry is None:
            return
        reg = self.registry
        reg.gauge("autopilot-replicas").set(float(self.replicas.count))
        reg.gauge("autopilot-workers").set(float(self._worker_count()))
        reg.counter("autopilot-actions").set_total(self.counts["actions"])
        reg.counter("autopilot-scale-out").set_total(self.counts["scale_out"])
        reg.counter("autopilot-scale-in").set_total(self.counts["scale_in"])
        reg.counter("autopilot-respawns").set_total(
            self.counts["straggler_respawns"]
        )
        reg.counter("autopilot-rate-limited").set_total(
            self.engine.n_rate_limited
        )
        reg.counter("autopilot-clamped").set_total(self.engine.n_clamped)
        reg.counter("autopilot-scrape-errors").set_total(self.scraper.n_errors)
        if self._emitter is not None:
            self._emitter.maybe_emit()
        if self._json_exp is not None and self._json_exp.maybe_export():
            if self._history is not None:
                from tpu_rl.autopilot.signals import signal_channels

                # One history row per export: own metrics + the latest
                # value of every scraped signal (the rehydration source).
                self._history.record(
                    self.aggregator, extra=signal_channels(self.store)
                )

    # ----------------------------------------------------------------- audit
    def _event(self, ev: dict) -> None:
        from tpu_rl.obs.audit import append_jsonl

        ev = {**ev, "t": time.time()}
        append_jsonl(self.base.result_dir, "autopilot.jsonl", ev)
        if self.log:
            print(f"[autopilot] {json.dumps(ev)}", flush=True)
        if self.on_event is not None:
            self.on_event(ev)

    # ------------------------------------------------------------ status doc
    def status_doc(self) -> dict:
        """The live ``GET /autopilot`` payload (and the dashboard panel's
        input): counts, recent actions with reasons, cooldown status."""
        return {
            "replicas": self.replicas.count,
            "replica_capacity": self.replicas.capacity,
            "workers": self._worker_count(),
            "actions": list(self._recent),
            "cooldowns": self.engine.cooldowns(),
            "counts": dict(self.counts),
            "rate_limited": self.engine.n_rate_limited,
            "clamped": self.engine.n_clamped,
            "rehydrated": self.n_rehydrated,
            "signals": self.store.snapshot(),
        }

    def _worker_count(self) -> int:
        return sum(
            1 for c in self.sup.children
            if c.name.startswith("worker-") and c.proc.is_alive()
        )

    # -------------------------------------------------------------- actuation
    def _apply(self, decision: dict) -> None:
        action, target = decision["action"], decision["target"]
        if action == "respawn":
            self._respawn_worker(decision)
            return
        if target == "replicas":
            events = self.replicas.scale_to(decision["to"])
            if not events:
                self.counts["skipped"] += 1
                self._event(
                    {**decision, "ev": "action-skip",
                     "skip_reason": "replica count already at bound"}
                )
                return
            self._record_action(decision)
            for sub in events:
                self._event(sub)
            return
        # target == "workers"
        if action == "scale_out":
            if self.worker_spawn is None:
                self.counts["skipped"] += 1
                self._event(
                    {**decision, "ev": "action-skip",
                     "skip_reason": "no worker spawn factory wired"}
                )
                return
            for _ in range(decision["step"]):
                idx = self._next_worker_idx
                self._next_worker_idx += 1
                self.worker_spawn(self.sup, idx)
                self._event({"ev": "spawn", "kind": "worker", "index": idx})
            self._record_action(decision)
        else:  # scale_in: retire the newest autopilot-spawned workers first
            managed = [
                c for c in self.sup.children
                if c.name.startswith("worker-a-") and c.proc.is_alive()
            ]
            if not managed:
                self.counts["skipped"] += 1
                self._event(
                    {**decision, "ev": "action-skip",
                     "skip_reason": "no autopilot-managed workers to retire"}
                )
                return
            for child in sorted(managed, key=lambda c: c.name)[
                -decision["step"]:
            ]:
                self.sup._ensure_dead(child)
                self.sup.children.remove(child)
                self._event(
                    {"ev": "retire", "kind": "worker", "child": child.name}
                )
            self._record_action(decision)

    def _respawn_worker(self, decision: dict) -> None:
        wid = decision.get("wid")
        suffix = f"-{wid}"
        child = next(
            (
                c for c in self.sup.children
                if c.name.startswith("worker-") and c.name.endswith(suffix)
                and not c.exhausted
            ),
            None,
        )
        if child is None:
            self.counts["skipped"] += 1
            self._event(
                {**decision, "ev": "action-skip",
                 "skip_reason": f"no supervised child for wid {wid}"}
            )
            return
        # Deliberate evict-and-respawn (the population exploit pattern):
        # straight back through _start, no restart budget burned — the
        # straggler is presumed wedged, not buggy. Quarantine (PR 13) at
        # the storage edge remains the data-plane enforcement arm; this is
        # the process-plane one.
        self.sup._ensure_dead(child)
        self.sup._start(child)
        self.counts["straggler_respawns"] += 1
        self._record_action({**decision, "child": child.name})

    def _record_action(self, decision: dict) -> None:
        self.counts["actions"] += 1
        if decision["action"] == "scale_out":
            self.counts["scale_out"] += 1
        elif decision["action"] == "scale_in":
            self.counts["scale_in"] += 1
        record = {**decision, "ev": "action", "replicas": self.replicas.count,
                  "workers": self._worker_count()}
        self._recent.append({**record, "t": time.time()})
        self._event(record)

    # ------------------------------------------------------------------- run
    def install_signal_handlers(self) -> None:
        self.sup.install_signal_handlers()

    def run(self) -> dict:
        """Drive the loop until external stop (the normal end for a pilot
        daemon) or a child exhausting its restart budget (failure).
        Returns the final summary (also at ``result_dir/autopilot.json``)."""
        os.makedirs(self.base.result_dir, exist_ok=True)
        self._event(
            {
                "ev": "start",
                "spec": self.base.autopilot_spec,
                "capacity": self.replicas.capacity,
                "static": self.replicas.static,
                "initial": self._initial,
                "rules": len(self.spec.rules),
                "scrape_url": self.scraper.base_url,
            }
        )
        if self._initial:
            for sub in self.replicas.scale_to(
                self.replicas.static + self._initial
            ):
                self._event(sub)
        poll = self.base.autopilot_poll_s
        ok = True
        while not self.sup.stop_event.is_set():
            if self.sup.chaos is not None:
                for action, name in self.sup.chaos.poll(self.sup.children):
                    self.counts["chaos"] += 1
                    self._event(
                        {"ev": "chaos", "action": action, "target": name}
                    )
            for name in self.sup.check():
                self.counts["respawns"] += 1
                self._event({"ev": "respawn", "child": name})
            signals, meta = self.scraper.poll()
            counts = {
                "replicas": self.replicas.count,
                "workers": self._worker_count(),
            }
            for decision in self.engine.decide(signals, counts, meta=meta):
                self._apply(decision)
            self._tick_metrics()
            if any(c.exhausted for c in self.sup.children):
                self._event({"ev": "exhausted"})
                ok = False
                break
            time.sleep(poll)
        self.sup.stop()
        self._tick_metrics()
        doc = {
            "ok": ok,
            "replicas": self.replicas.count,
            "workers": self._worker_count(),
            "counts": dict(self.counts),
            "rate_limited": self.engine.n_rate_limited,
            "clamped": self.engine.n_clamped,
            "decisions": self.engine.n_decisions,
            "polls": self.scraper.n_polls,
        }
        self._write_doc(doc)
        if self._emitter is not None:
            self._emitter.maybe_emit(now=float("inf"))
        if self._json_exp is not None:
            self._json_exp.maybe_export(now=float("inf"))
        if self._history is not None:
            from tpu_rl.autopilot.signals import signal_channels

            # Final row + release the active chunk handle.
            self._history.record(
                self.aggregator, extra=signal_channels(self.store)
            )
            self._history.close()
        if self._http is not None:
            self._http.close()
        if self._telem_pub is not None:
            self._telem_pub.close()
        self._event({"ev": "done", "ok": ok, "counts": dict(self.counts)})
        return doc

    def _write_doc(self, doc: dict) -> None:
        path = os.path.join(self.base.result_dir, "autopilot.json")
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
