"""Autopilot decision plane: declarative scaling rules + the engine.

One spec string (``Config.autopilot_spec``, chaos-grammar style: parsed
once at config validation, consumed only in resolved form) maps the
fleet's read-only health signals — SLO burn rates, goodput ratios,
straggler scores, raw gauges/counters — to the three actions the
actuator knows how to take: scale inference replicas, scale workers,
evict-and-respawn a pegged straggler.

Grammar (comma-separated clauses)::

    spec      := clause ("," clause)*
    clause    := rule | limit
    rule      := action ":" target "?" signal op value ("@" qualifier)*
    action    := scale_out | scale_in      (targets: replicas | workers)
               | respawn                   (target: worker)
    signal    := "burn:" metric            (per-rule /slo burn rate, 0..1)
               | "gauge:" name             (fleet-max gauge off /metrics)
               | "counter:" name           (fleet-sum counter off /metrics)
               | "goodput:" role           (role goodput ratio off /goodput)
               | "straggler:score"         (top straggler score off /goodput)
    op        := "<" | "<=" | ">" | ">=" | "=="
    qualifier := "sustain=<polls>"         (consecutive satisfied polls, default 3)
               | "cooldown=<seconds>s"     (per-rule refractory, default 30s)
               | "step=<n>"                (members moved per firing, default 1)
               | "min=<n>" | "max=<n>"     (hard bounds on the target count)
    limit     := "limit=" n "/" seconds "s"  (global action rate cap,
                                              default 6/60s)

Example — the closed loop the smoke drives::

    scale_out:replicas?burn:inference-rtt>0.5@sustain=3@cooldown=6s@max=3,
    scale_in:replicas?burn:inference-rtt<0.05@sustain=8@cooldown=8s@min=1,
    respawn:worker?straggler:score>8@sustain=10@cooldown=60s,
    limit=6/60s

Anti-flap semantics (all enforced by :class:`DecisionEngine`, all
covered by synthetic-trace tests):

- **sustain**: a rule arms only after its predicate held for N
  *consecutive* polls — one blip resets the streak, so slow drift and
  flapping signals never fire;
- **cooldown**: a fired rule is refractory for its cooldown — a
  sustained burn produces exactly one action per cooldown window;
- **hysteresis**: a firing resets the streak of *every* rule aimed at
  the same target, so an opposing rule must re-earn its full sustain
  from scratch after any movement — out/in oscillation is structurally
  impossible within one sustain window;
- **bounds**: ``min``/``max`` clamp the target count; a firing that
  cannot move the count is dropped (counted, no cooldown burned);
- **rate limit**: one global token bucket across all rules — a
  misconfigured spec can never churn the fleet faster than
  ``limit_n`` actions per ``limit_window_s``.

Pure stdlib with an injectable clock, so ``Config.validate()`` can
parse-check specs without importing jax and the engine is exactly
reproducible under synthetic traces.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

ACTIONS = frozenset({"scale_out", "scale_in", "respawn"})
SCALE_TARGETS = frozenset({"replicas", "workers"})
SIGNAL_KINDS = frozenset({"burn", "gauge", "counter", "goodput", "straggler"})
DEFAULT_SUSTAIN = 3
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_LIMIT_N = 6
DEFAULT_LIMIT_WINDOW_S = 60.0
# Longest-first so "<=" wins over "<" (same table discipline as slo.py).
_OPS: tuple[tuple[str, Callable[[float, float], bool]], ...] = (
    ("<=", lambda v, t: v <= t),
    (">=", lambda v, t: v >= t),
    ("==", lambda v, t: v == t),
    ("<", lambda v, t: v < t),
    (">", lambda v, t: v > t),
)


@dataclass(frozen=True)
class Rule:
    """One resolved rule clause."""

    raw: str
    action: str
    target: str
    signal: str  # full "kind:name" key into the signal dict
    op: str
    threshold: float
    sustain: int = DEFAULT_SUSTAIN
    cooldown_s: float = DEFAULT_COOLDOWN_S
    step: int = 1
    lo: int | None = None
    hi: int | None = None

    def check(self, value: float) -> bool:
        for sym, fn in _OPS:
            if sym == self.op:
                return fn(value, self.threshold)
        raise ValueError(f"autopilot rule {self.raw!r}: unknown op {self.op!r}")


@dataclass(frozen=True)
class AutopilotSpec:
    """Parsed spec: the rule list plus the global action rate limit."""

    rules: tuple[Rule, ...]
    limit_n: int = DEFAULT_LIMIT_N
    limit_window_s: float = DEFAULT_LIMIT_WINDOW_S

    @staticmethod
    def parse(spec: str) -> "AutopilotSpec":
        """Parse a full spec; every ``ValueError`` names the offending
        clause. Empty/whitespace spec -> no rules (a do-nothing pilot)."""
        rules: list[Rule] = []
        limit_n, limit_window_s = DEFAULT_LIMIT_N, DEFAULT_LIMIT_WINDOW_S
        for clause in spec.split(","):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("limit="):
                limit_n, limit_window_s = _parse_limit(clause)
            else:
                rules.append(_parse_rule(clause))
        return AutopilotSpec(
            rules=tuple(rules), limit_n=limit_n, limit_window_s=limit_window_s
        )


def _int_field(clause: str, name: str, text: str, lo: int = 0) -> int:
    try:
        value = int(text)
    except ValueError:
        raise ValueError(
            f"autopilot clause {clause!r}: bad {name} {text!r} "
            "(expected an integer)"
        ) from None
    if value < lo:
        raise ValueError(
            f"autopilot clause {clause!r}: {name} must be >= {lo}, got {value}"
        )
    return value


def _parse_limit(clause: str) -> tuple[int, float]:
    body = clause[len("limit="):]
    n_text, sep, win_text = body.partition("/")
    if not sep or not win_text.endswith("s"):
        raise ValueError(
            f"autopilot clause {clause!r}: expected 'limit=<n>/<seconds>s'"
        )
    n = _int_field(clause, "limit count", n_text, lo=1)
    try:
        window_s = float(win_text[:-1])
    except ValueError:
        window_s = -1.0
    if window_s <= 0:
        raise ValueError(
            f"autopilot clause {clause!r}: bad limit window {win_text!r} "
            "(expected '<seconds>s', positive)"
        )
    return n, window_s


def _parse_rule(clause: str) -> Rule:
    head, sep, tail = clause.partition("?")
    if not sep:
        raise ValueError(
            f"autopilot clause {clause!r}: no '?' predicate separator "
            "(expected 'action:target?signal op value')"
        )
    action, sep, target = head.partition(":")
    action, target = action.strip(), target.strip()
    if not sep or action not in ACTIONS:
        raise ValueError(
            f"autopilot clause {clause!r}: unknown action {action!r} "
            f"(expected one of {sorted(ACTIONS)})"
        )
    if action == "respawn":
        if target != "worker":
            raise ValueError(
                f"autopilot clause {clause!r}: respawn targets 'worker', "
                f"got {target!r}"
            )
    elif target not in SCALE_TARGETS:
        raise ValueError(
            f"autopilot clause {clause!r}: unknown target {target!r} "
            f"(expected one of {sorted(SCALE_TARGETS)})"
        )

    body, *quals = tail.split("@")
    for sym, _fn in _OPS:
        signal, sep, value_text = body.partition(sym)
        if sep:
            op = sym
            break
    else:
        raise ValueError(
            f"autopilot clause {clause!r}: no comparison "
            "(expected < <= > >= ==)"
        )
    signal = signal.strip()
    kind, sep, name = signal.partition(":")
    if not sep or kind not in SIGNAL_KINDS or not name:
        raise ValueError(
            f"autopilot clause {clause!r}: bad signal {signal!r} "
            f"(expected '<kind>:<name>' with kind one of "
            f"{sorted(SIGNAL_KINDS)})"
        )
    try:
        threshold = float(value_text.strip())
    except ValueError:
        raise ValueError(
            f"autopilot clause {clause!r}: bad threshold "
            f"{value_text.strip()!r} (expected a float)"
        ) from None

    sustain, cooldown_s, step = DEFAULT_SUSTAIN, DEFAULT_COOLDOWN_S, 1
    lo: int | None = None
    hi: int | None = None
    for qual in quals:
        qual = qual.strip()
        key, sep, val = qual.partition("=")
        if not sep:
            raise ValueError(
                f"autopilot clause {clause!r}: unknown qualifier {qual!r} "
                "(expected sustain=/cooldown=/step=/min=/max=)"
            )
        if key == "sustain":
            sustain = _int_field(clause, "sustain", val, lo=1)
        elif key == "cooldown":
            if not val.endswith("s"):
                raise ValueError(
                    f"autopilot clause {clause!r}: bad cooldown {val!r} "
                    "(expected '<seconds>s')"
                )
            try:
                cooldown_s = float(val[:-1])
            except ValueError:
                cooldown_s = -1.0
            if cooldown_s < 0:
                raise ValueError(
                    f"autopilot clause {clause!r}: bad cooldown {val!r} "
                    "(expected '<seconds>s', non-negative)"
                )
        elif key == "step":
            step = _int_field(clause, "step", val, lo=1)
        elif key == "min":
            lo = _int_field(clause, "min", val)
        elif key == "max":
            hi = _int_field(clause, "max", val)
        else:
            raise ValueError(
                f"autopilot clause {clause!r}: unknown qualifier {qual!r} "
                "(expected sustain=/cooldown=/step=/min=/max=)"
            )
    if lo is not None and hi is not None and lo > hi:
        raise ValueError(
            f"autopilot clause {clause!r}: min={lo} > max={hi}"
        )
    return Rule(
        raw=clause, action=action, target=target, signal=signal, op=op,
        threshold=threshold, sustain=sustain, cooldown_s=cooldown_s,
        step=step, lo=lo, hi=hi,
    )


# ------------------------------------------------------------------ engine
class DecisionEngine:
    """Deterministic rule evaluator: :meth:`decide` once per poll tick.

    Stateless about the fleet (current counts come in as an argument) and
    pure given (signals, counts, now) — the controller owns actuation;
    this class only says *what* to do and enforces every anti-flap
    guarantee documented in the module docstring.
    """

    def __init__(
        self,
        spec: AutopilotSpec,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.spec = spec
        self._clock = clock
        self._streak = [0] * len(spec.rules)
        self._cooldown_until = [0.0] * len(spec.rules)
        self._fires: deque = deque()  # global rate-limit window
        self.n_decisions = 0
        self.n_rate_limited = 0
        self.n_clamped = 0

    def decide(
        self,
        signals: dict,
        counts: dict,
        now: float | None = None,
        meta: dict | None = None,
    ) -> list[dict]:
        """One pass over all rules -> the (possibly empty) decision list.

        ``signals`` maps full signal keys (``"burn:inference-rtt"``) to the
        latest value; a missing signal HOLDS the rule's streak (silence is
        not evidence either way). ``counts`` maps targets (``"replicas"``,
        ``"workers"``) to current member counts. ``meta`` carries action
        context — ``straggler_wid`` for respawn decisions.
        """
        now = self._clock() if now is None else now
        meta = meta or {}
        decisions: list[dict] = []
        fired_targets: set[str] = set()
        for i, rule in enumerate(self.spec.rules):
            value = signals.get(rule.signal)
            if value is None:
                continue  # no data: hold the streak, never fire on silence
            if not rule.check(float(value)):
                self._streak[i] = 0
                continue
            self._streak[i] += 1
            if self._streak[i] < rule.sustain:
                continue
            if now < self._cooldown_until[i]:
                continue
            if rule.target in fired_targets:
                continue  # one movement per target per pass
            while self._fires and now - self._fires[0] > self.spec.limit_window_s:
                self._fires.popleft()
            if len(self._fires) >= self.spec.limit_n:
                self.n_rate_limited += 1
                continue
            decision = self._build(rule, float(value), counts, meta)
            if decision is None:
                # Bounds already satisfied (or no wid to respawn): no
                # action, no cooldown burned — the rule stays armed and
                # acts the moment movement becomes possible again.
                self.n_clamped += 1
                continue
            self._cooldown_until[i] = now + rule.cooldown_s
            self._fires.append(now)
            fired_targets.add(rule.target)
            self.n_decisions += 1
            decisions.append(decision)
        # Hysteresis: any movement of a target resets every rule aimed at
        # it — applied AFTER the pass so same-pass streak increments are
        # wiped too and an opposing rule re-earns its FULL sustain.
        if fired_targets:
            for j, other in enumerate(self.spec.rules):
                if other.target in fired_targets:
                    self._streak[j] = 0
        return decisions

    def _build(
        self, rule: Rule, value: float, counts: dict, meta: dict
    ) -> dict | None:
        reason = (
            f"{rule.signal} {rule.op} {rule.threshold} sustained "
            f"{rule.sustain} polls (value={value:.6g})"
        )
        base = {
            "action": rule.action,
            "target": rule.target,
            "rule": rule.raw,
            "signal": rule.signal,
            "value": value,
            "reason": reason,
        }
        if rule.action == "respawn":
            wid = meta.get("straggler_wid")
            if wid is None:
                return None
            cur = int(counts.get("workers", 0))
            return {**base, "wid": wid, "step": 0, "from": cur, "to": cur}
        cur = int(counts.get(rule.target, 0))
        desired = cur + rule.step if rule.action == "scale_out" else cur - rule.step
        if rule.lo is not None:
            desired = max(desired, rule.lo)
        if rule.hi is not None:
            desired = min(desired, rule.hi)
        desired = max(desired, 0)
        if desired == cur:
            return None
        return {**base, "step": abs(desired - cur), "from": cur, "to": desired}

    def cooldowns(self, now: float | None = None) -> dict:
        """Remaining refractory seconds per rule (0.0 = armed) — the
        dashboard's cooldown-status column."""
        now = self._clock() if now is None else now
        return {
            rule.raw: round(max(0.0, self._cooldown_until[i] - now), 3)
            for i, rule in enumerate(self.spec.rules)
        }
