"""Fleet autopilot: closed-loop autoscaling from the fleet's own health
signals (SLO burn rates, goodput ratios, straggler scores).

Three parts, mirroring every control plane in the repo:

- :mod:`tpu_rl.autopilot.signals` — scrape the existing read-only HTTP
  endpoints (``/slo``, ``/goodput``, ``/metrics``) into a windowed
  signal store; zero new member-side protocol;
- :mod:`tpu_rl.autopilot.policy` — the declarative rule grammar
  (``Config.autopilot_spec``) and the deterministic decision engine
  with sustain/cooldown/hysteresis/bounds/rate-limit anti-flap
  guarantees;
- :mod:`tpu_rl.autopilot.controller` — the actuator: spawn/retire
  ``inference-<i>`` replicas and workers through the real
  :class:`~tpu_rl.runtime.runner.Supervisor` inside the portplan's
  pre-planned port range, audit every decision to
  ``result_dir/autopilot.jsonl``.
"""

from tpu_rl.autopilot.controller import AutopilotController, ReplicaSet
from tpu_rl.autopilot.policy import AutopilotSpec, DecisionEngine, Rule
from tpu_rl.autopilot.signals import SignalScraper, SignalStore

__all__ = [
    "AutopilotController",
    "AutopilotSpec",
    "DecisionEngine",
    "ReplicaSet",
    "Rule",
    "SignalScraper",
    "SignalStore",
]
