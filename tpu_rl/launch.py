"""Cluster launcher: fan the role processes out over machines.

Capability parity with the reference ``run.py`` (``/root/reference/run.py:28-99``):
per-machine tmux session + ssh + rsync code push + role command, driven by the
machines topology. Differences: commands are composed as argv lists (no shell
string splicing), ``--dry-run`` prints the plan instead of executing, and the
single-host path needs no ssh at all (``python -m tpu_rl local``).

Usage:
    python -m tpu_rl.launch --machines machines.json [--params params.json]
        [--dry-run] [--ssh-user me] [--conda-env rl]
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess

from tpu_rl.config import MachinesConfig

RSYNC_EXCLUDES = [
    ".git", "__pycache__", "results", "logs", "native/build",
]  # reference run.py:15,21 exclude list


def _remote(cmd: str, host: str, user: str | None) -> list[str]:
    target = f"{user}@{host}" if user else host
    return ["ssh", "-o", "StrictHostKeyChecking=accept-new", target, cmd]


def _tmux_wrap(session: str, cmd: str) -> str:
    """Run ``cmd`` inside a detached tmux session (reference run.py:28-29)."""
    return (
        f"tmux kill-session -t {session} 2>/dev/null; "
        f"tmux new-session -d -s {session} {shlex.quote(cmd)}"
    )


def rsync_cmd(host: str, user: str | None, repo: str, dest: str) -> list[str]:
    target = f"{user}@{host}:{dest}" if user else f"{host}:{dest}"
    ex = [f"--exclude={e}" for e in RSYNC_EXCLUDES]
    return ["rsync", "-az", "--delete", *ex, repo + "/", target]


def role_cmd(
    role: str,
    machines_path: str,
    params_path: str | None,
    machine_idx: int | None = None,
    python: str = "python",
    conda_env: str | None = None,
    workdir: str = "~/tpu_rl_deploy",
) -> str:
    parts = [python, "-m", "tpu_rl", role, "--machines", machines_path]
    if params_path:
        parts += ["--params", params_path]
    if machine_idx is not None:
        parts += ["--machine-idx", str(machine_idx)]
    cmd = " ".join(parts)
    if conda_env:  # reference run.py:40-41 conda activate
        cmd = f"conda activate {conda_env} && {cmd}"
    return f"cd {workdir} && {cmd}"


def plan(
    machines: MachinesConfig,
    machines_path: str,
    params_path: str | None,
    repo: str,
    ssh_user: str | None,
    conda_env: str | None,
    workdir: str = "~/tpu_rl_deploy",
    population: bool = False,
) -> list[list[str]]:
    """The full launch plan as a list of argv commands, in execution order:
    rsync to every machine, then learner, then per worker-machine a manager
    and the workers (reference run.py:54-99). With ``population=True`` the
    learner host runs the PBT controller instead (``tpu_rl.population``) —
    the controller supervises its K member fleets itself inside private
    port blocks, so no manager/worker fan-out is launched."""
    cmds: list[list[str]] = []
    hosts = (
        {machines.learner_ip}
        | {w.ip for w in machines.workers}
        | {w.manager_ip for w in machines.workers}  # manager may be a 3rd host
    )
    if population:
        cmds.append(rsync_cmd(machines.learner_ip, ssh_user, repo, workdir))
        cmds.append(
            _remote(
                _tmux_wrap(
                    "tpurl-population",
                    role_cmd("population", machines_path, params_path,
                             conda_env=conda_env, workdir=workdir),
                ),
                machines.learner_ip,
                ssh_user,
            )
        )
        return cmds
    for host in sorted(hosts):
        cmds.append(rsync_cmd(host, ssh_user, repo, workdir))
    cmds.append(
        _remote(
            _tmux_wrap(
                "tpurl-learner",
                role_cmd("learner", machines_path, params_path,
                         conda_env=conda_env, workdir=workdir),
            ),
            machines.learner_ip,
            ssh_user,
        )
    )
    for idx, w in enumerate(machines.workers):
        cmds.append(
            _remote(
                _tmux_wrap(
                    f"tpurl-manager-{idx}",
                    role_cmd("manager", machines_path, params_path, idx,
                             conda_env=conda_env, workdir=workdir),
                ),
                w.manager_ip,
                ssh_user,
            )
        )
        cmds.append(
            _remote(
                _tmux_wrap(
                    f"tpurl-worker-{idx}",
                    role_cmd("worker", machines_path, params_path, idx,
                             conda_env=conda_env, workdir=workdir),
                ),
                w.ip,
                ssh_user,
            )
        )
    return cmds


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu_rl.launch")
    p.add_argument("--machines", required=True)
    p.add_argument("--params")
    p.add_argument("--repo", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    p.add_argument("--ssh-user")
    p.add_argument("--conda-env")
    p.add_argument("--workdir", default="~/tpu_rl_deploy")
    p.add_argument("--population", action="store_true",
                   help="launch the PBT controller on the learner host "
                   "instead of a single fleet (params must set pop_spec)")
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    machines = MachinesConfig.from_json(args.machines)
    cmds = plan(
        machines, args.machines, args.params, args.repo,
        args.ssh_user, args.conda_env, args.workdir,
        population=args.population,
    )
    for cmd in cmds:
        print("$", " ".join(shlex.quote(c) for c in cmd))
        if not args.dry_run:
            subprocess.run(cmd, check=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
