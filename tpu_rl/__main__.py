"""CLI: ``python -m tpu_rl <role> [options]``.

Replaces the reference's argv dispatch (``/root/reference/main.py:475-529``)
with argparse. Roles mirror the reference's ``*_sub_process`` entry points
plus ``local`` (whole cluster on one host — the smallest real deployment).

Examples:
    python -m tpu_rl local --env CartPole-v1 --algo PPO
    python -m tpu_rl local --env CartPole-v1 --algo PPO --env-mode colocated
    python -m tpu_rl learner --params params.json --machines machines.json
    python -m tpu_rl manager --machines machines.json --machine-idx 0
    python -m tpu_rl worker  --machines machines.json --machine-idx 0
"""

from __future__ import annotations

import argparse
import os
import sys

from tpu_rl.config import Config, MachinesConfig, default_result_dirs


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu_rl")
    p.add_argument(
        "role",
        choices=[
            "local", "learner", "manager", "worker", "population", "autopilot",
        ],
        help="which role this host runs ('population' = PBT controller "
        "orchestrating K member runs; 'autopilot' = closed-loop autoscaler "
        "driving the elastic inference fleet from SLO burn rates, goodput "
        "and straggler scores; see tpu_rl.population / tpu_rl.autopilot)",
    )
    p.add_argument("--params", help="parameters.json-shaped config file")
    p.add_argument("--machines", help="machines.json-shaped topology file")
    p.add_argument("--machine-idx", type=int, default=0,
                   help="index into machines.workers for manager/worker roles")
    p.add_argument("--env", help="override env id")
    p.add_argument("--algo", help="override algorithm")
    p.add_argument("--env-mode", choices=["distributed", "colocated"],
                   default=None,
                   help="'colocated' fuses act->env.step->train into one "
                   "jitted on-device program (jittable envs only; see "
                   "tpu_rl/envs)")
    p.add_argument("--colocated-envs", type=int, default=None,
                   help="env-batch size for colocated mode (overrides "
                   "batch_size there; 0/unset = batch_size)")
    p.add_argument("--sebulba-split", type=int, default=None,
                   help="colocated mode: dedicate this many local devices "
                   "to the rollout program (actor group); the rest run "
                   "train_step, fed through a bounded on-device queue "
                   "(Podracer Sebulba). 0/unset = fused Anakin")
    p.add_argument("--sebulba-queue", type=int, default=None,
                   help="bounded device-resident batch slots between the "
                   "sebulba device groups (2 = double buffering)")
    p.add_argument("--mesh-data", type=int, help="learner data-mesh size")
    p.add_argument("--act-mode", choices=["local", "remote"], default=None,
                   help="'remote' routes worker acting through the "
                   "centralized inference service/fleet (SEED-style); "
                   "'local' acts on worker host cores (default: local)")
    p.add_argument("--inference-replicas", type=int, default=None,
                   help="inference fleet size for act_mode=remote: replica 0 "
                   "serves in-process in the learner, replicas 1..N-1 are "
                   "supervised children fed by the model broadcast "
                   "(default 1 = the single in-learner service)")
    p.add_argument("--inference-base-port", type=int, default=None,
                   help="first port of the fleet's consecutive replica port "
                   "range, collision-checked against the learner/model/"
                   "telemetry/manager ports (0/unset = learner_port + 2)")
    p.add_argument("--inference-hedge-ms", type=int, default=None,
                   help="resend an unanswered inference request to a second "
                   "replica after this many ms (0/unset = hedge only at the "
                   "full timeout boundary — plain failover)")
    p.add_argument("--inference-mesh-data", type=int, default=None,
                   help="GSPMD data-mesh size each inference replica shards "
                   "its act batch over (1/unset = single-device)")
    p.add_argument("--inference-dtype", choices=["f32", "bf16", "int8"],
                   default=None,
                   help="serving-param precision: bf16 halves / int8 "
                   "quarters the resident actor tree; the jitted act step "
                   "dequantizes, so compute stays f32 (default: f32)")
    p.add_argument("--inference-buckets", type=int, default=None,
                   help="smallest bucket of the power-of-two flush-shape "
                   "ladder, all compiled before the socket binds; 0/unset = "
                   "one padded program (the bit-for-bit legacy path)")
    p.add_argument("--act-kernel", choices=["xla", "pallas"], default=None,
                   help="'pallas' fuses the act step into one VMEM-resident "
                   "TPU kernel where it fits, falling back to XLA elsewhere "
                   "(default: xla)")
    p.add_argument("--max-updates", type=int, default=None)
    p.add_argument("--publish-interval", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no-result-dir", action="store_true",
                   help="disable tensorboard/checkpoint output")
    p.add_argument("--result-dir", default=None,
                   help="fixed result dir (checkpoints land in "
                   "<result-dir>/models). Run the same role twice with the "
                   "same --result-dir and the learner resumes from the "
                   "newest committed checkpoint instead of starting over "
                   "(default: a fresh timestamped dir per run)")
    p.add_argument("--model-save-interval", type=int, default=None,
                   help="checkpoint every N learner updates")
    p.add_argument("--ckpt-keep", type=int, default=None,
                   help="committed checkpoints retained on disk (>= 1)")
    p.add_argument("--ckpt-sync", action="store_true",
                   help="blocking checkpoint saves on the update loop "
                   "(default: async background writer; both are "
                   "commit-atomic — this is the A/B baseline)")
    p.add_argument("--resume-force", action="store_true",
                   help="resume even if the checkpoint's config fingerprint "
                   "(model/env structure) disagrees with the current config")
    p.add_argument("--telemetry-port", type=int, default=None,
                   help="serve Prometheus /metrics + /healthz from the "
                   "storage process on this port (0/unset = off)")
    p.add_argument("--history-dir", default=None,
                   help="run-history time-series store location (unset = "
                   "result_dir/history; the store exists only while the "
                   "telemetry plane is on)")
    p.add_argument("--history-chunk-s", type=float, default=None,
                   help="history store chunk rotation period in seconds "
                   "(default 60)")
    p.add_argument("--history-retention-s", type=float, default=None,
                   help="history store retention horizon in seconds — older "
                   "chunks are GC'd at rotation (default 3600)")
    p.add_argument("--no-learn-diag", action="store_true",
                   help="disable the learning-dynamics plane (in-jit "
                   "entropy/KL/ESS/clip diagnostics, staleness-conditioned "
                   "learner-diag-* gauges, result_dir/learn.jsonl); on by "
                   "default — readback rides the loss-log cadence, so the "
                   "steady-state cost is one extra fused device program")
    p.add_argument("--watchdog-diag", action="store_true",
                   help="feed approx-KL and negated ESS from the "
                   "learning-dynamics plane into the divergence watchdog's "
                   "z-score channels (requires the watchdog and learn-diag "
                   "both on)")
    p.add_argument("--trace-sample-n", type=int, default=None,
                   help="sample every Nth worker tick into the fleet trace "
                   "(result_dir/fleet_trace.json); 0/unset = off")
    p.add_argument("--transport", choices=["tcp", "shm", "auto"],
                   default=None,
                   help="data-hop fabric for the rollout/stat fan-in: 'shm' "
                   "routes same-host manager->storage and learner->storage "
                   "hops through shared-memory rings (no sockets), 'auto' "
                   "picks shm only when the peer address is loopback "
                   "(default: tcp)")
    p.add_argument("--slo-spec", default=None,
                   help="declarative SLO rules evaluated live, e.g. "
                   "'p99:inference-rtt<5ms@window=30s,gauge:learner-mfu"
                   ">0.002,rate:transport-rejected-frames<1/s' "
                   "(see tpu_rl.obs.slo; served at /slo)")
    p.add_argument("--slo-fail-run", action="store_true",
                   help="exit nonzero (storage child) when the final SLO "
                   "verdict has a hard-failing rule")
    p.add_argument("--chaos-spec", default=None,
                   help="deterministic fault plan, e.g. "
                   "'kill:worker-0-1@t+3s,corrupt:rollout@p=0.01,"
                   "delay:manager@50ms' (see tpu_rl.chaos.plan)")
    p.add_argument("--chaos-seed", type=int, default=None,
                   help="seed for the chaos plane's per-site RNG streams")
    p.add_argument("--pop-spec", default=None,
                   help="PBT search-space grammar for the population role, "
                   "e.g. 'lr:log[1e-4,1e-2] entropy_coef:lin[0,0.05] "
                   "perturb=1.2,0.8 interval=200u k=4' "
                   "(see tpu_rl.population.spec)")
    p.add_argument("--pop-seed", type=int, default=None,
                   help="seed for population sampling/mutation/selection "
                   "(deterministic per-member streams)")
    p.add_argument("--autopilot-spec", default=None,
                   help="closed-loop autoscaling rules for the autopilot "
                   "role, e.g. 'scale_out:replicas?burn:inference-rtt>0.5"
                   "@sustain=3@cooldown=10s@max=4,scale_in:replicas?burn:"
                   "inference-rtt<0.05@min=1,limit=6/60s' "
                   "(see tpu_rl.autopilot.policy)")
    p.add_argument("--autopilot-poll", type=float, default=None,
                   help="seconds between autopilot control ticks "
                   "(scrape -> decide -> actuate)")
    p.add_argument("--autopilot-manage-all", action="store_true",
                   help="autopilot owns the whole replica range from index "
                   "0 (standalone fleets); default: the statically "
                   "provisioned learner-owned replicas stay untouched and "
                   "the autopilot manages only the elastic tail")
    p.add_argument("--heartbeat-timeout", type=float, default=None,
                   help="seconds of child-heartbeat silence before the "
                   "supervisor declares it hung and restarts it")
    p.add_argument("--startup-grace", type=float, default=None,
                   help="seconds after spawn before silence counts "
                   "(covers jit compile / env build)")
    p.add_argument("--supervise-poll", type=float, default=None,
                   help="supervisor health-check interval in seconds")
    p.add_argument("--max-restarts", type=int, default=None,
                   help="restarts allowed per child within restart_window_s "
                   "before the fleet shuts down")
    return p


def load_config(args: argparse.Namespace) -> tuple[Config, MachinesConfig]:
    cfg = Config.from_json(args.params) if args.params else Config()
    overrides = {}
    if args.env:
        overrides["env"] = args.env
    if args.algo:
        overrides["algo"] = args.algo
    if args.env_mode is not None:
        overrides["env_mode"] = args.env_mode
    if args.colocated_envs is not None:
        overrides["colocated_envs"] = args.colocated_envs
    if args.sebulba_split is not None:
        overrides["sebulba_split"] = args.sebulba_split
    if args.sebulba_queue is not None:
        overrides["sebulba_queue"] = args.sebulba_queue
    if args.mesh_data:
        overrides["mesh_data"] = args.mesh_data
    if args.act_mode is not None:
        overrides["act_mode"] = args.act_mode
    if args.inference_replicas is not None:
        overrides["inference_replicas"] = args.inference_replicas
    if args.inference_base_port is not None:
        overrides["inference_base_port"] = args.inference_base_port
    if args.inference_hedge_ms is not None:
        overrides["inference_hedge_ms"] = args.inference_hedge_ms
    if args.inference_mesh_data is not None:
        overrides["inference_mesh_data"] = args.inference_mesh_data
    if args.inference_dtype is not None:
        overrides["inference_dtype"] = args.inference_dtype
    if args.inference_buckets is not None:
        overrides["inference_buckets"] = args.inference_buckets
    if args.act_kernel is not None:
        overrides["act_kernel"] = args.act_kernel
    if args.telemetry_port is not None:
        overrides["telemetry_port"] = args.telemetry_port
    if args.history_dir is not None:
        overrides["history_dir"] = args.history_dir
    if args.history_chunk_s is not None:
        overrides["history_chunk_s"] = args.history_chunk_s
    if args.history_retention_s is not None:
        overrides["history_retention_s"] = args.history_retention_s
    if args.no_learn_diag:
        overrides["learn_diag"] = False
    if args.watchdog_diag:
        overrides["watchdog_diag"] = True
    if args.trace_sample_n is not None:
        overrides["trace_sample_n"] = args.trace_sample_n
    if args.transport is not None:
        overrides["transport"] = args.transport
    if args.slo_spec is not None:
        overrides["slo_spec"] = args.slo_spec
    if args.slo_fail_run:
        overrides["slo_fail_run"] = True
    if args.chaos_spec is not None:
        overrides["chaos_spec"] = args.chaos_spec
    if args.pop_spec is not None:
        overrides["pop_spec"] = args.pop_spec
    if args.pop_seed is not None:
        overrides["pop_seed"] = args.pop_seed
    if args.autopilot_spec is not None:
        overrides["autopilot_spec"] = args.autopilot_spec
    if args.autopilot_poll is not None:
        overrides["autopilot_poll_s"] = args.autopilot_poll
    if args.chaos_seed is not None:
        overrides["chaos_seed"] = args.chaos_seed
    if args.heartbeat_timeout is not None:
        overrides["heartbeat_timeout_s"] = args.heartbeat_timeout
    if args.startup_grace is not None:
        overrides["startup_grace_s"] = args.startup_grace
    if args.supervise_poll is not None:
        overrides["supervise_poll_s"] = args.supervise_poll
    if args.max_restarts is not None:
        overrides["max_restarts"] = args.max_restarts
    if args.result_dir is not None:
        overrides["result_dir"] = args.result_dir
        # A user-set model_dir (e.g. from --params) still wins; otherwise
        # checkpoints live under the pinned result dir so a rerun with the
        # same flag resumes from them.
        if cfg.model_dir is None:
            overrides["model_dir"] = os.path.join(args.result_dir, "models")
    if args.model_save_interval is not None:
        overrides["model_save_interval"] = args.model_save_interval
    if args.ckpt_keep is not None:
        overrides["ckpt_keep"] = args.ckpt_keep
    if args.ckpt_sync:
        overrides["ckpt_async"] = False
    if args.resume_force:
        overrides["resume_force"] = True
    if overrides:
        cfg = cfg.replace(**overrides)
    machines = (
        MachinesConfig.from_json(args.machines)
        if args.machines
        else MachinesConfig()
    )
    return cfg, machines


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cfg, machines = load_config(args)

    # Probe env spaces once, in the parent (reference ``main.py:82-95``).
    from tpu_rl.runtime.env import probe_spaces

    cfg = probe_spaces(cfg)
    if not args.no_result_dir and (
        cfg.result_dir is None or cfg.model_dir is None
    ):
        result_dir, model_dir = default_result_dirs()
        # Fill only the unset dirs — a user-configured model_dir (checkpoint
        # resume target) must never be clobbered by the timestamped default.
        cfg = cfg.replace(
            result_dir=cfg.result_dir or result_dir,
            model_dir=cfg.model_dir or model_dir,
        )

    from tpu_rl.runtime import runner

    if args.role == "population":
        # The controller IS the orchestrator: it runs in this process and
        # drives its own supervisor (members are the children), so it does
        # not go through the sup.loop() path below.
        ctrl = runner.population_role(
            cfg, machines, max_updates=args.max_updates
        )
        ctrl.install_signal_handlers()
        doc = ctrl.run()
        return 0 if doc.get("ok") else 1
    if args.role == "autopilot":
        # Same controller-as-orchestrator shape as the population role.
        ctrl = runner.autopilot_role(
            cfg, machines, manage_all=args.autopilot_manage_all,
            seed=args.seed,
        )
        ctrl.install_signal_handlers()
        doc = ctrl.run()
        return 0 if doc.get("ok") else 1
    if cfg.env_mode == "colocated" and args.role in ("manager", "worker"):
        print(
            f"colocated mode has no {args.role} role: the envs live inside "
            "the fused on-device program (use 'local' or 'learner')",
            file=sys.stderr,
        )
        return 2
    if cfg.env_mode == "colocated" and args.role == "learner":
        sup = runner.colocated_role(
            cfg, machines, max_updates=args.max_updates, seed=args.seed
        )
    elif args.role == "local":
        sup = runner.local_cluster(
            cfg,
            machines,
            max_updates=args.max_updates,
            publish_interval=args.publish_interval,
            seed=args.seed,
        )
    elif args.role == "learner":
        sup = runner.learner_role(
            cfg,
            machines,
            max_updates=args.max_updates,
            publish_interval=args.publish_interval,
            seed=args.seed,
        )
    elif args.role == "manager":
        sup = runner.manager_role(cfg, machines, machine_idx=args.machine_idx)
    else:
        sup = runner.worker_role(
            cfg, machines, machine_idx=args.machine_idx, seed=args.seed
        )

    sup.install_signal_handlers()
    try:
        sup.loop()
    finally:
        sup.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
