"""tpu_rl — a TPU-native distributed reinforcement-learning framework.

A clean-room JAX/XLA re-design of the capabilities of
``ymg1114/pytorch-distributed-reinforcement-learning`` (see /root/repo/SURVEY.md):
an IMPALA-style actor–learner architecture with six algorithms (PPO, PPO-Continuous,
IMPALA/V-trace, V-MPO, SAC, SAC-Continuous), a fleet of CPU env workers streaming
trajectories over ZMQ through per-machine manager relays into a learner-host storage
process, and a mesh-data-parallel TPU learner compiled with ``jax.jit``.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

- ``tpu_rl.config``     — typed config, parameters/machines JSON loaders
- ``tpu_rl.models``     — Flax policies: MLP torso -> lax.scan LSTM -> heads
- ``tpu_rl.ops``        — pure-JAX GAE / V-trace / distributions / huber / polyak
- ``tpu_rl.algos``      — jitted train_step per algorithm + registry
- ``tpu_rl.data``       — trajectory assembly, shared-memory batch store, replay
- ``tpu_rl.transport``  — ZMQ PUB/SUB wire protocol + codec (DCN path)
- ``tpu_rl.agents``     — worker / manager / storage / learner processes
- ``tpu_rl.parallel``   — device mesh, data-parallel shardings (ICI path)
- ``tpu_rl.envs``       — Gym adapter + fake envs for tests
- ``tpu_rl.utils``      — timers, checkpointing, logging, process supervision
"""

__version__ = "0.1.0"

from tpu_rl.config import Config, MachinesConfig  # noqa: F401
