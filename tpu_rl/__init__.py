"""tpu_rl — a TPU-native distributed reinforcement-learning framework.

A clean-room JAX/XLA re-design of the capabilities of
``ymg1114/pytorch-distributed-reinforcement-learning`` (see /root/repo/SURVEY.md):
an IMPALA-style actor-learner architecture with six algorithms (PPO, PPO-Continuous,
IMPALA/V-trace, V-MPO, SAC, SAC-Continuous), a fleet of CPU env workers streaming
trajectories over ZMQ through per-machine manager relays into a learner-host storage
process, and a mesh-data-parallel TPU learner compiled with ``jax.jit``.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

- ``tpu_rl.config``     — typed config, parameters/machines JSON loaders
- ``tpu_rl.models``     — Flax policies: LSTM families, transformer, fused cell
- ``tpu_rl.ops``        — pure-JAX GAE / V-trace / losses / distributions /
  target nets + the Pallas fused-LSTM kernel
- ``tpu_rl.algos``      — jitted train_step per algorithm + registry
- ``tpu_rl.data``       — trajectory assembly, shm batch stores, batch layout
- ``tpu_rl.runtime``    — wire protocol/codec (DCN path), ZMQ transport,
  worker / manager / storage / learner processes, supervisor/runner, env
  adapter, native-codec loader
- ``tpu_rl.parallel``   — device mesh, data-parallel jit, ring/Ulysses
  sequence parallelism (ICI path), multihost init
- ``tpu_rl.checkpoint`` — orbax params+opt+step save/resume
- ``tpu_rl.launch``     — cluster launcher (rsync+ssh+tmux plan/execute)
- ``tpu_rl.utils``      — timers, metrics, crash logs, platform forcing
"""

__version__ = "0.1.0"

from tpu_rl.config import Config, MachinesConfig  # noqa: F401
