"""Core data types shared across the framework."""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# The eight per-step fields of a training batch, in canonical order. Mirrors the
# reference's shared-memory field set (``/root/reference/agents/storage_module/
# shared_batch.py:19-64`` and ``utils/utils.py:66-76``).
BATCH_FIELDS = ("obs", "act", "rew", "logits", "log_prob", "is_fir", "hx", "cx")


def field_widths(
    obs_dim: int,
    action_space: int,
    hidden: int,
    continuous: bool,
    hx_width: int | None = None,
    cx_width: int | None = None,
) -> dict[str, int]:
    """Canonical feature width of every batch field — THE single source of
    truth shared by host buffers (``data.layout.BatchLayout``) and device
    shapes (``Batch.zeros``). Discrete actions/log-probs are width-1 float
    columns (reference convention,
    ``/root/reference/agents/storage_module/shared_batch.py:28-31``).
    ``hx_width``/``cx_width`` override the LSTM default for model families
    with a different acting carry (transformer: obs-history window +
    step counter)."""
    wide = action_space if continuous else 1
    return dict(
        obs=obs_dim,
        act=wide,
        rew=1,
        logits=action_space,
        log_prob=wide,
        is_fir=1,
        hx=hidden if hx_width is None else hx_width,
        cx=hidden if cx_width is None else cx_width,
    )


@struct.dataclass
class Batch:
    """A training batch of fixed-length trajectory sequences, shaped
    ``(batch, seq, feat)`` exactly as the reference samples them out of shared
    memory (``/root/reference/agents/learner.py:197-233``).

    obs      : (B, S, *obs_shape) float32
    act      : (B, S, A_act) — discrete: (B, S, 1) action index as float
    rew      : (B, S, 1) pre-scaled reward
    logits   : (B, S, A) behavior-policy log-softmax logits (zeros for Normal
               policies, matching ``networks/models.py:46-49``)
    log_prob : (B, S, A_lp) behavior log-prob (discrete: A_lp=1)
    is_fir   : (B, S, 1) 1.0 at episode-first steps (incl. splice seams)
    hx, cx   : (B, S, H) pre-step LSTM states; training uses [:, 0]
    """

    obs: jax.Array
    act: jax.Array
    rew: jax.Array
    logits: jax.Array
    log_prob: jax.Array
    is_fir: jax.Array
    hx: jax.Array
    cx: jax.Array

    @property
    def batch_size(self) -> int:
        return self.obs.shape[0]

    @property
    def seq_len(self) -> int:
        return self.obs.shape[1]

    def astuple(self) -> tuple[jax.Array, ...]:
        return tuple(getattr(self, k) for k in BATCH_FIELDS)

    @classmethod
    def from_mapping(cls, m: Mapping[str, Any]) -> "Batch":
        return cls(**{k: jnp.asarray(m[k]) for k in BATCH_FIELDS})

    @classmethod
    def zeros(
        cls,
        batch: int,
        seq: int,
        obs_shape: tuple[int, ...],
        action_space: int,
        hidden: int,
        continuous: bool = False,
        dtype=jnp.float32,
        hx_width: int | None = None,
        cx_width: int | None = None,
    ) -> "Batch":
        import numpy as _np

        widths = field_widths(
            int(_np.prod(obs_shape)),
            action_space,
            hidden,
            continuous,
            hx_width=hx_width,
            cx_width=cx_width,
        )
        z = lambda *sh: jnp.zeros((batch, seq, *sh), dtype)
        return cls(
            obs=z(*obs_shape),
            **{
                f: z(widths[f])
                for f in BATCH_FIELDS
                if f != "obs"
            },
        )


def batch_to_numpy(b: Batch) -> dict[str, np.ndarray]:
    return {k: np.asarray(getattr(b, k)) for k in BATCH_FIELDS}


def maybe_zero_carry(cfg, mapping: dict) -> dict:
    """R2D2-style zero-init of the training-window recurrent carry, gated on
    ``cfg.zero_window_carry``: stored carries come from the (possibly long
    gone) behavior policy, and bootstrapping values off those off-manifold
    hidden states measurably drives value hallucination (CLUSTER_LEARNING.md).
    The reference always trusts the stale carry (``ppo/learning.py:37-40``);
    default False = parity. Returns a shallow copy when zeroing."""
    if not getattr(cfg, "zero_window_carry", False):
        return mapping
    out = dict(mapping)
    out["hx"] = np.zeros_like(mapping["hx"])
    out["cx"] = np.zeros_like(mapping["cx"])
    return out
