"""Shared, collision-checked port allocation for fleet-shaped deployments.

PR 12 grew the first explicit port plan (``MachinesConfig.inference_ports``:
N consecutive replica ports checked against the learner/model/telemetry/
manager ports). The population plane needs the same arithmetic — K member
telemetry ports, and per-member learner-port blocks for distributed
members — so the allocator lives here once and both planes call it. The
contract is unchanged from PR 12: a range that lands on a reserved port
fails at topology load with a named collision, not as an EADDRINUSE
minutes later inside a spawned child.

Pure stdlib and import-free of ``tpu_rl.config`` (the ``MachinesConfig``
methods delegate here lazily; importing config back would cycle), so every
helper takes the topology duck-typed: anything with ``learner_port``,
``model_port`` and ``workers[*].port`` works.
"""

from __future__ import annotations


def reserved_ports(machines, cfg=None) -> dict[int, str]:
    """Port -> human-readable owner for every port the topology already
    claims. The owner string lands verbatim in collision errors, so it
    names the config knob to move, not just the number."""
    reserved = {
        machines.learner_port: "learner_port (rollout/stat fan-in)",
        machines.model_port: "model_port (weight broadcast)",
    }
    if cfg is not None and cfg.telemetry_port:
        reserved[cfg.telemetry_port] = "telemetry_port (HTTP exporter)"
    for w in machines.workers:
        reserved.setdefault(w.port, "worker manager port")
    return reserved


def plan_range(
    base: int, n: int, reserved: dict[int, str], what: str
) -> list[int]:
    """``n`` consecutive ports starting at ``base``, or ValueError naming
    the first collision / port-space overflow. ``what`` labels the range in
    errors (e.g. "inference replica", "population member telemetry")."""
    if not (0 < base and base + n <= 65536):
        raise ValueError(
            f"{what} ports [{base}, {base + n}) fall outside the port space"
        )
    ports = [base + i for i in range(n)]
    for p in ports:
        if p in reserved:
            raise ValueError(
                f"{what} port {p} (range [{base}, {base + n})) collides "
                f"with {reserved[p]}"
            )
    return ports


def plan_member_telemetry_ports(machines, cfg, k: int) -> list[int]:
    """Telemetry HTTP ports for K population members: the K ports after the
    controller's own ``telemetry_port``, collision-checked against the
    topology. When the plane is off (``telemetry_port == 0``) members
    export file-only snapshots (the controller scrapes
    ``member-<k>/telemetry.json``) and no sockets open: all zeros."""
    if not cfg.telemetry_port:
        return [0] * k
    reserved = reserved_ports(machines, cfg)
    return plan_range(
        cfg.telemetry_port + 1, k, reserved, "population member telemetry"
    )


def plan_member_port_blocks(
    machines, cfg, k: int, block: int = 8
) -> list[int]:
    """Base port of each distributed member's private port block: member i
    lays out its nested fleet (learner/model/inference/manager ports)
    inside ``[base_i, base_i + block)``. Blocks start after the outer
    topology's highest claimed port — including the K member telemetry
    ports, which count as claimed here — and are checked against every
    reserved port, so K nested fleets on one host never cross-bind."""
    reserved = reserved_ports(machines, cfg)
    for p in plan_member_telemetry_ports(machines, cfg, k):
        if p:
            reserved[p] = "population member telemetry port"
    first = max(reserved) + 1
    bases = []
    for i in range(k):
        base = first + i * block
        plan_range(base, block, reserved, f"population member-{i} block")
        bases.append(base)
    return bases
