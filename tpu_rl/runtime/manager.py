"""Manager relay process: per-machine fan-in between workers and the learner
storage.

Capability parity with the reference manager
(``/root/reference/agents/manager.py:11-90``): SUB-bind on the machine's
worker port, forward Rollout messages to the learner storage, window worker
episode rewards and publish the mean every ``stat_window`` episodes. The
bounded drop-oldest queue (deque maxlen 1024, ``manager.py:45-47``) is kept —
back-pressure on a best-effort fleet means shedding the *oldest* data, since
stale rollouts are the least on-policy.

Zero-copy relay (``Config.relay_mode="raw"``, the default): the manager never
inspects rollout payloads, so it routes on the proto byte alone —
``protocol.peek`` validates the header (magic/version/size caps) without the
CRC pass, LZ4 decompress, or schema unpack, and the received wire parts are
forwarded verbatim via ``Pub.send_raw``. Per-frame relay cost drops from
O(payload) (decode + re-encode) to O(1); the single full CRC+decode runs at
the storage edge, the only consumer. Only the rare, tiny ``Stat`` frames are
decoded here, for the windowed mean. ``relay_mode="decode"`` keeps the old
decode-re-encode hop as the A/B baseline (``bench_relay.cpu.json``).

Sync loop instead of the reference's two asyncio tasks: one poll-drain-forward
pass per iteration keeps ordering within a worker's stream and needs no
coordination.
"""

from __future__ import annotations

import os
import time
from collections import deque

from tpu_rl.config import Config
from tpu_rl.runtime.protocol import Protocol, decode, encode, unpack_trace
from tpu_rl.runtime.transport import Pub, Sub, make_data_pub

RELAY_QUEUE_MAX = 1024  # reference manager.py:45-47
STAT_WINDOW = 50  # reference manager.py:19,62-79


class Manager:
    def __init__(
        self,
        cfg: Config,
        worker_port: int,
        learner_ip: str,
        learner_port: int,
        stop_event=None,
        heartbeat=None,
    ):
        self.cfg = cfg
        self.raw = cfg.relay_mode == "raw"
        self.worker_port = worker_port
        self.learner_addr = (learner_ip, learner_port)
        self.stop_event = stop_event
        self.heartbeat = heartbeat
        # Relay queue holds fully-encoded wire parts (list[bytes]) in BOTH
        # modes: raw mode appends the received parts untouched; decode mode
        # decodes + re-encodes at ingest (the A/B baseline's per-frame
        # codec cost), so the flush path is mode-agnostic byte forwarding.
        self.queue: deque = deque(maxlen=RELAY_QUEUE_MAX)
        self.stat_q: deque = deque(maxlen=STAT_WINDOW)
        self.n_stats = 0
        self.n_forwarded = 0
        # Observability (ISSUE 3 satellites): frames shed by the drop-oldest
        # deque (previously silent data loss) and bytes forwarded to storage
        # — both relayed in the windowed stat publish so they land on the
        # learner's dashboards next to transport-rejected-frames.
        self.n_dropped = 0
        self.n_forward_bytes = 0
        # Stat frames that passed peek but failed the full decode (raw mode
        # decodes only stats; a corrupt stat body is dropped + counted).
        self.n_stat_rejected = 0
        # Per-worker health counters (last-seen cumulative values, keyed by
        # wid) relayed in the windowed stat publish so they reach the
        # learner's dashboards (ISSUE 2 satellites: n_model_loads,
        # n_rejected visibility).
        self.model_loads: dict = {}
        self.worker_rejected: dict = {}
        self._sub: Sub | None = None
        # Rollout-lineage tracing (tpu_rl.obs): spans recorded ONLY for
        # frames that arrive with a trace trailer (the third wire part), so
        # the untraced relay path's trace cost is one length check. None
        # when there is nowhere to dump (no result_dir).
        self._tracer = None
        self._trace_path = None
        # Goodput ledger (tpu_rl.obs.goodput), built in run() iff telemetry
        # has a sink; None keeps the plane-off loop to one check.
        self.ledger = None

    def run(self) -> None:
        # Fault injection (tpu_rl.chaos): delay:manager shims the forward
        # sends to storage. None unless a chaos_spec names this site.
        chaos = None
        if self.cfg.chaos_spec:
            from tpu_rl.chaos import maybe_transport_chaos

            chaos = maybe_transport_chaos(self.cfg, "manager")
        sub = self._sub = Sub("*", self.worker_port, bind=True, chaos=chaos)
        # Storage hop: shm ring when Config.transport selects it for the
        # learner address (same host), else the TCP PUB — same chaos shim,
        # same send_raw surface either way.
        pub = make_data_pub(
            self.cfg, *self.learner_addr, bind=False, chaos=chaos
        )
        recv = sub.recv_raw if self.raw else sub.recv_traced

        # Telemetry (tpu_rl.obs): the relay's own health snapshot, emitted
        # on the clock onto the storage-bound PUB. None when the plane has
        # no sink — the loop then pays one `is None` check per iteration.
        registry = emitter = ledger = None
        if self.cfg.telemetry_enabled:
            from tpu_rl.obs import MetricsRegistry, PeriodicSnapshot
            from tpu_rl.obs.goodput import COMPUTE, IDLE, WIRE, GoodputLedger
            from tpu_rl.obs.perf import process_self_stats

            registry = MetricsRegistry(role="manager")
            # Goodput ledger: the pump (drain + forward) is the work this
            # relay exists for — its compute bucket; the bounded idle recv
            # splits into wire (frame landed) vs idle (timeout).
            ledger = self.ledger = GoodputLedger("manager")

            def _send_snap(snap):
                # One-way clock-sync stamp: the storage edge pairs our send
                # time with its receive time (no return path to a relay, so
                # this bounds rather than measures the offset).
                snap["clk"] = {"t2": time.time_ns()}
                pub.send(Protocol.Telemetry, snap)

            emitter = PeriodicSnapshot(
                registry, _send_snap, interval_s=self.cfg.telemetry_interval_s
            )
        if self.cfg.result_dir is not None:
            from tpu_rl.obs import TraceRecorder, flightrec

            self._tracer = TraceRecorder(
                capacity=self.cfg.trace_capacity,
                pid=os.getpid(),
                role="manager",
            )
            self._trace_path = os.path.join(
                self.cfg.result_dir, f"trace-manager-{os.getpid()}.json"
            )
            flightrec.install(
                "manager",
                self.cfg.result_dir,
                tracer=self._tracer,
                cfg=self.cfg,
                extra=lambda: {
                    "queue_depth": len(self.queue),
                    "n_forwarded": self.n_forwarded,
                    "n_dropped": self.n_dropped,
                },
            )
        try:
            while not self._stopped():
                t_pump = time.perf_counter()
                moved = self._pump(sub, pub)
                if ledger is not None:
                    ledger.add(COMPUTE, time.perf_counter() - t_pump)
                if registry is not None:
                    registry.counter("manager-forwarded-frames").set_total(
                        self.n_forwarded
                    )
                    registry.counter("manager-forward-bytes").set_total(
                        self.n_forward_bytes
                    )
                    registry.counter("manager-dropped-frames").set_total(
                        self.n_dropped
                    )
                    registry.counter("manager-stats-seen").set_total(
                        self.n_stats
                    )
                    registry.counter("manager-rejected-frames").set_total(
                        sub.n_rejected + self.n_stat_rejected
                    )
                    registry.gauge("manager-queue-depth").set(len(self.queue))
                    if hasattr(pub, "n_dropped_full"):
                        # Shm-channel shedding (ring full / no consumer
                        # bound yet) — the fabric's analogue of PUB HWM
                        # drops, surfaced on the same dashboards.
                        registry.counter("shm-dropped-full").set_total(
                            pub.n_dropped_full
                        )
                        registry.counter("shm-dropped-no-peer").set_total(
                            pub.n_dropped_no_peer
                        )
                    if chaos is not None:
                        registry.counter(
                            "chaos-corrupted-frames"
                        ).set_total(chaos.n_corrupted)
                        registry.counter(
                            "chaos-dropped-frames"
                        ).set_total(chaos.n_dropped)
                        registry.counter(
                            "chaos-delayed-frames"
                        ).set_total(chaos.n_delayed)
                    if emitter.due():
                        # /proc self-stats refreshed only just before an
                        # emit (syscalls; the gauges only travel then).
                        rss, n_fds = process_self_stats()
                        registry.gauge("manager-rss-bytes").set(rss)
                        registry.gauge("manager-open-fds").set(float(n_fds))
                        ledger.publish(registry)
                    if emitter.maybe_emit() and self._tracer is not None:
                        # Trace dumps ride the telemetry cadence so a recent
                        # ring is always on disk for the merger.
                        self._tracer.dump(self._trace_path)
                if self.heartbeat is not None:
                    self.heartbeat.value = time.time()
                if not moved:
                    # Idle: block briefly on the socket instead of spinning.
                    t_recv = time.perf_counter()
                    msg = recv(timeout_ms=50)
                    if ledger is not None:
                        ledger.add(
                            WIRE if msg is not None else IDLE,
                            time.perf_counter() - t_recv,
                        )
                    if msg is not None:
                        self._ingest(
                            msg[0],
                            msg[1],
                            pub,
                            msg[2] if len(msg) > 2 else None,
                        )
        finally:
            if self._tracer is not None and self._tracer.n_recorded:
                self._tracer.dump(self._trace_path)
            sub.close()
            pub.close()

    # ---------------------------------------------------------------- pump
    def _pump(self, sub: Sub, pub: Pub) -> int:
        moved = 0
        drain = sub.drain_raw if self.raw else sub.drain_traced
        for got in drain():
            self._ingest(
                got[0], got[1], pub, got[2] if len(got) > 2 else None
            )
            moved += 1
        while self.queue:
            parts = self.queue.popleft()
            pub.send_raw(parts)
            self.n_forwarded += 1
            if len(parts) == 3:
                # Sampled frame: the trailer's bytes count too, and the
                # forward hop lands in the lineage timeline.
                self.n_forward_bytes += (
                    len(parts[0]) + len(parts[1]) + len(parts[2])
                )
                if self._tracer is not None:
                    self._note_trace("relay-out", parts[2])
            else:
                self.n_forward_bytes += len(parts[0]) + len(parts[1])
            moved += 1
        return moved

    def _note_trace(self, name: str, trailer: bytes) -> None:
        """One lineage span for a trailer-carrying frame at this hop."""
        t0 = time.perf_counter()
        try:
            wid, seq, trace_id, _ts = unpack_trace(trailer)
        except ValueError:
            return  # peek validated shape/magic; don't crash on a race
        self._tracer.add(
            name,
            t0,
            time.perf_counter() - t0,
            args={"trace_id": trace_id, "wid": wid, "seq": seq},
        )

    def _ingest(
        self, proto: Protocol, item, pub: Pub, trailer: bytes | None = None
    ) -> None:
        """One received message. ``item`` is the opaque wire-parts list in
        raw mode, the decoded payload in decode mode (where ``trailer`` is
        the frame's trace context, re-attached on the re-encode so the A/B
        baseline preserves lineage)."""
        if proto in (Protocol.Rollout, Protocol.RolloutBatch, Protocol.Telemetry):
            # Relay a RolloutBatch as one frame — never unpacked into
            # per-step messages. Drop-oldest granularity is one frame: a
            # whole tick for batched workers, exactly the steps that are
            # most stale together. Telemetry snapshots take the same path:
            # tiny frames, forwarded verbatim in raw mode (the aggregator at
            # the storage edge is their consumer, not this relay).
            parts = item if self.raw else encode(proto, item, trace=trailer)
            if self._tracer is not None and len(parts) == 3:
                self._note_trace("relay-in", parts[2])
            if len(self.queue) == self.queue.maxlen:
                # deque(maxlen) evicts silently; count the shed frame so the
                # loss is visible fleet-wide (satellite: silent drop fix).
                self.n_dropped += 1
            self.queue.append(parts)
        elif proto == Protocol.Stat:
            if self.raw:
                # Stats are the one frame kind the manager consumes: full
                # decode (CRC included) of a tiny payload, a few per episode.
                try:
                    _, item = decode(item)
                except ValueError:
                    self.n_stat_rejected += 1
                    return
            self._ingest_stat(item, pub)

    def _ingest_stat(self, payload, pub: Pub) -> None:
        # Workers send either the reference's bare episode reward or the
        # dict form carrying per-worker health counters.
        if isinstance(payload, dict):
            self.stat_q.append(float(payload.get("rew", 0.0)))
            wid = payload.get("wid", -1)
            self.model_loads[wid] = int(payload.get("n_model_loads", 0))
            self.worker_rejected[wid] = int(payload.get("n_rejected", 0))
        else:
            self.stat_q.append(float(payload))
        self.n_stats += 1
        if self.n_stats % STAT_WINDOW == 0:
            mean = sum(self.stat_q) / len(self.stat_q)
            own_rejected = self._sub.n_rejected if self._sub else 0
            pub.send(
                Protocol.Stat,
                {
                    "mean": mean,
                    "n": len(self.stat_q),
                    # Fleet totals: this relay's own corrupt-frame drops
                    # (peek rejects + stat-decode rejects) plus every
                    # worker's model-SUB drops / reloads.
                    "rejected": own_rejected
                    + self.n_stat_rejected
                    + sum(self.worker_rejected.values()),
                    "model_loads": sum(self.model_loads.values()),
                    # Relay health (ISSUE 3): drop-oldest evictions and
                    # forwarded wire bytes -> learner gauges.
                    "relay_dropped": self.n_dropped,
                    "forward_bytes": self.n_forward_bytes,
                },
            )

    def _stopped(self) -> bool:
        return self.stop_event is not None and self.stop_event.is_set()


def manager_main(
    cfg: Config,
    worker_port: int,
    learner_ip: str,
    learner_port: int,
    stop_event,
    heartbeat,
) -> None:
    """mp.Process target (reference ``manager_sub_process``,
    ``main.py:228-242``)."""
    Manager(
        cfg, worker_port, learner_ip, learner_port, stop_event, heartbeat
    ).run()
