"""Manager relay process: per-machine fan-in between workers and the learner
storage.

Capability parity with the reference manager
(``/root/reference/agents/manager.py:11-90``): SUB-bind on the machine's
worker port, forward Rollout messages to the learner storage, window worker
episode rewards and publish the mean every ``stat_window`` episodes. The
bounded drop-oldest queue (deque maxlen 1024, ``manager.py:45-47``) is kept —
back-pressure on a best-effort fleet means shedding the *oldest* data, since
stale rollouts are the least on-policy.

Sync loop instead of the reference's two asyncio tasks: one poll-drain-forward
pass per iteration keeps ordering within a worker's stream and needs no
coordination.
"""

from __future__ import annotations

import time
from collections import deque

from tpu_rl.config import Config
from tpu_rl.runtime.protocol import Protocol
from tpu_rl.runtime.transport import Pub, Sub

RELAY_QUEUE_MAX = 1024  # reference manager.py:45-47
STAT_WINDOW = 50  # reference manager.py:19,62-79


class Manager:
    def __init__(
        self,
        cfg: Config,
        worker_port: int,
        learner_ip: str,
        learner_port: int,
        stop_event=None,
        heartbeat=None,
    ):
        self.cfg = cfg
        self.worker_port = worker_port
        self.learner_addr = (learner_ip, learner_port)
        self.stop_event = stop_event
        self.heartbeat = heartbeat
        self.queue: deque = deque(maxlen=RELAY_QUEUE_MAX)
        self.stat_q: deque = deque(maxlen=STAT_WINDOW)
        self.n_stats = 0
        self.n_forwarded = 0
        # Per-worker health counters (last-seen cumulative values, keyed by
        # wid) relayed in the windowed stat publish so they reach the
        # learner's dashboards (ISSUE 2 satellites: n_model_loads,
        # n_rejected visibility).
        self.model_loads: dict = {}
        self.worker_rejected: dict = {}
        self._sub: Sub | None = None

    def run(self) -> None:
        sub = self._sub = Sub("*", self.worker_port, bind=True)
        pub = Pub(*self.learner_addr, bind=False)
        try:
            while not self._stopped():
                moved = self._pump(sub, pub)
                if self.heartbeat is not None:
                    self.heartbeat.value = time.time()
                if not moved:
                    # Idle: block briefly on the socket instead of spinning.
                    msg = sub.recv(timeout_ms=50)
                    if msg is not None:
                        self._ingest(*msg, pub)
        finally:
            sub.close()
            pub.close()

    # ---------------------------------------------------------------- pump
    def _pump(self, sub: Sub, pub: Pub) -> int:
        moved = 0
        for proto, payload in sub.drain():
            self._ingest(proto, payload, pub)
            moved += 1
        while self.queue:
            pub.send(*self.queue.popleft())
            self.n_forwarded += 1
            moved += 1
        return moved

    def _ingest(self, proto: Protocol, payload, pub: Pub) -> None:
        if proto in (Protocol.Rollout, Protocol.RolloutBatch):
            # Relay a RolloutBatch as one frame — never unpacked into
            # per-step messages (the SUB/PUB hop still decodes+re-encodes
            # once per frame, so batching also cuts this hop's codec calls
            # N-fold). Drop-oldest granularity is therefore one frame: a
            # whole tick for batched workers, exactly the steps that are
            # most stale together.
            self.queue.append((proto, payload))  # drop-oldest at maxlen
        elif proto == Protocol.Stat:
            # Workers send either the reference's bare episode reward or the
            # dict form carrying per-worker health counters.
            if isinstance(payload, dict):
                self.stat_q.append(float(payload.get("rew", 0.0)))
                wid = payload.get("wid", -1)
                self.model_loads[wid] = int(payload.get("n_model_loads", 0))
                self.worker_rejected[wid] = int(payload.get("n_rejected", 0))
            else:
                self.stat_q.append(float(payload))
            self.n_stats += 1
            if self.n_stats % STAT_WINDOW == 0:
                mean = sum(self.stat_q) / len(self.stat_q)
                own_rejected = self._sub.n_rejected if self._sub else 0
                pub.send(
                    Protocol.Stat,
                    {
                        "mean": mean,
                        "n": len(self.stat_q),
                        # Fleet totals: this relay's own corrupt-frame drops
                        # plus every worker's model-SUB drops / reloads.
                        "rejected": own_rejected
                        + sum(self.worker_rejected.values()),
                        "model_loads": sum(self.model_loads.values()),
                    },
                )

    def _stopped(self) -> bool:
        return self.stop_event is not None and self.stop_event.is_set()


def manager_main(
    cfg: Config,
    worker_port: int,
    learner_ip: str,
    learner_port: int,
    stop_event,
    heartbeat,
) -> None:
    """mp.Process target (reference ``manager_sub_process``,
    ``main.py:228-242``)."""
    Manager(
        cfg, worker_port, learner_ip, learner_port, stop_event, heartbeat
    ).run()
