"""Wire protocol: message kinds + framed codec.

Capability parity with the reference's 3-symbol protocol and
pickle+blosc2 codec (``/root/reference/utils/utils.py:229-249``), upgraded:

- the protocol symbol travels as a single byte, not a pickled enum;
- payload frames carry a header (magic, codec id, raw size, crc32 of the
  compressed body) so a corrupt or foreign frame is rejected early —
  PUB/SUB is best-effort and the reference feeds whatever arrives straight
  into ``pickle.loads``;
- the body is a **schema-bound binary serialization** (:func:`pack` /
  :func:`unpack`) over a closed type set — numeric numpy arrays, str, bytes,
  int, float, bool, None, list/tuple, str-keyed dict. Unlike the reference's
  pickle, a hostile frame cannot execute code on decode: there is no object
  reconstruction, only ``np.frombuffer`` on validated dtypes. (The CRC is an
  integrity check, not authentication — this closes the RCE the round-1
  advisor flagged. Ports should still be firewalled to the cluster.);
- compression is the native C++ LZ4-block codec (``native/codec.cpp``) with a
  zlib fallback, chosen per-process at import; both ends interoperate because
  the codec id is in the header;
- tiny payloads skip compression (codec=raw) — the reference pays blosc on
  every 2-float stat message.
"""

from __future__ import annotations

import enum
import struct
import zlib
from typing import Any

import numpy as np

from tpu_rl.runtime import native

# ---------------------------------------------------------------- pack/unpack
# Closed-schema serializer replacing pickle on the wire (the reference
# unpickles network input, ``utils/utils.py:248-249`` — arbitrary code
# execution for anyone who can reach a bound port). Everything the framework
# ships — rollout step dicts, stat floats, param pytrees (nested str-keyed
# dicts of numeric numpy arrays after ``device_get``) — fits this type set.

_LEN = struct.Struct("<I")  # lengths / counts
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
# numpy dtype kinds that are pure data (no object reconstruction on load)
_ARRAY_KINDS = frozenset("biufc")
_MAX_DEPTH = 32


def _pack_into(obj: Any, out: list[bytes], depth: int = 0) -> None:
    if depth > _MAX_DEPTH:
        raise ValueError("payload nesting too deep")
    if obj is None:
        out.append(b"n")
    elif obj is True:
        out.append(b"t")
    elif obj is False:
        out.append(b"f")
    elif isinstance(obj, int):
        try:
            out.append(b"i" + _I64.pack(obj))
        except struct.error as e:
            raise ValueError(f"int out of int64 wire range: {obj}") from e
    elif isinstance(obj, float):
        out.append(b"d" + _F64.pack(obj))
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(b"s" + _LEN.pack(len(b)) + b)
    elif isinstance(obj, bytes):
        out.append(b"y" + _LEN.pack(len(obj)) + obj)
    elif isinstance(obj, (np.ndarray, np.generic)):
        arr = np.ascontiguousarray(obj)
        if arr.dtype.kind not in _ARRAY_KINDS:
            raise ValueError(f"non-numeric array dtype {arr.dtype} on wire")
        dt = arr.dtype.str.encode("ascii")  # e.g. b"<f4"
        body = arr.tobytes()
        out.append(
            b"a"
            + _LEN.pack(len(dt))
            + dt
            + _LEN.pack(arr.ndim)
            + b"".join(_I64.pack(s) for s in arr.shape)
            + _LEN.pack(len(body))
            + body
        )
    elif isinstance(obj, (list, tuple)):
        out.append((b"l" if isinstance(obj, list) else b"u") + _LEN.pack(len(obj)))
        for item in obj:
            _pack_into(item, out, depth + 1)
    elif isinstance(obj, dict):
        out.append(b"m" + _LEN.pack(len(obj)))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise ValueError(f"non-str dict key {type(k).__name__} on wire")
            kb = k.encode("utf-8")
            out.append(_LEN.pack(len(kb)) + kb)
            _pack_into(v, out, depth + 1)
    else:
        # jax Arrays land here (don't import jax in this host-side module):
        # anything exposing __array__ with a numeric dtype is accepted once.
        a = np.asarray(obj)
        if a.dtype.kind not in _ARRAY_KINDS:
            raise ValueError(f"unsupported wire type {type(obj).__name__}")
        _pack_into(a, out, depth)


def pack(obj: Any) -> bytes:
    out: list[bytes] = []
    _pack_into(obj, out)
    return b"".join(out)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.buf):
            raise ValueError("truncated wire payload")
        b = self.buf[self.pos : self.pos + n]
        self.pos += n
        return b

    def u32(self) -> int:
        return _LEN.unpack(self.take(4))[0]


def _unpack_from(r: _Reader, depth: int = 0) -> Any:
    if depth > _MAX_DEPTH:
        raise ValueError("payload nesting too deep")
    tag = r.take(1)
    if tag == b"n":
        return None
    if tag == b"t":
        return True
    if tag == b"f":
        return False
    if tag == b"i":
        return _I64.unpack(r.take(8))[0]
    if tag == b"d":
        return _F64.unpack(r.take(8))[0]
    if tag == b"s":
        return r.take(r.u32()).decode("utf-8")
    if tag == b"y":
        return r.take(r.u32())
    if tag == b"a":
        try:
            dt = np.dtype(r.take(r.u32()).decode("ascii", errors="strict"))
        except (TypeError, UnicodeDecodeError) as e:
            # np.dtype raises TypeError for garbage strings; normalize to the
            # module's ValueError contract so Sub.recv's reject path holds.
            raise ValueError(f"bad wire dtype: {e}") from e
        if dt.kind not in _ARRAY_KINDS:
            raise ValueError(f"non-numeric array dtype {dt} on wire")
        ndim = r.u32()
        if ndim > 32:
            raise ValueError("array rank too large")
        shape = tuple(_I64.unpack(r.take(8))[0] for _ in range(ndim))
        if any(s < 0 for s in shape):
            raise ValueError("negative array dim")
        body = r.take(r.u32())
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if len(body) != n * dt.itemsize:
            raise ValueError("array byte-size mismatch")
        return np.frombuffer(body, dtype=dt).reshape(shape).copy()
    if tag in (b"l", b"u"):
        n = r.u32()
        items = [_unpack_from(r, depth + 1) for _ in range(n)]
        return items if tag == b"l" else tuple(items)
    if tag == b"m":
        n = r.u32()
        d = {}
        for _ in range(n):
            k = r.take(r.u32()).decode("utf-8")
            d[k] = _unpack_from(r, depth + 1)
        return d
    raise ValueError(f"unknown wire tag {tag!r}")


def unpack(buf: bytes) -> Any:
    r = _Reader(buf)
    obj = _unpack_from(r)
    if r.pos != len(buf):
        raise ValueError("trailing bytes in wire payload")
    return obj


def _lz4_decompress_py(src: bytes, raw_size: int) -> bytes:
    """Pure-Python LZ4 block decoder — fallback mirror of
    ``native/codec.cpp:tpurl_decompress`` for hosts without a C++ toolchain."""
    out = bytearray()
    i, n = 0, len(src)
    while i < n:
        token = src[i]
        i += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                if i >= n:
                    raise ValueError("truncated LZ4 literal length")
                b = src[i]
                i += 1
                lit_len += b
                if b != 255:
                    break
        if i + lit_len > n:
            raise ValueError("truncated LZ4 literals")
        if len(out) + lit_len > raw_size:
            raise ValueError("LZ4 literals exceed declared raw size")
        out += src[i : i + lit_len]
        i += lit_len
        if i >= n:
            break  # last sequence has no match
        if i + 2 > n:
            raise ValueError("truncated LZ4 offset")
        offset = src[i] | (src[i + 1] << 8)
        i += 2
        if offset == 0 or offset > len(out):
            raise ValueError("corrupt LZ4 offset")
        match_len = token & 15
        if match_len == 15:
            while True:
                if i >= n:
                    raise ValueError("truncated LZ4 match length")
                b = src[i]
                i += 1
                match_len += b
                if b != 255:
                    break
        match_len += 4
        if len(out) + match_len > raw_size:
            # A single declared match run must not blow past the target size
            # (a 16 MB body can otherwise declare a multi-GB expansion).
            raise ValueError("LZ4 match exceeds declared raw size")
        # Overlapping copy must be byte-serial when offset < match_len.
        pos = len(out) - offset
        for _ in range(match_len):
            out.append(out[pos])
            pos += 1
    if len(out) != raw_size:
        raise ValueError(f"LZ4 size mismatch: {len(out)} != {raw_size}")
    return bytes(out)


class Protocol(enum.IntEnum):
    """Message kinds (reference ``utils/utils.py:229-232``)."""

    Model = 0  # learner -> workers: parameter broadcast
    Rollout = 1  # worker -> manager -> storage: one env step
    Stat = 2  # worker -> manager -> storage: episode reward
    # One worker TICK: all worker_num_envs transitions stacked on a leading
    # env axis, one frame. The reference publishes one dict per env step
    # (``agents/worker.py:110-125``); at 32 envs that is 32 encode+send
    # calls per tick, and framing overhead was measured to cap the wire at
    # ~3.2k env-steps/s — batched, one encode covers the whole tick (and
    # the stacked arrays compress far better). Split back into per-step
    # dicts by ``tpu_rl.data.assembler.split_rollout_batch``.
    RolloutBatch = 3
    # SEED-style centralized inference (runtime/inference_service.py):
    # worker DEALER -> learner ROUTER, one frame per worker tick carrying
    # the tick's observations {"wid", "seq", "obs" (n, obs_dim),
    # "first" (n,)} — the recurrent carry stays server-side, it never
    # rides this request.
    ObsRequest = 4
    # The reply: {"seq", "act", "logits", "log_prob"} (+ "hx"/"cx" pre-step
    # carry rows for store_carry families — the learner trains from them,
    # so they must reach the RolloutBatch the worker publishes).
    Act = 5
    # Periodic MetricsRegistry snapshot (tpu_rl.obs): every role ships its
    # counters/gauges/histograms as one tiny labeled frame on the stat
    # channel. The manager FORWARDS these like rollout frames (verbatim
    # parts in raw relay mode — peek routes on the proto byte); the storage
    # edge decodes and feeds the TelemetryAggregator.
    Telemetry = 6


class Codec(enum.IntEnum):
    RAW = 0
    LZ4 = 1  # native/codec.cpp
    ZLIB = 2


_MAGIC = 0x5452  # "TR"
_HEADER = struct.Struct("<HBBII")  # magic, version, codec, raw_size, crc32
# Declared wire size of the frame header. The assert makes a format edit
# fail at import instead of silently skewing every peek/encode offset; the
# static twin lives in tools/analysis (protocol checker, PC001).
HEADER_BYTES = 12
assert _HEADER.size == HEADER_BYTES, (
    f"frame header format {_HEADER.format!r} packs {_HEADER.size} bytes, "
    f"declared HEADER_BYTES is {HEADER_BYTES} — update both together "
    "(and bump _VERSION: this is a wire-format change)"
)
_VERSION = 1
_MIN_COMPRESS = 128  # bytes; below this, framing overhead beats compression
# Hard ceiling on a frame's declared decompressed size: a hostile header may
# claim up to 4 GB (u32) — reject before any allocation. 1 GiB comfortably
# covers the largest legitimate payload (a full model broadcast).
_MAX_RAW = 1 << 30

# Standard IEEE CRC-32 (zlib's C implementation; interoperates with the
# native tpurl_crc32, which implements the same polynomial).
_crc = zlib.crc32

# ------------------------------------------------------------- trace trailer
# Rollout-lineage trace context (tpu_rl.obs): a sampled frame carries its
# origin as an OPTIONAL THIRD wire part, so the raw relay forwards it
# verbatim (send_multipart ships whatever parts arrived) and every other
# frame stays the exact 2-part message it always was. Fixed-size struct, own
# magic — a relay can validate it in O(1) without touching the payload.
_TRAILER_MAGIC = 0x5443  # "TC"
_TRAILER_VERSION = 1
# magic u16, version u8, pad, wid i32, frame seq u32, trace id u64,
# sender's time.time_ns() at send i64
_TRAILER = struct.Struct("<HBxiIQq")
# Declared wire size of the trace trailer — the 28-byte third part every
# relay validates in O(1). Same contract as HEADER_BYTES above: a format
# edit must fail here, not skew unpack_trace/_check_trailer offsets.
TRAILER_BYTES = 28
assert _TRAILER.size == TRAILER_BYTES, (
    f"trace trailer format {_TRAILER.format!r} packs {_TRAILER.size} bytes, "
    f"declared TRAILER_BYTES is {TRAILER_BYTES} — update both together "
    "(and bump _TRAILER_VERSION: this is a wire-format change)"
)
# The only kinds that may carry a trailer: the rollout data plane. A trailer
# on anything else (Model, Stat, control frames) is a hostile/corrupt frame
# and is rejected into the receiver's ``n_rejected`` path.
TRACE_KINDS = frozenset({Protocol.Rollout, Protocol.RolloutBatch})

# Derived forms handed to the native batch validator (native/codec.cpp) so
# the enum above stays the single source of truth: a bitmask over protocol
# bytes allowed to carry a trailer, and the highest known protocol byte.
TRACE_KINDS_MASK = 0
for _k in TRACE_KINDS:
    TRACE_KINDS_MASK |= 1 << int(_k)
MAX_PROTO = max(int(_p) for _p in Protocol)


def make_trace_id(wid: int, seq: int) -> int:
    """Deterministic fleet-unique trace id for a sampled tick: the origin
    worker in the high bits, its tick sequence below. Stays under 2**54 so
    the id survives JSON consumers that parse ints as doubles."""
    return ((wid & 0x3FFFFF) << 32) | (seq & 0xFFFFFFFF)


def pack_trace(wid: int, seq: int, trace_id: int, send_ts_ns: int) -> bytes:
    """Encode one trace-context trailer (the optional third wire part)."""
    return _TRAILER.pack(
        _TRAILER_MAGIC, _TRAILER_VERSION, wid, seq & 0xFFFFFFFF,
        trace_id & 0xFFFFFFFFFFFFFFFF, send_ts_ns,
    )


def unpack_trace(trailer: bytes) -> tuple[int, int, int, int]:
    """-> ``(wid, seq, trace_id, send_ts_ns)``; ValueError on garbage."""
    if len(trailer) != _TRAILER.size:
        raise ValueError(f"bad trace trailer size {len(trailer)}")
    magic, version, wid, seq, trace_id, ts = _TRAILER.unpack(trailer)
    if magic != _TRAILER_MAGIC or version != _TRAILER_VERSION:
        raise ValueError(f"bad trace trailer magic/version {magic:#x}/{version}")
    return wid, seq, trace_id, ts


def _check_trailer(proto: Protocol, parts: list[bytes]) -> None:
    """Relay-grade trailer validation (size cap = the exact struct size, kind
    allowlist, magic/version) — no payload decode, same cost class as
    :func:`peek`'s header checks."""
    if proto not in TRACE_KINDS:
        raise ValueError(f"trace trailer not allowed on {proto!r}")
    trailer = parts[2]
    if len(trailer) != _TRAILER.size:
        raise ValueError(f"bad trace trailer size {len(trailer)}")
    magic, version = _TRAILER.unpack_from(trailer)[:2]
    if magic != _TRAILER_MAGIC or version != _TRAILER_VERSION:
        raise ValueError(f"bad trace trailer magic/version {magic:#x}/{version}")


def encode(
    proto: Protocol, payload: Any, trace: bytes | None = None
) -> list[bytes]:
    """-> multipart message ``[proto_byte, frame]`` (reference ``encode``,
    ``utils/utils.py:244-245``), plus the optional trace-context trailer as a
    third part (see :func:`pack_trace`)."""
    raw = pack(payload)
    if len(raw) < _MIN_COMPRESS:
        codec, body = Codec.RAW, raw
    elif native.available():
        codec, body = Codec.LZ4, native.compress(raw)
    else:
        codec, body = Codec.ZLIB, zlib.compress(raw, level=1)
    if codec != Codec.RAW and len(body) >= len(raw):
        codec, body = Codec.RAW, raw  # incompressible: ship raw
    header = _HEADER.pack(_MAGIC, _VERSION, codec, len(raw), _crc(body) & 0xFFFFFFFF)
    if trace is None:
        return [bytes([proto]), header + body]
    return [bytes([proto]), header + body, trace]


def peek(parts: list[bytes]) -> Protocol:
    """Cheap relay-hop validation of a multipart frame: proto byte, header
    magic/version, known codec, declared-size cap — WITHOUT the CRC pass,
    decompression, or unpack that :func:`decode` performs. O(1) in the
    payload size, so a relay can route millions of frames/s on the proto
    byte alone. The full CRC + decode runs once, at the storage edge — the
    only hop that consumes rollout payloads. Raises ValueError on frames a
    relay must not forward (foreign publishers, truncated frames, hostile
    size declarations); a corrupt *body* under a valid header passes peek
    and is rejected downstream by decode's CRC. A third part, when present,
    must be a valid trace trailer on a kind that allows one
    (:func:`_check_trailer`) — anything else is rejected here so relays never
    amplify garbage trailers."""
    if len(parts) not in (2, 3) or len(parts[0]) != 1:
        raise ValueError(f"malformed multipart message: {len(parts)} parts")
    proto = Protocol(parts[0][0])  # ValueError on an unknown proto byte
    frame = parts[1]
    if len(frame) < _HEADER.size:
        raise ValueError("short frame")
    magic, version, codec, raw_size, _crc32 = _HEADER.unpack_from(frame)
    if magic != _MAGIC or version != _VERSION:
        raise ValueError(f"bad frame magic/version {magic:#x}/{version}")
    if raw_size > _MAX_RAW:
        raise ValueError(f"declared raw size {raw_size} exceeds cap {_MAX_RAW}")
    if codec == Codec.RAW:
        # Uncompressed body: the size invariant is free to check here.
        if len(frame) - _HEADER.size != raw_size:
            raise ValueError("raw body size mismatch")
    elif codec not in (Codec.LZ4, Codec.ZLIB):
        raise ValueError(f"unknown codec {codec}")
    if len(parts) == 3:
        _check_trailer(proto, parts)
    return proto


def decode(parts: list[bytes], validated: bool = False) -> tuple[Protocol, Any]:
    """Inverse of :func:`encode` (reference ``decode``,
    ``utils/utils.py:248-249``). Raises ValueError on malformed frames —
    including a trace trailer on a kind that doesn't allow one (the trailer
    itself is otherwise ignored here; lineage consumers read it via
    ``Sub.recv_traced``).

    ``validated=True`` skips the structural checks AND the body CRC pass:
    the caller already ran them, e.g. via the native batch validator's
    crc variant (``native.validate_batch(check_crc=True)``) over a whole
    drained deque — re-hashing every body here would pay the batch's
    dominant cost a second time. Decompress + schema unpack still run."""
    if len(parts) not in (2, 3) or len(parts[0]) != 1:
        raise ValueError(f"malformed multipart message: {len(parts)} parts")
    proto = Protocol(parts[0][0])
    if not validated and len(parts) == 3:
        _check_trailer(proto, parts)
    frame = parts[1]
    if len(frame) < _HEADER.size:
        raise ValueError("short frame")
    magic, version, codec, raw_size, crc = _HEADER.unpack_from(frame)
    if not validated:
        if magic != _MAGIC or version != _VERSION:
            raise ValueError(f"bad frame magic/version {magic:#x}/{version}")
        if raw_size > _MAX_RAW:
            raise ValueError(
                f"declared raw size {raw_size} exceeds cap {_MAX_RAW}"
            )
    body = frame[_HEADER.size :]
    if not validated and _crc(body) & 0xFFFFFFFF != crc:
        raise ValueError("frame crc mismatch")
    if codec == Codec.RAW:
        raw = body
    elif codec == Codec.LZ4:
        try:
            if native.available():
                raw = native.decompress(body, raw_size)
            else:
                # Peer has the native codec, this host does not (no
                # toolchain): decode in Python so interop is bidirectional.
                # Slow, but only ever hit on degraded hosts.
                raw = _lz4_decompress_py(body, raw_size)
        except (RuntimeError, MemoryError) as e:
            # native codec error / allocation failure -> reject, not crash
            raise ValueError(f"corrupt LZ4 body: {e}") from e
    elif codec == Codec.ZLIB:
        try:
            # Bounded decompress: a zlib bomb must not expand past the
            # declared raw_size before the size check below runs.
            d = zlib.decompressobj()
            raw = d.decompress(body, raw_size + 1)
        except zlib.error as e:
            raise ValueError(f"corrupt zlib body: {e}") from e
    else:
        raise ValueError(f"unknown codec {codec}")
    if len(raw) != raw_size:
        raise ValueError(f"size mismatch: {len(raw)} != {raw_size}")
    return proto, unpack(raw)
