"""Wire protocol: message kinds + framed codec.

Capability parity with the reference's 3-symbol protocol and
pickle+blosc2 codec (``/root/reference/utils/utils.py:229-249``), upgraded:

- the protocol symbol travels as a single byte, not a pickled enum;
- payload frames carry a header (magic, codec id, raw size, crc32 of the
  compressed body) so a corrupt or foreign frame is rejected instead of
  unpickled — PUB/SUB is best-effort and the reference feeds whatever arrives
  straight into ``pickle.loads``;
- compression is the native C++ LZ4-block codec (``native/codec.cpp``) with a
  zlib fallback, chosen per-process at import; both ends interoperate because
  the codec id is in the header;
- tiny payloads skip compression (codec=raw) — the reference pays blosc on
  every 2-float stat message.
"""

from __future__ import annotations

import enum
import pickle
import struct
import zlib
from typing import Any

from tpu_rl.runtime import native


def _lz4_decompress_py(src: bytes, raw_size: int) -> bytes:
    """Pure-Python LZ4 block decoder — fallback mirror of
    ``native/codec.cpp:tpurl_decompress`` for hosts without a C++ toolchain."""
    out = bytearray()
    i, n = 0, len(src)
    while i < n:
        token = src[i]
        i += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                if i >= n:
                    raise ValueError("truncated LZ4 literal length")
                b = src[i]
                i += 1
                lit_len += b
                if b != 255:
                    break
        if i + lit_len > n:
            raise ValueError("truncated LZ4 literals")
        out += src[i : i + lit_len]
        i += lit_len
        if i >= n:
            break  # last sequence has no match
        if i + 2 > n:
            raise ValueError("truncated LZ4 offset")
        offset = src[i] | (src[i + 1] << 8)
        i += 2
        if offset == 0 or offset > len(out):
            raise ValueError("corrupt LZ4 offset")
        match_len = token & 15
        if match_len == 15:
            while True:
                if i >= n:
                    raise ValueError("truncated LZ4 match length")
                b = src[i]
                i += 1
                match_len += b
                if b != 255:
                    break
        match_len += 4
        # Overlapping copy must be byte-serial when offset < match_len.
        pos = len(out) - offset
        for _ in range(match_len):
            out.append(out[pos])
            pos += 1
    if len(out) != raw_size:
        raise ValueError(f"LZ4 size mismatch: {len(out)} != {raw_size}")
    return bytes(out)


class Protocol(enum.IntEnum):
    """Message kinds (reference ``utils/utils.py:229-232``)."""

    Model = 0  # learner -> workers: parameter broadcast
    Rollout = 1  # worker -> manager -> storage: one env step
    Stat = 2  # worker -> manager -> storage: episode reward


class Codec(enum.IntEnum):
    RAW = 0
    LZ4 = 1  # native/codec.cpp
    ZLIB = 2


_MAGIC = 0x5452  # "TR"
_HEADER = struct.Struct("<HBBII")  # magic, version, codec, raw_size, crc32
_VERSION = 1
_MIN_COMPRESS = 128  # bytes; below this, framing overhead beats compression

# Standard IEEE CRC-32 (zlib's C implementation; interoperates with the
# native tpurl_crc32, which implements the same polynomial).
_crc = zlib.crc32


def encode(proto: Protocol, payload: Any) -> list[bytes]:
    """-> 2-part multipart message ``[proto_byte, frame]`` (reference
    ``encode``, ``utils/utils.py:244-245``)."""
    raw = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    if len(raw) < _MIN_COMPRESS:
        codec, body = Codec.RAW, raw
    elif native.available():
        codec, body = Codec.LZ4, native.compress(raw)
    else:
        codec, body = Codec.ZLIB, zlib.compress(raw, level=1)
    if codec != Codec.RAW and len(body) >= len(raw):
        codec, body = Codec.RAW, raw  # incompressible: ship raw
    header = _HEADER.pack(_MAGIC, _VERSION, codec, len(raw), _crc(body) & 0xFFFFFFFF)
    return [bytes([proto]), header + body]


def decode(parts: list[bytes]) -> tuple[Protocol, Any]:
    """Inverse of :func:`encode` (reference ``decode``,
    ``utils/utils.py:248-249``). Raises ValueError on malformed frames."""
    if len(parts) != 2 or len(parts[0]) != 1:
        raise ValueError(f"malformed multipart message: {len(parts)} parts")
    proto = Protocol(parts[0][0])
    frame = parts[1]
    if len(frame) < _HEADER.size:
        raise ValueError("short frame")
    magic, version, codec, raw_size, crc = _HEADER.unpack_from(frame)
    if magic != _MAGIC or version != _VERSION:
        raise ValueError(f"bad frame magic/version {magic:#x}/{version}")
    body = frame[_HEADER.size :]
    if _crc(body) & 0xFFFFFFFF != crc:
        raise ValueError("frame crc mismatch")
    if codec == Codec.RAW:
        raw = body
    elif codec == Codec.LZ4:
        if native.available():
            raw = native.decompress(body, raw_size)
        else:
            # Peer has the native codec, this host does not (no toolchain):
            # decode in Python so interop is bidirectional. Slow, but only
            # ever hit on degraded hosts.
            raw = _lz4_decompress_py(body, raw_size)
    elif codec == Codec.ZLIB:
        raw = zlib.decompress(body)
    else:
        raise ValueError(f"unknown codec {codec}")
    if len(raw) != raw_size:
        raise ValueError(f"size mismatch: {len(raw)} != {raw_size}")
    return proto, pickle.loads(raw)
