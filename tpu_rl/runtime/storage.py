"""Learner-storage process: bridge from the DCN transport into device-feedable
shared memory.

Capability parity with the reference ``LearnerStorage``
(``/root/reference/agents/learner_storage.py:25-159``): SUB-bind on the
learner port, push Rollout steps through the assembler, write completed
windows into the shm store, relay episode-reward stats into the 3-float stat
mailbox ``[global_game_count, mean_rew, activate]``
(``learner_storage.py:104-121``, created at ``main.py:324-326``).

This is the storage edge of the zero-copy fan-in (ISSUE 3): the one hop that
runs the full frame validation (CRC + decompress + schema unpack, inside
``Sub.recv``/``drain``) — relays upstream only ``peek`` the header. Whole
worker ticks then enter the assembler columnar-wise via
``RolloutAssembler.push_tick`` (row views per env, no per-step dicts) and
completed windows leave in bursts via the stores' ``put_many`` (one slice
write per field). ``Config.relay_mode="decode"`` keeps the per-step
``split_rollout_batch`` + ``push`` reference path as the A/B baseline.
"""

from __future__ import annotations

import os
import time

from tpu_rl.config import Config
from tpu_rl.data.assembler import RolloutAssembler, split_rollout_batch
from tpu_rl.data.layout import BatchLayout
from tpu_rl.data.shm_ring import ShmHandles, make_store
from tpu_rl.runtime.mailbox import (
    SLOT_ACTIVATE,
    SLOT_FORWARD_BYTES,
    SLOT_GAME_COUNT,
    SLOT_JOIN_REQ,
    SLOT_MEAN_REW,
    SLOT_MODEL_LOADS,
    SLOT_REJECTED,
    SLOT_RELAY_DROPPED,
    SLOT_RUN_EPOCH,
    STAT_SLOTS,
)
from tpu_rl.runtime.protocol import Protocol, unpack_trace
from tpu_rl.runtime.transport import Sub, make_data_sub

# Slot layout lives in tpu_rl.runtime.mailbox (shared with the learner's
# reader); STAT_SLOTS is re-exported here for existing importers.
__all__ = ["LearnerStorage", "MembershipTable", "STAT_SLOTS", "storage_main"]


class MembershipTable:
    """Lease-based live membership of acting workers, keyed by wid.

    Any frame carrying a wid (RolloutBatch or Telemetry) renews the lease;
    silence past ``lease_s`` evicts. The table is always on (one dict write
    per frame) because the JOIN signal is functional, not observational: a
    new wid raises the learner's immediate weight-push flag so a joining or
    supervisor-respawned worker converges onto the live policy at once
    instead of waiting out ``rebroadcast_idle_s``. Join/evict totals and the
    active-count gauge surface through the telemetry plane when it's on.
    """

    def __init__(self, lease_s: float, clock=time.monotonic):
        self.lease_s = float(lease_s)
        self._clock = clock
        self.active: dict[int, float] = {}  # wid -> last-seen monotonic
        self.n_joined = 0
        self.n_evicted = 0
        # Quarantine plane (tpu_rl.heal): per-wid poisoned-frame strikes and
        # the quarantined set (wid -> last-strike monotonic). A quarantined
        # wid keeps its LEASE (it is alive, just untrusted) — its rollout
        # frames are dropped at the ingress edge until a clean re-probe.
        self.strikes: dict[int, int] = {}
        self.quarantined: dict[int, float] = {}
        self.n_quarantines = 0
        self.n_unquarantines = 0

    def touch(self, wid: int, now: float | None = None) -> bool:
        """Renew wid's lease; True iff this is a (re)join."""
        now = self._clock() if now is None else now
        joined = wid not in self.active
        if joined:
            self.n_joined += 1
        self.active[wid] = now
        return joined

    def evict_expired(self, now: float | None = None) -> list[int]:
        now = self._clock() if now is None else now
        dead = [w for w, t in self.active.items() if now - t > self.lease_s]
        for w in dead:
            del self.active[w]
            self.n_evicted += 1
        return dead

    # ------------------------------------------------- quarantine (hot path)
    def strike(self, wid: int, limit: int, now: float | None = None) -> bool:
        """One poisoned frame from wid; True iff this strike quarantines it.
        An already-quarantined wid refreshes its last-strike time (the
        clean-re-probe cooldown restarts)."""
        now = self._clock() if now is None else now
        self.strikes[wid] = self.strikes.get(wid, 0) + 1
        if wid in self.quarantined:
            self.quarantined[wid] = now
            return False
        if self.strikes[wid] >= limit:
            self.quarantined[wid] = now
            self.n_quarantines += 1
            return True
        return False

    def is_quarantined(self, wid: int) -> bool:
        return wid in self.quarantined

    def probe_clear(
        self, wid: int, cooldown: float, now: float | None = None
    ) -> bool:
        """A CLEAN frame arrived from a quarantined wid: clear the
        quarantine (and its strikes) iff the last poisoned frame is at
        least ``cooldown`` seconds old. True = cleared, frame admissible."""
        now = self._clock() if now is None else now
        if now - self.quarantined[wid] >= cooldown:
            del self.quarantined[wid]
            self.strikes[wid] = 0
            self.n_unquarantines += 1
            return True
        return False


class LearnerStorage:
    def __init__(
        self,
        cfg: Config,
        handles: ShmHandles,
        learner_port: int,
        stat_array=None,
        stop_event=None,
        heartbeat=None,
    ):
        self.cfg = cfg
        self.handles = handles
        self.learner_port = learner_port
        self.stat_array = stat_array
        self.stop_event = stop_event
        self.heartbeat = heartbeat
        self.game_count = 0
        self.n_windows = 0
        self.n_requeue_full = 0  # windows requeued because the store was full
        self._sub: Sub | None = None
        # Run-epoch fence (durable-fleet plane): the highest epoch learned
        # from the mailbox slot (primary — the mp.Array outlives child
        # respawns, so a respawned storage re-arms instantly) or from frame
        # echoes. Frames stamped with a KNOWN older epoch were acted under a
        # pre-crash learner incarnation: dropped and counted here, never
        # mixed into training and never conflated with corrupt-frame
        # n_rejected (chaos parity). epoch < 0 = unknown, always accepted.
        self.run_epoch = -1
        self.n_stale_epoch = 0
        # Worker join/leave registry (heartbeat lease over frame arrivals).
        self.members = MembershipTable(cfg.membership_lease_s)
        # Inference-replica registry: same lease mechanics keyed by the
        # `rid` on replica telemetry snapshots, plus per-replica served
        # versions and the fleet's monotonic version floor. Import is lazy
        # (fleet.membership subclasses MembershipTable from THIS module).
        from tpu_rl.fleet.membership import ReplicaTable

        self.replicas = ReplicaTable(cfg.membership_lease_s)
        self._next_evict = 0.0
        # Telemetry plane (tpu_rl.obs): the aggregator lives HERE — storage
        # is the learner-side edge of the stat channel, the one hop every
        # role's snapshots already reach. None when disabled; every call
        # site guards on that, so the off state costs one check per frame.
        self.aggregator = None
        self._http = None
        self._json_exp = None
        self._tb_exp = None
        # Run-history plane (tpu_rl.obs.history): the embedded time-series
        # store fed on the JSON exporter's cadence; /query serves it live.
        # None when the plane is off — one `is None` check per export tick.
        self._history = None
        # Goodput plane (tpu_rl.obs.goodput): this loop's own wall-clock
        # ledger plus the per-wid straggler signals the fleet report is
        # built from. `_wid_frames` doubles as the plane gate on the ingest
        # hot path (None when telemetry is off — one `is None` check per
        # frame, same discipline as the aggregator above).
        self.ledger = None
        self._wid_frames = None  # wid -> cumulative admitted frames
        self._wid_ver = {}  # wid -> last echoed policy version
        self._wid_rtt = {}  # wid -> rtt EWMA, seconds
        self._wid_rate = {}  # wid -> frames/s over the last straggler tick
        self._frames_prev = {}  # wid -> (count, t_mono) at the last tick
        self._straggler_top = []  # last top-k report (GET /goodput)
        # SLO engine (tpu_rl.obs.slo): storage owns fleet-wide evaluation —
        # it already aggregates every role's snapshots. Evaluated on a 1s
        # cadence (not per frame); /slo serves the last verdict. None unless
        # Config.slo_spec is set.
        self._slo = None
        self._next_slo = 0.0
        # On-demand profiler captures (/prof?ms=N) for THIS process; the
        # flight-recorder crash hook guarantees stop_trace on fatal exits.
        self._prof = None
        # Rollout-lineage tracing (tpu_rl.obs): the storage edge records the
        # ingest + window-close hops for sampled frames, estimates every
        # remote source's clock offset from telemetry echoes, and auto-
        # merges all roles' dumps into result_dir/fleet_trace.json at
        # shutdown. Everything None when there is no result_dir; untraced
        # frames cost one `is None` check.
        self._tracer = None
        self._trace_path = None
        self.clocksync = None
        # Fault injection (tpu_rl.chaos): corrupt/drop:rollout|stat|telemetry
        # and delay:storage apply at THIS Sub's receives — the consuming edge
        # — so every injected corruption pairs with one n_rejected in the
        # same recv call. None unless a chaos_spec names this site.
        self._chaos = None
        if cfg.chaos_spec:
            from tpu_rl.chaos import maybe_transport_chaos

            self._chaos = maybe_transport_chaos(cfg, "storage")
        # Ingress validation (tpu_rl.heal): finite/range checks over each
        # RolloutBatch's obs/rew columns BEFORE the epoch fence, feeding the
        # membership table's per-wid quarantine strikes. None when off — the
        # ingest path then pays one `is None` check per frame.
        self._ingress = None
        if cfg.ingress_validate:
            from tpu_rl.heal.ingress import IngressGuard

            self._ingress = IngressGuard(abs_max=cfg.ingress_abs_max)

    def run(self) -> None:
        cfg = self.cfg
        layout = BatchLayout.from_config(cfg)
        assembler = RolloutAssembler(layout, lag_sec=cfg.rollout_lag_sec)
        store = make_store(cfg, layout, handles=self.handles)
        # Fan-in edge: a FanInSub (shm rings + the TCP SUB) when
        # Config.transport enables the shm channel, else the plain TCP SUB.
        # Either way the ingest loop below sees the same recv_traced/
        # drain_traced surface and the same n_rejected accounting.
        sub = self._sub = make_data_sub(
            cfg, "*", self.learner_port, bind=True, chaos=self._chaos
        )
        self._setup_trace(assembler)
        self._setup_telemetry()
        led = self.ledger
        if led is not None:
            from tpu_rl.obs.goodput import COMPUTE, IDLE, WIRE
        try:
            while not self._stopped():
                self._poll_epoch()
                t_recv = time.perf_counter()
                msg = sub.recv_traced(timeout_ms=50)
                t_work = time.perf_counter()
                if led is not None:
                    # The bounded recv is the loop's only wait: wire time
                    # when a frame landed, idle when the fleet was quiet.
                    led.add(WIRE if msg is not None else IDLE, t_work - t_recv)
                if msg is not None:
                    self._ingest(msg[0], msg[1], assembler, msg[2])
                for proto, payload, trailer in sub.drain_traced():
                    self._ingest(proto, payload, assembler, trailer)
                self._flush(assembler, store)
                if led is not None:
                    # Ingest + assembly + window flush: the work this role
                    # exists for — its compute bucket.
                    led.add(COMPUTE, time.perf_counter() - t_work)
                now_m = time.monotonic()
                if now_m >= self._next_evict:
                    self._next_evict = now_m + 1.0
                    self.members.evict_expired(now_m)
                    self.replicas.evict_expired(now_m)
                if self.aggregator is not None:
                    self._telemetry_tick()
                if self.heartbeat is not None:
                    self.heartbeat.value = time.time()
        finally:
            sub.close()
            self._close_trace()
            self._close_telemetry()

    # ----------------------------------------------------------------- trace
    def _setup_trace(self, assembler) -> None:
        cfg = self.cfg
        if cfg.result_dir is None:
            return
        from tpu_rl.obs import ClockSync, TraceRecorder, flightrec

        self._tracer = TraceRecorder(
            capacity=cfg.trace_capacity, pid=os.getpid(), role="storage"
        )
        self._trace_path = os.path.join(
            cfg.result_dir, f"trace-storage-{os.getpid()}.json"
        )
        # Offsets of every remote process against THIS host's clock (learner
        # and storage are shm-colocated, so this is the fleet's reference).
        self.clocksync = ClockSync()
        flightrec.install(
            "storage",
            cfg.result_dir,
            tracer=self._tracer,
            cfg=cfg,
            extra=lambda: {
                "assembler": assembler.stats,
                "windows": self.n_windows,
                "requeue_full": self.n_requeue_full,
            },
        )

    def _tracez(self) -> dict:
        """Live snapshot for the HTTP /tracez endpoint."""
        return {
            "role": "storage",
            "pid": os.getpid(),
            "trace": (
                self._tracer.to_chrome() if self._tracer is not None else None
            ),
            "clock": (
                self.clocksync.snapshot() if self.clocksync is not None else {}
            ),
        }

    def _close_trace(self) -> None:
        if self._tracer is None:
            return
        extra = (
            {"clock": self.clocksync.snapshot()}
            if self.clocksync is not None
            else None
        )
        self._tracer.dump(self._trace_path, extra_meta=extra)
        # Auto-merge at shutdown: storage is the last data-plane process to
        # exit and every role dumps on the telemetry cadence, so what's on
        # disk now is the fleet's final (or near-final) state. Best-effort —
        # the per-role dumps stay either way and the CLI merger can rerun.
        try:
            from tpu_rl.obs.merge import merge_result_dir

            merge_result_dir(self.cfg.result_dir)
        except Exception as e:  # noqa: BLE001 — shutdown must not crash
            print(f"[storage] fleet-trace merge failed: {e!r}", flush=True)

    # ------------------------------------------------------------- telemetry
    def _setup_telemetry(self) -> None:
        """Construct the aggregator + exporters iff the plane has a sink
        (``Config.telemetry_enabled``); otherwise everything stays None and
        the ingest/tick paths reduce to a single ``is None`` check."""
        cfg = self.cfg
        if not cfg.telemetry_enabled:
            return
        from tpu_rl.obs import (
            GoodputLedger,
            JsonExporter,
            MetricsRegistry,
            ProfilerCapture,
            TelemetryAggregator,
            TelemetryHTTPServer,
            TensorboardExporter,
            maybe_history,
            maybe_slo_engine,
        )
        from tpu_rl.utils.metrics import NullWriter, make_writer

        self.aggregator = TelemetryAggregator(
            registry=MetricsRegistry(role="storage"),
            stale_after_s=cfg.telemetry_stale_s,
        )
        self.ledger = GoodputLedger("storage")
        self._wid_frames = {}
        self._slo = maybe_slo_engine(cfg)
        self._history = maybe_history(cfg)
        if cfg.result_dir is not None:
            self._prof = ProfilerCapture(os.path.join(cfg.result_dir, "prof"))
        if cfg.telemetry_port > 0:
            self._http = TelemetryHTTPServer(
                self.aggregator,
                cfg.telemetry_port,
                tracez=self._tracez,
                slo=self._slo.report if self._slo is not None else None,
                prof=(
                    self._prof.capture_async if self._prof is not None else None
                ),
                goodput=self._goodput_payload,
                query=(
                    self._history.http_query
                    if self._history is not None else None
                ),
            )
        if cfg.result_dir is not None:
            self._json_exp = JsonExporter(
                self.aggregator,
                os.path.join(cfg.result_dir, "telemetry.json"),
                interval_s=cfg.telemetry_interval_s,
            )
            writer = make_writer(os.path.join(cfg.result_dir, "telemetry"))
            if not isinstance(writer, NullWriter):
                # Fleet health next to the loss curves; rides the JSON
                # exporter's cadence (no writer of its own clock). Skipped
                # when tensorboardX is absent — the JSON file still lands.
                self._tb_exp = TensorboardExporter(writer)

    def _telemetry_tick(self) -> None:
        reg = self.aggregator.registry
        reg.counter("storage-windows").set_total(self.n_windows)
        reg.counter("storage-requeue-full").set_total(self.n_requeue_full)
        reg.counter("storage-rejected-frames").set_total(
            self._sub.n_rejected if self._sub is not None else 0
        )
        reg.counter("storage-telemetry-ingested").set_total(
            self.aggregator.n_ingested
        )
        reg.gauge("storage-game-count").set(self.game_count)
        # Durability plane: the epoch fence and the membership lease table.
        reg.gauge("storage-run-epoch").set(self.run_epoch)
        reg.counter("storage-stale-epoch-frames").set_total(
            self.n_stale_epoch
        )
        reg.gauge("storage-members-active").set(len(self.members.active))
        reg.counter("storage-members-joined").set_total(self.members.n_joined)
        reg.counter("storage-members-evicted").set_total(
            self.members.n_evicted
        )
        # Inference-fleet membership + the version-consistency watch: the
        # floor is the ratchet clients pin to, min-active the worst
        # staleness a balanced request can land on right now.
        reg.gauge("fleet-replicas-active").set(len(self.replicas.active))
        reg.counter("fleet-replicas-joined").set_total(
            self.replicas.n_joined
        )
        reg.counter("fleet-replicas-evicted").set_total(
            self.replicas.n_evicted
        )
        reg.gauge("fleet-version-floor").set(self.replicas.floor)
        reg.gauge("fleet-min-active-version").set(
            self.replicas.min_active_version()
        )
        if self._ingress is not None:
            # Self-healing plane: poisoned (failed validation) and
            # quarantined (clean but from a quarantined wid) frame drops
            # are SEPARATE counters — and separate from n_rejected and
            # n_stale_epoch — so the chaos injected==poisoned parity is
            # assertable exactly.
            reg.counter("storage-poisoned-frames").set_total(
                self._ingress.n_poisoned
            )
            reg.counter("storage-quarantined-frames").set_total(
                self._ingress.n_quarantined_frames
            )
            reg.counter("storage-quarantines").set_total(
                self.members.n_quarantines
            )
            reg.counter("storage-unquarantines").set_total(
                self.members.n_unquarantines
            )
            reg.gauge("storage-wids-quarantined").set(
                len(self.members.quarantined)
            )
        if self._chaos is not None:
            reg.counter("chaos-corrupted-frames").set_total(
                self._chaos.n_corrupted
            )
            reg.counter("chaos-dropped-frames").set_total(
                self._chaos.n_dropped
            )
            reg.counter("chaos-delayed-frames").set_total(
                self._chaos.n_delayed
            )
        now_m = time.monotonic()
        if now_m >= self._next_slo:
            # 1s cadence for the expensive bits: /proc self-stats and the
            # fleet-wide SLO pass (the tick itself runs every poll loop).
            self._next_slo = now_m + 1.0
            from tpu_rl.obs.perf import process_self_stats

            rss, n_fds = process_self_stats()
            reg.gauge("storage-rss-bytes").set(rss)
            reg.gauge("storage-open-fds").set(float(n_fds))
            if self.ledger is not None:
                self.ledger.publish(reg)
            if self._wid_frames:
                # Straggler gauges BEFORE the SLO pass so rules over
                # worker-straggler-score see this second's values.
                self._straggler_tick(reg, now_m)
            if self._slo is not None:
                self._slo.evaluate(self.aggregator)
        if self._json_exp is not None and self._json_exp.maybe_export():
            if self._history is not None:
                # History rides the SAME cadence decision the JSON exporter
                # just made: one flattened row of every role's snapshot per
                # export, no clock of its own.
                self._history.record(self.aggregator)
            if self.ledger is not None:
                # Ledger + straggler audit trail on the exporter's cadence:
                # one JSON line per export, the offline twin of GET /goodput.
                from tpu_rl.obs.audit import append_jsonl

                append_jsonl(
                    self.cfg.result_dir, "goodput.jsonl",
                    self._goodput_payload(),
                )
            if self._tb_exp is not None:
                self._tb_exp.export(self.aggregator)
            if self._tracer is not None:
                # Ride the JSON exporter's cadence: a recent storage ring
                # (with the clock map the merger needs) is always on disk.
                self._tracer.dump(
                    self._trace_path,
                    extra_meta={"clock": self.clocksync.snapshot()},
                )

    def _straggler_tick(self, reg, now_m: float) -> None:
        """Refresh the per-wid straggler signals and score gauges (1 Hz).

        Three signals, robust z-scored against the fleet median
        (tpu_rl.obs.goodput.straggler_report): admitted-frame rate over the
        last tick window, policy staleness vs the aggregator's version
        ratchet, and the clock-sync rtt EWMA. Report-only — quarantine (the
        heal plane) stays the enforcement arm."""
        from tpu_rl.obs.goodput import STRAGGLER_GAUGE, straggler_report

        rates = {}
        for wid, count in self._wid_frames.items():
            prev = self._frames_prev.get(wid)
            if prev is not None and now_m > prev[1]:
                rates[wid] = (count - prev[0]) / (now_m - prev[1])
            self._frames_prev[wid] = (count, now_m)
        self._wid_rate = rates
        floor = self.aggregator.max_version
        staleness = {
            wid: float(max(0, floor - ver))
            for wid, ver in self._wid_ver.items()
        }
        scores, top = straggler_report(
            frame_rate=rates or None,
            staleness=staleness or None,
            rtt=dict(self._wid_rtt) or None,
        )
        self._straggler_top = top
        for wid, score in scores.items():
            reg.gauge(STRAGGLER_GAUGE, {"wid": str(wid)}).set(score)

    def _goodput_payload(self) -> dict:
        """The GET /goodput document: this loop's own ledger snapshot, every
        source's published goodput/bucket gauges (rebuilt from the
        aggregator, keyed ``role/pid``), and the straggler top-k."""
        roles: dict = {}
        if self.aggregator is not None:
            for snap, _age in self.aggregator.all_snapshots():
                role = str(snap.get("role", "?"))
                ratios: dict = {}
                goodput = overcommit = None
                for name, _labels, value in snap.get("gauges", ()):
                    if name == role + "-goodput-ratio":
                        goodput = value
                    elif name.startswith(role + "-time-") and name.endswith(
                        "-ratio"
                    ):
                        bucket = name[len(role) + 6 : -6]
                        if bucket == "overcommit":
                            overcommit = value
                        else:
                            ratios[bucket] = value
                if goodput is None and not ratios:
                    continue
                roles[f"{role}/{snap.get('pid', '?')}"] = {
                    "goodput": goodput,
                    "ratios": ratios,
                    "overcommit_ratio": overcommit,
                }
        return {
            "storage": (
                self.ledger.snapshot() if self.ledger is not None else None
            ),
            "roles": roles,
            "stragglers": self._straggler_top,
            "rates": {str(w): r for w, r in self._wid_rate.items()},
        }

    def _close_telemetry(self) -> None:
        if self._http is not None:
            self._http.close()
        if self._prof is not None:
            self._prof.close()
        if self._slo is not None:
            # Final pass so the written verdict covers the run's last data.
            self._slo.evaluate(self.aggregator)
            if self.cfg.result_dir is not None:
                import json

                with open(
                    os.path.join(self.cfg.result_dir, "slo.json"), "w"
                ) as f:
                    json.dump(self._slo.report(), f, indent=2)
        if self._json_exp is not None:
            self._json_exp.maybe_export(now=float("inf"))  # final snapshot
        if self._history is not None:
            # One last row so the stored run ends at the final state, then
            # release the active chunk handle.
            self._history.record(self.aggregator)
            self._history.close()
        if self._tb_exp is not None:
            self._tb_exp.export(self.aggregator)
            self._tb_exp.close()

    @property
    def slo_failed(self) -> bool:
        """The ``Config.slo_fail_run`` exit gate: True when the final SLO
        verdict has a hard-failing rule."""
        return self._slo is not None and self._slo.failed

    def _ingest(
        self, proto: Protocol, payload, assembler, trailer: bytes | None = None
    ) -> None:
        if proto == Protocol.Rollout:
            assembler.push(payload)
        elif proto == Protocol.RolloutBatch:
            # Membership lease BEFORE the epoch fence: a stale-epoch frame
            # still proves its worker is alive (it is mid re-attach), and
            # evicting it would mis-fire a join push when it converges.
            self._touch_member(payload)
            # Ingress validation BEFORE the epoch fence: a poisoned frame
            # counts poisoned no matter its epoch, so the chaos plane's
            # injected == poisoned parity holds exactly and never shares a
            # frame with n_stale_epoch (or with transport n_rejected).
            if self._ingress is not None and not self._ingress_admit(payload):
                return  # poisoned or quarantined: dropped + counted
            if not self._epoch_admit(payload):
                return  # pre-crash incarnation's rollout: fenced + counted
            if self.aggregator is not None and isinstance(payload, dict):
                # Policy-staleness echo (tagged on Model broadcasts, echoed
                # by workers): how many updates behind was the policy this
                # tick was acted with?
                ver = payload.get("ver")
                if isinstance(ver, int):
                    self.aggregator.observe_staleness(
                        int(payload.get("wid", -1)), ver
                    )
                if self._wid_frames is not None:
                    wid = payload.get("wid")
                    if isinstance(wid, int):
                        self._wid_frames[wid] = self._wid_frames.get(wid, 0) + 1
                        if isinstance(ver, int):
                            self._wid_ver[wid] = ver
            trace_id = None
            if trailer is not None and self._tracer is not None:
                trace_id = self._note_ingest(trailer)
            # One worker tick, all envs stacked: unpack at the storage edge
            # (the only hop that needs per-step granularity — the assembler
            # keys on episode id).
            if self.cfg.relay_mode == "decode":
                # A/B baseline: per-step dicts through the scalar push path.
                for step in split_rollout_batch(payload):
                    assembler.push(step)
            else:
                # Columnar: the whole tick in one call, row views per env.
                assembler.push_tick(payload, trace_id=trace_id)
        elif proto == Protocol.Stat:
            self._relay_stat(payload)
        elif proto == Protocol.Telemetry:
            # Telemetry is health data: ratchet the fence and renew the
            # lease from its epoch echo, but never reject a snapshot — a
            # stale-epoch worker must stay visible to /healthz while it
            # re-attaches.
            self._touch_member(payload)
            self._touch_replica(payload)
            if isinstance(payload, dict):
                e = payload.get("epoch")
                if isinstance(e, int) and e > self.run_epoch:
                    self.run_epoch = e
            if self.aggregator is not None:
                if self.clocksync is not None and isinstance(payload, dict):
                    self._clock_sample(payload)
                self.aggregator.ingest(payload)

    # ---------------------------------------------------- self-healing plane
    def _ingress_admit(self, payload) -> bool:
        """True to ingest. Classification is the IngressGuard's; the
        quarantine lifecycle (strike -> drop -> clean re-probe) and every
        drop count live here, at one site. A poisoned frame from a
        quarantined wid still counts poisoned (exact chaos parity), and a
        clean frame from a quarantined wid is dropped (quarantined-frames)
        until the cooldown clears it."""
        guard = self._ingress
        if guard.tick_clean(payload):
            wid = payload.get("wid") if isinstance(payload, dict) else None
            if isinstance(wid, int) and self.members.is_quarantined(wid):
                if self.members.probe_clear(wid, self.cfg.quarantine_clear_s):
                    return True
                guard.n_quarantined_frames += 1
                return False
            return True
        guard.n_poisoned += 1
        wid = payload.get("wid") if isinstance(payload, dict) else None
        if isinstance(wid, int):
            self.members.strike(wid, self.cfg.quarantine_strikes)
        return False

    # ----------------------------------------------------- durability plane
    def _poll_epoch(self) -> None:
        """Ratchet the fence from the learner-written mailbox slot (encoded
        epoch + 1; 0 = no learner wrote yet). The mp.Array outlives child
        respawns, so this wins every race against frame echoes."""
        sa = self.stat_array
        if sa is None or len(sa) <= SLOT_RUN_EPOCH:
            return
        e = int(sa[SLOT_RUN_EPOCH]) - 1
        if e > self.run_epoch:
            self.run_epoch = e

    def _epoch_admit(self, payload) -> bool:
        """True to ingest. A frame stamped with a known epoch older than the
        fence is dropped and counted; unknown (< 0 or absent) is admitted —
        fresh fleets and pre-upgrade workers must not stall."""
        if not isinstance(payload, dict):
            return True
        e = payload.get("epoch")
        if not isinstance(e, int) or e < 0:
            return True
        if e > self.run_epoch:
            self.run_epoch = e  # frame echo: secondary ratchet source
            return True
        if e < self.run_epoch:
            self.n_stale_epoch += 1
            return False
        return True

    def _touch_member(self, payload) -> None:
        """Renew the wid's membership lease; on a NEW member, raise the
        mailbox join flag so the learner pushes weights+ver immediately."""
        if not isinstance(payload, dict):
            return
        wid = payload.get("wid")
        if not isinstance(wid, int):
            return
        if self.members.touch(wid):
            sa = self.stat_array
            if sa is not None and len(sa) > SLOT_JOIN_REQ:
                sa[SLOT_JOIN_REQ] = 1.0

    def _touch_replica(self, payload) -> None:
        """Renew an inference replica's lease from its telemetry snapshot
        (``rid`` + served ``ver``). A NEW replica raises the same join flag
        a worker join does: the learner's join-push re-broadcasts current
        weights+ver, which is exactly what a random-init replica needs to
        converge onto the live policy — zero new wire machinery."""
        if not isinstance(payload, dict):
            return
        rid = payload.get("rid")
        if not isinstance(rid, int):
            return
        ver = payload.get("ver")
        if self.replicas.touch(
            rid, ver=ver if isinstance(ver, int) else -1
        ):
            sa = self.stat_array
            if sa is not None and len(sa) > SLOT_JOIN_REQ:
                sa[SLOT_JOIN_REQ] = 1.0

    def _note_ingest(self, trailer: bytes) -> int | None:
        """Record the storage-ingest hop for a sampled frame; returns its
        trace id for the assembler's window lineage."""
        t0 = time.perf_counter()
        try:
            wid, seq, trace_id, t_send_ns = unpack_trace(trailer)
        except ValueError:
            return None  # decode validated shape/magic; never crash on it
        self._tracer.add(
            "storage-ingest",
            t0,
            time.perf_counter() - t0,
            args={
                "trace_id": trace_id,
                "wid": wid,
                "seq": seq,
                # Raw (uncorrected) transport latency worker->here; the
                # merged timeline shows the clock-corrected truth.
                "wire_ns": time.time_ns() - t_send_ns,
            },
        )
        return trace_id

    def _clock_sample(self, payload: dict) -> None:
        """Fold one Telemetry snapshot's ``clk`` stamps into the clock-sync
        estimator: a full round trip when the source echoes a Model
        broadcast (workers), one-way otherwise (managers)."""
        clk = payload.get("clk")
        if not isinstance(clk, dict):
            return
        t2 = clk.get("t2")
        if not isinstance(t2, int):
            return
        t3 = time.time_ns()
        key = (
            f"{payload.get('role', '?')}/{payload.get('host', '?')}"
            f"/{payload.get('pid', '?')}"
        )
        t0, t1 = clk.get("t0"), clk.get("t1")
        if isinstance(t0, int) and isinstance(t1, int):
            self.clocksync.add_round_trip(key, t0, t1, t2, t3)
            wid = payload.get("wid")
            if isinstance(wid, int):
                # Per-wid transport rtt (minus the remote's hold time) as a
                # straggler signal — EWMA so one slow scrape doesn't flag.
                rtt_s = max(0.0, ((t3 - t0) - (t2 - t1)) / 1e9)
                prev = self._wid_rtt.get(wid)
                self._wid_rtt[wid] = (
                    rtt_s if prev is None else 0.8 * prev + 0.2 * rtt_s
                )
        else:
            self.clocksync.add_one_way(key, t2, t3)

    def _flush(self, assembler: RolloutAssembler, store) -> None:
        windows, traces, vers = assembler.pop_many_full()
        if not windows:
            return
        accepted = store.put_many(windows, vers=vers)
        self.n_windows += accepted
        if accepted < len(windows):
            # On-policy store full: the learner hasn't consumed yet. Requeue
            # the rejected tail in order and yield (reference spins on
            # ``num < mem_size``, ``learner_storage.py:139``).
            assembler.requeue(
                windows[accepted:],
                traces[accepted:] if traces is not None else None,
                vers[accepted:],
            )
            self.n_requeue_full += 1
        if traces is not None:
            t0 = time.perf_counter()
            for tr in traces[:accepted]:
                if not tr:
                    continue
                # A window that contains rows from sampled ticks closes
                # here: the last lineage hop the wire can measure (the shm
                # plane carries no metadata; the merger synthesizes the
                # learner consume from the first train-step after this).
                for tid in tr:
                    self._tracer.add(
                        "window-close",
                        t0,
                        time.perf_counter() - t0,
                        args={"trace_id": tid},
                    )

    def _relay_stat(self, payload) -> None:
        """Manager sends ``{"mean": m, "n": window}``; fold into the stat
        mailbox for the learner's tensorboard tick
        (``learner_storage.py:104-121``)."""
        if self.stat_array is None:
            return
        mean = float(payload["mean"]) if isinstance(payload, dict) else float(payload)
        n = int(payload.get("n", 1)) if isinstance(payload, dict) else 1
        self.game_count += n
        self.stat_array[SLOT_GAME_COUNT] = float(self.game_count)
        self.stat_array[SLOT_MEAN_REW] = mean
        if len(self.stat_array) > SLOT_MODEL_LOADS:
            # Fleet health: manager-relayed totals (worker model-SUB drops +
            # the relay's own) plus THIS sub's corrupt-frame count — every
            # transport hop is covered. Written before the activate flag so
            # the learner never reads a half-updated mailbox.
            own = self._sub.n_rejected if self._sub is not None else 0
            relayed = (
                float(payload.get("rejected", 0.0))
                if isinstance(payload, dict) else 0.0
            )
            self.stat_array[SLOT_REJECTED] = relayed + own
            self.stat_array[SLOT_MODEL_LOADS] = (
                float(payload.get("model_loads", 0.0))
                if isinstance(payload, dict) else 0.0
            )
        if len(self.stat_array) > SLOT_FORWARD_BYTES and isinstance(payload, dict):
            # Relay health (ISSUE 3): manager drop-oldest evictions and
            # forwarded wire bytes -> learner gauges.
            self.stat_array[SLOT_RELAY_DROPPED] = float(
                payload.get("relay_dropped", 0.0)
            )
            self.stat_array[SLOT_FORWARD_BYTES] = float(
                payload.get("forward_bytes", 0.0)
            )
        self.stat_array[SLOT_ACTIVATE] = 1.0  # activate flag; learner clears it

    def _stopped(self) -> bool:
        return self.stop_event is not None and self.stop_event.is_set()


def storage_main(
    cfg: Config,
    handles: ShmHandles,
    learner_port: int,
    stat_array,
    stop_event,
    heartbeat,
) -> None:
    """mp.Process target (reference ``storage_run``, ``main.py:164-187``)."""
    storage = LearnerStorage(
        cfg, handles, learner_port, stat_array, stop_event, heartbeat
    )
    storage.run()
    if cfg.slo_fail_run and storage.slo_failed:
        print("[storage] SLO verdict failing; exiting nonzero", flush=True)
        raise SystemExit(3)
