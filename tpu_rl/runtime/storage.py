"""Learner-storage process: bridge from the DCN transport into device-feedable
shared memory.

Capability parity with the reference ``LearnerStorage``
(``/root/reference/agents/learner_storage.py:25-159``): SUB-bind on the
learner port, push Rollout steps through the assembler, write completed
windows into the shm store, relay episode-reward stats into the 3-float stat
mailbox ``[global_game_count, mean_rew, activate]``
(``learner_storage.py:104-121``, created at ``main.py:324-326``).

This is the storage edge of the zero-copy fan-in (ISSUE 3): the one hop that
runs the full frame validation (CRC + decompress + schema unpack, inside
``Sub.recv``/``drain``) — relays upstream only ``peek`` the header. Whole
worker ticks then enter the assembler columnar-wise via
``RolloutAssembler.push_tick`` (row views per env, no per-step dicts) and
completed windows leave in bursts via the stores' ``put_many`` (one slice
write per field). ``Config.relay_mode="decode"`` keeps the per-step
``split_rollout_batch`` + ``push`` reference path as the A/B baseline.
"""

from __future__ import annotations

import os
import time

from tpu_rl.config import Config
from tpu_rl.data.assembler import RolloutAssembler, split_rollout_batch
from tpu_rl.data.layout import BatchLayout
from tpu_rl.data.shm_ring import ShmHandles, make_store
from tpu_rl.runtime.mailbox import (
    SLOT_ACTIVATE,
    SLOT_FORWARD_BYTES,
    SLOT_GAME_COUNT,
    SLOT_MEAN_REW,
    SLOT_MODEL_LOADS,
    SLOT_REJECTED,
    SLOT_RELAY_DROPPED,
    STAT_SLOTS,
)
from tpu_rl.runtime.protocol import Protocol
from tpu_rl.runtime.transport import Sub

# Slot layout lives in tpu_rl.runtime.mailbox (shared with the learner's
# reader); STAT_SLOTS is re-exported here for existing importers.
__all__ = ["LearnerStorage", "STAT_SLOTS", "storage_main"]


class LearnerStorage:
    def __init__(
        self,
        cfg: Config,
        handles: ShmHandles,
        learner_port: int,
        stat_array=None,
        stop_event=None,
        heartbeat=None,
    ):
        self.cfg = cfg
        self.handles = handles
        self.learner_port = learner_port
        self.stat_array = stat_array
        self.stop_event = stop_event
        self.heartbeat = heartbeat
        self.game_count = 0
        self.n_windows = 0
        self.n_requeue_full = 0  # windows requeued because the store was full
        self._sub: Sub | None = None
        # Telemetry plane (tpu_rl.obs): the aggregator lives HERE — storage
        # is the learner-side edge of the stat channel, the one hop every
        # role's snapshots already reach. None when disabled; every call
        # site guards on that, so the off state costs one check per frame.
        self.aggregator = None
        self._http = None
        self._json_exp = None
        self._tb_exp = None

    def run(self) -> None:
        cfg = self.cfg
        layout = BatchLayout.from_config(cfg)
        assembler = RolloutAssembler(layout, lag_sec=cfg.rollout_lag_sec)
        store = make_store(cfg, layout, handles=self.handles)
        sub = self._sub = Sub("*", self.learner_port, bind=True)
        self._setup_telemetry()
        try:
            while not self._stopped():
                msg = sub.recv(timeout_ms=50)
                if msg is not None:
                    self._ingest(*msg, assembler)
                for proto, payload in sub.drain():
                    self._ingest(proto, payload, assembler)
                self._flush(assembler, store)
                if self.aggregator is not None:
                    self._telemetry_tick()
                if self.heartbeat is not None:
                    self.heartbeat.value = time.time()
        finally:
            sub.close()
            self._close_telemetry()

    # ------------------------------------------------------------- telemetry
    def _setup_telemetry(self) -> None:
        """Construct the aggregator + exporters iff the plane has a sink
        (``Config.telemetry_enabled``); otherwise everything stays None and
        the ingest/tick paths reduce to a single ``is None`` check."""
        cfg = self.cfg
        if not cfg.telemetry_enabled:
            return
        from tpu_rl.obs import (
            JsonExporter,
            MetricsRegistry,
            TelemetryAggregator,
            TelemetryHTTPServer,
            TensorboardExporter,
        )
        from tpu_rl.utils.metrics import NullWriter, make_writer

        self.aggregator = TelemetryAggregator(
            registry=MetricsRegistry(role="storage"),
            stale_after_s=cfg.telemetry_stale_s,
        )
        if cfg.telemetry_port > 0:
            self._http = TelemetryHTTPServer(self.aggregator, cfg.telemetry_port)
        if cfg.result_dir is not None:
            self._json_exp = JsonExporter(
                self.aggregator,
                os.path.join(cfg.result_dir, "telemetry.json"),
                interval_s=cfg.telemetry_interval_s,
            )
            writer = make_writer(os.path.join(cfg.result_dir, "telemetry"))
            if not isinstance(writer, NullWriter):
                # Fleet health next to the loss curves; rides the JSON
                # exporter's cadence (no writer of its own clock). Skipped
                # when tensorboardX is absent — the JSON file still lands.
                self._tb_exp = TensorboardExporter(writer)

    def _telemetry_tick(self) -> None:
        reg = self.aggregator.registry
        reg.counter("storage-windows").set_total(self.n_windows)
        reg.counter("storage-requeue-full").set_total(self.n_requeue_full)
        reg.counter("storage-rejected-frames").set_total(
            self._sub.n_rejected if self._sub is not None else 0
        )
        reg.counter("storage-telemetry-ingested").set_total(
            self.aggregator.n_ingested
        )
        reg.gauge("storage-game-count").set(self.game_count)
        if self._json_exp is not None and self._json_exp.maybe_export():
            if self._tb_exp is not None:
                self._tb_exp.export(self.aggregator)

    def _close_telemetry(self) -> None:
        if self._http is not None:
            self._http.close()
        if self._json_exp is not None:
            self._json_exp.maybe_export(now=float("inf"))  # final snapshot
        if self._tb_exp is not None:
            self._tb_exp.export(self.aggregator)
            self._tb_exp.close()

    def _ingest(self, proto: Protocol, payload, assembler) -> None:
        if proto == Protocol.Rollout:
            assembler.push(payload)
        elif proto == Protocol.RolloutBatch:
            if self.aggregator is not None and isinstance(payload, dict):
                # Policy-staleness echo (tagged on Model broadcasts, echoed
                # by workers): how many updates behind was the policy this
                # tick was acted with?
                ver = payload.get("ver")
                if isinstance(ver, int):
                    self.aggregator.observe_staleness(
                        int(payload.get("wid", -1)), ver
                    )
            # One worker tick, all envs stacked: unpack at the storage edge
            # (the only hop that needs per-step granularity — the assembler
            # keys on episode id).
            if self.cfg.relay_mode == "decode":
                # A/B baseline: per-step dicts through the scalar push path.
                for step in split_rollout_batch(payload):
                    assembler.push(step)
            else:
                # Columnar: the whole tick in one call, row views per env.
                assembler.push_tick(payload)
        elif proto == Protocol.Stat:
            self._relay_stat(payload)
        elif proto == Protocol.Telemetry:
            if self.aggregator is not None:
                self.aggregator.ingest(payload)

    def _flush(self, assembler: RolloutAssembler, store) -> None:
        windows = assembler.pop_many()
        if not windows:
            return
        accepted = store.put_many(windows)
        self.n_windows += accepted
        if accepted < len(windows):
            # On-policy store full: the learner hasn't consumed yet. Requeue
            # the rejected tail in order and yield (reference spins on
            # ``num < mem_size``, ``learner_storage.py:139``).
            assembler.ready.extendleft(reversed(windows[accepted:]))
            self.n_requeue_full += 1

    def _relay_stat(self, payload) -> None:
        """Manager sends ``{"mean": m, "n": window}``; fold into the stat
        mailbox for the learner's tensorboard tick
        (``learner_storage.py:104-121``)."""
        if self.stat_array is None:
            return
        mean = float(payload["mean"]) if isinstance(payload, dict) else float(payload)
        n = int(payload.get("n", 1)) if isinstance(payload, dict) else 1
        self.game_count += n
        self.stat_array[SLOT_GAME_COUNT] = float(self.game_count)
        self.stat_array[SLOT_MEAN_REW] = mean
        if len(self.stat_array) > SLOT_MODEL_LOADS:
            # Fleet health: manager-relayed totals (worker model-SUB drops +
            # the relay's own) plus THIS sub's corrupt-frame count — every
            # transport hop is covered. Written before the activate flag so
            # the learner never reads a half-updated mailbox.
            own = self._sub.n_rejected if self._sub is not None else 0
            relayed = (
                float(payload.get("rejected", 0.0))
                if isinstance(payload, dict) else 0.0
            )
            self.stat_array[SLOT_REJECTED] = relayed + own
            self.stat_array[SLOT_MODEL_LOADS] = (
                float(payload.get("model_loads", 0.0))
                if isinstance(payload, dict) else 0.0
            )
        if len(self.stat_array) > SLOT_FORWARD_BYTES and isinstance(payload, dict):
            # Relay health (ISSUE 3): manager drop-oldest evictions and
            # forwarded wire bytes -> learner gauges.
            self.stat_array[SLOT_RELAY_DROPPED] = float(
                payload.get("relay_dropped", 0.0)
            )
            self.stat_array[SLOT_FORWARD_BYTES] = float(
                payload.get("forward_bytes", 0.0)
            )
        self.stat_array[SLOT_ACTIVATE] = 1.0  # activate flag; learner clears it

    def _stopped(self) -> bool:
        return self.stop_event is not None and self.stop_event.is_set()


def storage_main(
    cfg: Config,
    handles: ShmHandles,
    learner_port: int,
    stat_array,
    stop_event,
    heartbeat,
) -> None:
    """mp.Process target (reference ``storage_run``, ``main.py:164-187``)."""
    LearnerStorage(
        cfg, handles, learner_port, stat_array, stop_event, heartbeat
    ).run()
