"""Learner-storage process: bridge from the DCN transport into device-feedable
shared memory.

Capability parity with the reference ``LearnerStorage``
(``/root/reference/agents/learner_storage.py:25-159``): SUB-bind on the
learner port, push Rollout steps through the assembler, write completed
windows into the shm store, relay episode-reward stats into the 3-float stat
mailbox ``[global_game_count, mean_rew, activate]``
(``learner_storage.py:104-121``, created at ``main.py:324-326``).

This is the storage edge of the zero-copy fan-in (ISSUE 3): the one hop that
runs the full frame validation (CRC + decompress + schema unpack, inside
``Sub.recv``/``drain``) — relays upstream only ``peek`` the header. Whole
worker ticks then enter the assembler columnar-wise via
``RolloutAssembler.push_tick`` (row views per env, no per-step dicts) and
completed windows leave in bursts via the stores' ``put_many`` (one slice
write per field). ``Config.relay_mode="decode"`` keeps the per-step
``split_rollout_batch`` + ``push`` reference path as the A/B baseline.
"""

from __future__ import annotations

import time

from tpu_rl.config import Config
from tpu_rl.data.assembler import RolloutAssembler, split_rollout_batch
from tpu_rl.data.layout import BatchLayout
from tpu_rl.data.shm_ring import ShmHandles, make_store
from tpu_rl.runtime.protocol import Protocol
from tpu_rl.runtime.transport import Sub

# [game_count, mean_rew, activate, rejected_frames, model_loads,
#  relay_dropped, forward_bytes] — the first three are the reference's 3-float
# mailbox (``main.py:324-326``); the fleet health slots (transport
# corrupt-frame drops, worker model reloads — ISSUE 2, and the manager's
# drop-oldest evictions + forwarded wire bytes — ISSUE 3) ride the same
# activate flag and become learner timer gauges.
STAT_SLOTS = 7


class LearnerStorage:
    def __init__(
        self,
        cfg: Config,
        handles: ShmHandles,
        learner_port: int,
        stat_array=None,
        stop_event=None,
        heartbeat=None,
    ):
        self.cfg = cfg
        self.handles = handles
        self.learner_port = learner_port
        self.stat_array = stat_array
        self.stop_event = stop_event
        self.heartbeat = heartbeat
        self.game_count = 0
        self.n_windows = 0
        self.n_requeue_full = 0  # windows requeued because the store was full
        self._sub: Sub | None = None

    def run(self) -> None:
        cfg = self.cfg
        layout = BatchLayout.from_config(cfg)
        assembler = RolloutAssembler(layout, lag_sec=cfg.rollout_lag_sec)
        store = make_store(cfg, layout, handles=self.handles)
        sub = self._sub = Sub("*", self.learner_port, bind=True)
        try:
            while not self._stopped():
                msg = sub.recv(timeout_ms=50)
                if msg is not None:
                    self._ingest(*msg, assembler)
                for proto, payload in sub.drain():
                    self._ingest(proto, payload, assembler)
                self._flush(assembler, store)
                if self.heartbeat is not None:
                    self.heartbeat.value = time.time()
        finally:
            sub.close()

    def _ingest(self, proto: Protocol, payload, assembler) -> None:
        if proto == Protocol.Rollout:
            assembler.push(payload)
        elif proto == Protocol.RolloutBatch:
            # One worker tick, all envs stacked: unpack at the storage edge
            # (the only hop that needs per-step granularity — the assembler
            # keys on episode id).
            if self.cfg.relay_mode == "decode":
                # A/B baseline: per-step dicts through the scalar push path.
                for step in split_rollout_batch(payload):
                    assembler.push(step)
            else:
                # Columnar: the whole tick in one call, row views per env.
                assembler.push_tick(payload)
        elif proto == Protocol.Stat:
            self._relay_stat(payload)

    def _flush(self, assembler: RolloutAssembler, store) -> None:
        windows = assembler.pop_many()
        if not windows:
            return
        accepted = store.put_many(windows)
        self.n_windows += accepted
        if accepted < len(windows):
            # On-policy store full: the learner hasn't consumed yet. Requeue
            # the rejected tail in order and yield (reference spins on
            # ``num < mem_size``, ``learner_storage.py:139``).
            assembler.ready.extendleft(reversed(windows[accepted:]))
            self.n_requeue_full += 1

    def _relay_stat(self, payload) -> None:
        """Manager sends ``{"mean": m, "n": window}``; fold into the stat
        mailbox for the learner's tensorboard tick
        (``learner_storage.py:104-121``)."""
        if self.stat_array is None:
            return
        mean = float(payload["mean"]) if isinstance(payload, dict) else float(payload)
        n = int(payload.get("n", 1)) if isinstance(payload, dict) else 1
        self.game_count += n
        self.stat_array[0] = float(self.game_count)
        self.stat_array[1] = mean
        if len(self.stat_array) > 4:
            # Fleet health: manager-relayed totals (worker model-SUB drops +
            # the relay's own) plus THIS sub's corrupt-frame count — every
            # transport hop is covered. Written before the activate flag so
            # the learner never reads a half-updated mailbox.
            own = self._sub.n_rejected if self._sub is not None else 0
            relayed = (
                float(payload.get("rejected", 0.0))
                if isinstance(payload, dict) else 0.0
            )
            self.stat_array[3] = relayed + own
            self.stat_array[4] = (
                float(payload.get("model_loads", 0.0))
                if isinstance(payload, dict) else 0.0
            )
        if len(self.stat_array) > 6 and isinstance(payload, dict):
            # Relay health (ISSUE 3): manager drop-oldest evictions and
            # forwarded wire bytes -> learner gauges.
            self.stat_array[5] = float(payload.get("relay_dropped", 0.0))
            self.stat_array[6] = float(payload.get("forward_bytes", 0.0))
        self.stat_array[2] = 1.0  # activate flag; learner clears it

    def _stopped(self) -> bool:
        return self.stop_event is not None and self.stop_event.is_set()


def storage_main(
    cfg: Config,
    handles: ShmHandles,
    learner_port: int,
    stat_array,
    stop_event,
    heartbeat,
) -> None:
    """mp.Process target (reference ``storage_run``, ``main.py:164-187``)."""
    LearnerStorage(
        cfg, handles, learner_port, stat_array, stop_event, heartbeat
    ).run()
