"""Learner process: the only process that owns the TPU.

Capability parity with the reference learner
(``/root/reference/agents/learner.py:39-305`` + the per-algo loops in
``agents/learner_module/*/learning.py``): sample trajectory batches out of
shared memory, run the algorithm's update, broadcast fresh policy weights to
every worker, log losses/timers/fleet-reward to tensorboard, checkpoint every
``model_save_interval`` updates, heartbeat.

TPU-first redesign:
- the six per-algo asyncio coroutines collapse into ONE loop around the
  algorithm's pure jitted ``train_step`` (the registry supplies it);
- when ``cfg.mesh_data > 1`` the step is compiled with GSPMD shardings over
  the data mesh (``tpu_rl.parallel.dp``) — XLA inserts the ICI gradient
  all-reduce the reference has no equivalent of;
- the host data plane is PIPELINED (``cfg.learner_prefetch``): a feeder
  thread samples shm, assembles the batch, and eagerly places it on device
  with the step's sharding, so the next dispatch's shm copy + H2D transfer
  overlaps the current ``train_step`` (``tpu_rl/data/prefetch.py``; the
  Podracer overlap, Hessel et al. 2104.06272). ``learner_prefetch=0``
  restores the serial feed for A/B;
- weight broadcast is an ASYNC host-copy snapshot of the actor tree only —
  a device-side copy + ``copy_to_host_async``, with the blocking
  ``device_get`` and the ZMQ send on a publisher thread — throttled by
  ``publish_interval``, so host transfer never stalls the device pipeline
  (SURVEY.md §7 hard-parts);
- off-policy learners honor ``cfg.max_update_data_ratio`` (update:data
  ratio gate — the replay learner waits for fresh transitions instead of
  free-running against the ring, CLUSTER_R5_SAC.md);
- checkpoints carry params + optimizer state + update counter (orbax).
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from tpu_rl.config import Config, is_off_policy
from tpu_rl.data.layout import BatchLayout
from tpu_rl.data.prefetch import (
    PrefetchPipeline,
    SynchronousFeed,
    UpdateRatioGate,
)
from tpu_rl.data.shm_ring import ShmHandles, make_store
from tpu_rl.runtime.mailbox import (
    SLOT_ACTIVATE,
    SLOT_FORWARD_BYTES,
    SLOT_GAME_COUNT,
    SLOT_JOIN_REQ,
    SLOT_MEAN_REW,
    SLOT_MODEL_LOADS,
    SLOT_REJECTED,
    SLOT_RELAY_DROPPED,
    SLOT_RUN_EPOCH,
)
from tpu_rl.runtime.manager import STAT_WINDOW
from tpu_rl.runtime.protocol import Protocol
from tpu_rl.runtime.transport import MODEL_HWM, Pub, make_data_pub
from tpu_rl.utils.metrics import LearnerLogger, make_writer
from tpu_rl.utils.timer import ExecutionTimer


def _crossed(prev: int, cur: int, interval: int) -> bool:
    """Did the counter cross a multiple of ``interval`` moving prev -> cur?
    Equivalent to ``cur % interval == 0`` when steps are 1; with chained
    dispatch the counter advances K per iteration and plain modulo would
    skip firings whose multiple falls inside the jump."""
    return cur // interval > prev // interval


class AsyncPublisher:
    """Weight broadcast off the learner's critical path.

    ``publish(actor)`` runs only cheap async dispatches on the caller:
    a device-side ``jnp.copy`` of the actor tree (independent buffers, so
    the next ``train_step``'s donation of the state cannot invalidate the
    snapshot mid-copy) and ``copy_to_host_async`` to start the D2H DMA.
    The blocking ``jax.device_get`` — which must wait for the update that
    produced the weights AND the transfer — plus codec + ZMQ send happen on
    this thread, overlapped with the learner's next dispatches.

    Latest-wins slot (not a queue): under backpressure workers want the
    NEWEST weights, and per-snapshot order is irrelevant once superseded.
    The ZMQ ``Pub`` is used from this thread only after construction
    (sockets are single-threaded); ``close()`` flushes a pending snapshot
    so the final weights of a run still reach the fleet, then joins.
    A send failure re-raises out of the next ``publish()``.
    """

    def __init__(self, pub: Pub):
        self._pub = pub
        self._cond = threading.Condition()
        self._pending = None
        self._error: BaseException | None = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="learner-publish", daemon=True
        )
        self._thread.start()

    def publish(self, actor, ver: int = -1, epoch: int = 0) -> None:
        import jax
        import jax.numpy as jnp

        if self._error is not None:
            raise self._error
        snap = jax.tree.map(jnp.copy, actor)  # donation-proof device copy
        jax.tree.map(lambda x: x.copy_to_host_async(), snap)
        with self._cond:
            self._pending = (snap, ver, epoch)  # latest wins
            self._cond.notify()

    def _run(self) -> None:
        import jax

        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait(timeout=0.1)
                if self._pending is None:  # closed and flushed
                    return
                (snap, ver, epoch), self._pending = self._pending, None
            try:
                # "ver" is the learner update index that produced these
                # weights: workers echo it through their rollouts so storage
                # can measure per-worker policy staleness (tpu_rl.obs).
                # "epoch" is the run epoch (bumped on every checkpoint
                # resume): workers adopt and echo it so storage can fence
                # out frames acted under a pre-crash learner incarnation.
                self._pub.send(
                    Protocol.Model,
                    {
                        "actor": jax.device_get(snap),
                        "ver": ver,
                        "epoch": epoch,
                        # Clock-sync echo origin (t0): workers pair this with
                        # their receive time and ship both back on their
                        # Telemetry snapshots, closing the NTP round trip at
                        # the storage edge (tpu_rl.obs.clocksync).
                        "t_tx": time.time_ns(),
                    },
                )
            except BaseException as e:  # noqa: BLE001 — surfaces in publish()
                self._error = e
                return

    def close(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=timeout)


class LearnerService:
    def __init__(
        self,
        cfg: Config,
        handles: ShmHandles,
        model_port: int,
        stat_array=None,
        stop_event=None,
        heartbeat=None,
        max_updates: int | None = None,
        publish_interval: int = 1,
        seed: int = 0,
        inference_port: int | None = None,
        stat_port: int | None = None,
    ):
        self.cfg = cfg
        self.handles = handles
        self.model_port = model_port
        self.stat_array = stat_array
        self.stop_event = stop_event
        self.heartbeat = heartbeat
        self.max_updates = max_updates
        self.publish_interval = publish_interval
        self.seed = seed
        self.inference_port = inference_port
        # Stat-channel port (the one storage SUB-binds): the learner's own
        # Telemetry snapshots ship there over a tiny local PUB — storage is
        # colocated (same runner host), so 127.0.0.1 always reaches it.
        self.stat_port = stat_port
        self._publisher: AsyncPublisher | None = None
        self._inference = None  # InferenceService when act_mode="remote"
        self._tracer = None  # TraceRecorder when result_dir is set
        self._perf = None  # PerfTracker when telemetry is on
        self._prof_capture = None  # ProfilerCapture when any capture path is
        # Idle-rebroadcast odometer: model publishes fired from the starving
        # branch (no fresh update) so late-joining or restarted workers stop
        # acting on a stale/random policy (chaos-plane hardening).
        self.n_rebroadcasts = 0
        # Run epoch: 0 for a fresh run, (checkpointed epoch + 1) on every
        # resume. Stamped on Model broadcasts/telemetry and echoed by
        # workers; storage fences stale-epoch frames on it.
        self.run_epoch = 0
        # Publishes triggered by storage's join flag (a NEW worker appeared
        # in the membership table): the joiner gets weights+ver now instead
        # of waiting out rebroadcast_idle_s.
        self.n_join_pushes = 0
        self._ckpt = None  # Checkpointer while cfg.model_dir is set
        # Self-healing plane (tpu_rl.heal): cumulative guard-skipped updates
        # (host mirror of the on-device accumulator, refreshed at the
        # loss-log cadence) and watchdog-triggered rollbacks performed.
        self.n_nonfinite_updates = 0.0
        self.n_rollbacks = 0
        # Learning-dynamics plane (tpu_rl.obs.learn): the on-device diag
        # accumulator and the per-dispatch staleness sidecar FIFO (filled on
        # the feeder thread, drained by the hot loop — same ordering as the
        # prefetch queue). Both None unless Config.learn_diag.
        self._diag = None
        self._diag_vers = None

    # ------------------------------------------------------------------ run
    def run(self) -> None:
        cfg = self.cfg
        if cfg.multihost:
            # Must precede any backend use in this process; afterwards
            # jax.devices() spans every host in the slice.
            from tpu_rl.parallel.multihost import init_multihost

            init_multihost(**cfg.multihost)

        import jax

        from tpu_rl.algos.registry import get_algo
        from tpu_rl.checkpoint import Checkpointer
        layout = BatchLayout.from_config(cfg)
        store = make_store(cfg, layout, handles=self.handles)
        off_policy = is_off_policy(cfg.algo)
        rng = np.random.default_rng(self.seed)

        # Compile target meshes first: the family needs the mesh when the
        # transformer's ring/Ulysses attention is sequence-sharded.
        mesh = None
        if cfg.mesh_seq > 1:
            from tpu_rl.parallel import make_sp_mesh

            mesh = make_sp_mesh(cfg.mesh_data, cfg.mesh_seq)
        spec = get_algo(cfg.algo)
        family, state, train_step = spec.build(
            cfg, jax.random.key(self.seed), mesh=mesh
        )

        # ---- checkpoint resume (newest COMMITTED index wins) ----
        # Full-run resume: train state + update index + learner PRNG key +
        # run epoch, refused on config-fingerprint mismatch unless
        # cfg.resume_force. A torn (uncommitted) save is invisible here by
        # construction (tpu_rl/checkpoint.py's marker protocol).
        from tpu_rl.checkpoint import resume_fingerprint

        ckpt = None
        start_idx = 0
        resumed_key_data = None
        fingerprint = resume_fingerprint(cfg)
        if cfg.model_dir:
            ckpt = self._ckpt = Checkpointer(
                cfg.model_dir,
                cfg.algo,
                keep=cfg.ckpt_keep,
                async_save=cfg.ckpt_async,
            )
            restored = ckpt.restore_run(
                state, fingerprint=fingerprint, force=cfg.resume_force
            )
            if restored is not None:
                state, start_idx, meta = restored
                self.run_epoch = int(meta.get("epoch", 0)) + 1
                resumed_key_data = meta.get("key")
                print(
                    f"[learner] resumed from checkpoint idx {start_idx} "
                    f"(run epoch {self.run_epoch})"
                )
                self._record_resume(start_idx)
        # Publish the epoch into the cross-respawn mailbox BEFORE the first
        # broadcast: storage (its mp.Array outlives child respawns) learns
        # the new fence before any worker can act on the new weights, which
        # makes stale-epoch rejection deterministic instead of a race.
        sa = self.stat_array
        if sa is not None and len(sa) > SLOT_RUN_EPOCH:
            sa[SLOT_RUN_EPOCH] = float(self.run_epoch + 1)  # 0 = unknown

        # ---- compile: single-chip jit, data-parallel, or data x seq mesh ----
        # _wrap is reused by the entropy-anneal switch below, which rebuilds
        # the raw train step with the post-switch cfg and must re-apply the
        # same mesh/jit wrapping.
        self._place_global = None
        chain = max(1, cfg.learner_chain)
        if self.max_updates is not None and chain > self.max_updates:
            # A budget smaller than the chain would otherwise complete
            # "successfully" with ZERO updates (the pre-dispatch budget
            # check fires before the first dispatch). Clamp so a small
            # budget performs real updates; callers wanting a hard error
            # should validate their own run plans.
            print(
                f"[learner] learner_chain {chain} exceeds max_updates "
                f"{self.max_updates}; clamping chain to "
                f"{max(1, self.max_updates)}", flush=True,
            )
            chain = max(1, self.max_updates)
        self._chain_mesh = None
        self._batch_sharding = None  # eager-placement target (prefetch feed)
        self._device = jax.devices()[0]
        if mesh is not None:  # built above iff cfg.mesh_seq > 1
            from jax.sharding import NamedSharding, PartitionSpec as P

            from tpu_rl.parallel.dp import make_sp_train_step, replicate
            from tpu_rl.parallel.sequence import DATA_AXIS, SEQ_AXIS

            def _wrap(step, wcfg):
                return make_sp_train_step(step, mesh, wcfg)

            state = replicate(state, mesh)
            self._batch_sharding = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
            self._setup_multihost_feed(self._batch_sharding)
        elif cfg.mesh_data > 1 or chain > 1:
            # chain > 1 rides the same GSPMD wrapper even on one device
            # (make_mesh(1)): the chained lax.scan program is what
            # amortizes per-dispatch overhead, mesh width is orthogonal.
            from tpu_rl.parallel.dp import make_parallel_train_step, replicate
            from tpu_rl.parallel.mesh import batch_sharding, make_mesh

            mesh = make_mesh(cfg.mesh_data)
            if chain > 1:
                self._chain_mesh = mesh

            def _wrap(step, wcfg):
                return make_parallel_train_step(step, mesh, wcfg, chain=chain)

            state = replicate(state, mesh)
            if chain == 1:
                # chain > 1 places via shard_chained_batch in _assemble;
                # chain == 1 places eagerly against the DP batch sharding.
                self._batch_sharding = batch_sharding(mesh)
            self._setup_multihost_feed(batch_sharding(mesh))
        else:

            def _wrap(step, wcfg):
                return jax.jit(step, donate_argnums=(0,))

        train_step = _wrap(train_step, cfg)

        # Two-phase entropy/lr anneal switch point (Config.entropy_anneal;
        # same semantics as the inline harness, examples/train_inline.py).
        # "at" is an ABSOLUTE update index — checked with >= against the
        # global counter, so a run resumed past the switch re-enters the
        # cold phase on its first update instead of undoing the anneal.
        # "frac" is relative to THIS run's max_updates budget.
        anneal = cfg.entropy_anneal
        anneal_at = None
        anneal_absolute = False
        if anneal is not None:
            if "at" in anneal:
                anneal_at = max(1, int(anneal["at"]))
                anneal_absolute = True
            elif self.max_updates is not None:
                anneal_at = max(1, int(float(anneal["frac"]) * self.max_updates))
            else:
                print(
                    "[learner] entropy_anneal uses 'frac' but the run has no "
                    "max_updates budget; anneal disabled", flush=True,
                )

        # Fault injection (tpu_rl.chaos): delay:learner shims the model
        # broadcast sends. None unless a chaos_spec names this site.
        chaos = None
        if cfg.chaos_spec:
            from tpu_rl.chaos import maybe_transport_chaos

            chaos = maybe_transport_chaos(cfg, "learner")
        pub = Pub("*", self.model_port, bind=True, hwm=MODEL_HWM, chaos=chaos)
        # Async broadcast rides the same switch as the feed pipeline so
        # learner_prefetch=0 is a FULLY serial A/B baseline.
        self._publisher = (
            AsyncPublisher(pub) if cfg.learner_prefetch > 0 else None
        )
        writer = make_writer(cfg.result_dir)
        logger = LearnerLogger(writer, cfg.algo)
        # Telemetry plane (tpu_rl.obs): the learner ships its own registry
        # snapshots to the storage-side aggregator over the stat channel —
        # the same port every other role's telemetry already converges on.
        # None when disabled: the hot loop then pays one `is None` check per
        # update and opens no extra socket (pinned by tests/test_obs.py).
        telem_reg = telem_pub = None
        telem_last = float("-inf")
        self._perf = None
        ledger = self.ledger = None
        if cfg.telemetry_enabled and self.stat_port is not None:
            from tpu_rl.obs import MetricsRegistry
            from tpu_rl.obs.goodput import (
                CKPT,
                COMPUTE,
                H2D,
                IDLE,
                QUEUE_WAIT,
                RECOMPILE,
                ROLLBACK,
                WIRE,
                GoodputLedger,
            )
            from tpu_rl.obs.perf import PerfTracker

            telem_reg = MetricsRegistry(role="learner")
            # Goodput ledger (tpu_rl.obs.goodput): exhaustive wall-clock
            # attribution for THIS thread only — feeder / async-ckpt-writer /
            # async-publisher lanes overlap the device step and would
            # double-count. With prefetch the pop wait is residual feed
            # latency (queue-wait); the synchronous feed does the shm copy +
            # H2D inside get(), so the same span is h2d there.
            ledger = self.ledger = GoodputLedger("learner")
            wait_bucket = QUEUE_WAIT if cfg.learner_prefetch > 0 else H2D
            # Live performance plane (tpu_rl.obs.perf): FLOPs/MFU from a
            # one-time AOT cost analysis of train_step, recompile and
            # device-memory watermarks on the emit cadence. None when
            # telemetry is off — the hot loop pays one `is None` check.
            self._perf = PerfTracker()
            # Storage telemetry hop: loopback by construction (learner and
            # storage share the host), so transport="shm"/"auto" routes it
            # through the shm channel instead of a TCP loopback socket.
            telem_pub = make_data_pub(
                cfg, "127.0.0.1", self.stat_port, bind=False
            )
        # Span tracing: ring buffer over the batch timeline (assemble ->
        # queue-wait -> H2D -> train_step -> broadcast), dumped as Chrome
        # trace-event JSON at result_dir/trace.json on every loss-log flush.
        # The deep-dive companion is the jax.profiler window below
        # (profile_dir/profile_start/profile_steps).
        if cfg.result_dir is not None:
            from tpu_rl.obs import TraceRecorder, flightrec

            self._tracer = TraceRecorder(
                capacity=cfg.trace_capacity, pid=os.getpid(), role="learner"
            )
            flightrec.install(
                "learner", cfg.result_dir, tracer=self._tracer, cfg=cfg
            )
        tracer = self._tracer
        # Profiler capture gate (tpu_rl.obs.perf.ProfilerCapture): ONE
        # serialized gate for the config window below, `kill -USR2 <pid>`
        # (mirroring the flight recorder's SIGUSR1), and the telemetry
        # server's /prof?ms=N. Its flight-recorder crash hook guarantees
        # stop_trace() on fatal exceptions, so the capture meant to explain
        # a crash is flushed instead of dying with the process.
        prof_capture = self._prof_capture = None
        if cfg.profile_dir is not None or cfg.result_dir is not None:
            from tpu_rl.obs.perf import ProfilerCapture

            prof_capture = self._prof_capture = ProfilerCapture(
                cfg.profile_dir or os.path.join(cfg.result_dir, "prof")
            )
            prof_capture.install_sigusr2()
        # One timed window per DISPATCH; a chained dispatch carries
        # chain x (seq x batch) transitions. Kept on self so harnesses
        # (examples/run_tpu_e2e_learner.py) can read the steady-state
        # windowed rates after run() — the window excludes idle polls and
        # dilutes the first dispatch's compile across the deque.
        timer = self.timer = ExecutionTimer(
            num_transition=cfg.seq_len * cfg.batch_size * chain
        )
        key = jax.random.key(self.seed + 1)
        if resumed_key_data is not None:
            # Continue the checkpointed RNG stream instead of replaying the
            # seed's: a resumed run keeps sampling fresh subkeys.
            import jax.numpy as jnp

            try:
                key = jax.random.wrap_key_data(
                    jnp.asarray(resumed_key_data, dtype=jnp.uint32)
                )
            except (TypeError, ValueError):
                print(
                    "[learner] checkpointed PRNG key unreadable; keeping "
                    "the seed-derived stream", flush=True,
                )

        def _ckpt_meta() -> dict:
            # Captures the loop's live `key` binding: the meta snapshot is
            # taken at save-call time, consistent with the state snapshot.
            return {
                "epoch": self.run_epoch,
                "key": np.asarray(jax.random.key_data(key)).tolist(),
                "fingerprint": fingerprint,
            }

        # SEED-style centralized inference (act_mode="remote"): serve
        # batched acting from THIS process on the learner's device. Params
        # reach the service as a device-side snapshot after every update —
        # zero broadcast staleness, no host copy, no wire. The service
        # shares `timer`, so inference-batch-size / inference-step-time land
        # on the learner's tensorboard alongside the hot-loop timings.
        if cfg.act_mode == "remote" and self.inference_port is not None:
            if cfg.inference_replicas > 1:
                # Fleet mode: the in-learner service is replica 0 —
                # continuous batching + the ver-keyed swap, so its replies
                # respect the same version monotonicity the standalone
                # replicas give (learner versions only ever rise, so every
                # in-process swap applies).
                from tpu_rl.fleet import InferenceReplica as InferenceService
            else:
                from tpu_rl.runtime.inference_service import InferenceService

            self._inference = InferenceService(
                cfg,
                family,
                self._actor_snapshot(state),
                self.inference_port,
                timer=timer,
                seed=self.seed,
                version=start_idx,
            ).start()
            self._inference.wait_ready()

        # First broadcast so workers act with the resumed/initial policy
        # rather than their own random init. It answers any join request
        # already pending (a respawned learner typically finds the flag
        # raised: storage re-registered every worker while it was booting).
        self._publish(pub, state, ver=start_idx)
        self._consume_join_flag()
        last_pub_m = time.monotonic()

        if (
            self.max_updates is not None
            and chain > 1
            and self.max_updates % chain
        ):
            print(
                f"[learner] max_updates {self.max_updates} is not a multiple "
                f"of learner_chain {chain}; budget rounds DOWN to "
                f"{self.max_updates // chain * chain} updates", flush=True,
            )
        # Self-healing plane (tpu_rl.heal): the guards already run inside
        # train_step (cfg.update_guard, folded in at make_train_step time);
        # here lives the host side — a lazy on-device accumulator over the
        # per-dispatch "nonfinite-updates" metric (one jnp add per update,
        # read back only at the loss-log cadence) plus the divergence
        # watchdog + rollback budget when enabled. The watchdog needs a
        # checkpointer to roll back to, so it stays off without model_dir.
        track_nf = cfg.update_guard
        nf_acc = 0.0  # device scalar after the first guarded dispatch
        nf_base = 0.0  # cumulative count at the last rollback (host float)
        watchdog = budget = None
        if cfg.watchdog_enabled and ckpt is not None:
            from tpu_rl.heal import DivergenceWatchdog, RollbackBudget

            watchdog = DivergenceWatchdog(
                window=cfg.watchdog_window,
                z_max=cfg.watchdog_z,
                sustain=cfg.watchdog_sustain,
                nonfinite_max=cfg.watchdog_nonfinite,
            )
            budget = RollbackBudget(
                max_rollbacks=cfg.max_rollbacks,
                window_s=cfg.rollback_window_s,
            )
        # Learning-dynamics plane (tpu_rl.obs.learn): fold every dispatch's
        # in-jit diag pytree into an on-device accumulator bucketed by the
        # batch's policy staleness (the per-slot version sidecar the store
        # reads back); host readback only at the loss-log cadence below.
        # Must exist BEFORE the feed: the feeder thread's _assemble_device
        # detaches the sidecar into _diag_vers.
        diag_acc = diag_vers = None
        _stale_rows = _learn_record = _publish_diag = None
        if cfg.learn_diag:
            from collections import deque as _deque

            from tpu_rl.obs.learn import (
                DiagAccumulator,
                host_stale_rows as _stale_rows,
                learn_record as _learn_record,
                publish as _publish_diag,
            )

            diag_acc = self._diag = DiagAccumulator()
            diag_vers = self._diag_vers = _deque()
        # The feed: a background prefetch pipeline (default) or the inline
        # synchronous path (learner_prefetch=0). Either way the loop below
        # pops ONE device-ready dispatch batch per iteration.
        feed = self._make_feed(store, rng, chain)
        idx = start_idx
        profiling = False
        try:
            while not self._stopped():
                # A dispatch always advances the counter by `chain`, so stop
                # before one that would exceed the budget (never overshoot;
                # non-divisible budgets round down, warned above).
                if (
                    self.max_updates is not None
                    and idx - start_idx + chain > self.max_updates
                ):
                    break
                # Idle polls (store starving, or the update-ratio gate
                # holding) stay OUTSIDE the throughput timer: they process
                # zero transitions and must not deflate the learner-FPS
                # window. A successful pop's bounded wait IS counted — with
                # prefetch it is the pipeline's residual feed latency, the
                # honest critical-path cost of a dispatch.
                t_wait = time.perf_counter()
                item = feed.get(timeout=0.05)
                if item is None:
                    if self.heartbeat is not None:
                        self.heartbeat.value = time.time()
                    # Idle rebroadcast (chaos-plane hardening): a PUB frame
                    # is lost to any SUB that connected after the send
                    # (slow-joiner), so a worker restarted by the supervisor
                    # — or a learner restarted mid-run — would act on a
                    # stale/random policy until the next update-driven
                    # publish. While the store starves, re-ship the current
                    # weights + ver on a slow clock so joiners converge.
                    if self._maybe_join_push(pub, state, ver=idx):
                        last_pub_m = time.monotonic()
                    elif cfg.rebroadcast_idle_s > 0:
                        now_m = time.monotonic()
                        if now_m - last_pub_m >= cfg.rebroadcast_idle_s:
                            self._publish(pub, state, ver=idx)
                            last_pub_m = time.monotonic()
                            self.n_rebroadcasts += 1
                    self._note_ckpt(timer)
                    if telem_reg is not None:
                        now_m = time.monotonic()
                        if now_m - telem_last >= cfg.telemetry_interval_s:
                            telem_last = now_m
                            self._emit_telemetry(
                                telem_reg, telem_pub, timer, idx
                            )
                    if feed.poll_sleep:
                        time.sleep(feed.poll_sleep)
                    if ledger is not None:
                        ledger.add(IDLE, time.perf_counter() - t_wait)
                    continue
                wait_secs = time.perf_counter() - t_wait
                batch, feed_secs = item
                key, sub_key = jax.random.split(key)
                rc0 = self._perf.recompiles if self._perf is not None else 0
                if self._perf is not None:
                    # Identity check after the first call; first sight of a
                    # (re)built train_step runs the one-time cost analysis
                    # and rebinds the recompile watch — BEFORE dispatch, so
                    # the donated buffers are still alive to lower against.
                    self._perf.capture(train_step, state, batch, sub_key)
                t_step = time.perf_counter()
                state, metrics = train_step(state, batch, sub_key)
                step_secs = time.perf_counter() - t_step
                if track_nf:
                    # Lazy device-side add — no host sync per dispatch; the
                    # loss-log branch below reads it back with float().
                    nf_acc = nf_acc + metrics["nonfinite-updates"]
                if diag_acc is not None and isinstance(metrics, dict):
                    # Detach diag BEFORE the loss logger's float() walk (it
                    # is a nested pytree, not a scalar) and fold it with this
                    # dispatch's per-row staleness — one async device
                    # program, zero host syncs.
                    diag = metrics.pop("diag", None)
                    if diag is not None:
                        vers = diag_vers.popleft() if diag_vers else None
                        n_rows = (
                            next(iter(diag["rows"].values())).shape[0]
                            if diag["rows"]
                            else 0
                        )
                        diag_acc.add(
                            diag, _stale_rows(idx, vers, n_rows)
                        )
                if self._perf is not None:
                    # The dispatch critical path (same window as the
                    # learner-throughput timer) drives achieved FLOPs/s.
                    self._perf.note(wait_secs + step_secs)
                if tracer is not None:
                    tracer.add("queue-wait", t_wait, wait_secs)
                    tracer.add("train-step", t_step, step_secs)
                if self._inference is not None:
                    # Snapshot (not reference): the NEXT dispatch donates
                    # this state's buffers, and the serve thread must never
                    # act on deleted arrays.
                    self._inference.set_params(
                        self._actor_snapshot(state), version=idx + chain
                    )
                # learner-batching-time is the feed-side host work (shm
                # copies + assembly + H2D placement). With prefetch it
                # overlaps the device step, so the per-dispatch critical
                # path — the throughput window — is queue-wait + step;
                # overlap shows as queue-wait << batching-time.
                timer.record("learner-batching-time", feed_secs)
                timer.record("learner-queue-wait-time", wait_secs)
                timer.record("learner-step-time", step_secs)
                if ledger is not None:
                    ledger.add(wait_bucket, wait_secs)
                    # A dispatch that retraced spent its span in XLA, not in
                    # useful device math — divert it out of compute.
                    recompiled = (
                        self._perf is not None
                        and self._perf.recompiles > rc0
                    )
                    ledger.add(RECOMPILE if recompiled else COMPUTE, step_secs)
                timer.record_gauge("learner-queue-depth", feed.qsize())
                timer.record(
                    "learner-throughput",
                    wait_secs + step_secs,
                    check_throughput=True,
                )
                prev_idx, idx = idx, idx + chain

                progress = idx if anneal_absolute else idx - start_idx
                if anneal_at is not None and progress >= anneal_at:
                    # Rebuild the step with the cold-phase coefficients (one
                    # extra jit compile; optimizer state carries over — the
                    # on-policy families use rmsprop, whose accumulator is
                    # lr-independent). std_floor/family changes are NOT
                    # supported here: workers build their own family from the
                    # original cfg and cannot re-floor mid-run.
                    cfg = cfg.replace(
                        entropy_coef=float(anneal["coef"]),
                        lr=float(anneal.get("lr", cfg.lr)),
                    )
                    self.cfg = cfg
                    train_step = _wrap(spec.make_train_step(cfg, family), cfg)
                    anneal_at = None  # fire once
                    print(
                        f"[learner] update {idx}: entropy_coef -> "
                        f"{cfg.entropy_coef}, lr -> {cfg.lr}", flush=True,
                    )

                if cfg.profile_dir is not None:
                    # Window is relative to THIS run's updates (resume-safe).
                    # start() returns None when a /prof or SIGUSR2 capture
                    # is already in flight — the window then simply skips.
                    rel = idx - start_idx
                    if not profiling and rel >= cfg.profile_start:
                        profiling = prof_capture.start() is not None
                    elif profiling and rel >= cfg.profile_start + cfg.profile_steps:
                        jax.block_until_ready(metrics)
                        prof_capture.stop()
                        profiling = False
                t_pub = time.perf_counter()
                if _crossed(prev_idx, idx, self.publish_interval):
                    self._publish(pub, state, ver=idx)
                    self._consume_join_flag()  # this broadcast serves joiners
                    last_pub_m = time.monotonic()
                elif self._maybe_join_push(pub, state, ver=idx):
                    last_pub_m = time.monotonic()
                if ledger is not None:
                    # Main-lane broadcast cost only (async dispatch + codec
                    # handoff); the publisher thread's device_get + send
                    # overlap the next step and stay off the ledger.
                    ledger.add(WIRE, time.perf_counter() - t_pub)
                if telem_reg is not None:
                    now_m = time.monotonic()
                    if now_m - telem_last >= cfg.telemetry_interval_s:
                        telem_last = now_m
                        self._emit_telemetry(telem_reg, telem_pub, timer, idx)
                if _crossed(prev_idx, idx, cfg.loss_log_interval):
                    jax.block_until_ready(metrics)
                    logger.log_losses(idx, {k: float(v) for k, v in metrics.items()})
                    logger.log_timers(idx, timer)
                    self._log_fleet_stat(logger)
                    logger.flush()
                    if tracer is not None:
                        tracer.dump(os.path.join(cfg.result_dir, "trace.json"))
                    if track_nf:
                        # metrics is already host-synced (block_until_ready
                        # above), so this read costs nothing extra.
                        self.n_nonfinite_updates = float(nf_acc)
                    diag_doc = None
                    if diag_acc is not None:
                        # The plane's ONLY readback: derive the accumulated
                        # sums into gauges + the learn.jsonl audit line,
                        # then reset the on-device accumulator.
                        diag_doc = diag_acc.drain(idx)
                    if diag_doc is not None:
                        if telem_reg is not None:
                            _publish_diag(telem_reg, diag_doc)
                        if cfg.result_dir is not None:
                            from tpu_rl.obs.audit import append_jsonl

                            append_jsonl(
                                cfg.result_dir,
                                "learn.jsonl",
                                _learn_record(idx, diag_doc),
                            )
                    if watchdog is not None:
                        sa_h = self.stat_array
                        signals = {
                            "loss": float(metrics["loss"]),
                            "grad-norm": float(metrics.get("grad-norm", 0.0)),
                        }
                        if cfg.watchdog_diag and diag_doc is not None:
                            # Algorithm-health channels: a KL spike is an
                            # upward anomaly as-is; ESS collapses DOWNWARD,
                            # so it enters negated to spike the z-score.
                            g = diag_doc["global"]
                            if "approx-kl" in g:
                                signals["diag-approx-kl"] = float(
                                    g["approx-kl"]
                                )
                            if "ess" in g:
                                signals["diag-neg-ess"] = -float(g["ess"])
                        if (
                            sa_h is not None
                            and len(sa_h) > SLOT_MEAN_REW
                            and sa_h[SLOT_GAME_COUNT] > 0
                        ):
                            signals["mean-return"] = float(sa_h[SLOT_MEAN_REW])
                        tripped = watchdog.observe(signals)
                        # The guards contained these updates (params never
                        # touched), but a sustained NaN stream means the data
                        # or optimizer state is poisoned — count since the
                        # last rollback, trip immediately at the threshold.
                        if watchdog.note_nonfinite(
                            self.n_nonfinite_updates - nf_base
                        ):
                            tripped = True
                        if tripped:
                            if budget.exhausted():
                                print(
                                    f"[learner] rollback budget exhausted "
                                    f"({budget.used}/{cfg.max_rollbacks} in "
                                    f"{cfg.rollback_window_s:.0f}s): "
                                    f"{watchdog.last_reason}; stopping "
                                    f"cleanly", flush=True,
                                )
                                break
                            t_rb = time.perf_counter()
                            rolled = self._rollback(
                                ckpt, state, mesh, pub, fingerprint, key,
                                watchdog.last_reason,
                            )
                            if ledger is not None:
                                ledger.add(
                                    ROLLBACK, time.perf_counter() - t_rb
                                )
                            if rolled is not None:
                                state, idx, key = rolled
                                last_pub_m = time.monotonic()
                                watchdog.reset()
                                nf_base = self.n_nonfinite_updates
                                budget.record()
                                # Skip this iteration's save branch: the
                                # restored index is already committed on
                                # disk, re-saving it would race the
                                # just-finished restore.
                                continue
                if ckpt is not None and _crossed(
                    prev_idx, idx, cfg.model_save_interval
                ):
                    # Async mode: snapshot + enqueue only; the D2H, orbax
                    # write, commit marker, and GC run on the writer thread.
                    t_ck = time.perf_counter()
                    ckpt.save(state, idx, meta=_ckpt_meta())
                    if ledger is not None:
                        # The synchronous remnant of the save (device-side
                        # snapshot + enqueue; the full blocking write when
                        # async is off). Writer-thread time stays off-ledger.
                        ledger.add(CKPT, time.perf_counter() - t_ck)
                self._note_ckpt(timer)
                if self.heartbeat is not None:
                    self.heartbeat.value = time.time()
                sa = self.stat_array
                if (
                    cfg.stop_at_reward is not None
                    and sa is not None
                    # window full: a real STAT_WINDOW-game mean, not a
                    # lucky few-episode start
                    and sa[SLOT_GAME_COUNT] >= STAT_WINDOW
                    and sa[SLOT_MEAN_REW] >= cfg.stop_at_reward
                ):
                    logger.log_stat(
                        int(sa[SLOT_GAME_COUNT]), float(sa[SLOT_MEAN_REW])
                    )
                    logger.flush()
                    print(
                        f"[learner] fleet 50-game mean {sa[SLOT_MEAN_REW]:.1f} >= "
                        f"stop_at_reward {cfg.stop_at_reward}: solved, "
                        f"stopping at update {idx}", flush=True,
                    )
                    break
        finally:
            # Feeder first (stops shm sampling), then the publisher (joins
            # its thread, flushing the final snapshot — the Pub socket is
            # only safe to close once no other thread can touch it).
            if self._inference is not None:
                self._inference.close()
            feed.close()
            if self._publisher is not None:
                self._publisher.close()
            if prof_capture is not None:
                # Never leave a trace open (early exit / stop-event / crash)
                # and unhook from the crash path; idempotent with the
                # flight-recorder hook that covers non-finally death.
                prof_capture.close()
            if ckpt is not None:
                if idx > start_idx:
                    ckpt.save(state, idx, meta=_ckpt_meta())
                # close() drains the pending save (the run's final weights
                # are committed, not dropped) then joins the writer thread.
                ckpt.close()
                self._note_ckpt(timer)
            if telem_reg is not None:
                # Final snapshot (then the socket): the run's closing update
                # index reaches the aggregator even on early exit.
                self._emit_telemetry(telem_reg, telem_pub, timer, idx)
                telem_pub.close()
            if tracer is not None and tracer.n_recorded:
                tracer.dump(os.path.join(cfg.result_dir, "trace.json"))
            pub.close()
            writer.close()

    # ------------------------------------------------------------- batching
    def _assemble(self, raws: list):
        """One device-ready batch per dispatch: the single consumed batch
        (chain == 1), or K consumed batches stacked on the chained layout
        (``shard_chained_batch``'s contract: update axis replicated — the
        scan consumes it sequentially — batch axis sharded on "data")."""
        if self._chain_mesh is None:
            return self._to_batch(raws[0])
        from tpu_rl.parallel.dp import shard_chained_batch

        return shard_chained_batch(
            [self._to_batch(r) for r in raws], self._chain_mesh
        )

    def _next_batch(self, store, rng) -> dict | None:
        if is_off_policy(self.cfg.algo):
            return store.sample(self.cfg.batch_size, rng)
        return store.consume()

    def _make_fetch(self, store, rng):
        """Raw-batch producer for the feed, with the off-policy update:data
        ratio gate folded in. The gate counts batches at FETCH time (not at
        update completion) so the prefetch pipeline cannot overdraw the data
        budget by pre-pulling samples the learner has not yet earned."""
        gate = None
        if (
            is_off_policy(self.cfg.algo)
            and self.cfg.max_update_data_ratio is not None
        ):
            gate = UpdateRatioGate(self.cfg.max_update_data_ratio)
        self._feed_gate = gate  # introspection hook for tests

        def fetch():
            if gate is not None and not gate.ready(
                store.transitions_received()
            ):
                return None
            raw = self._next_batch(store, rng)
            if raw is not None and gate is not None:
                gate.note_fetched()
            return raw

        return fetch

    def _make_feed(self, store, rng, chain: int):
        """The learner's data plane: prefetch pipeline (feeder thread,
        device-ready double buffering) or the inline synchronous equivalent.
        Both produce identical batches in identical order — the sampler RNG
        and the chain accumulation live in the shared fetch/assemble
        closures — so the A/B switch changes timing only."""
        fetch = self._make_fetch(store, rng)
        if self.cfg.learner_prefetch > 0:
            return PrefetchPipeline(
                fetch,
                self._assemble_device,
                chain=chain,
                depth=self.cfg.learner_prefetch,
                stop_event=self.stop_event,
            )
        return SynchronousFeed(fetch, self._assemble_device, chain=chain)

    def _assemble_device(self, raws: list):
        """Assemble + eager device placement with the step's input sharding,
        so the H2D transfer happens feed-side (overlapped under prefetch)
        instead of inside the jitted call's implicit transfer. Runs on the
        feeder thread under prefetch — its trace spans land on the "feeder"
        lane, where the overlap with the main lane's train-step is visible."""
        import jax

        tracer = self._tracer
        t0 = time.perf_counter()
        self._pop_vers(raws)
        batch = self._assemble(raws)
        t1 = time.perf_counter()
        if tracer is not None:
            tracer.add("assemble", t0, t1 - t0, tid="feeder")
        if self._place_global is not None or self._chain_mesh is not None:
            # Already placed during assembly: host_local_batch_to_global /
            # shard_chained_batch both produce global device arrays.
            return batch
        if self._batch_sharding is not None:
            placed = jax.device_put(batch, self._batch_sharding)
        else:
            placed = jax.device_put(batch, self._device)
        if tracer is not None:
            tracer.add("h2d", t1, time.perf_counter() - t1, tid="feeder")
        return placed

    def _pop_vers(self, raws: list) -> None:
        """Detach each raw batch's ``"ver"`` staleness sidecar (a non-batch
        key the Batch/multihost constructors must never see) and enqueue the
        dispatch's concatenated per-row versions for the diag fold. Runs on
        the feeder thread; the FIFO mirrors the feed queue's ordering
        (single producer, single consumer)."""
        vs = [
            r.pop("ver", None) if isinstance(r, dict) else None for r in raws
        ]
        if self._diag_vers is None:
            return
        if any(v is None for v in vs):
            self._diag_vers.append(None)
        else:
            self._diag_vers.append(
                np.concatenate([np.asarray(v).reshape(-1) for v in vs])
            )

    def _setup_multihost_feed(self, sharding) -> None:
        """On a multi-host mesh, each learner host feeds its OWN rows of the
        global batch (its storage process only sees local workers); batches
        must be placed as global arrays via the sharding's device->row map."""
        import jax

        if jax.process_count() > 1:
            self._place_global = sharding

    def _to_batch(self, raw: dict):
        from tpu_rl.types import Batch, maybe_zero_carry

        raw = maybe_zero_carry(self.cfg, raw)
        if self._place_global is not None:
            from tpu_rl.parallel.multihost import host_local_batch_to_global

            return Batch(
                **host_local_batch_to_global(raw, self._place_global)
            )
        return Batch.from_mapping(raw)

    # ------------------------------------------------------------ broadcast
    def _actor_snapshot(self, state) -> dict:
        """Donation-proof device copy of the actor tree, shaped as the
        ``{"actor": ...}`` pytree ``family.act`` consumes (the same contract
        workers build from the model broadcast)."""
        import jax
        import jax.numpy as jnp

        actor = (
            state.actor_params
            if hasattr(state, "actor_params")
            else state.params["actor"]
        )
        return {"actor": jax.tree.map(jnp.copy, actor)}

    def _publish(self, pub: Pub, state, ver: int = -1) -> None:
        """Ship the actor tree as host numpy (SAC broadcasts the actor only,
        reference ``sac/learning.py:145``), tagged with the update index
        (``ver``) that produced it — workers echo it so storage can measure
        policy staleness. With the async publisher the caller only snapshots
        + starts the D2H; the blocking device_get and ZMQ send run on the
        publisher thread."""
        t0 = time.perf_counter()
        actor = (
            state.actor_params
            if hasattr(state, "actor_params")
            else state.params["actor"]
        )
        if self._publisher is not None:
            self._publisher.publish(actor, ver, epoch=self.run_epoch)
        else:
            import jax

            pub.send(
                Protocol.Model,
                {
                    "actor": jax.device_get(actor),
                    "ver": ver,
                    "epoch": self.run_epoch,
                    "t_tx": time.time_ns(),
                },
            )
        if self._tracer is not None:
            # Async path: this span is the cheap dispatch cost the hot loop
            # actually pays; the blocking device_get runs on the publisher
            # thread, outside the batch timeline.
            self._tracer.add("broadcast", t0, time.perf_counter() - t0)

    def _consume_join_flag(self) -> bool:
        """Clear a pending join request and count it answered. A PUB frame
        reaches every connected SUB, so ANY broadcast serves the joiner —
        the update-driven publish consumes the flag too, not just the
        dedicated idle-path push (a busy learner publishing every update
        must not leave the flag stranded)."""
        sa = self.stat_array
        if sa is None or len(sa) <= SLOT_JOIN_REQ or sa[SLOT_JOIN_REQ] < 1.0:
            return False
        sa[SLOT_JOIN_REQ] = 0.0
        self.n_join_pushes += 1
        return True

    def _maybe_join_push(self, pub: Pub, state, ver: int) -> bool:
        """Storage raised the join flag (a NEW wid entered the membership
        table): push current weights+ver immediately so the joiner does not
        wait out rebroadcast_idle_s acting on a random/stale policy."""
        if not self._consume_join_flag():
            return False
        self._publish(pub, state, ver=ver)
        return True

    def _note_ckpt(self, timer: ExecutionTimer) -> None:
        """Fold checkpoint instrumentation into the loop's timer: wall
        seconds of saves committed since the last call (sync or async — the
        A/B observable) and the count still in flight."""
        ckpt = self._ckpt
        if ckpt is None:
            return
        for dur in ckpt.drain_save_secs():
            timer.record("learner-ckpt-time", dur)
        timer.record_gauge("learner-ckpt-pending", float(ckpt.pending))

    def _rollback(
        self, ckpt, state, mesh, pub, fingerprint, key, reason: str
    ):
        """Watchdog-triggered restore of the PREVIOUS committed checkpoint
        (the newest may already contain the divergence). Bumps the run
        epoch so every in-flight pre-rollback rollout is fenced by storage
        exactly like post-crash frames, rebroadcasts the restored weights,
        and appends an audit record. Returns (state, idx, key) or None when
        nothing committed exists to restore."""
        import jax
        import jax.numpy as jnp

        # Drain in-flight async saves first: a save committing AFTER
        # discard_above would resurrect the diverged window on the next
        # newest-wins resume.
        ckpt.flush()
        restored = ckpt.restore_nth_latest(
            state, n=2, fingerprint=fingerprint, force=self.cfg.resume_force
        )
        if restored is None:
            print(
                f"[learner] watchdog tripped ({reason}) but no committed "
                "checkpoint exists to roll back to; continuing", flush=True,
            )
            return None
        state, r_idx, meta = restored
        ckpt.discard_above(r_idx)
        if mesh is not None:
            from tpu_rl.parallel.dp import replicate

            state = replicate(state, mesh)
        key_data = meta.get("key")
        if key_data is not None:
            try:
                key = jax.random.wrap_key_data(
                    jnp.asarray(key_data, dtype=jnp.uint32)
                )
            except (TypeError, ValueError):
                pass  # keep the live stream; the restore itself still holds
        # Epoch fence: every rollout produced against the rolled-back
        # policy (or assembled from pre-rollback frames) is now stale by
        # construction — same mechanism as the post-crash resume fence.
        self.run_epoch += 1
        sa = self.stat_array
        if sa is not None and len(sa) > SLOT_RUN_EPOCH:
            sa[SLOT_RUN_EPOCH] = float(self.run_epoch + 1)  # 0 = unknown
        self._publish(pub, state, ver=r_idx)
        self.n_rollbacks += 1
        self._record_rollback(r_idx, reason)
        print(
            f"[learner] rollback #{self.n_rollbacks}: {reason}; restored "
            f"committed idx {r_idx}, run epoch -> {self.run_epoch}",
            flush=True,
        )
        return state, r_idx, key

    def _record_rollback(self, idx: int, reason: str) -> None:
        """Append one rollback record to result_dir/learner_rollback.jsonl —
        the audit trail heal-smoke asserts against (same contract as
        :meth:`_record_resume`)."""
        from tpu_rl.obs.audit import append_jsonl

        append_jsonl(
            self.cfg.result_dir,
            "learner_rollback.jsonl",
            {
                "idx": idx,
                "epoch": self.run_epoch,
                "reason": reason,
                "nonfinite": self.n_nonfinite_updates,
            },
        )

    def _record_resume(self, idx: int) -> None:
        """Append one resume record to result_dir/learner_resume.jsonl —
        the audit trail resume-smoke asserts monotonicity against (child
        stdout is not capturable from the in-process smoke harness). The
        record shape lives in ``obs.audit.append_resume``, shared with the
        colocated loop (schema equality pinned by test)."""
        from tpu_rl.obs.audit import append_resume

        append_resume(self.cfg.result_dir, idx, self.run_epoch)

    def _emit_telemetry(self, reg, pub: Pub, timer: ExecutionTimer, idx: int
                        ) -> None:
        """Refresh the learner registry from the loop's own instruments and
        ship one snapshot. "learner-update-index" is the authoritative policy
        version the aggregator's staleness math ratchets on."""
        from tpu_rl.obs import LEARNER_VERSION_GAUGE

        reg.gauge(LEARNER_VERSION_GAUGE).set(idx)
        if self.ledger is not None:
            self.ledger.publish(reg)
        for name, val in timer.scalars().items():
            reg.gauge(name).set(val)
        reg.counter("learner-rebroadcasts").set_total(self.n_rebroadcasts)
        reg.gauge("learner-run-epoch").set(self.run_epoch)
        reg.counter("learner-join-pushes").set_total(self.n_join_pushes)
        # Self-healing plane: exported whenever the guards are compiled in
        # (update_guard default-on), so the shipped SLO example rule
        # `counter:learner-nonfinite-updates==0` always has data.
        if self.cfg.update_guard:
            reg.counter("learner-nonfinite-updates").set_total(
                self.n_nonfinite_updates
            )
        reg.counter("learner-rollbacks").set_total(self.n_rollbacks)
        perf = self._perf
        if perf is not None:
            # Performance plane: analytical FLOPs per dispatch, achieved
            # FLOPs/s over the dispatch window, MFU (omitted when the
            # device has no peak entry — CPU runs without
            # TPU_RL_PEAK_FLOPS), shape-drift retraces, and device-memory
            # watermarks. All refreshed on the emit cadence only.
            from tpu_rl.obs.perf import device_memory_bytes, process_self_stats

            reg.gauge("learner-flops-per-step").set(perf.flops_per_call)
            achieved = perf.achieved_flops_per_s()
            if achieved is not None:
                reg.gauge("learner-achieved-flops").set(achieved)
            mfu = perf.mfu()
            if mfu is not None:
                reg.gauge("learner-mfu").set(mfu)
            reg.counter("learner-xla-recompiles").set_total(perf.recompiles)
            mem_used, mem_peak = device_memory_bytes(self._device)
            reg.gauge("learner-device-mem-bytes").set(mem_used)
            reg.gauge("learner-device-mem-peak-bytes").set(mem_peak)
            rss, n_fds = process_self_stats()
            reg.gauge("learner-rss-bytes").set(rss)
            reg.gauge("learner-open-fds").set(n_fds)
        sa = self.stat_array
        if sa is not None and len(sa) > SLOT_MODEL_LOADS:
            # Fleet-total corrupt-frame counter (the mailbox aggregate the
            # timer gauge above also mirrors) as a true counter, so SLO
            # `rate:` rules can differentiate it.
            reg.counter("transport-rejected-frames").set_total(
                float(sa[SLOT_REJECTED])
            )
        if self._ckpt is not None:
            reg.gauge("learner-ckpt-pending").set(float(self._ckpt.pending))
            reg.counter("learner-ckpt-saves").set_total(self._ckpt.n_saves)
        svc = self._inference
        if svc is not None:
            reg.counter("inference-requests").set_total(svc.n_requests)
            reg.counter("inference-replies").set_total(svc.n_replies)
            reg.counter("inference-batches").set_total(svc.n_batches)
            if svc.chaos is not None:
                reg.counter("inference-chaos-stalls").set_total(
                    svc.chaos.n_stalled
                )
                reg.counter("inference-chaos-refusals").set_total(
                    svc.chaos.n_refused
                )
            if svc.ledger is not None:
                # The serve thread's own lane (wait/flush buckets under the
                # "inference" prefix) — reported, never folded into the
                # learner's ledger above.
                svc.ledger.publish(reg)
            if svc.perf is not None:
                reg.gauge("inference-flops-per-step").set(
                    svc.perf.flops_per_call
                )
                achieved = svc.perf.achieved_flops_per_s()
                if achieved is not None:
                    reg.gauge("inference-achieved-flops").set(achieved)
            # Fast-path observables: summed per-bucket recompile watch,
            # param footprint, bucket dispatch histogram + counters.
            svc.publish_serving_metrics(reg)
        snap = reg.snapshot()
        # Top-level epoch echo (same convention as workers): storage
        # ratchets its stale-frame fence from whichever epoch source lands
        # first — the mailbox slot normally wins, this covers remote setups.
        snap["epoch"] = self.run_epoch
        pub.send(Protocol.Telemetry, snap)

    def _log_fleet_stat(self, logger: LearnerLogger) -> None:
        """Consume the stat mailbox if storage activated it (reference
        ``agents/learner.py:136-148``)."""
        sa = self.stat_array
        if sa is not None and sa[SLOT_ACTIVATE] >= 1.0:
            logger.log_stat(int(sa[SLOT_GAME_COUNT]), float(sa[SLOT_MEAN_REW]))
            if len(sa) > SLOT_MODEL_LOADS:
                # Fleet-health slots (storage._relay_stat): corrupt-frame
                # drops across every transport hop, and worker model-reload
                # totals — exported as timer gauges so they reach the same
                # dashboards as the loop timings.
                self.timer.record_gauge(
                    "transport-rejected-frames", float(sa[SLOT_REJECTED])
                )
                self.timer.record_gauge(
                    "worker-model-loads", float(sa[SLOT_MODEL_LOADS])
                )
            if len(sa) > SLOT_FORWARD_BYTES:
                # Relay health (storage._relay_stat slots 5/6): frames shed
                # by the manager's drop-oldest queue and wire bytes forwarded
                # to storage — the fan-in path's loss and volume odometers.
                self.timer.record_gauge(
                    "relay-dropped-frames", float(sa[SLOT_RELAY_DROPPED])
                )
                self.timer.record_gauge(
                    "manager-forward-bytes", float(sa[SLOT_FORWARD_BYTES])
                )
            sa[SLOT_ACTIVATE] = 0.0

    def _stopped(self) -> bool:
        return self.stop_event is not None and self.stop_event.is_set()


def learner_main(
    cfg: Config,
    handles: ShmHandles,
    model_port: int,
    stat_array,
    stop_event,
    heartbeat,
    max_updates=None,
    publish_interval: int = 1,
    seed: int = 0,
    inference_port: int | None = None,
    stat_port: int | None = None,
) -> None:
    """mp.Process target (reference ``run_learner``, ``main.py:189-226``)."""
    LearnerService(
        cfg,
        handles,
        model_port,
        stat_array,
        stop_event,
        heartbeat,
        max_updates,
        publish_interval,
        seed,
        inference_port=inference_port,
        stat_port=stat_port,
    ).run()
