"""SEED-style centralized inference service: batched remote acting on the
learner's device.

New subsystem, no reference equivalent. The reference (and ``act_mode=
"local"``) runs one jitted policy forward per worker process on host CPU —
acting throughput scales only with host cores, and every worker acts on
stale broadcast weights. SEED RL (Espeholt et al. 1910.06591) and the
Podracer/Sebulba split (Hessel et al. 2104.06272) move inference onto the
accelerator behind a batching server; workers become thin env-steppers.

Design:

- a ZMQ ROUTER (``transport.Router``) bound next to the learner collects
  ``ObsRequest`` frames (one per worker tick: the tick's observations and
  episode-first flags — the recurrent carry does NOT ride the request);
- requests accumulate until ``Config.inference_batch`` observation rows are
  pending or the oldest request is ``Config.inference_flush_us`` old, then
  ONE jitted act step runs over padded batch slots on the learner's device.
  Padding comes from a power-of-two **bucket ladder**
  (``Config.inference_buckets``): each flush dispatches the smallest
  pre-warmed bucket program covering its rows, so small flushes stop paying
  the full padded step; every bucket compiles before the socket binds, so
  the recompile ratchet (``inference-xla-recompiles``) stays at zero.
  ``inference_buckets = 0`` keeps the single fixed
  ``pad_rows = max(inference_batch, worker_num_envs)`` shape bit-for-bit
  (the A/B baseline);
- the **serving fast path** (tpu_rl.models.quant) composes here: params are
  cast to ``Config.inference_dtype`` once at ``set_params`` time and
  dequantized inside the jitted step (fewer HBM bytes per flush), and
  ``Config.act_kernel = "pallas"`` swaps the act computation for the fused
  torso->LSTM->head kernel (tpu_rl.ops.pallas_act) where supported;
- the recurrent carry (h/c) lives server-side per worker-env slot, zeroed
  where the request flags an episode first — workers never maintain or ship
  acting state. For ``store_carry`` families (LSTM) the *reply* carries the
  pre-step carry rows, because the learner trains from them and they must
  reach the RolloutBatch the worker publishes;
- params are swapped in-process by the learner (``set_params`` after every
  update): remote acting is ZERO-staleness — no model broadcast lag, no
  codec, no wire copy. (The model PUB channel stays up regardless: it feeds
  the worker's local-fallback path and any late local-mode joiners.)

The service runs as a daemon thread inside the learner process so the param
handoff is a pointer swap. It is transport-complete on its own (tests run it
against synthetic Dealer clients without a learner).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from tpu_rl.config import Config
from tpu_rl.runtime.protocol import Protocol
from tpu_rl.runtime.transport import Router
from tpu_rl.utils.timer import ExecutionTimer


class _ClientState:
    """Per-DEALER-identity acting state: the env-slot carries (HOST numpy
    rows — see ``_flush``) and the row count the client established on
    first contact."""

    __slots__ = ("n", "h", "c")

    def __init__(self, n: int, h, c):
        self.n = n
        self.h = h
        self.c = c


class _Pending:
    __slots__ = ("identity", "seq", "obs", "first", "arrived")

    def __init__(self, identity: bytes, seq: int, obs, first, arrived: float):
        self.identity = identity
        self.seq = seq
        self.obs = obs
        self.first = first
        self.arrived = arrived


class InferenceService:
    """Batched acting server. ``start()`` spawns the serve thread;
    ``set_params`` swaps the policy in-process (zero staleness);
    ``close()`` shuts the thread down and releases the socket.

    ``timer`` (optional, shared with the learner's ``ExecutionTimer``)
    receives ``inference-batch-size`` / ``inference-wait-rows`` gauges and
    the ``inference-step-time`` span, so the service shows up on the same
    tensorboard dashboards as the learner hot loop.
    """

    def __init__(
        self,
        cfg: Config,
        family,
        params,
        port: int,
        ip: str = "*",
        timer: ExecutionTimer | None = None,
        seed: int = 0,
        version: int = -1,
    ):
        self.cfg = cfg
        self.family = family
        self._params = params
        # Policy version of the params currently served (the learner update
        # index). Echoed in every Act reply ("ver") so remote-acting workers
        # can tag their rollouts for the staleness histograms (tpu_rl.obs).
        self._version = version
        self.addr = (ip, port)
        self.timer = timer or ExecutionTimer()
        self.seed = seed
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._ready = threading.Event()  # set once compiled and serving
        self._lock = threading.Lock()  # guards the params slot
        self.clients: dict[bytes, _ClientState] = {}
        # observability counters
        self.n_requests = 0
        self.n_replies = 0
        self.n_batches = 0
        self.n_flush_full = 0
        self.n_flush_deadline = 0
        self.n_rejected_payload = 0
        # Per-bucket flush counts {bucket_rows: n} — the serving fast path's
        # dispatch histogram source (emitters replay deltas into the
        # inference-bucket-rows registry histogram).
        self.n_flush_bucket: dict[int, int] = {}
        self.error: BaseException | None = None
        # Live perf accounting for the act step (tpu_rl.obs.perf): FLOPs
        # per flushed batch + recompile watch. Built by the serve thread iff
        # telemetry is on; the learner's _emit_telemetry reads it.
        # One tracker per bucket program (each bucket is its own jit, so
        # each _JitWatch sees exactly its one expected compile); ``perf``
        # stays the largest bucket's tracker — the shape whose FLOPs defines
        # the headline MFU, and the only tracker in the single-bucket
        # baseline.
        self.perf = None
        self.perf_buckets: dict[int, object] = {}
        # Bucket ladder actually compiled (set by the serve thread) and the
        # served param-tree footprint (inference-param-bytes gauge).
        self.buckets: list[int] = []
        self.param_bytes = 0
        # Per-bucket flush counts already replayed into the registry
        # histogram (publish_serving_metrics delta bookkeeping).
        self._hist_emitted: dict[int, int] = {}
        # Goodput ledger for the SERVE thread (tpu_rl.obs.goodput), built in
        # _warm iff telemetry is on. Its own thread-lane: inference wait /
        # flush time must not double into the owning learner's ledger.
        # Published by whoever owns the registry (learner _emit_telemetry or
        # fleet.replica_main).
        self.ledger = None
        self._jnp = None  # bound by the serve thread (deferred jax import)
        # Service-level fault injection (tpu_rl.chaos): stall:inference
        # sleeps before a batch flush, refuse:inference swallows replies so
        # clients time out — exercising the worker fallback + re-probe
        # path. None unless cfg.chaos_spec names this service.
        self.chaos = None
        if getattr(cfg, "chaos_spec", None):
            from tpu_rl.chaos import maybe_service_chaos

            self.chaos = maybe_service_chaos(cfg)

    # --------------------------------------------------------------- control
    def start(self) -> "InferenceService":
        self._thread = threading.Thread(
            target=self._serve, name="inference-service", daemon=True
        )
        self._thread.start()
        return self

    def wait_ready(self, timeout: float = 60.0) -> bool:
        """Block until the act program is compiled and the socket is bound
        (first-request latency then excludes the XLA compile)."""
        return self._ready.wait(timeout)

    def _quantize(self, params):
        """Cast to the serving precision (``Config.inference_dtype``) —
        idempotent, so re-applied frames never double-scale. EVERY mode then
        commits the tree to the default device: the bucket jits have no
        in_shardings, so their cache keys on the param placement, and swap
        sources disagree about it — wire-decoded HOST trees (fleet replicas
        off the model broadcast) vs the learner's in-process trees carrying
        the train step's NamedSharding. Either one, unpinned, lands in a
        fresh jit cache entry vs the warmup trace — a real executable build
        on the serve path and a false positive on the recompile ratchet.
        (The GSPMD replica path is placement-insensitive — its jits pin
        explicit in_shardings — so the committed copy is just as correct
        there.) Boot params pass through this same gate at serve start, so
        warmup and swaps agree by construction."""
        import jax

        mode = getattr(self.cfg, "inference_dtype", "f32")
        if mode != "f32":
            from tpu_rl.models.quant import quantize_tree

            params = quantize_tree(params, mode)
        return jax.device_put(params, jax.devices()[0])

    def set_params(self, params, version: int = -1) -> None:
        """In-process param swap from the learner — quantize to the serving
        dtype OUTSIDE the lock, then one reference assignment of the device
        pytree (the swap itself stays atomic and copy-free). The NEXT
        flushed batch acts with the new weights, and replies echo the new
        ``version``."""
        params = self._quantize(params)
        with self._lock:
            self._params = params
            self._version = version

    def close(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def version(self) -> int:
        """Policy version currently served (the update index echoed in every
        Act reply)."""
        with self._lock:
            return self._version

    # ----------------------------------------------------------------- serve
    def _serve(self) -> None:
        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        # Boot params enter through the same quantization gate as swaps
        # (idempotent, so a set_params that already ran is a no-op cast).
        with self._lock:
            self._params = self._quantize(self._params)
        steps, buckets = self._build_step(jax, jnp)
        self.buckets = list(buckets)
        router = None
        try:
            self._warm(jax, jnp, steps, buckets)
            router = Router(*self.addr, bind=True)
            key = jax.random.key(self.seed * 7919 + 17)
            self._ready.set()
            self._loop(jax, router, steps, buckets, key)
        except BaseException as e:  # noqa: BLE001 — surfaced via .error
            self.error = e
            self._ready.set()  # never leave wait_ready() hanging
            raise
        finally:
            if router is not None:
                router.close()

    def _step_fn(self, jnp):
        """The pure padded act program (shared by every jit variant).
        Serving-dtype params are dequantized INSIDE the program (the
        compiled step reads the narrow bytes from HBM and widens on chip);
        the act computation itself is the ``Config.act_kernel`` dispatch."""
        from tpu_rl.models.quant import dequantize_tree, make_act_fn

        act = make_act_fn(self.cfg, self.family)

        def _step(params, obs, h, c, first, key):
            params = dequantize_tree(params)
            # Zero the carry rows whose env just reset (server-side episode
            # seam — the request's `first` flag is the only state the worker
            # contributes). The zeroed PRE-step carry is what local workers
            # store into the RolloutBatch, so it is returned alongside the
            # post-step carry.
            keep = (first < 0.5)[:, None]
            h = jnp.where(keep, h, 0.0)
            c = jnp.where(keep, c, 0.0)
            a, logits, log_prob, h2, c2 = act(params, obs, h, c, key)
            return a, logits, log_prob, h, c, h2, c2

        return _step

    def _bucket_ladder(self) -> list[int]:
        """Padded-batch shapes to pre-compile, ascending. ``inference_buckets
        = 0`` (default) reproduces the legacy single fixed shape
        ``max(inference_batch, worker_num_envs)`` bit-for-bit; > 0 is the
        power-of-two ladder from that floor up to pad_rows, so a flush of r
        rows dispatches the smallest covering program instead of always
        paying the largest."""
        cfg = self.cfg
        pad_rows = max(cfg.inference_batch, cfg.worker_num_envs)
        floor = int(getattr(cfg, "inference_buckets", 0))
        if floor <= 0 or floor >= pad_rows:
            return [pad_rows]
        b = 1
        while b < floor:
            b *= 2
        ladder = []
        while b < pad_rows:
            ladder.append(b)
            b *= 2
        ladder.append(pad_rows)
        return ladder

    def _build_step(self, jax, jnp):
        """Jit the padded act program, once per bucket shape; ->
        (steps: {bucket_rows: jitted step}, buckets ascending). Each bucket
        is a SEPARATE ``jax.jit`` (fresh closure) so every program carries
        its own dispatch cache — the per-bucket PerfTracker's recompile
        watch then expects exactly one compile each. Overridden by the fleet
        replica (tpu_rl.fleet) to apply GSPMD batch sharding and
        mesh-divisible bucket rounding."""
        buckets = self._bucket_ladder()
        steps = {rows: jax.jit(self._step_fn(jnp)) for rows in buckets}
        return steps, buckets

    def _warm(self, jax, jnp, steps, buckets) -> None:
        """Compile EVERY bucket shape BEFORE binding the socket: the first
        real request must never eat an XLA compile inside the workers'
        inference_timeout_ms window, at any flush size."""
        hw, cw = self.family.carry_widths
        obs_dim = int(self.cfg.obs_shape[0])
        with self._lock:
            params = self._params
        telemetry = getattr(self.cfg, "telemetry_enabled", False)
        if telemetry:
            from tpu_rl.obs.goodput import GoodputLedger
            from tpu_rl.models.quant import tree_bytes

            self.ledger = GoodputLedger("inference")
            self.param_bytes = tree_bytes(params)
        for rows in buckets:
            step = steps[rows]
            # HOST zeros, matching the arg kinds `_flush` passes at runtime
            # (numpy staging buffers): host and device operands land in
            # DIFFERENT jit cache entries even at identical avals, so
            # warming with device arrays would make the first real flush
            # count as a recompile.
            zeros = (
                np.zeros((rows, obs_dim), np.float32),
                np.zeros((rows, hw), np.float32),
                np.zeros((rows, cw), np.float32),
                np.zeros((rows,), np.float32),
            )
            if telemetry:
                from tpu_rl.obs.perf import PerfTracker

                tracker = PerfTracker()
                # One-time cost analysis at this bucket's padded shape —
                # the only shape its program ever dispatches, so a later
                # cache miss is a real drift signal
                # (inference-xla-recompiles sums the per-bucket watches).
                tracker.capture(
                    step, params, *zeros, jax.random.key(self.seed)
                )
                self.perf_buckets[rows] = tracker
            jax.block_until_ready(
                step(params, *zeros, jax.random.key(self.seed))
            )
        if telemetry:
            self.perf = self.perf_buckets[buckets[-1]]

    @property
    def recompiles(self) -> int:
        """Act-program recompiles after warmup, summed over every bucket
        program — the PR 11 ratchet (and the loadgen smoke's
        ``counter:inference-xla-recompiles==0`` SLO source). 0 when
        telemetry is off (no watches installed)."""
        return sum(t.recompiles for t in self.perf_buckets.values())

    def publish_serving_metrics(self, registry) -> None:
        """Replay the serving fast-path observables into a MetricsRegistry —
        called by whoever owns the registry (the learner's telemetry emit or
        ``fleet.replica_main``). Cumulative counters use set_total; the
        bucket histogram replays per-bucket flush-count DELTAS so repeated
        calls never double-observe."""
        registry.counter("inference-xla-recompiles").set_total(
            self.recompiles
        )
        registry.gauge("inference-param-bytes").set(self.param_bytes)
        hist = registry.histogram("inference-bucket-rows")
        for rows, n in list(self.n_flush_bucket.items()):
            registry.counter(
                "inference-bucket-flushes", labels={"rows": str(rows)}
            ).set_total(n)
            prev = self._hist_emitted.get(rows, 0)
            if n > prev:
                hist.observe_n(rows, n - prev)
                self._hist_emitted[rows] = n

    def _loop(self, jax, router, steps, buckets, key) -> None:
        """Max-batch-or-deadline dynamic batching (the PR 2 semantics): a
        flush dispatches when ``inference_batch`` rows are pending or the
        oldest request is ``inference_flush_us`` old — into the smallest
        covering bucket program. The fleet replica overrides this with
        continuous batching."""
        from bisect import bisect_left

        cfg = self.cfg
        jnp = self._jnp
        pad_rows = buckets[-1]  # chunk capacity = the largest program
        store_carry = self.family.store_carry
        pending: list[_Pending] = []
        pending_rows = 0
        flush_s = cfg.inference_flush_us / 1e6
        ledger = self.ledger
        if ledger is not None:
            from tpu_rl.obs.goodput import COMPUTE, IDLE, QUEUE_WAIT, WIRE

        while not self._stop.is_set():
            # Bounded poll: until the flush deadline when requests are
            # pending, a housekeeping tick otherwise.
            if pending:
                budget = flush_s - (time.perf_counter() - pending[0].arrived)
                timeout_ms = max(0, int(budget * 1e3))
            else:
                timeout_ms = 20
            t_recv = time.perf_counter()
            got = router.recv(timeout_ms=timeout_ms)
            if ledger is not None:
                # Holding a partial batch for the deadline is queue-wait; a
                # bare poll that delivered a request is wire; a bare timeout
                # is idle.
                if pending:
                    recv_bucket = QUEUE_WAIT
                elif got is not None:
                    recv_bucket = WIRE
                else:
                    recv_bucket = IDLE
                ledger.add(recv_bucket, time.perf_counter() - t_recv)
            if got is not None:
                req = self._ingest(*got)
                if req is not None:
                    pending.append(req)
                    pending_rows += req.obs.shape[0]
                for parts in router.drain():
                    req = self._ingest(*parts)
                    if req is not None:
                        pending.append(req)
                        pending_rows += req.obs.shape[0]
            if not pending:
                continue
            full = pending_rows >= cfg.inference_batch
            expired = (
                time.perf_counter() - pending[0].arrived >= flush_s
            )
            if not (full or expired):
                continue
            self.n_flush_full += 1 if full else 0
            self.n_flush_deadline += 0 if full else 1
            # Flush whole-client chunks of at most pad_rows rows; a
            # burst larger than one padded program drains over several
            # back-to-back dispatches.
            while pending:
                chunk, rows = [], 0
                while pending and rows + pending[0].obs.shape[0] <= pad_rows:
                    req = pending.pop(0)
                    chunk.append(req)
                    rows += req.obs.shape[0]
                pending_rows -= rows
                bucket = buckets[bisect_left(buckets, rows)]
                key, sub = jax.random.split(key)
                t_fl = time.perf_counter()
                self._flush(
                    router, steps[bucket], chunk, rows, bucket, sub,
                    store_carry, jnp,
                )
                if ledger is not None:
                    ledger.add(COMPUTE, time.perf_counter() - t_fl)
                if rows < cfg.inference_batch:
                    break  # partial tail came from the deadline, done

    # ---------------------------------------------------------------- ingest
    def _ingest(self, identity: bytes, proto: Protocol, payload
                ) -> _Pending | None:
        """Validate one request; establish the client's carry slots on first
        contact. Malformed-but-decodable payloads are dropped (counted on the
        router's reject counter semantics: a bad client must not kill the
        fleet's acting path)."""
        if proto != Protocol.ObsRequest or not isinstance(payload, dict):
            self.n_rejected_payload += 1
            return None
        try:
            obs = np.asarray(payload["obs"], np.float32)
            first = np.asarray(payload["first"], np.float32).reshape(-1)
            seq = int(payload["seq"])
        except (KeyError, TypeError, ValueError):
            self.n_rejected_payload += 1
            return None
        if obs.ndim != 2 or obs.shape[0] != first.shape[0]:
            self.n_rejected_payload += 1
            return None
        self.n_requests += 1
        client = self.clients.get(identity)
        if client is None or client.n != obs.shape[0]:
            hw, cw = self.family.carry_widths
            n = obs.shape[0]
            client = _ClientState(
                n,
                np.zeros((n, hw), np.float32),
                np.zeros((n, cw), np.float32),
            )
            self.clients[identity] = client
        return _Pending(identity, seq, obs, first, time.perf_counter())

    # ----------------------------------------------------------------- flush
    def _flush(self, router, step, chunk, rows, pad_rows, key,
               store_carry, jnp) -> None:
        if self.chaos is not None:
            self.chaos.maybe_stall()
        t0 = time.perf_counter()
        # Shape-stable staging: obs/first/h/c are built as HOST buffers at
        # exactly the bucket's padded shape, so the ONLY device programs a
        # flush ever runs are the pre-warmed bucket jits. Gathering carries
        # with jnp.concatenate over per-client device slices would compile
        # a fresh concat executable for every novel chunk composition
        # (20ms+ each, unbounded combos under open-loop load) — a hidden
        # recompile the bucket ratchet exists to forbid.
        obs = np.zeros((pad_rows, chunk[0].obs.shape[1]), np.float32)
        first = np.ones((pad_rows,), np.float32)  # pad slots: reset carry
        hw, cw = self.family.carry_widths
        h = np.zeros((pad_rows, hw), np.float32)
        c = np.zeros((pad_rows, cw), np.float32)
        off = 0
        offsets = []
        for req in chunk:
            n = req.obs.shape[0]
            obs[off:off + n] = req.obs
            first[off:off + n] = req.first
            client = self.clients[req.identity]
            h[off:off + n] = client.h
            c[off:off + n] = client.c
            offsets.append(off)
            off += n
        with self._lock:
            params = self._params
            version = self._version
        a, logits, log_prob, h_pre, c_pre, h2, c2 = step(
            params, obs, h, c, first, key
        )
        # One host transfer for the whole batch; per-client row slices view it.
        a_np = np.asarray(a)
        logits_np = np.asarray(logits)
        lp_np = np.asarray(log_prob)
        h2_np = np.asarray(h2)
        c2_np = np.asarray(c2)
        h_pre_np = np.asarray(h_pre) if store_carry else None
        c_pre_np = np.asarray(c_pre) if store_carry else None
        for req, off in zip(chunk, offsets, strict=True):
            n = req.obs.shape[0]
            client = self.clients[req.identity]
            client.h = h2_np[off:off + n]
            client.c = c2_np[off:off + n]
            reply = {
                "seq": req.seq,
                "act": a_np[off:off + n],
                "logits": logits_np[off:off + n],
                "log_prob": lp_np[off:off + n],
                # Policy version these actions were sampled with — the
                # worker echoes it into the published RolloutBatch.
                "ver": version,
            }
            if store_carry:
                reply["hx"] = h_pre_np[off:off + n]
                reply["cx"] = c_pre_np[off:off + n]
            if self.chaos is not None and self.chaos.refuse():
                # Swallowed reply: the client burns a timeout and retries /
                # falls back. n_replies stays honest — it counts replies
                # actually sent. The carry above already advanced, the same
                # smudge a genuinely lost reply leaves (see InferenceClient).
                continue
            router.send(req.identity, Protocol.Act, reply)
            self.n_replies += 1
        self.n_batches += 1
        self.timer.record_gauge("inference-batch-size", rows)
        # ``pad_rows`` here is the dispatched bucket's padded shape: the
        # per-bucket flush count feeds the inference-bucket-rows histogram
        # (emitters replay the deltas) and the per-bucket FLOPs tracker
        # keeps MFU honest at every shape.
        self.n_flush_bucket[pad_rows] = self.n_flush_bucket.get(pad_rows, 0) + 1
        flush_secs = time.perf_counter() - t0
        self.timer.record("inference-step-time", flush_secs)
        tracker = self.perf_buckets.get(pad_rows)
        if tracker is not None:
            tracker.note(flush_secs)


class InferenceClient:
    """Worker-side remote-acting client: one in-flight request per tick
    (send then timed receive), correlated by a monotonically increasing
    ``seq`` echo — stale replies (a retry's ghost) are skipped by seq.

    ``act`` returns the reply payload dict, or None once
    ``Config.inference_retries`` retries have all timed out
    (``Config.inference_timeout_ms`` each) — the caller's cue to fall back
    to local acting. Retries resend the same seq: if the server actually
    served the lost reply, its carry advanced once more than the episode —
    a policy-lag-sized smudge on a fault path the IS corrections absorb.
    """

    def __init__(
        self,
        cfg: Config,
        ip: str,
        port: int,
        wid: int = 0,
        identity: bytes | None = None,
        timer: ExecutionTimer | None = None,
    ):
        import uuid

        self.cfg = cfg
        self.wid = wid
        self.timer = timer
        self.seq = 0
        self.n_timeouts = 0
        # Identity must be unique per socket across worker restarts: a
        # restarted worker reusing a dead identity would inherit the old
        # carry rows AND zmq may still route the dead peer's queue.
        from tpu_rl.runtime.transport import Dealer

        self.dealer = Dealer(
            ip, port,
            identity=identity or f"w{wid}-{uuid.uuid4().hex[:8]}".encode(),
        )

    @property
    def n_rejected(self) -> int:
        """Corrupt/foreign replies dropped by the DEALER — surfaced so the
        worker's stat dict covers this receive channel too, not just the
        model SUB."""
        return self.dealer.n_rejected

    def act(
        self,
        obs: np.ndarray,
        first: np.ndarray,
        retries: int | None = None,
    ) -> dict | None:
        """``retries`` overrides ``Config.inference_retries`` for this call
        (the worker's re-probe uses 0: one cheap attempt, not a full retry
        burst against a possibly-still-dead server)."""
        cfg = self.cfg
        attempts = (
            cfg.inference_retries if retries is None else int(retries)
        ) + 1
        req = {"wid": self.wid, "seq": self.seq, "obs": obs, "first": first}
        t0 = time.perf_counter()
        try:
            for _attempt in range(attempts):
                self.dealer.send(Protocol.ObsRequest, req)
                deadline = time.perf_counter() + cfg.inference_timeout_ms / 1e3
                while True:
                    left_ms = int((deadline - time.perf_counter()) * 1e3)
                    if left_ms <= 0:
                        break
                    got = self.dealer.recv(timeout_ms=left_ms)
                    if got is None:
                        continue  # rejected frame burned some budget; keep waiting
                    proto, payload = got
                    if proto != Protocol.Act or not isinstance(payload, dict):
                        continue
                    if payload.get("seq") != self.seq:
                        continue  # stale ghost from an earlier retry
                    if self.timer is not None:
                        self.timer.record(
                            "inference-rtt", time.perf_counter() - t0
                        )
                    return payload
                self.n_timeouts += 1
            return None
        finally:
            self.seq += 1

    def close(self) -> None:
        self.dealer.close()
