"""ZeroMQ PUB/SUB transport wrappers.

Capability parity with the per-role raw socket setup scattered through the
reference (``/root/reference/agents/worker.py:45-56``,
``agents/manager.py:30-40``, ``agents/learner_storage.py:60-66``,
``agents/learner.py:85-90``), centralized: every channel is a PUB or SUB
endpoint created from one factory, always carrying :mod:`protocol` frames.
PUB/SUB is deliberate — best-effort, lossy, connection-free — because the
algorithms absorb drops (off-policy corrections) and workers must be able to
join/leave freely (SURVEY.md §5.3).

The DCN topology (SURVEY.md §1 "physical process topology"):

- rollout/stat channel: worker PUB -> manager SUB (bind) -> manager PUB ->
  storage SUB (bind). ``Protocol.Telemetry`` snapshots (tpu_rl.obs) ride
  this channel too: worker/manager frames fan in through the relay, and the
  learner process publishes its own snapshots straight onto the storage
  SUB over a loopback PUB — no extra port, no new socket pattern;
- model channel: learner PUB (bind) -> every worker SUB, on ``model_port =
  learner_port + 1`` — the broadcast bypasses managers.

On TPU pods this remains the host-side fabric; chip-to-chip traffic rides ICI
via XLA collectives instead (``tpu_rl.parallel``), which the reference has no
equivalent of.
"""

from __future__ import annotations

import secrets
import struct
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Iterator

import zmq
import zmq.asyncio

from tpu_rl.runtime import native
from tpu_rl.runtime.protocol import (
    MAX_PROTO,
    TRACE_KINDS_MASK,
    Protocol,
    decode,
    encode,
    peek,
)

# Keep only the newest model broadcast in flight (a worker that lags wants the
# freshest params, not a backlog); rollout channels buffer more.
MODEL_HWM = 4
DATA_HWM = 4096


def _endpoint(ip: str, port: int) -> str:
    return f"tcp://{ip}:{port}"


# -------------------------------------------------------- batch validation
# A drained deque is validated in ONE native call (tpurl_validate_batch in
# native/codec.cpp — GIL released for the whole batch) instead of a Python
# peek()/CRC pass per frame. The pure-Python per-frame path stays both as the
# no-toolchain fallback and as the bench A/B baseline (native_batch=False).


def _validate_raw(
    frames: list[list[bytes]], use_native: bool
) -> tuple[list[tuple[Protocol, list[bytes]]], int]:
    """peek-grade validation of many frames -> (valid, n_rejected)."""
    if use_native and native.available():
        verdicts = native.validate_batch(frames, TRACE_KINDS_MASK, MAX_PROTO)
        out = [
            (Protocol(parts[0][0]), parts)
            for parts, v in zip(frames, verdicts, strict=True)
            if v == 0
        ]
        return out, len(frames) - len(out)
    out, rejected = [], 0
    for parts in frames:
        try:
            out.append((peek(parts), parts))
        except ValueError:
            rejected += 1
    return out, rejected


def _validate_traced(
    frames: list[list[bytes]], use_native: bool
) -> tuple[list[tuple[Protocol, Any, bytes | None]], int]:
    """Full storage-edge validation + decode of many frames. The native path
    CRCs every body in one call, then ``decode(validated=True)`` skips the
    per-frame re-hash; decompress/unpack errors still reject."""
    out: list[tuple[Protocol, Any, bytes | None]] = []
    rejected = 0
    if use_native and native.available():
        verdicts = native.validate_batch(
            frames, TRACE_KINDS_MASK, MAX_PROTO, check_crc=True
        )
        for parts, v in zip(frames, verdicts, strict=True):
            if v != 0:
                rejected += 1
                continue
            try:
                proto, payload = decode(parts, validated=True)
            except ValueError:
                rejected += 1
                continue
            out.append(
                (proto, payload, parts[2] if len(parts) == 3 else None)
            )
        return out, rejected
    for parts in frames:
        try:
            proto, payload = decode(parts)
        except ValueError:
            rejected += 1
            continue
        out.append((proto, payload, parts[2] if len(parts) == 3 else None))
    return out, rejected


class Pub:
    """Synchronous PUB endpoint (the learner's model broadcast is sync in the
    reference too, ``agents/learner.py:85-90``)."""

    def __init__(self, ip: str, port: int, bind: bool, hwm: int = DATA_HWM,
                 ctx: Any = None, chaos: Any = None) -> None:
        self._ctx = ctx or zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.PUB)
        self.sock.set_hwm(hwm)
        # Optional fault injector (tpu_rl.chaos.TransportChaos). None — the
        # default and the production state — keeps the send path on the
        # exact pre-chaos code: one `is None` check, no allocations (pinned
        # by tests/test_chaos.py tracemalloc).
        self._chaos = chaos
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    def send(
        self, proto: Protocol, payload: Any, trace: bytes | None = None
    ) -> None:
        """``trace`` (a ``protocol.pack_trace`` trailer) rides as the
        optional third wire part on sampled rollout frames; None (the
        default and the sampling-off state) keeps the exact 2-part frame."""
        parts = encode(proto, payload, trace)
        if self._chaos is not None:
            parts = self._chaos.on_send(parts)
            if parts is None:
                return
        self.sock.send_multipart(parts)

    def send_raw(self, parts: list[bytes]) -> None:
        """Forward already-encoded wire parts verbatim — the zero-copy relay
        hop (no pack/compress/CRC; zmq ships the same buffers it received).
        A trace trailer, being just a third part, is forwarded for free."""
        if self._chaos is not None:
            parts = self._chaos.on_send(parts)
            if parts is None:
                return
        self.sock.send_multipart(parts)

    def close(self) -> None:
        self.sock.close(linger=0)


class Sub:
    """Synchronous SUB endpoint subscribed to everything.

    Malformed/foreign frames (``decode`` raising ValueError) are dropped and
    counted, never raised — one stray publisher on a best-effort PUB/SUB
    fabric must not crash a role process."""

    def __init__(self, ip: str, port: int, bind: bool, hwm: int = DATA_HWM,
                 ctx: Any = None, chaos: Any = None,
                 native_batch: bool = True) -> None:
        self._ctx = ctx or zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.SUB)
        self.sock.set_hwm(hwm)
        self.sock.setsockopt(zmq.SUBSCRIBE, b"")
        self.n_rejected = 0
        # Optional fault injector applied to received parts BEFORE decode:
        # an injected corruption therefore pairs with its n_rejected bump in
        # the same call, which is what makes chaos accounting exact. None
        # (default) costs one `is None` check per frame.
        self._chaos = chaos
        # Validate drained batches through the native codec when it's loaded
        # (one ctypes call per drain instead of a Python peek per frame);
        # False forces the pure-Python path — the bench A/B baseline.
        self._native_batch = native_batch
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    def _collect(self, max_msgs: int) -> list[list[bytes]]:
        """Drain up to ``max_msgs`` queued frames (chaos applied per frame),
        without validating — batch validation follows in one call."""
        frames: list[list[bytes]] = []
        for _ in range(max_msgs):
            try:
                parts = self.sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                break
            if self._chaos is not None:
                parts = self._chaos.on_recv(parts)
                if parts is None:
                    continue
            frames.append(parts)
        return frames

    def recv(self, timeout_ms: int | None = None) -> tuple[Protocol, Any] | None:
        """Blocking (or timed) receive of one decoded message; None on
        timeout or on a rejected frame."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        parts = self.sock.recv_multipart()
        if self._chaos is not None:
            parts = self._chaos.on_recv(parts)
            if parts is None:
                return None
        try:
            return decode(parts)
        except ValueError:
            self.n_rejected += 1
            return None

    def drain(self, max_msgs: int = 1024) -> Iterator[tuple[Protocol, Any]]:
        """Yield every decodable message currently queued, newest-bounded."""
        for _ in range(max_msgs):
            try:
                parts = self.sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                return
            if self._chaos is not None:
                parts = self._chaos.on_recv(parts)
                if parts is None:
                    continue
            try:
                yield decode(parts)
            except ValueError:
                self.n_rejected += 1

    def recv_traced(
        self, timeout_ms: int | None = None
    ) -> tuple[Protocol, Any, bytes | None] | None:
        """:meth:`recv` plus the raw trace trailer when the frame carried one
        (already validated by ``decode``; parse with ``protocol.unpack_trace``
        at the consumer). The 2-part common case yields ``trailer=None`` with
        no extra work beyond one length check."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        parts = self.sock.recv_multipart()
        if self._chaos is not None:
            parts = self._chaos.on_recv(parts)
            if parts is None:
                return None
        try:
            proto, payload = decode(parts)
        except ValueError:
            self.n_rejected += 1
            return None
        return proto, payload, parts[2] if len(parts) == 3 else None

    def drain_traced(
        self, max_msgs: int = 1024
    ) -> Iterator[tuple[Protocol, Any, bytes | None]]:
        """Yield every decodable queued message with its trace trailer (or
        None) — the lineage-aware counterpart of :meth:`drain`. The whole
        batch is structurally validated + CRC'd in one native call when the
        codec is loaded (storage-edge hot path)."""
        got, rejected = _validate_traced(
            self._collect(max_msgs), self._native_batch
        )
        self.n_rejected += rejected
        yield from got

    def recv_raw(
        self, timeout_ms: int | None = None
    ) -> tuple[Protocol, list[bytes]] | None:
        """Blocking (or timed) receive of one frame as opaque wire parts,
        validated by :func:`protocol.peek` only (proto byte, header, size
        caps — no CRC/decompress/unpack). None on timeout or on a rejected
        frame (counted in ``n_rejected``, same contract as :meth:`recv`)."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        parts = self.sock.recv_multipart()
        if self._chaos is not None:
            parts = self._chaos.on_recv(parts)
            if parts is None:
                return None
        try:
            return peek(parts), parts
        except ValueError:
            self.n_rejected += 1
            return None

    def drain_raw(
        self, max_msgs: int = 1024
    ) -> Iterator[tuple[Protocol, list[bytes]]]:
        """Yield every queued frame as peek-validated opaque wire parts,
        newest-bounded (the raw-relay counterpart of :meth:`drain`). The
        batch is validated in one native call when the codec is loaded."""
        got, rejected = _validate_raw(
            self._collect(max_msgs), self._native_batch
        )
        self.n_rejected += rejected
        yield from got

    def close(self) -> None:
        self.sock.close(linger=0)


class Router:
    """ROUTER endpoint for the centralized inference service (new capability,
    no reference equivalent — the SEED RL request/reply pattern).

    Unlike PUB/SUB, ROUTER/DEALER is connection-addressed: every frame a
    DEALER sends arrives prefixed with that peer's identity, and a reply sent
    to the same identity routes back to exactly that peer. Malformed frames
    are dropped and counted (``n_rejected``), same contract as :class:`Sub` —
    one corrupt client must not crash the inference server."""

    def __init__(self, ip: str, port: int, bind: bool = True,
                 hwm: int = DATA_HWM, ctx: Any = None) -> None:
        self._ctx = ctx or zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.ROUTER)
        self.sock.set_hwm(hwm)
        self.n_rejected = 0
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    def recv(self, timeout_ms: int | None = None
             ) -> tuple[bytes, Protocol, Any] | None:
        """One ``(identity, proto, payload)`` request; None on timeout or on
        a rejected frame."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        parts = self.sock.recv_multipart()
        return self._split(parts)

    def drain(self, max_msgs: int = 1024
              ) -> Iterator[tuple[bytes, Protocol, Any]]:
        """Yield every decodable queued request, newest-bounded."""
        for _ in range(max_msgs):
            try:
                parts = self.sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                return
            got = self._split(parts)
            if got is not None:
                yield got

    def _split(self, parts: list[bytes]
               ) -> tuple[bytes, Protocol, Any] | None:
        # ROUTER prepends the peer identity to whatever the DEALER sent.
        try:
            if len(parts) < 2:
                raise ValueError(f"short ROUTER frame: {len(parts)} parts")
            proto, payload = decode(parts[1:])
            return parts[0], proto, payload
        except ValueError:
            self.n_rejected += 1
            return None

    def send(self, identity: bytes, proto: Protocol, payload: Any) -> None:
        """Route one reply back to ``identity``. A vanished peer is a normal
        fleet event (worker died between request and reply): with
        ROUTER_MANDATORY unset zmq silently drops the frame, which is the
        behavior we want on a best-effort fabric."""
        self.sock.send_multipart([identity, *encode(proto, payload)])

    def close(self) -> None:
        self.sock.close(linger=0)


class Dealer:
    """DEALER endpoint: the worker side of the inference channel. One
    in-flight request per tick (send -> timed recv), so no correlation
    machinery beyond the payload's own ``seq`` echo is needed."""

    def __init__(self, ip: str, port: int, bind: bool = False,
                 hwm: int = DATA_HWM, identity: bytes | None = None,
                 ctx: Any = None) -> None:
        self._ctx = ctx or zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.DEALER)
        self.sock.set_hwm(hwm)
        if identity is not None:
            self.sock.setsockopt(zmq.IDENTITY, identity)
        self.n_rejected = 0
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    def send(self, proto: Protocol, payload: Any) -> None:
        self.sock.send_multipart(encode(proto, payload))

    def recv(self, timeout_ms: int | None = None) -> tuple[Protocol, Any] | None:
        """Timed receive of one decoded reply; None on timeout or on a
        rejected frame."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        try:
            return decode(self.sock.recv_multipart())
        except ValueError:
            self.n_rejected += 1
            return None

    def close(self) -> None:
        self.sock.close(linger=0)


class AsyncSub:
    """asyncio SUB endpoint (storage/manager event loops, reference
    ``zmq.asyncio`` usage)."""

    def __init__(self, ip: str, port: int, bind: bool, hwm: int = DATA_HWM,
                 ctx: Any = None) -> None:
        self._ctx = ctx or zmq.asyncio.Context.instance()
        self.sock = self._ctx.socket(zmq.SUB)
        self.sock.set_hwm(hwm)
        self.sock.setsockopt(zmq.SUBSCRIBE, b"")
        self.n_rejected = 0
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    async def recv(self) -> tuple[Protocol, Any]:
        """Receive the next decodable message (rejected frames are dropped)."""
        while True:
            try:
                return decode(await self.sock.recv_multipart())
            except ValueError:
                self.n_rejected += 1

    def close(self) -> None:
        self.sock.close(linger=0)


class AsyncPub:
    def __init__(self, ip: str, port: int, bind: bool, hwm: int = DATA_HWM,
                 ctx: Any = None) -> None:
        self._ctx = ctx or zmq.asyncio.Context.instance()
        self.sock = self._ctx.socket(zmq.PUB)
        self.sock.set_hwm(hwm)
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    async def send(self, proto: Protocol, payload: Any) -> None:
        await self.sock.send_multipart(encode(proto, payload))

    def close(self) -> None:
        self.sock.close(linger=0)


# ===================================================== shared-memory channel
# Same-host data hops (manager -> storage, learner -> storage telemetry) over
# named POSIX shared memory instead of a TCP loopback socket: a send is a
# short memcpy into a lock-free ring, a drain is a batch of memcpys out — no
# syscalls, no kernel socket buffers, no zmq IO thread. Selected per hop by
# ``Config.transport`` ("shm" forces it; "auto" picks it when the peer
# address is loopback; "tcp" — the default — never builds any of this).
#
# Topology: one SPSC byte-ring PER PRODUCER, fanned in by the single
# consumer. Rendezvous is by segment NAME, keyed on the (unique per channel)
# TCP port number the hop would otherwise use:
#
#   tpurl-{port}-ctl   consumer-owned control block: magic, a fresh session
#                      nonce per consumer lifetime, the ring capacity, and a
#                      claimed-slot bitmap;
#   tpurl-{port}-p{k}  producer k's ring (128-byte header + capacity bytes).
#
# A producer claims slot k by creating its segment with O_EXCL (the atomic
# arbiter — two racers cannot both win a name), initializes the ring header,
# THEN sets bitmap[k], so the consumer never attaches a half-built ring. A
# consumer (re)start unlinks every stale segment and mints a new nonce;
# producers re-check the nonce (time-gated, ~1s) and re-rendezvous onto the
# new session, which is how the channel survives a storage restart under
# supervision. Like PUB/SUB, the channel is best-effort: no consumer bound
# yet, or a full ring, drops the frame (counted).
#
# Ring protocol (seqlock, in the spirit of tpu_rl/data/shm_ring.py): byte
# positions are MONOTONIC u64s (wrap = position % capacity, records may
# split across the physical end). The writer copies the record into
# [wpos, wpos+len), then publishes wpos under its seqlock (odd = mid-
# publish); the reader snapshots a stable wpos, consumes [rpos, wpos), and
# publishes rpos under its own seqlock for the writer's free-space check.
# Each side WRITES only its own counter, so one torn-read-retry loop per
# snapshot is the entire synchronization story. Record framing:
# u8 part-count, u32 length per part, then the part bytes — the same
# multipart shape zmq carries, so chaos shims and validators apply
# unchanged.

SHM_MAX_PRODUCERS = 64
SHM_RING_BYTES = 1 << 26  # 64 MiB per producer ring (~2.6k 25 KB ticks)

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_SHM_CTL_MAGIC = 0x54524C43  # "TRLC"
_RING_MAGIC = 0x54524C52  # "TRLR"
_RING_HDR = 128
# ring header offsets: writer's cache line, then the reader's
_WSEQ, _WPOS, _RMAGIC, _RCAP = 0, 8, 16, 24
_RSEQ, _RPOS = 64, 72
# ctl offsets: magic u32 (written LAST — publishes the block), nonce u64,
# capacity u64, then the claimed-slot bitmap
_CTL_NONCE, _CTL_CAP, _CTL_BITMAP = 8, 16, 24
_SEQLOCK_SPINS = 10_000

# Per-part-count record framing structs ("<B{n}I" preamble, "<{n}I" length
# table), cached so the ring's per-record write/read never rebuilds a format
# string — the hot-path purity checker (tools/analysis) holds these
# functions to zero per-call formatting.
_PREAMBLE_STRUCTS: dict[int, struct.Struct] = {}
_LENS_STRUCTS: dict[int, struct.Struct] = {}


def _preamble_struct(nparts: int) -> struct.Struct:
    s = _PREAMBLE_STRUCTS.get(nparts)
    if s is None:
        s = _PREAMBLE_STRUCTS[nparts] = struct.Struct("<B%dI" % nparts)
    return s


def _lens_struct(nparts: int) -> struct.Struct:
    s = _LENS_STRUCTS.get(nparts)
    if s is None:
        s = _LENS_STRUCTS[nparts] = struct.Struct("<%dI" % nparts)
    return s


def _ctl_name(port: int) -> str:
    return f"tpurl-{port}-ctl"


def _slot_name(port: int, k: int) -> str:
    return f"tpurl-{port}-p{k}"


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Detach ``shm`` from the resource tracker: it would otherwise unlink
    the segment when ANY attaching process exits (and warn about 'leaks').
    Lifetime is owned explicitly by the consumer (`ShmConsumer.close`)."""
    try:
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass  # tracker internals vary across minor versions; never fatal


def _attach(name: str) -> shared_memory.SharedMemory:
    shm = shared_memory.SharedMemory(name)
    _untrack(shm)
    return shm


def _shm_unlink(name: str) -> None:
    """Unlink by name WITHOUT SharedMemory.unlink(): that method also
    unregisters from the resource tracker, and since _untrack already did,
    the tracker process would log a KeyError for every segment."""
    try:
        import _posixshmem

        _posixshmem.shm_unlink("/" + name)
    except (ImportError, FileNotFoundError):
        pass


def _unlink_stale(port: int) -> None:
    """Remove every segment a previous session on this channel left behind
    (crashed consumer, orphaned producers)."""
    for name in [_ctl_name(port)] + [
        _slot_name(port, k) for k in range(SHM_MAX_PRODUCERS)
    ]:
        _shm_unlink(name)


class _RingWriter:
    """Producer side of one SPSC byte ring."""

    __slots__ = ("_shm", "buf", "cap", "wpos", "_wseq")

    def __init__(self, shm: shared_memory.SharedMemory, capacity: int) -> None:
        self._shm = shm
        self.buf = shm.buf
        self.cap = capacity
        self.wpos = _U64.unpack_from(self.buf, _WPOS)[0]
        self._wseq = _U64.unpack_from(self.buf, _WSEQ)[0]

    def _read_rpos(self) -> int | None:
        buf = self.buf
        for _ in range(_SEQLOCK_SPINS):
            s1 = _U64.unpack_from(buf, _RSEQ)[0]
            if s1 & 1:
                continue
            rpos = _U64.unpack_from(buf, _RPOS)[0]
            if _U64.unpack_from(buf, _RSEQ)[0] == s1:
                return rpos
        # Reader wedged mid-publish (it died between the two seqlock writes).
        # Conservative: report no known free space rather than risk
        # overwriting unread bytes on a bogus rpos.
        return None

    def _put(self, pos: int, data: bytes) -> int:
        off = pos % self.cap
        n = len(data)
        base = _RING_HDR
        if off + n <= self.cap:
            self.buf[base + off : base + off + n] = data
        else:
            k = self.cap - off
            self.buf[base + off : base + self.cap] = data[:k]
            self.buf[base : base + n - k] = data[k:]
        return pos + n

    def write(self, parts: list[bytes]) -> bool:
        """Copy one multipart record in; False = ring full (caller counts
        the drop — same shed-newest behavior as a PUB at HWM)."""
        nparts = len(parts)
        if not nparts or nparts > 255:
            return False
        lens = list(map(len, parts))
        pre = _preamble_struct(nparts).pack(nparts, *lens)
        rec = len(pre) + sum(lens)
        rpos = self._read_rpos()
        if rpos is None or self.wpos + rec - rpos > self.cap:
            return False
        pos = self._put(self.wpos, pre)
        for p in parts:
            pos = self._put(pos, p)
        # Publish: data writes above happen-before the wpos store (CPython
        # executes these sequentially; x86/ARM64 store ordering suffices for
        # the paired acquire loop in _read_wpos).
        buf = self.buf
        _U64.pack_into(buf, _WSEQ, self._wseq + 1)  # odd: mid-publish
        _U64.pack_into(buf, _WPOS, pos)
        self._wseq += 2
        _U64.pack_into(buf, _WSEQ, self._wseq)
        self.wpos = pos
        return True


class _RingReader:
    """Consumer side of one SPSC byte ring."""

    __slots__ = ("_shm", "buf", "cap", "rpos", "_rseq", "n_resync")

    def __init__(self, shm: shared_memory.SharedMemory, capacity: int) -> None:
        self._shm = shm
        self.buf = shm.buf
        self.cap = capacity
        self.rpos = _U64.unpack_from(self.buf, _RPOS)[0]
        self._rseq = _U64.unpack_from(self.buf, _RSEQ)[0]
        self.n_resync = 0

    def _read_wpos(self) -> int:
        buf = self.buf
        for _ in range(_SEQLOCK_SPINS):
            s1 = _U64.unpack_from(buf, _WSEQ)[0]
            if s1 & 1:
                continue
            wpos = _U64.unpack_from(buf, _WPOS)[0]
            if _U64.unpack_from(buf, _WSEQ)[0] == s1:
                return wpos
        return self.rpos  # writer wedged mid-publish: read nothing new

    def _get(self, pos: int, n: int) -> bytes:
        off = pos % self.cap
        base = _RING_HDR
        if off + n <= self.cap:
            return bytes(self.buf[base + off : base + off + n])
        k = self.cap - off
        return bytes(self.buf[base + off : base + self.cap]) + bytes(
            self.buf[base : base + n - k]
        )

    def read(self, max_msgs: int) -> list[list[bytes]]:
        """Copy out up to ``max_msgs`` complete records; empty list = ring
        idle. A structurally impossible record (only reachable through real
        memory corruption — chaos corrupts part BYTES, which keep framing
        intact) resyncs the ring by skipping to the writer's position."""
        wpos = self._read_wpos()
        pos = self.rpos
        out: list[list[bytes]] = []
        while pos < wpos and len(out) < max_msgs:
            nparts = self._get(pos, 1)[0]
            if nparts == 0:
                self.n_resync += 1
                pos = wpos
                break
            lens = _lens_struct(nparts).unpack(self._get(pos + 1, 4 * nparts))
            end = pos + 1 + 4 * nparts + sum(lens)
            if end > wpos or max(lens) > self.cap:
                self.n_resync += 1
                pos = wpos
                break
            p = pos + 1 + 4 * nparts
            parts = []
            for n in lens:
                parts.append(self._get(p, n))
                p += n
            out.append(parts)
            pos = end
        if pos != self.rpos:
            self.rpos = pos
            buf = self.buf
            _U64.pack_into(buf, _RSEQ, self._rseq + 1)
            _U64.pack_into(buf, _RPOS, pos)
            self._rseq += 2
            _U64.pack_into(buf, _RSEQ, self._rseq)
        return out


class ShmPub:
    """Producer endpoint of the shm channel, Pub-compatible (``send`` /
    ``send_raw`` / ``close``, chaos ``on_send`` applied identically).

    Best-effort like PUB: frames sent before the consumer binds, or while
    the ring is full, are dropped and counted. Rendezvous and session-loss
    recovery are time-gated so the hot path pays one ``monotonic()`` call."""

    _RETRY_S = 0.2  # how often to re-attempt rendezvous with no consumer
    _CHECK_S = 1.0  # how often to verify the consumer session nonce

    def __init__(self, port: int, chaos: Any = None) -> None:
        self.port = port
        self._chaos = chaos
        self._writer: _RingWriter | None = None
        self._seg: shared_memory.SharedMemory | None = None
        self._nonce = 0
        self.slot: int | None = None
        self.n_dropped_full = 0
        self.n_dropped_no_peer = 0
        self._next_try = 0.0
        self._next_check = 0.0
        self._rendezvous()

    # ------------------------------------------------------------ session
    def _rendezvous(self) -> None:
        try:
            ctl = _attach(_ctl_name(self.port))
        except (FileNotFoundError, OSError):
            return
        try:
            if _U32.unpack_from(ctl.buf, 0)[0] != _SHM_CTL_MAGIC:
                return  # consumer still initializing; retry later
            nonce = _U64.unpack_from(ctl.buf, _CTL_NONCE)[0]
            cap = _U64.unpack_from(ctl.buf, _CTL_CAP)[0]
            for k in range(SHM_MAX_PRODUCERS):
                if ctl.buf[_CTL_BITMAP + k]:
                    continue
                try:
                    seg = shared_memory.SharedMemory(
                        _slot_name(self.port, k),
                        create=True,  # O_EXCL: the slot-claim arbiter
                        size=_RING_HDR + cap,
                    )
                except FileExistsError:
                    continue  # lost the race for k; try the next slot
                _untrack(seg)
                seg.buf[:_RING_HDR] = bytes(_RING_HDR)
                _U32.pack_into(seg.buf, _RMAGIC, _RING_MAGIC)
                _U64.pack_into(seg.buf, _RCAP, cap)
                # Bitmap set LAST: the consumer only attaches rings whose
                # header is fully initialized.
                ctl.buf[_CTL_BITMAP + k] = 1
                self._seg = seg
                self._writer = _RingWriter(seg, cap)
                self._nonce = nonce
                self.slot = k
                return
        finally:
            ctl.close()

    def _session_alive(self) -> bool:
        """Fresh-attach the ctl block by NAME (a held mapping would keep
        showing the dead session's inode after a consumer restart)."""
        try:
            ctl = _attach(_ctl_name(self.port))
        except (FileNotFoundError, OSError):
            return False
        try:
            return (
                _U32.unpack_from(ctl.buf, 0)[0] == _SHM_CTL_MAGIC
                and _U64.unpack_from(ctl.buf, _CTL_NONCE)[0] == self._nonce
            )
        finally:
            ctl.close()

    def _detach(self) -> None:
        self._writer = None
        self.slot = None
        if self._seg is not None:
            try:
                self._seg.close()
            except BufferError:
                pass
            self._seg = None

    # --------------------------------------------------------------- send
    def send(
        self, proto: Protocol, payload: Any, trace: bytes | None = None
    ) -> None:
        self.send_raw(encode(proto, payload, trace))

    def send_raw(self, parts: list[bytes]) -> None:
        if self._chaos is not None:
            parts = self._chaos.on_send(parts)
            if parts is None:
                return
        now = time.monotonic()
        if self._writer is not None and now >= self._next_check:
            self._next_check = now + self._CHECK_S
            if not self._session_alive():
                self._detach()  # consumer restarted: rejoin its new session
        if self._writer is None:
            if now >= self._next_try:
                self._next_try = now + self._RETRY_S
                self._rendezvous()
            if self._writer is None:
                self.n_dropped_no_peer += 1
                return
        if not self._writer.write(parts):
            self.n_dropped_full += 1

    def close(self) -> None:
        self._detach()


class ShmConsumer:
    """Consumer endpoint: owns the channel's segments (creates the ctl block
    with a fresh session nonce, unlinks everything at close), fans in every
    claimed producer ring. Raw frames only — validation/decode layers on top
    (:class:`FanInSub`)."""

    def __init__(self, port: int, capacity: int = SHM_RING_BYTES) -> None:
        self.port = port
        self.cap = capacity
        _unlink_stale(port)
        size = _CTL_BITMAP + SHM_MAX_PRODUCERS
        self._ctl = shared_memory.SharedMemory(
            _ctl_name(port), create=True, size=size
        )
        _untrack(self._ctl)
        self._ctl.buf[:size] = bytes(size)
        _U64.pack_into(
            self._ctl.buf, _CTL_NONCE, int.from_bytes(secrets.token_bytes(8), "little")
        )
        _U64.pack_into(self._ctl.buf, _CTL_CAP, capacity)
        # Magic last: producers treat a magicless ctl as "still initializing".
        _U32.pack_into(self._ctl.buf, 0, _SHM_CTL_MAGIC)
        self._readers: dict[int, _RingReader] = {}
        self._segs: dict[int, shared_memory.SharedMemory] = {}

    @property
    def n_resync(self) -> int:
        return sum(r.n_resync for r in self._readers.values())

    def _scan(self) -> None:
        """Attach rings of newly-claimed slots (bitmap poll: one 64-byte
        read per drain)."""
        bm = bytes(
            self._ctl.buf[_CTL_BITMAP : _CTL_BITMAP + SHM_MAX_PRODUCERS]
        )
        for k, claimed in enumerate(bm):
            if not claimed or k in self._readers:
                continue
            try:
                seg = _attach(_slot_name(self.port, k))
            except (FileNotFoundError, OSError):
                continue
            if _U32.unpack_from(seg.buf, _RMAGIC)[0] != _RING_MAGIC:
                seg.close()
                continue
            cap = _U64.unpack_from(seg.buf, _RCAP)[0]
            self._readers[k] = _RingReader(seg, cap)
            self._segs[k] = seg

    def drain_frames(self, max_msgs: int = 1024) -> list[list[bytes]]:
        """All complete records currently readable across producers."""
        self._scan()
        out: list[list[bytes]] = []
        for reader in self._readers.values():
            left = max_msgs - len(out)
            if left <= 0:
                break
            out.extend(reader.read(left))
        return out

    def close(self) -> None:
        for seg in self._segs.values():
            try:
                seg.close()
            except BufferError:
                pass
        self._segs.clear()
        self._readers.clear()
        try:
            self._ctl.close()
        except BufferError:
            pass
        # Unlink everything by name — including slots claimed by producers
        # this consumer never attached.
        _unlink_stale(self.port)


class FanInSub:
    """Sub-compatible fan-in over BOTH fabrics: the shm channel for same-host
    producers plus the TCP SUB for remote ones (a mixed fleet has both; the
    TCP socket also keeps slow-joiner semantics for late remote workers).
    Exposes the exact :class:`Sub` surface the manager/storage loops use.
    Chaos ``on_recv`` applies to shm frames identically to TCP ones, so the
    injected == n_rejected accounting invariant holds under shm."""

    _SLICE_MS = 5  # zmq poll slice while also watching the shm side

    def __init__(self, ip: str, port: int, bind: bool = True,
                 hwm: int = DATA_HWM, ctx: Any = None, chaos: Any = None,
                 capacity: int = SHM_RING_BYTES,
                 native_batch: bool = True) -> None:
        self._zmq = Sub(ip, port, bind=bind, hwm=hwm, ctx=ctx, chaos=chaos,
                        native_batch=native_batch)
        self.shm = ShmConsumer(port, capacity=capacity)
        self._chaos = chaos
        self._native_batch = native_batch
        self._shm_rejected = 0

    @property
    def n_rejected(self) -> int:
        return self._zmq.n_rejected + self._shm_rejected

    def _shm_frames(self, max_msgs: int) -> list[list[bytes]]:
        frames = self.shm.drain_frames(max_msgs)
        if self._chaos is not None and frames:
            kept = []
            for parts in frames:
                parts = self._chaos.on_recv(parts)
                if parts is not None:
                    kept.append(parts)
            frames = kept
        return frames

    # ------------------------------------------------------------- drains
    def drain_raw(
        self, max_msgs: int = 1024
    ) -> Iterator[tuple[Protocol, list[bytes]]]:
        got, rejected = _validate_raw(
            self._shm_frames(max_msgs), self._native_batch
        )
        self._shm_rejected += rejected
        yield from got
        yield from self._zmq.drain_raw(max_msgs)

    def drain_traced(
        self, max_msgs: int = 1024
    ) -> Iterator[tuple[Protocol, Any, bytes | None]]:
        got, rejected = _validate_traced(
            self._shm_frames(max_msgs), self._native_batch
        )
        self._shm_rejected += rejected
        yield from got
        yield from self._zmq.drain_traced(max_msgs)

    def drain(self, max_msgs: int = 1024) -> Iterator[tuple[Protocol, Any]]:
        for proto, payload, _trailer in self.drain_traced(max_msgs):
            yield proto, payload

    # ----------------------------------------------------- timed receives
    def recv_traced(
        self, timeout_ms: int | None = None
    ) -> tuple[Protocol, Any, bytes | None] | None:
        """Shm checked first (it has no poll(); a drain is just memory
        reads), then the TCP socket in short slices until the deadline."""
        deadline = (
            None if timeout_ms is None
            else time.monotonic() + timeout_ms / 1e3
        )
        while True:
            frames = self._shm_frames(1)
            if frames:
                got, rejected = _validate_traced(frames, self._native_batch)
                self._shm_rejected += rejected
                return got[0] if got else None
            got = self._zmq.recv_traced(timeout_ms=self._SLICE_MS)
            if got is not None:
                return got
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def recv_raw(
        self, timeout_ms: int | None = None
    ) -> tuple[Protocol, list[bytes]] | None:
        deadline = (
            None if timeout_ms is None
            else time.monotonic() + timeout_ms / 1e3
        )
        while True:
            frames = self._shm_frames(1)
            if frames:
                got, rejected = _validate_raw(frames, self._native_batch)
                self._shm_rejected += rejected
                return got[0] if got else None
            got = self._zmq.recv_raw(timeout_ms=self._SLICE_MS)
            if got is not None:
                return got
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def recv(
        self, timeout_ms: int | None = None
    ) -> tuple[Protocol, Any] | None:
        got = self.recv_traced(timeout_ms)
        return None if got is None else (got[0], got[1])

    def close(self) -> None:
        self._zmq.close()
        self.shm.close()


# ------------------------------------------------------- transport selection
def is_loopback(ip: str) -> bool:
    """Both-endpoints-on-this-host heuristic for ``transport="auto"``: the
    connect-side addresses we'd dial. Bind-side wildcards count too — the
    consumer always ALSO binds its TCP SUB, so an shm consumer on a
    wildcard bind only adds a fabric, never loses remote peers."""
    return ip in ("127.0.0.1", "localhost", "::1", "*", "0.0.0.0")


def use_shm(cfg: Any, ip: str) -> bool:
    transport = getattr(cfg, "transport", "tcp")
    return transport == "shm" or (transport == "auto" and is_loopback(ip))


def make_data_pub(cfg: Any, ip: str, port: int, bind: bool = False,
                  hwm: int = DATA_HWM, ctx: Any = None,
                  chaos: Any = None) -> "Pub | ShmPub":
    """Producer endpoint for a DATA hop (rollout/stat/telemetry fan-in),
    honoring ``Config.transport``. The model broadcast is NOT a data hop —
    it fans OUT to remote workers and always stays TCP."""
    if use_shm(cfg, ip):
        return ShmPub(port, chaos=chaos)
    return Pub(ip, port, bind=bind, hwm=hwm, ctx=ctx, chaos=chaos)


def make_data_sub(cfg: Any, ip: str, port: int, bind: bool = True,
                  hwm: int = DATA_HWM, ctx: Any = None,
                  chaos: Any = None) -> "Sub | FanInSub":
    """Consumer endpoint for a DATA hop: a :class:`FanInSub` (shm + TCP)
    whenever shm producers may exist, else the plain TCP :class:`Sub`."""
    if getattr(cfg, "transport", "tcp") != "tcp":
        return FanInSub(ip, port, bind=bind, hwm=hwm, ctx=ctx, chaos=chaos)
    return Sub(ip, port, bind=bind, hwm=hwm, ctx=ctx, chaos=chaos)
