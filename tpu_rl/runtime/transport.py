"""ZeroMQ PUB/SUB transport wrappers.

Capability parity with the per-role raw socket setup scattered through the
reference (``/root/reference/agents/worker.py:45-56``,
``agents/manager.py:30-40``, ``agents/learner_storage.py:60-66``,
``agents/learner.py:85-90``), centralized: every channel is a PUB or SUB
endpoint created from one factory, always carrying :mod:`protocol` frames.
PUB/SUB is deliberate — best-effort, lossy, connection-free — because the
algorithms absorb drops (off-policy corrections) and workers must be able to
join/leave freely (SURVEY.md §5.3).

The DCN topology (SURVEY.md §1 "physical process topology"):

- rollout/stat channel: worker PUB -> manager SUB (bind) -> manager PUB ->
  storage SUB (bind). ``Protocol.Telemetry`` snapshots (tpu_rl.obs) ride
  this channel too: worker/manager frames fan in through the relay, and the
  learner process publishes its own snapshots straight onto the storage
  SUB over a loopback PUB — no extra port, no new socket pattern;
- model channel: learner PUB (bind) -> every worker SUB, on ``model_port =
  learner_port + 1`` — the broadcast bypasses managers.

On TPU pods this remains the host-side fabric; chip-to-chip traffic rides ICI
via XLA collectives instead (``tpu_rl.parallel``), which the reference has no
equivalent of.
"""

from __future__ import annotations

from typing import Any, Iterator

import zmq
import zmq.asyncio

from tpu_rl.runtime.protocol import Protocol, decode, encode, peek

# Keep only the newest model broadcast in flight (a worker that lags wants the
# freshest params, not a backlog); rollout channels buffer more.
MODEL_HWM = 4
DATA_HWM = 4096


def _endpoint(ip: str, port: int) -> str:
    return f"tcp://{ip}:{port}"


class Pub:
    """Synchronous PUB endpoint (the learner's model broadcast is sync in the
    reference too, ``agents/learner.py:85-90``)."""

    def __init__(self, ip: str, port: int, bind: bool, hwm: int = DATA_HWM,
                 ctx=None, chaos=None):
        self._ctx = ctx or zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.PUB)
        self.sock.set_hwm(hwm)
        # Optional fault injector (tpu_rl.chaos.TransportChaos). None — the
        # default and the production state — keeps the send path on the
        # exact pre-chaos code: one `is None` check, no allocations (pinned
        # by tests/test_chaos.py tracemalloc).
        self._chaos = chaos
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    def send(
        self, proto: Protocol, payload: Any, trace: bytes | None = None
    ) -> None:
        """``trace`` (a ``protocol.pack_trace`` trailer) rides as the
        optional third wire part on sampled rollout frames; None (the
        default and the sampling-off state) keeps the exact 2-part frame."""
        parts = encode(proto, payload, trace)
        if self._chaos is not None:
            parts = self._chaos.on_send(parts)
            if parts is None:
                return
        self.sock.send_multipart(parts)

    def send_raw(self, parts: list[bytes]) -> None:
        """Forward already-encoded wire parts verbatim — the zero-copy relay
        hop (no pack/compress/CRC; zmq ships the same buffers it received).
        A trace trailer, being just a third part, is forwarded for free."""
        if self._chaos is not None:
            parts = self._chaos.on_send(parts)
            if parts is None:
                return
        self.sock.send_multipart(parts)

    def close(self) -> None:
        self.sock.close(linger=0)


class Sub:
    """Synchronous SUB endpoint subscribed to everything.

    Malformed/foreign frames (``decode`` raising ValueError) are dropped and
    counted, never raised — one stray publisher on a best-effort PUB/SUB
    fabric must not crash a role process."""

    def __init__(self, ip: str, port: int, bind: bool, hwm: int = DATA_HWM,
                 ctx=None, chaos=None):
        self._ctx = ctx or zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.SUB)
        self.sock.set_hwm(hwm)
        self.sock.setsockopt(zmq.SUBSCRIBE, b"")
        self.n_rejected = 0
        # Optional fault injector applied to received parts BEFORE decode:
        # an injected corruption therefore pairs with its n_rejected bump in
        # the same call, which is what makes chaos accounting exact. None
        # (default) costs one `is None` check per frame.
        self._chaos = chaos
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    def recv(self, timeout_ms: int | None = None) -> tuple[Protocol, Any] | None:
        """Blocking (or timed) receive of one decoded message; None on
        timeout or on a rejected frame."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        parts = self.sock.recv_multipart()
        if self._chaos is not None:
            parts = self._chaos.on_recv(parts)
            if parts is None:
                return None
        try:
            return decode(parts)
        except ValueError:
            self.n_rejected += 1
            return None

    def drain(self, max_msgs: int = 1024) -> Iterator[tuple[Protocol, Any]]:
        """Yield every decodable message currently queued, newest-bounded."""
        for _ in range(max_msgs):
            try:
                parts = self.sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                return
            if self._chaos is not None:
                parts = self._chaos.on_recv(parts)
                if parts is None:
                    continue
            try:
                yield decode(parts)
            except ValueError:
                self.n_rejected += 1

    def recv_traced(
        self, timeout_ms: int | None = None
    ) -> tuple[Protocol, Any, bytes | None] | None:
        """:meth:`recv` plus the raw trace trailer when the frame carried one
        (already validated by ``decode``; parse with ``protocol.unpack_trace``
        at the consumer). The 2-part common case yields ``trailer=None`` with
        no extra work beyond one length check."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        parts = self.sock.recv_multipart()
        if self._chaos is not None:
            parts = self._chaos.on_recv(parts)
            if parts is None:
                return None
        try:
            proto, payload = decode(parts)
        except ValueError:
            self.n_rejected += 1
            return None
        return proto, payload, parts[2] if len(parts) == 3 else None

    def drain_traced(
        self, max_msgs: int = 1024
    ) -> Iterator[tuple[Protocol, Any, bytes | None]]:
        """Yield every decodable queued message with its trace trailer (or
        None) — the lineage-aware counterpart of :meth:`drain`."""
        for _ in range(max_msgs):
            try:
                parts = self.sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                return
            if self._chaos is not None:
                parts = self._chaos.on_recv(parts)
                if parts is None:
                    continue
            try:
                proto, payload = decode(parts)
            except ValueError:
                self.n_rejected += 1
                continue
            yield proto, payload, parts[2] if len(parts) == 3 else None

    def recv_raw(
        self, timeout_ms: int | None = None
    ) -> tuple[Protocol, list[bytes]] | None:
        """Blocking (or timed) receive of one frame as opaque wire parts,
        validated by :func:`protocol.peek` only (proto byte, header, size
        caps — no CRC/decompress/unpack). None on timeout or on a rejected
        frame (counted in ``n_rejected``, same contract as :meth:`recv`)."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        parts = self.sock.recv_multipart()
        if self._chaos is not None:
            parts = self._chaos.on_recv(parts)
            if parts is None:
                return None
        try:
            return peek(parts), parts
        except ValueError:
            self.n_rejected += 1
            return None

    def drain_raw(
        self, max_msgs: int = 1024
    ) -> Iterator[tuple[Protocol, list[bytes]]]:
        """Yield every queued frame as peek-validated opaque wire parts,
        newest-bounded (the raw-relay counterpart of :meth:`drain`)."""
        for _ in range(max_msgs):
            try:
                parts = self.sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                return
            if self._chaos is not None:
                parts = self._chaos.on_recv(parts)
                if parts is None:
                    continue
            try:
                yield peek(parts), parts
            except ValueError:
                self.n_rejected += 1

    def close(self) -> None:
        self.sock.close(linger=0)


class Router:
    """ROUTER endpoint for the centralized inference service (new capability,
    no reference equivalent — the SEED RL request/reply pattern).

    Unlike PUB/SUB, ROUTER/DEALER is connection-addressed: every frame a
    DEALER sends arrives prefixed with that peer's identity, and a reply sent
    to the same identity routes back to exactly that peer. Malformed frames
    are dropped and counted (``n_rejected``), same contract as :class:`Sub` —
    one corrupt client must not crash the inference server."""

    def __init__(self, ip: str, port: int, bind: bool = True,
                 hwm: int = DATA_HWM, ctx=None):
        self._ctx = ctx or zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.ROUTER)
        self.sock.set_hwm(hwm)
        self.n_rejected = 0
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    def recv(self, timeout_ms: int | None = None
             ) -> tuple[bytes, Protocol, Any] | None:
        """One ``(identity, proto, payload)`` request; None on timeout or on
        a rejected frame."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        parts = self.sock.recv_multipart()
        return self._split(parts)

    def drain(self, max_msgs: int = 1024
              ) -> Iterator[tuple[bytes, Protocol, Any]]:
        """Yield every decodable queued request, newest-bounded."""
        for _ in range(max_msgs):
            try:
                parts = self.sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                return
            got = self._split(parts)
            if got is not None:
                yield got

    def _split(self, parts: list[bytes]
               ) -> tuple[bytes, Protocol, Any] | None:
        # ROUTER prepends the peer identity to whatever the DEALER sent.
        try:
            if len(parts) < 2:
                raise ValueError(f"short ROUTER frame: {len(parts)} parts")
            proto, payload = decode(parts[1:])
            return parts[0], proto, payload
        except ValueError:
            self.n_rejected += 1
            return None

    def send(self, identity: bytes, proto: Protocol, payload: Any) -> None:
        """Route one reply back to ``identity``. A vanished peer is a normal
        fleet event (worker died between request and reply): with
        ROUTER_MANDATORY unset zmq silently drops the frame, which is the
        behavior we want on a best-effort fabric."""
        self.sock.send_multipart([identity, *encode(proto, payload)])

    def close(self) -> None:
        self.sock.close(linger=0)


class Dealer:
    """DEALER endpoint: the worker side of the inference channel. One
    in-flight request per tick (send -> timed recv), so no correlation
    machinery beyond the payload's own ``seq`` echo is needed."""

    def __init__(self, ip: str, port: int, bind: bool = False,
                 hwm: int = DATA_HWM, identity: bytes | None = None, ctx=None):
        self._ctx = ctx or zmq.Context.instance()
        self.sock = self._ctx.socket(zmq.DEALER)
        self.sock.set_hwm(hwm)
        if identity is not None:
            self.sock.setsockopt(zmq.IDENTITY, identity)
        self.n_rejected = 0
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    def send(self, proto: Protocol, payload: Any) -> None:
        self.sock.send_multipart(encode(proto, payload))

    def recv(self, timeout_ms: int | None = None) -> tuple[Protocol, Any] | None:
        """Timed receive of one decoded reply; None on timeout or on a
        rejected frame."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        try:
            return decode(self.sock.recv_multipart())
        except ValueError:
            self.n_rejected += 1
            return None

    def close(self) -> None:
        self.sock.close(linger=0)


class AsyncSub:
    """asyncio SUB endpoint (storage/manager event loops, reference
    ``zmq.asyncio`` usage)."""

    def __init__(self, ip: str, port: int, bind: bool, hwm: int = DATA_HWM, ctx=None):
        self._ctx = ctx or zmq.asyncio.Context.instance()
        self.sock = self._ctx.socket(zmq.SUB)
        self.sock.set_hwm(hwm)
        self.sock.setsockopt(zmq.SUBSCRIBE, b"")
        self.n_rejected = 0
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    async def recv(self) -> tuple[Protocol, Any]:
        """Receive the next decodable message (rejected frames are dropped)."""
        while True:
            try:
                return decode(await self.sock.recv_multipart())
            except ValueError:
                self.n_rejected += 1

    def close(self) -> None:
        self.sock.close(linger=0)


class AsyncPub:
    def __init__(self, ip: str, port: int, bind: bool, hwm: int = DATA_HWM, ctx=None):
        self._ctx = ctx or zmq.asyncio.Context.instance()
        self.sock = self._ctx.socket(zmq.PUB)
        self.sock.set_hwm(hwm)
        ep = _endpoint(ip, port)
        self.sock.bind(ep) if bind else self.sock.connect(ep)

    async def send(self, proto: Protocol, payload: Any) -> None:
        await self.sock.send_multipart(encode(proto, payload))

    def close(self) -> None:
        self.sock.close(linger=0)
