"""Distributed runtime: transport, protocol, and the role processes
(worker / manager / storage / learner) — SURVEY.md §1 layers L2 and L6."""
