"""Colocated (Anakin-mode) driver: envs on-device, one fused program.

Podracer/Anakin (PAPERS.md, arxiv 2104.06272) colocates environments with
the learner on the same accelerator: ``act -> env.step -> train`` compiles
into ONE jitted program, so a training iteration is a single XLA dispatch
with zero host<->device traffic and none of the distributed plane's
worker/relay/storage machinery. This module is that mode for jittable envs
(``tpu_rl/envs``); the distributed path stays the default for real
(host-side) simulators.

The fused program per iteration:

1. ``lax.scan`` over ``cfg.seq_len`` acting ticks. Each tick reproduces the
   distributed worker's tick semantics EXACTLY (runtime/worker.py): store the
   pre-step obs / pre-step carry / pre-tick ``is_fir``, act, step the env,
   scale the reward, zero the carry on done (``where``, never multiply — NaN
   safety), raise ``is_fir`` for the post-reset step. Auto-reset and the
   ``time_horizon`` truncation live in ``envs.core.make_vec_env``.
2. Transpose the scan's ``(S, B, w)`` stack to the learner's ``(B, S, w)``
   :class:`~tpu_rl.types.Batch`. Because every env contributes exactly one
   full window per scan with no cross-env interleaving, this IS what
   ``data.assembler.RolloutAssembler`` would emit for the same transition
   stream (``tests/test_colocated.py`` pins it bit-for-bit).
3. Run the pure ``train_step(state, batch, key)`` from the algo registry —
   the same function the distributed learner compiles — on the batch while
   it is still on device.

The env batch is the train batch (``batch_size`` envs, overridable via
``Config.colocated_envs``), sharded over the data mesh like any learner
batch; parameters are replicated and GSPMD inserts the gradient all-reduce.
Episode bookkeeping (completed-episode count / return sum) is accumulated
*on device* in a replicated ``stats`` tree so the steady-state loop does
zero per-iteration host transfers; the host only fetches at log intervals.
"""

from __future__ import annotations

import os
import time
from typing import Any

import jax
import jax.numpy as jnp

from tpu_rl.config import Config
from tpu_rl.data.layout import BatchLayout
from tpu_rl.envs import get_spec, make_vec_env
from tpu_rl.parallel.mesh import (
    batch_sharding,
    check_divisible,
    make_mesh,
    replicated,
)
from tpu_rl.types import BATCH_FIELDS, Batch
from tpu_rl.utils.timer import ExecutionTimer


def act_params(state) -> dict:
    """Acting parameter tree for either train-state flavor (colocated mode is
    on-policy-only, but keep the SAC shape for completeness)."""
    if hasattr(state, "actor_params"):
        return {"actor": state.actor_params}
    return {"actor": state.params["actor"]}


def resolve_colocated_config(cfg: Config) -> Config:
    """Apply the colocated-mode config overrides: ``colocated_envs`` replaces
    ``batch_size`` (the env batch IS the train batch), and the obs/action
    spaces are derived from the jittable env spec (no gymnasium)."""
    if cfg.colocated_envs:
        cfg = cfg.replace(
            batch_size=cfg.colocated_envs,
            buffer_size=max(cfg.buffer_size, cfg.colocated_envs),
        )
    spec = get_spec(cfg.env)
    return cfg.replace(
        obs_shape=spec.obs_shape,
        action_space=spec.action_space,
        is_continuous=spec.is_continuous,
    )


class ColocatedLoop:
    """Owns the fused act->step->train program and its device-resident state.

    Two compiled entry points:

    - :attr:`rollout` — ``(params, carry, key) -> (carry, batch, done, ret)``:
      the acting scan alone. Used by tests (assembler equivalence) and the
      bench's pure-rollout row.
    - :attr:`program` — ``(state, carry, stats, k_roll, k_train) ->
      (state, carry, stats, metrics)``: rollout + train fused. ``state``,
      ``carry`` and ``stats`` are donated; the steady-state loop re-dispatches
      on the device-resident outputs without any host hop.
    """

    def __init__(
        self,
        cfg: Config,
        seed: int = 0,
        max_updates: int | None = None,
        stop_event=None,
        heartbeat=None,
    ):
        cfg = resolve_colocated_config(cfg)
        assert cfg.env_mode == "colocated", cfg.env_mode
        self.cfg = cfg
        self.seed = int(seed)
        self.max_updates = max_updates
        self._stop = stop_event
        self._heartbeat = heartbeat

        # Pod-Anakin: join the jax.distributed runtime BEFORE any device
        # query, exactly like the learner role (learner_service.run). After
        # init the meshes below span every host's chips and GSPMD inserts
        # the cross-host gradient all-reduce into the unchanged fused
        # program. Single-host configs (multihost=None or num_processes=1)
        # skip this entirely.
        if cfg.multihost:
            from tpu_rl.parallel.multihost import init_multihost

            init_multihost(**cfg.multihost)
        self._chief = jax.process_index() == 0
        self._build_meshes()
        self.spec = get_spec(cfg.env)
        self._v_reset, self._v_step = make_vec_env(
            self.spec, cfg.batch_size, cfg.time_horizon
        )
        key = jax.random.PRNGKey(self.seed)
        k_build, self._k_base = jax.random.split(key)
        from tpu_rl.algos.registry import get_algo

        self.family, self.state, self._train_step = get_algo(cfg.algo).build(
            cfg, k_build, self.mesh
        )
        self.layout = BatchLayout.from_config(cfg)

        # Durability (PR 9 semantics, extended to the fused loop for the
        # population plane): two-phase commits every model_save_interval,
        # newest-committed resume with fingerprint refusal, run-epoch chain
        # in the marker meta. The per-iteration PRNG is fold_in(base, it) —
        # stateless in it — so resuming needs only the update index: the
        # continued run replays the exact key stream the unbroken run would
        # have used.
        self.ckpt = None
        self.run_epoch = 0
        self._start_it = 0
        self._last_saved = -1
        self._fingerprint = None
        if cfg.model_dir:
            from tpu_rl.checkpoint import Checkpointer, resume_fingerprint

            self.ckpt = Checkpointer(
                cfg.model_dir,
                cfg.algo,
                keep=cfg.ckpt_keep,
                async_save=cfg.ckpt_async,
            )
            self._fingerprint = resume_fingerprint(cfg)

        self._compile()

        # Telemetry plane (same knobs/ports as every other role; satellite of
        # the obs registry — nothing is constructed when the plane is off).
        self.aggregator = None
        self._http = None
        self._json_exp = None
        self._perf = None
        self._prof = None
        self._slo = None
        # Run-history store (tpu_rl.obs.history): the colocated deployment
        # is its own storage side, so it self-serves the plane — fed on the
        # exporter cadence, served live at /query. None = plane off.
        self._history = None
        # Goodput ledger for the fused loop (tpu_rl.obs.goodput). The whole
        # deployment is one process, so one ledger covers it: dispatch +
        # blocking device_get land in compute, checkpoint saves in ckpt,
        # everything else (telemetry, logging) spills into overhead.
        self.ledger = None
        self._setup_telemetry()

    # ---------------------------------------------------------- topology hooks
    def _build_meshes(self) -> None:
        """Device-topology hook: the Anakin loop is ONE mesh for acting and
        training alike (the sebulba subclass splits them). Under multihost
        the default ``mesh_data=1`` widens to the full global device set —
        a pod run saying nothing about mesh width means "use the pod"."""
        cfg = self.cfg
        if cfg.multihost and jax.process_count() > 1 and cfg.mesh_data == 1:
            self.mesh = make_mesh(jax.device_count())
        else:
            self.mesh = make_mesh(cfg.mesh_data)
        self.act_mesh = self.mesh
        check_divisible(cfg.batch_size, self.mesh)

    def _compile(self) -> None:
        """Compile hook: build the jitted entry points for this topology."""
        rs, bs = replicated(self.mesh), batch_sharding(self.mesh)
        self._rs, self._bs = rs, bs
        # Acting-side shardings: identical to the train mesh here; the
        # sebulba split points them at the actor device group instead.
        self._act_rs, self._act_bs = rs, bs
        # Every rollout output is batch-leading, so one sharding prefix
        # covers carry, batch, done and ret alike.
        self.rollout = jax.jit(
            self._rollout_body,
            in_shardings=(rs, bs, rs),
            out_shardings=bs,
            donate_argnums=(1,),
        )
        self.program = jax.jit(
            self._program_body,
            in_shardings=(rs, bs, rs, rs, rs),
            out_shardings=(rs, bs, rs, rs),
            donate_argnums=(0, 1, 2),
        )

    def _place(self, tree, sharding):
        """Put host-built (or locally-committed) arrays under a global
        sharding. Single-process meshes take the direct ``device_put``;
        multi-process meshes route through an SPMD identity jit (same trick
        as ``parallel.dp.replicate`` — ``device_put`` refuses shardings that
        span non-addressable devices). Valid because every host builds the
        identical value (same seed/key stream)."""
        local = jax.process_index()
        if all(d.process_index == local for d in sharding.mesh.devices.flat):
            return jax.device_put(tree, sharding)
        return jax.jit(lambda t: t, out_shardings=sharding)(tree)

    # ------------------------------------------------------------ device init
    def init_carry(self, key: jax.Array) -> dict:
        """Fresh device carry: reset envs, zero recurrent state, ``is_fir=1``
        (every env starts an episode), zero running returns."""
        env, obs = self._v_reset(key)
        n = self.cfg.batch_size
        hw, cw = self.family.carry_widths
        carry = {
            "env": env,
            # copy: for state==obs envs (CartPole) reset returns ONE array for
            # both leaves, and the donated program rejects aliased buffers.
            "obs": jnp.array(obs, copy=True),
            "h": jnp.zeros((n, hw), jnp.float32),
            "c": jnp.zeros((n, cw), jnp.float32),
            "is_fir": jnp.ones((n,), jnp.float32),
            "ret": jnp.zeros((n,), jnp.float32),
        }
        return self._place(carry, self._act_bs)

    def init_stats(self) -> dict:
        return self._place(
            {
                "episodes": jnp.zeros((), jnp.int32),
                "ret_sum": jnp.zeros((), jnp.float32),
            },
            self._act_rs,
        )

    # -------------------------------------------------------------- jit bodies
    def _tick(self, params, cr: dict, k: jax.Array):
        """One acting tick — the worker loop's body as pure jax."""
        cfg, family = self.cfg, self.family
        k_act, k_env = jax.random.split(k)
        a, logits, log_prob, h2, c2 = family.act(
            params, cr["obs"], cr["h"], cr["c"], k_act
        )
        env, obs2, rew, done = self._v_step(cr["env"], a, k_env)
        ret2 = cr["ret"] + rew
        if family.store_carry:
            hx, cx = cr["h"], cr["c"]
        else:
            n = cfg.batch_size
            hx = jnp.zeros((n, self.layout.width("hx")), jnp.float32)
            cx = jnp.zeros((n, self.layout.width("cx")), jnp.float32)
        ys = dict(
            obs=cr["obs"],
            act=a,
            rew=(rew * cfg.reward_scale)[:, None].astype(jnp.float32),
            logits=logits,
            log_prob=log_prob,
            is_fir=cr["is_fir"][:, None],
            hx=hx,
            cx=cx,
            done=done,
            # Completed-episode RAW return, emitted on the terminal tick.
            ep_ret=jnp.where(done, ret2, 0.0),
        )
        keep = (~done)[:, None]
        cr2 = {
            "env": env,
            "obs": obs2,
            # where(), not multiply: a NaN carry from a diverged net must not
            # survive the reset (same guard as the worker).
            "h": jnp.where(keep, h2, 0.0),
            "c": jnp.where(keep, c2, 0.0),
            "is_fir": done.astype(jnp.float32),
            "ret": jnp.where(done, 0.0, ret2),
        }
        return cr2, ys

    def _rollout_body(self, params, carry: dict, key: jax.Array):
        keys = jax.random.split(key, self.cfg.seq_len)
        carry, ys = jax.lax.scan(
            lambda cr, k: self._tick(params, cr, k), carry, keys
        )
        swap = lambda x: jnp.swapaxes(x, 0, 1)  # noqa: E731 — (S,B,w)->(B,S,w)
        batch = Batch(**{f: swap(ys[f]) for f in BATCH_FIELDS})
        return carry, batch, swap(ys["done"]), swap(ys["ep_ret"])

    def _program_body(self, state, carry, stats, k_roll, k_train):
        # Register the mesh only while this body traces, so LSTM unrolls emit
        # the fused Pallas kernel as a shard_map island over the data axis
        # (same dance as parallel.dp.make_parallel_train_step).
        from tpu_rl.models import cells

        prev = cells._DATA_MESH
        cells.set_data_mesh(self.mesh)
        try:
            carry, batch, done, ep_ret = self._rollout_body(
                act_params(state), carry, k_roll
            )
            state, metrics = self._train_step(state, batch, k_train)
        finally:
            cells.set_data_mesh(prev)
        stats = {
            "episodes": stats["episodes"] + done.sum(dtype=jnp.int32),
            "ret_sum": stats["ret_sum"] + ep_ret.sum(),
        }
        return state, carry, stats, metrics

    # ---------------------------------------------------------------- telemetry
    def _setup_telemetry(self) -> None:
        cfg = self.cfg
        if not cfg.telemetry_enabled:
            return
        from tpu_rl.obs import (
            GoodputLedger,
            JsonExporter,
            MetricsRegistry,
            PerfTracker,
            ProfilerCapture,
            TelemetryAggregator,
            TelemetryHTTPServer,
            maybe_history,
            maybe_slo_engine,
        )

        self.aggregator = TelemetryAggregator(
            registry=MetricsRegistry(role="colocated"),
            stale_after_s=cfg.telemetry_stale_s,
        )
        self.ledger = GoodputLedger("colocated")
        self._perf = PerfTracker()
        self._slo = maybe_slo_engine(cfg)
        self._history = maybe_history(cfg)
        if cfg.result_dir is not None:
            self._prof = ProfilerCapture(os.path.join(cfg.result_dir, "prof"))
        if cfg.telemetry_port > 0:
            self._http = TelemetryHTTPServer(
                self.aggregator,
                cfg.telemetry_port,
                slo=self._slo.report if self._slo is not None else None,
                prof=(
                    self._prof.capture_async if self._prof is not None else None
                ),
                goodput=self._goodput_payload,
                query=(
                    self._history.http_query
                    if self._history is not None else None
                ),
            )
        if cfg.result_dir is not None:
            self._json_exp = JsonExporter(
                self.aggregator,
                os.path.join(cfg.result_dir, "telemetry.json"),
                interval_s=cfg.telemetry_interval_s,
            )

    def _telemetry_tick(
        self,
        updates: int,
        env_steps: int,
        episodes: int,
        ups: float,
        tps: float,
        chunk_s: float,
        mean_ret: float,
    ) -> None:
        if self.aggregator is None:
            return
        reg = self.aggregator.registry
        reg.counter("colocated-updates").set_total(updates)
        reg.counter("colocated-env-steps").set_total(env_steps)
        reg.counter("colocated-episodes").set_total(episodes)
        reg.gauge("colocated-updates-per-s").set(ups)
        reg.gauge("colocated-env-steps-per-s").set(tps)
        reg.gauge("colocated-mean-episode-return").set(mean_ret)
        reg.histogram("colocated-scan-chunk-s").observe(chunk_s)
        if self._perf is not None:
            # chunk_s is the per-iteration mean measured against a blocking
            # device_get — exactly the dispatch interval the tracker wants.
            self._perf.note(chunk_s)
            reg.gauge("colocated-flops-per-step").set(
                self._perf.flops_per_call
            )
            achieved = self._perf.achieved_flops_per_s()
            if achieved is not None:
                reg.gauge("colocated-achieved-flops").set(achieved)
            mfu = self._perf.mfu()
            if mfu is not None:
                reg.gauge("colocated-mfu").set(mfu)
            reg.counter("colocated-xla-recompiles").set_total(
                self._perf.recompiles
            )
            from tpu_rl.obs.perf import device_memory_bytes, process_self_stats

            in_use, peak = device_memory_bytes()
            reg.gauge("colocated-device-mem-bytes").set(in_use)
            reg.gauge("colocated-device-mem-peak-bytes").set(peak)
            rss, n_fds = process_self_stats()
            reg.gauge("colocated-rss-bytes").set(rss)
            reg.gauge("colocated-open-fds").set(float(n_fds))
        for led in self._ledgers():
            led.publish(reg)
        if self._slo is not None:
            self._slo.evaluate(self.aggregator)
        if self._json_exp is not None and self._json_exp.maybe_export():
            if self._history is not None:
                # Same cadence decision the JSON exporter just made: one
                # flattened history row per export.
                self._history.record(self.aggregator)
            if self.ledger is not None:
                # Ledger audit trail on the exporter's cadence — the offline
                # twin of GET /goodput, same file name as storage writes.
                from tpu_rl.obs.audit import append_jsonl

                append_jsonl(
                    self.cfg.result_dir, "goodput.jsonl",
                    self._goodput_payload(),
                )

    def _ledgers(self) -> list:
        """Every goodput ledger this loop owns (one per lane thread; the
        fused Anakin loop is one lane, the sebulba split is two)."""
        return [self.ledger] if self.ledger is not None else []

    def _goodput_payload(self) -> dict:
        """The GET /goodput document for the single-process deployment: just
        this loop's ledger snapshot (no fleet, so no stragglers)."""
        return {
            "colocated": (
                self.ledger.snapshot() if self.ledger is not None else None
            ),
            "roles": {},
            "stragglers": [],
        }

    def _record_resume(self, idx: int) -> None:
        """Append one resume record to result_dir/learner_resume.jsonl —
        the same audit file (and shape) the distributed learner writes
        (pinned by test), so resume-smoke-style assertions work against
        either mode."""
        from tpu_rl.obs.audit import append_resume

        append_resume(self.cfg.result_dir, idx, self.run_epoch)

    def close(self) -> None:
        if self.ckpt is not None:
            self.ckpt.close()
            self.ckpt = None
        if self._http is not None:
            self._http.close()
        if self._prof is not None:
            self._prof.close()
        if self._slo is not None and self.cfg.result_dir is not None:
            import json

            with open(
                os.path.join(self.cfg.result_dir, "slo.json"), "w"
            ) as f:
                json.dump(self._slo.report(), f, indent=2)
        if self._json_exp is not None:
            # Force a final write regardless of the exporter's cadence.
            self._json_exp.maybe_export(now=float("inf"))
        if self._history is not None:
            # Final history row + release the active chunk handle.
            self._history.record(self.aggregator)
            self._history.close()
            self._history = None

    @property
    def slo_failed(self) -> bool:
        """The ``Config.slo_fail_run`` exit gate for the colocated role."""
        return self._slo is not None and self._slo.failed

    # ---------------------------------------------------------------- run loop
    def _stopping(self) -> bool:
        return self._stop is not None and self._stop.is_set()

    def run(self, log: bool = True) -> dict:
        """Drive the fused program to ``max_updates`` (or until the stop
        event). Returns a summary dict with run totals and timer scalars."""
        cfg = self.cfg
        # Non-chief pod processes run the identical SPMD program but leave
        # stdout and checkpoint writes to process 0 (the restore below runs
        # everywhere — model_dir is shared storage on a pod).
        log = log and self._chief
        n, s = cfg.batch_size, cfg.seq_len
        timer = ExecutionTimer(num_transition=n * s)
        from tpu_rl.utils.metrics import make_writer

        writer = make_writer(cfg.result_dir)
        k_carry = jax.random.fold_in(self._k_base, 0xC0C0)
        from tpu_rl.parallel.dp import replicate

        state = self.state
        if self.ckpt is not None:
            restored = self.ckpt.restore_run(
                jax.device_get(state),
                fingerprint=self._fingerprint,
                force=cfg.resume_force,
            )
            if restored is not None:
                state, self._start_it, meta = restored
                self.run_epoch = int(meta.get("epoch", 0)) + 1
                self._record_resume(self._start_it)
                if log:
                    print(
                        f"[colocated] resumed from committed checkpoint "
                        f"idx {self._start_it} (run epoch {self.run_epoch})",
                        flush=True,
                    )
        state = replicate(state, self.mesh)
        carry = self.init_carry(k_carry)
        stats = self.init_stats()
        ledger = self.ledger
        if ledger is not None:
            from tpu_rl.obs.goodput import CKPT, COMPUTE
        metrics: Any = {}
        # Learning-dynamics plane: fold each iteration's in-jit ``diag`` into
        # the on-device accumulator (one tiny extra dispatch, zero syncs) and
        # drain on the log cadence below. Colocated rollouts are consumed the
        # same iteration they are produced, so every row is staleness-0.
        diag_acc = None
        if cfg.learn_diag:
            from tpu_rl.obs.learn import (
                DiagAccumulator,
                learn_record as _learn_record,
                publish as _publish_diag,
            )

            diag_acc = DiagAccumulator()
        stale0 = None
        log_every = max(1, cfg.loss_log_interval)
        it = self._start_it
        last_it, last_ep, last_ret = 0, 0, 0.0
        mean_ret, best_ret = 0.0, float("-inf")
        t_mark = time.perf_counter()
        t0 = t_mark
        while not self._stopping() and (
            self.max_updates is None or it < self.max_updates
        ):
            k_roll, k_train = jax.random.split(
                jax.random.fold_in(self._k_base, it)
            )
            if self._perf is not None:
                # One-time AOT cost analysis (identity no-op afterwards) —
                # must run before dispatch, while donated buffers are alive.
                self._perf.capture(
                    self.program, state, carry, stats, k_roll, k_train
                )
            t_disp = time.perf_counter()
            state, carry, stats, metrics = self.program(
                state, carry, stats, k_roll, k_train
            )
            if diag_acc is not None and isinstance(metrics, dict):
                diag = metrics.pop("diag", None)
                if diag is not None:
                    if stale0 is None:
                        n_rows = (
                            next(iter(diag["rows"].values())).shape[0]
                            if diag["rows"] else 0
                        )
                        stale0 = jnp.zeros((n_rows,), jnp.float32)
                    diag_acc.add(diag, stale0)
            if ledger is not None:
                ledger.add(COMPUTE, time.perf_counter() - t_disp)
            it += 1
            if self._heartbeat is not None:
                self._heartbeat.value = time.time()
            if (
                self.ckpt is not None
                and self._chief
                and it % cfg.model_save_interval == 0
            ):
                # `state` is the program's fresh output buffers (donation
                # consumes the inputs), so the save path may snapshot it.
                t_ck = time.perf_counter()
                self.ckpt.save(
                    state,
                    it,
                    meta={
                        "epoch": self.run_epoch,
                        "fingerprint": self._fingerprint,
                    },
                )
                if ledger is not None:
                    ledger.add(CKPT, time.perf_counter() - t_ck)
                self._last_saved = it
            if it % log_every and it != self.max_updates:
                continue
            # device_get blocks on iteration `it`, so the wall-clock delta
            # below covers real device work (dispatch is async in between) —
            # the block lands in the ledger's compute bucket for the same
            # reason.
            t_get = time.perf_counter()
            host_stats = jax.device_get(stats)
            host_metrics = {
                k: float(v) for k, v in jax.device_get(metrics).items()
            }
            if ledger is not None:
                ledger.add(COMPUTE, time.perf_counter() - t_get)
            now = time.perf_counter()
            iters = it - last_it
            chunk_s = (now - t_mark) / max(1, iters)
            timer.record("colocated-iteration", chunk_s, check_throughput=True)
            ups = iters / max(now - t_mark, 1e-9)
            tps = ups * n * s
            episodes = int(host_stats["episodes"])
            ret_sum = float(host_stats["ret_sum"])
            if episodes > last_ep:
                mean_ret = (ret_sum - last_ret) / (episodes - last_ep)
                best_ret = max(best_ret, mean_ret)
            self._telemetry_tick(
                it, it * n * s, episodes, ups, tps, chunk_s, mean_ret
            )
            if diag_acc is not None:
                diag_doc = diag_acc.drain(it)
                if diag_doc is not None:
                    if self.aggregator is not None:
                        _publish_diag(self.aggregator.registry, diag_doc)
                    if cfg.result_dir is not None:
                        from tpu_rl.obs.audit import append_jsonl

                        append_jsonl(
                            cfg.result_dir, "learn.jsonl",
                            _learn_record(it, diag_doc),
                        )
            for name, val in host_metrics.items():
                writer.add_scalar(f"loss/{name}", val, it)
            writer.add_scalar("colocated/env_steps_per_s", tps, it)
            writer.add_scalar("colocated/mean_episode_return", mean_ret, it)
            if log:
                print(
                    f"[colocated] update {it}  tps {tps:,.0f}  "
                    f"episodes {episodes}  mean_return {mean_ret:.1f}  "
                    + "  ".join(
                        f"{k} {v:.4f}" for k, v in host_metrics.items()
                    ),
                    flush=True,
                )
            last_it, last_ep, last_ret = it, episodes, ret_sum
            t_mark = time.perf_counter()
        host_stats = jax.device_get(stats)
        elapsed = time.perf_counter() - t0
        if (
            self.ckpt is not None
            and self._chief
            and it > self._start_it
            and it != self._last_saved
        ):
            # Final commit so a member finishing its budget (or stopped by
            # the controller for an exploit) leaves its newest state
            # durable — PBT winners are copied from disk, not from RAM.
            if ledger is not None:
                t_ck = time.perf_counter()
            self.ckpt.save(
                state,
                it,
                meta={
                    "epoch": self.run_epoch,
                    "fingerprint": self._fingerprint,
                },
            )
            if ledger is not None:
                ledger.add(CKPT, time.perf_counter() - t_ck)
        writer.flush()
        writer.close()
        self.close()
        # Expose the final device state: the donated input handles are dead,
        # and tests/parity probes read params from here after run().
        self.state = state
        episodes = int(host_stats["episodes"])
        ret_sum = float(host_stats["ret_sum"])
        new_it = it - self._start_it
        return {
            "updates": it,
            "env_steps": it * n * s,
            "episodes": episodes,
            "mean_return_overall": ret_sum / max(1, episodes),
            "mean_return_recent": mean_ret,
            # Max over per-log-window completed-episode means: the stable
            # "did it learn" signal (on-policy curves oscillate after peak).
            "mean_return_best_window": best_ret,
            "elapsed_s": elapsed,
            "transitions_per_s": new_it * n * s / max(elapsed, 1e-9),
            "scalars": timer.scalars(),
        }


def colocated_main(
    cfg: Config, stop_event, heartbeat, max_updates: int | None = None,
    seed: int = 0,
) -> None:
    """Supervised child entry: the whole colocated deployment is this one
    process (supervisor spawns it via ``runner.colocated_role``)."""
    loop = ColocatedLoop(
        cfg,
        seed=seed,
        max_updates=max_updates,
        stop_event=stop_event,
        heartbeat=heartbeat,
    )
    out = loop.run()
    print(
        f"[colocated] done: {out['updates']} updates, "
        f"{out['env_steps']:,} env steps, {out['episodes']} episodes, "
        f"mean return {out['mean_return_overall']:.1f}, "
        f"{out['transitions_per_s']:,.0f} transitions/s",
        flush=True,
    )
    if cfg.slo_fail_run and loop.slo_failed:
        print("[colocated] SLO verdict failing; exiting nonzero", flush=True)
        raise SystemExit(3)
