"""Actor (worker) process: step the env with the latest broadcast policy and
stream per-step transitions to the manager relay.

Capability parity with the reference worker
(``/root/reference/agents/worker.py:14-142``): rollout publish (the
reference sends one dict per env step, ``worker.py:110-125``; here one
framed ``RolloutBatch`` per tick carries all ``worker_num_envs``
transitions — same data, 1/N the frames), per-episode stat publish, hot
weight reload from the learner broadcast, ``time_horizon`` episode cap,
reward scaling, step throttle, heartbeat.
Re-designed: a single synchronous loop that drains the model SUB between env
steps (the reference runs two asyncio tasks for the same effect); inference is
a jitted pure function over explicit ``(params, obs, h, c, key)`` so a weight
swap is one pointer assignment, never a mid-step mutation
(the reference hot-swaps ``load_state_dict`` mid-episode).

Workers are CPU processes by design — the learner owns the TPU; the runner
forces ``JAX_PLATFORMS=cpu`` into worker/manager/storage children.

``Config.act_mode`` selects the acting path (SEED RL / Podracer split):

- ``"local"``: the loop above — jitted policy forward on the worker's host
  CPU against the freshest broadcast params;
- ``"remote"``: the tick's observations go to the learner-colocated
  :class:`~tpu_rl.runtime.inference_service.InferenceService` over a
  DEALER/ROUTER channel; actions/logits/log_prob (and, for ``store_carry``
  families, the pre-step carry rows) come back and the published
  RolloutBatch is **bit-identical in layout** to local mode — manager,
  storage, assembler and algorithms cannot tell the modes apart. If the
  service times out ``inference_retries`` times the worker logs once and
  falls back to local acting on its last-known broadcast params (the model
  SUB is drained in both modes precisely so this fallback never acts on
  init-fresh weights), then re-probes the service every
  ``inference_reprobe_s`` seconds (exponential backoff) so a restarted
  service regains its clients; ``inference_reprobe_s=0`` restores the old
  permanent fallback.
"""

from __future__ import annotations

import os
import sys
import time
import uuid

import numpy as np

from tpu_rl.config import Config
from tpu_rl.runtime.env import EnvAdapter
from tpu_rl.runtime.protocol import Protocol, make_trace_id, pack_trace
from tpu_rl.runtime.transport import MODEL_HWM, Pub, Sub


class Worker:
    def __init__(
        self,
        cfg: Config,
        worker_id: int,
        manager_ip: str,
        manager_port: int,
        learner_ip: str,
        model_port: int,
        stop_event=None,
        heartbeat=None,
        initial_params=None,
        seed: int = 0,
        inference_port: int | list[int] | None = None,
    ):
        self.cfg = cfg
        self.worker_id = worker_id
        self.addr = (manager_ip, manager_port, learner_ip, model_port)
        self.stop_event = stop_event
        self.heartbeat = heartbeat
        self.initial_params = initial_params
        self.seed = seed
        self.inference_port = inference_port
        self.fell_back = False  # currently acting locally after a timeout
        self.n_remote_acts = 0
        # Recovery-event counters (telemetry + flight recorder): fallbacks
        # to local acting, re-probe attempts, successful restorations.
        self.n_fallbacks = 0
        self.n_reprobes = 0
        self.n_restores = 0

    # ------------------------------------------------- cold one-time/fault
    # Helpers kept OUT of run(): the tick loop's function is held to the
    # hot-path purity gate's fmt tier (tools/analysis), so all string
    # rendering lives here on the cold setup/fault paths.
    def _init_tracer(self, cfg: Config):
        """Build the trace recorder + dump path and install the flight
        recorder; -> (tracer, trace_path)."""
        from tpu_rl.obs import TraceRecorder, flightrec

        tracer = TraceRecorder(
            capacity=cfg.trace_capacity, pid=os.getpid(), role="worker"
        )
        trace_path = os.path.join(
            cfg.result_dir, f"trace-worker-{os.getpid()}.json"
        )
        flightrec.install(
            "worker",
            cfg.result_dir,
            tracer=tracer,
            cfg=cfg,
            extra=lambda: {
                "fell_back": self.fell_back,
                "n_fallbacks": self.n_fallbacks,
                "n_reprobes": self.n_reprobes,
                "n_restores": self.n_restores,
            },
        )
        return tracer, trace_path

    def _log_fallback(self, cfg: Config, reprobe_backoff: float) -> None:
        """Log (once per fallback) the drop from remote to local acting."""
        print(
            f"[worker {self.worker_id}] inference service "
            f"unreachable after "
            f"{cfg.inference_retries + 1} attempts of "
            f"{cfg.inference_timeout_ms} ms; falling back to "
            f"local acting"
            + (
                f" (re-probing every {reprobe_backoff:.0f}s)"
                if cfg.inference_reprobe_s > 0
                else " permanently"
            ),
            file=sys.stderr,
            flush=True,
        )

    def _log_restore(self) -> None:
        print(
            f"[worker {self.worker_id}] inference service "
            "reachable again; remote acting restored",
            file=sys.stderr,
            flush=True,
        )

    def _make_remote(self, cfg: Config, learner_ip: str):
        """Build the remote-acting client for ``self.inference_port``: a
        fleet of endpoints (list of ports — hedged, load-balanced
        :class:`~tpu_rl.fleet.client.FleetClient`) or the single-service
        :class:`InferenceClient`. Used for both the initial client and
        every re-probe, so a fallback under a fleet re-probes the WHOLE
        fleet — one replica's death can only strand the worker on local
        acting while every replica is unreachable."""
        port = self.inference_port
        if isinstance(port, (list, tuple)):
            from tpu_rl.fleet import FleetClient

            return FleetClient(
                cfg, [(learner_ip, int(p)) for p in port],
                wid=self.worker_id,
            )
        from tpu_rl.runtime.inference_service import InferenceClient

        return InferenceClient(cfg, learner_ip, port, wid=self.worker_id)

    # ------------------------------------------------------------------ run
    def run(self) -> None:
        import jax
        import jax.numpy as jnp

        from tpu_rl.models.families import build_family

        cfg = self.cfg
        manager_ip, manager_port, learner_ip, model_port = self.addr
        # Fault injection (tpu_rl.chaos): delay:worker shims this worker's
        # sends, corrupt/drop:model its model-SUB receives; nan:/spike:
        # poison rollout payload VALUES pre-send (wire stays CRC-valid —
        # the self-healing plane must contain them). None unless a
        # chaos_spec names this site / this worker instance.
        chaos = None
        dchaos = None
        if cfg.chaos_spec:
            from tpu_rl.chaos import maybe_data_chaos, maybe_transport_chaos

            chaos = maybe_transport_chaos(
                cfg, "worker", instance=self.worker_id
            )
            dchaos = maybe_data_chaos(
                cfg, "worker", instance=self.worker_id
            )
        pub = Pub(manager_ip, manager_port, bind=False, chaos=chaos)
        model_sub = Sub(
            learner_ip, model_port, bind=False, hwm=MODEL_HWM, chaos=chaos
        )

        # Telemetry (tpu_rl.obs): periodic registry snapshots ride the same
        # PUB as rollouts/stats, emitted on the CLOCK — an idle or wedged
        # worker keeps announcing itself to /healthz. Disabled (None) when
        # the plane has no sink, so the tick loop pays one `is None` check.
        registry = emitter = None
        # Clock-sync echo (tpu_rl.obs.clocksync): (t0, t1) of the newest
        # Model broadcast — t0 the learner's send stamp, t1 our receive
        # stamp — shipped inside Telemetry snapshots so the storage edge can
        # close a full NTP round trip through this worker. None until the
        # first stamped broadcast arrives.
        clk_echo: list | None = None
        # Run epoch adopted from the newest Model broadcast; -1 = unknown
        # (no broadcast yet). Echoed on every RolloutBatch and Telemetry
        # frame so storage can fence out frames acted under a pre-crash
        # learner incarnation (unknown is always accepted).
        run_epoch = -1
        ledger = None
        if cfg.telemetry_enabled:
            from tpu_rl.obs import MetricsRegistry, PeriodicSnapshot
            from tpu_rl.obs.goodput import COMPUTE, IDLE, WIRE, GoodputLedger
            from tpu_rl.obs.perf import process_self_stats

            registry = MetricsRegistry(
                role="worker", labels={"wid": str(self.worker_id)}
            )
            # Goodput ledger: act + env stepping is this role's compute
            # (remote acting included — outsourced or not, it is the tick's
            # purposeful work); model-SUB drains and the rollout publish are
            # wire; the reference throttle sleep is idle.
            ledger = self.ledger = GoodputLedger("worker")

            def _send_snap(snap, _wid=self.worker_id):
                snap["wid"] = _wid  # aggregator source key + UI grouping
                snap["epoch"] = run_epoch  # membership lease + epoch fence
                clk = {"t2": time.time_ns()}  # our clock at snapshot send
                if clk_echo is not None:
                    clk["t0"], clk["t1"] = clk_echo
                snap["clk"] = clk
                pub.send(Protocol.Telemetry, snap)

            emitter = PeriodicSnapshot(
                registry, _send_snap, interval_s=cfg.telemetry_interval_s
            )

        # Rollout-lineage tracing (tpu_rl.obs): every trace_sample_n-th tick
        # ships a trace-context trailer as the frame's third wire part and
        # records a local span. sample_n == 0 (the default) keeps the loop's
        # entire trace branch to one falsy check; the recorder itself needs
        # result_dir to have somewhere to dump.
        sample_n = int(cfg.trace_sample_n)
        tracer = None
        trace_path = None
        if cfg.result_dir is not None:
            tracer, trace_path = self._init_tracer(cfg)

        family = build_family(cfg)
        key = jax.random.key(self.seed * 9973 + self.worker_id)
        if self.initial_params is not None:
            params = self.initial_params  # checkpoint-resume parity
        else:
            key, init_key = jax.random.split(key)
            params = family.init_params(init_key, seq_len=cfg.seq_len)
        # Local act path shares the serving kernel dispatch
        # (Config.act_kernel): "pallas" fuses the act step where supported,
        # "xla" (default) is family.act unchanged.
        from tpu_rl.models.quant import make_act_fn

        act = jax.jit(make_act_fn(cfg, family))

        # Remote acting (act_mode="remote"): ship obs to the learner-device
        # inference service, fall back to the local jitted path above if it
        # ever becomes unreachable.
        remote = None
        if cfg.act_mode == "remote" and self.inference_port is not None:
            remote = self._make_remote(cfg, learner_ip)
        # Corrupt-reply count accumulated from CLOSED inference clients
        # (each fallback/failed probe folds its client's n_rejected in
        # before closing); the live client's count is added at read sites,
        # so the published total survives any number of fallback/restore
        # cycles (satellite of ISSUE 3: remote-acting drops were invisible
        # — only the model-SUB count reached the dashboards).
        remote_rejected = 0
        # Fleet-event totals accumulated the same way across client
        # generations (FleetClient only; 0 forever under a single service).
        fleet_hedges = fleet_failovers = 0
        fleet_dedups = fleet_floor_rejects = fleet_reprobes = 0

        def _fold_fleet(client) -> None:
            nonlocal fleet_hedges, fleet_failovers
            nonlocal fleet_dedups, fleet_floor_rejects, fleet_reprobes
            fleet_hedges += getattr(client, "n_hedges", 0)
            fleet_failovers += getattr(client, "n_failovers", 0)
            fleet_dedups += getattr(client, "n_dedups", 0)
            fleet_floor_rejects += getattr(client, "n_floor_rejects", 0)
            fleet_reprobes += getattr(client, "n_reprobes", 0)

        # Fallback recovery state: when remote acting drops to local, probe
        # the service again every `inference_reprobe_s`, doubling up to
        # `inference_reprobe_max_s` while it stays down. 0 disables (the
        # old permanent one-way degradation).
        next_reprobe: float | None = None
        reprobe_backoff = cfg.inference_reprobe_s

        # Vectorized acting: N envs stepped per tick with ONE batched policy
        # forward (worker_num_envs; N=1 reproduces the reference's
        # one-env-per-process loop exactly). Each env keeps its own episode
        # identity, carry row, and stats; resets zero only that env's carry.
        n = cfg.worker_num_envs
        envs = [
            EnvAdapter(cfg, seed=self.seed * 131 + self.worker_id + i * 7919)
            for i in range(n)
        ]
        # Acting carry shapes come from the family (LSTM: hidden states;
        # transformer: obs-history window + counter); batch storage widths
        # come from the layout and may be placeholders when the carry is
        # worker-local (family.store_carry False).
        from tpu_rl.data.layout import BatchLayout

        lay = BatchLayout.from_config(cfg)
        hw, cw = family.carry_widths
        h = jnp.zeros((n, hw))
        c = jnp.zeros((n, cw))
        hx_stub = np.zeros((n, lay.hx), np.float32)
        cx_stub = np.zeros((n, lay.cx), np.float32)
        obs = np.stack([e.reset() for e in envs]).astype(np.float32)
        episode_ids = [uuid.uuid4().hex for _ in range(n)]
        is_fir = np.ones(n, np.float32)
        epi_rew = np.zeros(n, np.float64)
        epi_steps = np.zeros(n, np.int64)
        n_model_loads = 0
        # Policy version = the learner update index tagged onto the frame
        # that delivered the params this tick acts with ("ver" on Model
        # broadcasts and inference Act replies). Echoed into every
        # RolloutBatch so storage can measure policy staleness per worker;
        # -1 = still on local random init (never broadcast-loaded).
        policy_ver = -1
        tick_seq = 0  # advances only while lineage sampling is on

        try:
            while not self._stopped():
                # Lineage sampling decision for this tick (off: one falsy
                # check). The sampled tick's span covers act + env-step +
                # publish — the worker-side cost of the frame.
                sampled = False
                if sample_n:
                    tick_seq += 1
                    sampled = tick_seq % sample_n == 0
                    if sampled:
                        t_tick = time.perf_counter()
                        trace_id = make_trace_id(self.worker_id, tick_seq)
                # Hot-reload the freshest broadcast params (reference
                # ``req_model`` task, ``worker.py:62-72``).
                t_drain = time.perf_counter()
                for proto, payload in model_sub.drain(max_msgs=MODEL_HWM):
                    if proto == Protocol.Model:
                        params = {"actor": payload["actor"]}
                        policy_ver = int(payload.get("ver", -1))
                        run_epoch = int(payload.get("epoch", run_epoch))
                        n_model_loads += 1
                        if registry is not None:
                            # Clock-sync echo: pair the learner's send stamp
                            # with our receive stamp (t0, t1).
                            t_tx = payload.get("t_tx")
                            if isinstance(t_tx, int):
                                clk_echo = [t_tx, time.time_ns()]

                t_act = time.perf_counter()
                if ledger is not None:
                    ledger.add(WIRE, t_act - t_drain)
                if remote is not None:
                    t_rtt = time.perf_counter()
                    reply = remote.act(obs, is_fir)
                    if reply is not None and registry is not None:
                        # Worker-observed round trip through the inference
                        # service — the p99 the SLO examples budget against.
                        registry.histogram("inference-rtt").observe(
                            time.perf_counter() - t_rtt
                        )
                else:
                    reply = None
                if remote is not None and reply is None:
                    # Fault path: the service timed out through every retry.
                    # Log once per fallback, drop to local acting on the
                    # last broadcast params — a dead server must never
                    # wedge the fleet — and schedule a re-probe so a
                    # RESTARTED server regains this client.
                    self._log_fallback(cfg, reprobe_backoff)
                    remote_rejected += remote.n_rejected
                    _fold_fleet(remote)
                    remote.close()
                    remote = None
                    self.fell_back = True
                    self.n_fallbacks += 1
                    if cfg.inference_reprobe_s > 0:
                        next_reprobe = time.monotonic() + reprobe_backoff
                elif (
                    remote is None
                    and next_reprobe is not None
                    and time.monotonic() >= next_reprobe
                ):
                    # Re-probe: one zero-retry request on a FRESH client
                    # (fresh DEALER identities — the old ones may be black-
                    # holed in a dead server's queue). Under a fleet the
                    # probe client spans every replica, so ANY healthy
                    # replica restores remote acting — a single timeout
                    # never strands the worker on local acting while the
                    # rest of the fleet is up. Success restores remote
                    # acting and this tick already has its reply; failure
                    # costs one inference_timeout_ms and doubles the probe
                    # interval.
                    probe = self._make_remote(cfg, learner_ip)
                    self.n_reprobes += 1
                    reply = probe.act(obs, is_fir, retries=0)
                    if reply is not None:
                        remote = probe
                        self.fell_back = False
                        self.n_restores += 1
                        reprobe_backoff = cfg.inference_reprobe_s
                        next_reprobe = None
                        self._log_restore()
                    else:
                        remote_rejected += probe.n_rejected
                        _fold_fleet(probe)
                        probe.close()
                        reprobe_backoff = min(
                            reprobe_backoff * 2.0,
                            cfg.inference_reprobe_max_s,
                        )
                        next_reprobe = time.monotonic() + reprobe_backoff
                if reply is not None:
                    # The service already sampled on the learner's device;
                    # for store_carry families the reply carries the
                    # pre-step carry rows the learner trains from (the
                    # running carry itself stays server-side).
                    self.n_remote_acts += 1
                    a_np = np.asarray(reply["act"], np.float32)
                    logits_np = np.asarray(reply["logits"], np.float32)
                    lp_np = np.asarray(reply["log_prob"], np.float32)
                    h_np = (
                        np.asarray(reply["hx"], np.float32)
                        if family.store_carry else None
                    )
                    c_np = (
                        np.asarray(reply["cx"], np.float32)
                        if family.store_carry else None
                    )
                else:
                    key, sub_key = jax.random.split(key)
                    a, logits, log_prob, h2, c2 = act(
                        params, jnp.asarray(obs), h, c, sub_key
                    )
                    a_np = np.asarray(a)
                    logits_np = np.asarray(logits)
                    lp_np = np.asarray(log_prob)
                    h_np = np.asarray(h) if family.store_carry else None
                    c_np = np.asarray(c) if family.store_carry else None

                # One framed RolloutBatch per tick: step every env, stack
                # the tick's transitions, send ONCE (per-env sends were
                # measured to cap the wire at ~3.2k env-steps/s at 32 envs
                # — framing overhead, not stepping). Episode-end Stats stay
                # per-episode messages (rare).
                rews = np.zeros((n, 1), np.float32)
                dones = np.zeros(n, np.uint8)
                tick_obs = obs.copy()  # pre-step observations, (n, obs)
                tick_fir = is_fir.copy()
                tick_ids = list(episode_ids)
                for i, env in enumerate(envs):
                    next_ob, rew, done = env.step(a_np[i])
                    epi_rew[i] += rew
                    epi_steps[i] += 1
                    horizon_hit = epi_steps[i] >= cfg.time_horizon
                    rews[i, 0] = rew * cfg.reward_scale
                    dones[i] = 1 if (done or horizon_hit) else 0

                    is_fir[i] = 0.0
                    obs[i] = next_ob
                    if done or horizon_hit:
                        # Episode stat rides as a dict so per-worker health
                        # counters (model reloads — satellite of ISSUE 2)
                        # reach the dashboards; the manager also accepts the
                        # reference's bare-float form. n_rejected covers both
                        # of this worker's receive channels: the model SUB
                        # and (when acting remotely) the inference DEALER.
                        pub.send(
                            Protocol.Stat,
                            {
                                "rew": float(epi_rew[i]),
                                "n_model_loads": n_model_loads,
                                "n_rejected": model_sub.n_rejected
                                + remote_rejected
                                + (remote.n_rejected if remote else 0),
                                "wid": self.worker_id,
                            },
                        )
                        obs[i] = env.reset()
                        episode_ids[i] = uuid.uuid4().hex
                        is_fir[i], epi_rew[i], epi_steps[i] = 1.0, 0.0, 0
                t_built = time.perf_counter()
                if ledger is not None:
                    # Policy forward + env stepping (episode-end stat sends
                    # are rare and ride inside the span — sub-ms noise).
                    ledger.add(COMPUTE, t_built - t_act)
                # Version echo: remote ticks acted with the server's params
                # (the reply says which update produced them); local ticks
                # acted with the last broadcast. Extra keys are ignored by
                # the assembler (it reads only the batch fields + id/done),
                # so pre-upgrade consumers are unaffected.
                tick_ver = (
                    int(reply.get("ver", policy_ver))
                    if reply is not None
                    else policy_ver
                )
                trailer = (
                    pack_trace(
                        self.worker_id, tick_seq, trace_id, time.time_ns()
                    )
                    if sampled
                    else None
                )
                tick_payload = dict(
                    obs=tick_obs,
                    act=a_np,
                    rew=rews,
                    logits=logits_np,
                    log_prob=lp_np,
                    is_fir=tick_fir[:, None],
                    hx=h_np if family.store_carry else hx_stub,
                    cx=c_np if family.store_carry else cx_stub,
                    id=tick_ids,
                    done=dones,
                    wid=self.worker_id,
                    ver=tick_ver,
                    epoch=run_epoch,
                )
                if dchaos is not None:
                    dchaos.on_tick(tick_payload)
                pub.send(Protocol.RolloutBatch, tick_payload, trace=trailer)
                if ledger is not None:
                    ledger.add(WIRE, time.perf_counter() - t_built)
                if sampled and tracer is not None:
                    tracer.add(
                        "worker-tick",
                        t_tick,
                        time.perf_counter() - t_tick,
                        args={"trace_id": trace_id, "seq": tick_seq},
                    )

                # Carry forward; zero only the rows whose episode ended
                # (where(), not multiply: a transient NaN in a dying
                # episode's carry must not survive the reset as NaN*0).
                # Remote ticks skip this: the carry lives server-side and
                # the next request's is_fir flags do the zeroing there.
                if reply is None:
                    if dones.any():
                        keep = jnp.asarray(dones == 0)[:, None]
                        h = jnp.where(keep, h2, 0.0)
                        c = jnp.where(keep, c2, 0.0)
                    else:
                        h, c = h2, c2

                if registry is not None:
                    registry.counter("worker-env-steps").inc(n)
                    registry.counter("worker-ticks").inc()
                    if dones.any():
                        registry.counter("worker-episodes").inc(
                            int(dones.sum())
                        )
                    registry.gauge("worker-policy-version").set(tick_ver)
                    registry.gauge("worker-run-epoch").set(run_epoch)
                    registry.counter("worker-model-loads").set_total(
                        n_model_loads
                    )
                    registry.counter("worker-rejected-frames").set_total(
                        model_sub.n_rejected
                        + remote_rejected
                        + (remote.n_rejected if remote else 0)
                    )
                    if cfg.act_mode == "remote":
                        registry.counter(
                            "worker-remote-fallbacks"
                        ).set_total(self.n_fallbacks)
                        registry.counter(
                            "worker-remote-reprobes"
                        ).set_total(self.n_reprobes)
                        registry.counter(
                            "worker-remote-restores"
                        ).set_total(self.n_restores)
                        registry.counter("fleet-hedge-fired").set_total(
                            fleet_hedges
                            + getattr(remote, "n_hedges", 0)
                        )
                        registry.counter("fleet-failovers").set_total(
                            fleet_failovers
                            + getattr(remote, "n_failovers", 0)
                        )
                        registry.counter("fleet-dedup-replies").set_total(
                            fleet_dedups
                            + getattr(remote, "n_dedups", 0)
                        )
                        registry.counter("fleet-floor-rejects").set_total(
                            fleet_floor_rejects
                            + getattr(remote, "n_floor_rejects", 0)
                        )
                        registry.counter("fleet-reprobes").set_total(
                            fleet_reprobes
                            + getattr(remote, "n_reprobes", 0)
                        )
                    if chaos is not None:
                        registry.counter(
                            "chaos-corrupted-frames"
                        ).set_total(chaos.n_corrupted)
                        registry.counter(
                            "chaos-dropped-frames"
                        ).set_total(chaos.n_dropped)
                        registry.counter(
                            "chaos-delayed-frames"
                        ).set_total(chaos.n_delayed)
                    if dchaos is not None:
                        registry.counter(
                            "chaos-nan-injected"
                        ).set_total(dchaos.n_nan)
                        registry.counter(
                            "chaos-spike-injected"
                        ).set_total(dchaos.n_spike)
                        registry.counter(
                            "chaos-logp-nan-injected"
                        ).set_total(dchaos.n_logp_nan)
                    if emitter.due():
                        # /proc self-stats only just before an emit — the
                        # reads cost syscalls, the gauges only travel then.
                        rss, n_fds = process_self_stats()
                        registry.gauge("worker-rss-bytes").set(rss)
                        registry.gauge("worker-open-fds").set(float(n_fds))
                        ledger.publish(registry)
                    if emitter.maybe_emit() and tracer is not None:
                        # Trace dumps ride the telemetry cadence: no clock
                        # of their own, and a crash between dumps still
                        # leaves a recent ring on disk for the merger.
                        tracer.dump(trace_path)
                if self.heartbeat is not None:
                    self.heartbeat.value = time.time()
                if cfg.worker_step_sleep > 0:
                    # Reference throttle (``worker.py:131``); 0 disables.
                    # Applies per tick (= per batched act), so N envs yield
                    # N env-steps per throttle window.
                    time.sleep(cfg.worker_step_sleep)
                    if ledger is not None:
                        ledger.add(IDLE, cfg.worker_step_sleep)
        finally:
            if tracer is not None and tracer.n_recorded:
                tracer.dump(trace_path)
            for env in envs:
                env.close()
            pub.close()
            model_sub.close()
            if remote is not None:
                remote.close()

    def _stopped(self) -> bool:
        return self.stop_event is not None and self.stop_event.is_set()


def worker_main(
    cfg: Config,
    worker_id: int,
    manager_ip: str,
    manager_port: int,
    learner_ip: str,
    model_port: int,
    stop_event,
    heartbeat,
    initial_params=None,
    seed: int = 0,
    inference_port: int | list[int] | None = None,
) -> None:
    """mp.Process target (reference ``worker_run``, ``main.py:155-162``)."""
    Worker(
        cfg,
        worker_id,
        manager_ip,
        manager_port,
        learner_ip,
        model_port,
        stop_event,
        heartbeat,
        initial_params,
        seed,
        inference_port=inference_port,
    ).run()
