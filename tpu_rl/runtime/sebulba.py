"""Sebulba-mode driver: split actor/learner device groups + bounded queue.

Podracer/Sebulba (PAPERS.md, arxiv 2104.06272) splits one host's devices
into two groups instead of fusing everything into one dispatch the way
Anakin does: a dedicated ACTOR group runs the jitted act->env.step rollout
program while the remaining LEARNER group runs ``train_step``, and the two
overlap in wall time. The seam between them is a bounded queue of
device-resident :class:`~tpu_rl.types.Batch` slots:

    actor thread                          learner thread (main)
    ------------                          ---------------------
    rollout on act_mesh                   train_step on mesh
    device_put -> learner group   ──►     BoundedPipe.get (queue-wait)
    BoundedPipe.put (queue-wait)          fresh act params -> actor group

Queue protocol (``BoundedPipe``): ``Config.sebulba_queue`` slots (2 =
double buffering, 3 = triple). A full queue blocks the actor — that wait is
BACKPRESSURE and lands in the actor ledger's existing ``queue-wait``
bucket; an empty queue blocks the learner — actor-bound, same bucket on
the learner ledger. The queue holds batches already transferred to the
learner group (the ``jax.device_put`` reshard is actor-lane time, ``h2d``
bucket), so depth bounds BOTH learner-group staging memory and policy
staleness: a batch can be at most ``depth + 1`` updates stale.

Parameter feedback is latest-wins: after every update the learner reshards
``act_params(state)`` onto the actor group and swaps it into a slot the
actor reads at rollout start — no handshake, the actor never waits for
params.

Durability is inherited from :class:`ColocatedLoop` unchanged: two-phase
commits + newest-committed resume with a run-epoch bump, stateless
``fold_in`` key streams on both lanes (actor keys are derived from the
produced-batch index, so a resumed run replays the unbroken run's stream).

Telemetry: one goodput ledger per lane thread (``sebulba-actor`` /
``sebulba-learner`` roles — the ledger rule is one ledger per loop THREAD),
plus queue-depth gauges. Both lanes' compute ratios being simultaneously
nonzero in one snapshot window is the "acting overlaps training" acceptance
signal (``tests/test_sebulba.py``, ``examples/sebulba_smoke.py``).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp

from tpu_rl.config import Config
from tpu_rl.parallel.mesh import (
    batch_sharding,
    check_divisible,
    make_mesh,
    replicated,
)
from tpu_rl.runtime.colocated import ColocatedLoop, act_params
from tpu_rl.utils.timer import ExecutionTimer


def split_local_devices(n_act: int) -> tuple[list, list]:
    """Partition THIS process's devices into (actor, learner) groups:
    actors take the first ``n_act`` local devices, the learner the rest.
    Raises with the config knob's name when the split does not partition
    the local device count into two non-empty groups (the check needs
    ``jax.local_device_count()``, so it lives here, not in
    ``Config.validate`` — config never imports jax)."""
    local = jax.local_devices()
    if not 0 < n_act < len(local):
        raise ValueError(
            f"sebulba_split={n_act} must partition jax.local_device_count()"
            f"={len(local)} into two non-empty groups (actor devices "
            f"[0, split), learner devices [split, n))"
        )
    return local[:n_act], local[n_act:]


class BoundedPipe:
    """Bounded handoff of device-resident items between the two lanes.

    A thin ``queue.Queue`` wrapper that (a) attributes the caller's
    blocking time to its goodput ledger's ``queue-wait`` bucket — the
    backpressure signal — and (b) tracks the depth high-watermark so tests
    and telemetry can pin "bounded, never past ``depth``". Waits poll in
    ``poll_s`` slices so a stop event always unsticks both lanes (no
    deadlock on shutdown regardless of which side quit first)."""

    __slots__ = ("_q", "depth", "peak_depth", "_peak_lock")

    def __init__(self, depth: int):
        self._q = queue.Queue(maxsize=int(depth))
        self.depth = int(depth)
        self.peak_depth = 0
        self._peak_lock = threading.Lock()

    def qsize(self) -> int:
        return self._q.qsize()

    def put(self, item, ledger=None, stop=None, poll_s: float = 0.05) -> bool:
        """Enqueue; block while full (backpressure). False = stop was set
        before a slot opened, and the item was NOT enqueued."""
        t0 = time.perf_counter()
        ok = False
        while True:
            try:
                self._q.put(item, timeout=poll_s)
                ok = True
                break
            except queue.Full:
                if stop is not None and stop.is_set():
                    break
        if ledger is not None:
            from tpu_rl.obs.goodput import QUEUE_WAIT

            ledger.add(QUEUE_WAIT, time.perf_counter() - t0)
        if ok:
            with self._peak_lock:
                depth = self._q.qsize()
                if depth > self.peak_depth:
                    self.peak_depth = depth
        return ok

    def get(self, ledger=None, stop=None, poll_s: float = 0.05):
        """Dequeue; block while empty. None = stop was set while empty."""
        t0 = time.perf_counter()
        item = None
        while True:
            try:
                item = self._q.get(timeout=poll_s)
                break
            except queue.Empty:
                if stop is not None and stop.is_set():
                    break
        if ledger is not None:
            from tpu_rl.obs.goodput import QUEUE_WAIT

            ledger.add(QUEUE_WAIT, time.perf_counter() - t0)
        return item


class SebulbaLoop(ColocatedLoop):
    """Sebulba split of the colocated plane: same envs, same algo build,
    same checkpoint/resume semantics as :class:`ColocatedLoop`, different
    topology — ``cfg.sebulba_split`` local devices act, the rest train,
    and :meth:`run` drives the two lanes concurrently through a
    :class:`BoundedPipe` instead of one fused dispatch."""

    # ---------------------------------------------------------- topology hooks
    def _build_meshes(self) -> None:
        if jax.process_count() > 1:
            raise ValueError(
                "sebulba_split is a per-host (single-process) split; "
                "multihost pod scaling uses the fused Anakin path "
                "(Config.multihost without sebulba_split)"
            )
        acts, learns = split_local_devices(self.cfg.sebulba_split)
        self.act_mesh = make_mesh(devices=acts)
        self.mesh = make_mesh(devices=learns)
        check_divisible(self.cfg.batch_size, self.act_mesh)
        check_divisible(self.cfg.batch_size, self.mesh)

    def _compile(self) -> None:
        rs_l, bs_l = replicated(self.mesh), batch_sharding(self.mesh)
        rs_a, bs_a = replicated(self.act_mesh), batch_sharding(self.act_mesh)
        self._rs, self._bs = rs_l, bs_l
        self._act_rs, self._act_bs = rs_a, bs_a
        # Actor-lane program: rollout + on-device episode stats, everything
        # resident on the actor group. Carry is donated (it never leaves
        # the lane); stats are NOT — the live handle rides the queue to the
        # learner for log-interval reads, so its buffer must survive the
        # next dispatch.
        self.rollout = jax.jit(
            self._sebulba_rollout,
            in_shardings=(rs_a, bs_a, rs_a, rs_a),
            out_shardings=(bs_a, rs_a, bs_a),
            donate_argnums=(1,),
        )
        # Learner-lane program: the same pure train_step the fused program
        # embeds, compiled alone over the learner group.
        self.train = jax.jit(
            self._train_body,
            in_shardings=(rs_l, bs_l, rs_l),
            out_shardings=(rs_l, rs_l),
            donate_argnums=(0,),
        )
        # No fused `program` in this mode: ColocatedLoop.program users
        # (bench colocated rows, assembler-parity tests) run the Anakin
        # class.
        self.program = None

    # -------------------------------------------------------------- jit bodies
    def _sebulba_rollout(self, params, carry, stats, key):
        from tpu_rl.models import cells

        prev = cells._DATA_MESH
        cells.set_data_mesh(self.act_mesh)
        try:
            carry, batch, done, ep_ret = self._rollout_body(params, carry, key)
        finally:
            cells.set_data_mesh(prev)
        import jax.numpy as jnp

        stats = {
            "episodes": stats["episodes"] + done.sum(dtype=jnp.int32),
            "ret_sum": stats["ret_sum"] + ep_ret.sum(),
        }
        return carry, stats, batch

    def _train_body(self, state, batch, key):
        from tpu_rl.models import cells

        prev = cells._DATA_MESH
        cells.set_data_mesh(self.mesh)
        try:
            return self._train_step(state, batch, key)
        finally:
            cells.set_data_mesh(prev)

    # ---------------------------------------------------------------- telemetry
    def _setup_telemetry(self) -> None:
        self._pipe = None
        super()._setup_telemetry()
        self.ledger_actor = None
        if self.ledger is not None:
            from tpu_rl.obs import GoodputLedger

            # One ledger per lane THREAD (the ledger rule): re-role the
            # inherited main-lane ledger as the learner's, add the actor's.
            self.ledger = GoodputLedger("sebulba-learner")
            self.ledger_actor = GoodputLedger("sebulba-actor")

    def _ledgers(self) -> list:
        return [
            led for led in (self.ledger, self.ledger_actor) if led is not None
        ]

    def _goodput_payload(self) -> dict:
        return {
            "colocated": (
                self.ledger.snapshot() if self.ledger is not None else None
            ),
            "roles": {
                led.role: led.snapshot() for led in self._ledgers()
            },
            "stragglers": [],
        }

    def _telemetry_tick(self, *args) -> None:
        # Queue gauges BEFORE the base tick: the base tick may export (and
        # record a history row), and that row should carry this tick's
        # depth, not the previous one's.
        if self.aggregator is not None and self._pipe is not None:
            reg = self.aggregator.registry
            reg.gauge("sebulba-queue-depth").set(float(self._pipe.qsize()))
            reg.gauge("sebulba-queue-peak-depth").set(
                float(self._pipe.peak_depth)
            )
        super()._telemetry_tick(*args)

    # ---------------------------------------------------------------- run loop
    def _actor_loop(self, carry, stats, needed: int | None) -> None:
        """Actor-lane thread entry (tools/analysis threads INVENTORY). All
        cross-thread publication goes through the BoundedPipe or the
        params/stats slots under ``self._lane_lock``."""
        from tpu_rl.obs.goodput import COMPUTE, H2D

        ledger = self.ledger_actor
        pipe = self._pipe
        produced = self._start_it
        while not self._lane_stop.is_set() and (
            needed is None or produced < needed
        ):
            with self._lane_lock:
                params = self._params_slot
                pver = self._params_ver
            k = jax.random.fold_in(self._k_act_base, produced)
            t0 = time.perf_counter()
            carry, stats, batch = self.rollout(params, carry, stats, k)
            batch = jax.block_until_ready(batch)
            t1 = time.perf_counter()
            if ledger is not None:
                ledger.add(COMPUTE, t1 - t0)
            # Reshard onto the learner group while the NEXT rollout could
            # already be dispatched — device-to-device transfer time is the
            # actor lane's h2d bucket (the split's analogue of a host feed).
            lbatch = jax.device_put(batch, self._bs)
            if ledger is not None:
                ledger.add(H2D, time.perf_counter() - t1)
            with self._lane_lock:
                self._stats_slot = stats
            if not pipe.put(
                (lbatch, stats, pver), ledger=ledger, stop=self._lane_stop
            ):
                break
            produced += 1

    def run(self, log: bool = True) -> dict:
        """Drive both lanes to ``max_updates`` (or the stop event). The
        learner lane is this thread; the actor lane is a daemon thread
        joined on every exit path."""
        cfg = self.cfg
        n, s = cfg.batch_size, cfg.seq_len
        timer = ExecutionTimer(num_transition=n * s)
        from tpu_rl.utils.metrics import make_writer

        writer = make_writer(cfg.result_dir)
        from tpu_rl.parallel.dp import replicate

        state = self.state
        if self.ckpt is not None:
            restored = self.ckpt.restore_run(
                jax.device_get(state),
                fingerprint=self._fingerprint,
                force=cfg.resume_force,
            )
            if restored is not None:
                state, self._start_it, meta = restored
                self.run_epoch = int(meta.get("epoch", 0)) + 1
                self._record_resume(self._start_it)
                if log:
                    print(
                        f"[sebulba] resumed from committed checkpoint "
                        f"idx {self._start_it} (run epoch {self.run_epoch})",
                        flush=True,
                    )
        state = replicate(state, self.mesh)
        k_carry = jax.random.fold_in(self._k_base, 0xC0C0)
        self._k_act_base = jax.random.fold_in(self._k_base, 0xAC7)
        carry = self.init_carry(k_carry)
        stats = self.init_stats()
        self._pipe = BoundedPipe(cfg.sebulba_queue)
        self._lane_stop = threading.Event()
        self._lane_lock = threading.Lock()
        self._params_slot = jax.device_put(act_params(state), self._act_rs)
        # Learner version of the published acting params: every batch in the
        # pipe is stamped with it, so the learner can attribute diagnostics
        # to real policy staleness (bounded by queue depth, but measured,
        # not assumed).
        self._params_ver = self._start_it
        self._stats_slot = stats
        ledger = self.ledger
        if ledger is not None:
            from tpu_rl.obs.goodput import CKPT, COMPUTE, H2D
        metrics: Any = {}
        # Learning-dynamics plane: same fold/drain as the fused loop, but
        # each batch carries the REAL policy lag (learner updates applied
        # since its acting params were published), so the by-staleness
        # gauge families are live in the split too.
        diag_acc = None
        if cfg.learn_diag:
            from tpu_rl.obs.learn import (
                DiagAccumulator,
                learn_record as _learn_record,
                publish as _publish_diag,
            )

            diag_acc = DiagAccumulator()
        log_every = max(1, cfg.loss_log_interval)
        it = self._start_it
        last_it, last_ep, last_ret = 0, 0, 0.0
        mean_ret, best_ret = 0.0, float("-inf")
        actor = threading.Thread(
            target=self._actor_loop,
            args=(carry, stats, self.max_updates),
            name="sebulba-actor",
            daemon=True,
        )
        t_mark = time.perf_counter()
        t0 = t_mark
        actor.start()
        try:
            while not self._stopping() and (
                self.max_updates is None or it < self.max_updates
            ):
                item = self._pipe.get(ledger=ledger, stop=self._stop)
                if item is None:
                    break
                batch, stats_ref, bver = item
                k_train = jax.random.fold_in(self._k_base, it)
                if self._perf is not None:
                    self._perf.capture(self.train, state, batch, k_train)
                t_disp = time.perf_counter()
                state, metrics = self.train(state, batch, k_train)
                if diag_acc is not None and isinstance(metrics, dict):
                    diag = metrics.pop("diag", None)
                    if diag is not None:
                        n_rows = (
                            next(iter(diag["rows"].values())).shape[0]
                            if diag["rows"] else 0
                        )
                        stale = float(max(0, it - bver))
                        diag_acc.add(
                            diag, jnp.full((n_rows,), stale, jnp.float32)
                        )
                metrics = jax.block_until_ready(metrics)
                t_done = time.perf_counter()
                if ledger is not None:
                    ledger.add(COMPUTE, t_done - t_disp)
                # Latest-wins param feedback onto the actor group: staleness
                # is bounded by the queue depth, not by a handshake.
                aparams = jax.device_put(act_params(state), self._act_rs)
                if ledger is not None:
                    ledger.add(H2D, time.perf_counter() - t_done)
                with self._lane_lock:
                    self._params_slot = aparams
                    self._params_ver = it + 1
                it += 1
                if self._heartbeat is not None:
                    self._heartbeat.value = time.time()
                if (
                    self.ckpt is not None
                    and it % cfg.model_save_interval == 0
                ):
                    t_ck = time.perf_counter()
                    self.ckpt.save(
                        state,
                        it,
                        meta={
                            "epoch": self.run_epoch,
                            "fingerprint": self._fingerprint,
                        },
                    )
                    if ledger is not None:
                        ledger.add(CKPT, time.perf_counter() - t_ck)
                    self._last_saved = it
                if it % log_every and it != self.max_updates:
                    continue
                host_stats = jax.device_get(stats_ref)
                host_metrics = {
                    k: float(v) for k, v in jax.device_get(metrics).items()
                }
                now = time.perf_counter()
                iters = it - last_it
                chunk_s = (now - t_mark) / max(1, iters)
                timer.record(
                    "sebulba-iteration", chunk_s, check_throughput=True
                )
                ups = iters / max(now - t_mark, 1e-9)
                tps = ups * n * s
                episodes = int(host_stats["episodes"])
                ret_sum = float(host_stats["ret_sum"])
                if episodes > last_ep:
                    mean_ret = (ret_sum - last_ret) / (episodes - last_ep)
                    best_ret = max(best_ret, mean_ret)
                self._telemetry_tick(
                    it, it * n * s, episodes, ups, tps, chunk_s, mean_ret
                )
                if diag_acc is not None:
                    diag_doc = diag_acc.drain(it)
                    if diag_doc is not None:
                        if self.aggregator is not None:
                            _publish_diag(self.aggregator.registry, diag_doc)
                        if cfg.result_dir is not None:
                            from tpu_rl.obs.audit import append_jsonl

                            append_jsonl(
                                cfg.result_dir, "learn.jsonl",
                                _learn_record(it, diag_doc),
                            )
                for name, val in host_metrics.items():
                    writer.add_scalar(f"loss/{name}", val, it)
                writer.add_scalar("colocated/env_steps_per_s", tps, it)
                writer.add_scalar(
                    "colocated/mean_episode_return", mean_ret, it
                )
                if log:
                    print(
                        f"[sebulba] update {it}  tps {tps:,.0f}  "
                        f"queue {self._pipe.qsize()}/{self._pipe.depth}  "
                        f"episodes {episodes}  mean_return {mean_ret:.1f}  "
                        + "  ".join(
                            f"{k} {v:.4f}" for k, v in host_metrics.items()
                        ),
                        flush=True,
                    )
                last_it, last_ep, last_ret = it, episodes, ret_sum
                t_mark = time.perf_counter()
        finally:
            self._lane_stop.set()
            actor.join(timeout=30.0)
        with self._lane_lock:
            stats_ref = self._stats_slot
        host_stats = jax.device_get(stats_ref)
        elapsed = time.perf_counter() - t0
        if (
            self.ckpt is not None
            and it > self._start_it
            and it != self._last_saved
        ):
            if ledger is not None:
                t_ck = time.perf_counter()
            self.ckpt.save(
                state,
                it,
                meta={
                    "epoch": self.run_epoch,
                    "fingerprint": self._fingerprint,
                },
            )
            if ledger is not None:
                ledger.add(CKPT, time.perf_counter() - t_ck)
        writer.flush()
        writer.close()
        self.close()
        self.state = state
        episodes = int(host_stats["episodes"])
        ret_sum = float(host_stats["ret_sum"])
        new_it = it - self._start_it
        return {
            "updates": it,
            "env_steps": it * n * s,
            "episodes": episodes,
            "mean_return_overall": ret_sum / max(1, episodes),
            "mean_return_recent": mean_ret,
            "mean_return_best_window": best_ret,
            "elapsed_s": elapsed,
            "transitions_per_s": new_it * n * s / max(elapsed, 1e-9),
            "queue_peak_depth": self._pipe.peak_depth,
            "scalars": timer.scalars(),
        }


def sebulba_main(
    cfg: Config, stop_event, heartbeat, max_updates: int | None = None,
    seed: int = 0,
) -> None:
    """Supervised child entry for the sebulba split (the colocated role
    routes here when ``cfg.sebulba_split > 0``)."""
    loop = SebulbaLoop(
        cfg,
        seed=seed,
        max_updates=max_updates,
        stop_event=stop_event,
        heartbeat=heartbeat,
    )
    out = loop.run()
    print(
        f"[sebulba] done: {out['updates']} updates, "
        f"{out['env_steps']:,} env steps, {out['episodes']} episodes, "
        f"mean return {out['mean_return_overall']:.1f}, "
        f"{out['transitions_per_s']:,.0f} transitions/s, "
        f"queue peak {out['queue_peak_depth']}",
        flush=True,
    )
    if cfg.slo_fail_run and loop.slo_failed:
        print("[sebulba] SLO verdict failing; exiting nonzero", flush=True)
        raise SystemExit(3)
