"""Process orchestration: spawn, supervise, and tear down the role processes.

Capability parity with the reference ``Runner`` (``/root/reference/main.py:62-524``):
role dispatch, spawn-start-method child processes, stop-event + signal/atexit
cleanup, per-child heartbeats — plus the part the reference ships commented
out (``main.py:417-473``, "probably shouldn't use, has issues"): a working
supervisor that terminates and respawns any child whose heartbeat goes silent,
with a restart budget. Learner children resume from their newest checkpoint on
respawn (``checkpoint.py``), so supervision composes with resume.

Roles (reference CLI ``main.py:475-508``):
- ``learner``  : LearnerStorage + LearnerService sharing a shm store + stat
  mailbox (reference ``learner_sub_process``, ``main.py:301-414``)
- ``manager``  : one relay (reference ``manager_sub_process``)
- ``worker``   : ``num_p`` actor processes (reference ``worker_sub_process``)
- ``local``    : everything on one host — the smallest real cluster

Workers/managers/storage are CPU processes: ``role_entry`` forces the CPU
backend in-process (``utils.platform.force_cpu``) so only the learner touches
the TPU. The ``JAX_PLATFORMS=cpu`` env pin is kept as belt-and-braces, but it
is NOT sufficient on its own — the TPU plugin here ignores the env var.
"""

from __future__ import annotations

import contextlib
import functools
import multiprocessing as mp
import os
import signal
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from tpu_rl.config import Config, MachinesConfig
from tpu_rl.data.layout import BatchLayout
from tpu_rl.data.shm_ring import alloc_handles
from tpu_rl.runtime.mailbox import STAT_SLOTS

# Supervision defaults. Deployments override these via Config
# (heartbeat_timeout_s / startup_grace_s / supervise_poll_s / max_restarts /
# restart_*) — see Supervisor.from_config; the constants remain the
# dataclass defaults so direct Supervisor() construction keeps working.
HEARTBEAT_TIMEOUT = 60.0  # seconds of silence before a child is declared dead
STARTUP_GRACE = 180.0  # extra silence allowed after (re)start: jax import +
# XLA compile legitimately take minutes before the first loop heartbeat
SUPERVISE_POLL = 2.0
RESTART_WINDOW = 300.0  # sliding window for the restart budget
RESTART_BACKOFF = 1.0  # base respawn delay within a crash streak
RESTART_BACKOFF_MAX = 30.0


@contextlib.contextmanager
def _child_env(**env: str):
    """Temporarily set env vars so a spawn-child inherits them."""
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@dataclass
class Child:
    name: str
    target: Callable
    args: tuple
    proc: mp.Process
    heartbeat: Any  # mp.Value("d")
    cpu_only: bool
    # Daemonic children die with the supervisor (the default and the right
    # answer for leaf roles). Population members running a NESTED fleet
    # must be non-daemonic — multiprocessing forbids daemonic processes
    # from having children of their own.
    daemon: bool = True
    restarts: int = 0
    started_at: float = 0.0
    # Sliding-window restart budget + backoff state (Supervisor.check):
    restart_times: list = field(default_factory=list)  # respawn timestamps
    streak: int = 0  # consecutive crashes without an intervening healthy window
    respawn_at: float = 0.0  # dead, respawn scheduled at this time (0 = none)
    exhausted: bool = False  # budget blown; fleet shuts down


@dataclass
class Supervisor:
    """Owns the children of one role process; restart-on-silence is the
    feature the reference disabled (``main.py:417-473``). Every child is
    wrapped so a crash writes ``logs/<role>/error_log_<ts>.txt``
    (``utils.errlog``) before the supervisor sees the nonzero exit."""

    ctx: Any = field(default_factory=lambda: mp.get_context("spawn"))
    heartbeat_timeout: float = HEARTBEAT_TIMEOUT
    startup_grace: float = STARTUP_GRACE
    max_restarts: int = 3
    log_root: str = "logs"
    children: list[Child] = field(default_factory=list)
    # Restart budget is per sliding window, not per process lifetime: a
    # child may restart at most `max_restarts` times per trailing
    # `restart_window_s` seconds. Within a crash streak, respawn N is
    # delayed `backoff_s * 2**(N-2)` (first respawn immediate), capped at
    # `backoff_max_s`; a child that stays up a full window resets its
    # streak. This replaces the old lifetime counter + instant respawn,
    # which hot-looped a crashing child straight through its budget.
    restart_window_s: float = RESTART_WINDOW
    backoff_s: float = RESTART_BACKOFF
    backoff_max_s: float = RESTART_BACKOFF_MAX
    poll_s: float = SUPERVISE_POLL
    # Injectable for tests (backoff timing with a mocked clock).
    clock: Callable[[], float] = time.time
    # Optional tpu_rl.chaos.ProcessChaos, polled from loop() — the
    # supervisor is the only place that knows every child's name and pid.
    chaos: Any = None
    # Audit sink for chaos injections (result_dir/chaos.jsonl, the same
    # unified jsonl discipline as rollback/resume/population/autopilot
    # events) so post-hoc run reports can overlay process faults on the
    # recorded curves. None = no audit (best-effort either way).
    audit_dir: str | None = None

    def __post_init__(self):
        self.stop_event = self.ctx.Event()
        self._telem_cfg = None  # (cfg, ip, port) set by enable_telemetry
        self._telem = None  # lazily: (registry, pub, emitter)

    @classmethod
    def from_config(cls, cfg, **kw) -> "Supervisor":
        """Build a supervisor from Config's supervision fields; chaos
        process faults come from ``cfg.chaos_spec`` when set."""
        chaos = kw.pop("chaos", None)
        if chaos is None and getattr(cfg, "chaos_spec", None):
            from tpu_rl.chaos import ProcessChaos

            chaos = ProcessChaos.from_spec(cfg.chaos_spec)
        return cls(
            heartbeat_timeout=cfg.heartbeat_timeout_s,
            startup_grace=cfg.startup_grace_s,
            max_restarts=cfg.max_restarts,
            restart_window_s=cfg.restart_window_s,
            backoff_s=cfg.restart_backoff_s,
            backoff_max_s=cfg.restart_backoff_max_s,
            poll_s=cfg.supervise_poll_s,
            chaos=chaos,
            audit_dir=getattr(cfg, "result_dir", None),
            **kw,
        )

    # ----------------------------------------------------------------- spawn
    def spawn(
        self,
        name: str,
        target: Callable,
        *args,
        cpu_only: bool = True,
        daemon: bool = True,
    ) -> Child:
        from tpu_rl.utils.errlog import role_entry

        hb = self.ctx.Value("d", time.time())
        child = Child(
            name=name,
            target=functools.partial(
                role_entry, target, name, self.log_root, cpu_only=cpu_only
            ),
            args=(*args, self.stop_event, hb),
            proc=None,  # type: ignore[arg-type]
            heartbeat=hb,
            cpu_only=cpu_only,
            daemon=daemon,
        )
        self._start(child)
        self.children.append(child)
        return child

    def _start(self, child: Child) -> None:
        env = {"JAX_PLATFORMS": "cpu"} if child.cpu_only else {}
        target = child.target
        if not child.cpu_only and child.restarts > 0:
            # An accelerator-owning child being RESTARTED was most likely
            # killed for silence — and the axon tunnel's failure mode is a
            # silent indefinite hang in device init. Have the replacement
            # probe the accelerator (bounded) and degrade to CPU if it is
            # unreachable, instead of burning the whole restart budget
            # against a dead tunnel. First starts skip the probe: no
            # healthy-path overhead (role_entry docstring).
            target = functools.partial(target, probe_accelerator=True)
        with _child_env(**env):
            child.proc = self.ctx.Process(
                target=target,
                args=child.args,
                name=child.name,
                daemon=child.daemon,
            )
            child.heartbeat.value = self.clock()
            child.started_at = self.clock()
            child.proc.start()

    # ------------------------------------------------------------- supervise
    def _ensure_dead(self, child: Child) -> None:
        """Terminate, escalating to SIGKILL: SIGTERM stays *pending* on a
        SIGSTOP'd process, so a hung-but-stopped child survives terminate()
        and would wedge its bound ports forever without the escalation."""
        if child.proc.is_alive():
            child.proc.terminate()
            child.proc.join(5)
        if child.proc.is_alive():
            child.proc.kill()
            child.proc.join(5)

    def check(self) -> list[str]:
        """One supervision pass; returns names of children respawned."""
        restarted = []
        now = self.clock()
        for child in self.children:
            if child.exhausted:
                continue
            if child.respawn_at:
                # Dead, waiting out its backoff delay.
                if now >= child.respawn_at:
                    child.respawn_at = 0.0
                    child.restarts += 1
                    self._start(child)
                    restarted.append(child.name)
                continue
            dead = not child.proc.is_alive()
            if dead and child.proc.exitcode == 0:
                continue  # clean exit (e.g. learner hit max_updates): done
            # Silence only counts after the startup grace: jax import + XLA
            # compile block the child's first heartbeat for minutes.
            silent = (
                now - child.heartbeat.value > self.heartbeat_timeout
                and now - child.started_at
                > self.heartbeat_timeout + self.startup_grace
            )
            if not (dead or silent):
                continue
            self._ensure_dead(child)
            if now - child.started_at >= self.restart_window_s:
                child.streak = 0  # it ran healthy for a full window
            child.streak += 1
            child.restart_times = [
                t for t in child.restart_times
                if now - t < self.restart_window_s
            ]
            if len(child.restart_times) >= self.max_restarts:
                child.exhausted = True
                print(
                    f"[supervisor] {child.name}: {len(child.restart_times)} "
                    f"restarts within {self.restart_window_s:.0f}s — budget "
                    "exhausted"
                )
                continue
            child.restart_times.append(now)
            # First crash in a streak respawns immediately (a one-off kill
            # should not cost latency); repeats back off exponentially.
            delay = (
                0.0
                if child.streak <= 1
                else min(
                    self.backoff_s * 2.0 ** (child.streak - 2),
                    self.backoff_max_s,
                )
            )
            if delay > 0:
                child.respawn_at = now + delay
                print(
                    f"[supervisor] {child.name}: crash streak "
                    f"{child.streak}, respawn in {delay:.1f}s"
                )
                continue
            child.restarts += 1
            self._start(child)
            restarted.append(child.name)
        return restarted

    def loop(self, poll: float | None = None) -> None:
        """Block until stop: supervise children, exit when all are gone or
        any child exhausted its restart budget."""
        poll = self.poll_s if poll is None else poll
        while not self.stop_event.is_set():
            if self.chaos is not None:
                for action, name in self.chaos.poll(self.children):
                    print(f"[chaos] {action} -> {name}")
                    from tpu_rl.obs.audit import append_jsonl

                    # Same record shape the autopilot's chaos poll audits,
                    # so report overlays read one schema.
                    append_jsonl(
                        self.audit_dir, "chaos.jsonl",
                        {"ev": "chaos", "action": action, "target": name},
                    )
            restarted = self.check()
            for name in restarted:
                print(f"[supervisor] restarted silent/dead child: {name}")
            self._emit_telemetry()
            if any(
                not c.proc.is_alive() and c.proc.exitcode == 0
                and not c.respawn_at
                for c in self.children
            ):
                # A role completed its bounded work (learner max_updates):
                # wind the whole deployment down.
                self.stop_event.set()
                break
            if any(c.exhausted for c in self.children):
                print("[supervisor] child exhausted restart budget; stopping")
                self.stop_event.set()
                break
            if all(
                not c.proc.is_alive() and not c.respawn_at
                for c in self.children
            ):
                break
            time.sleep(poll)
        self._emit_telemetry(force=True)

    # ------------------------------------------------------------ telemetry
    def enable_telemetry(self, cfg, stat_ip: str, stat_port: int) -> None:
        """Arm supervisor telemetry (restart/chaos counters shipped onto the
        fleet's stat channel). Idempotent: the first caller wins, so
        local_cluster's three role builders don't triple-publish."""
        if self._telem_cfg is None and cfg.telemetry_enabled:
            self._telem_cfg = (cfg, stat_ip, stat_port)

    def _emit_telemetry(self, force: bool = False) -> None:
        if self._telem_cfg is None:
            return
        if self._telem is None:
            # Lazy build on the first loop() pass: keeps construction off
            # Supervisor.__init__ (tests build bare supervisors) and off
            # import time.
            from tpu_rl.obs import MetricsRegistry, PeriodicSnapshot
            from tpu_rl.runtime.protocol import Protocol
            from tpu_rl.runtime.transport import make_data_pub

            cfg, ip, port = self._telem_cfg
            reg = MetricsRegistry(role="supervisor")
            pub = make_data_pub(cfg, ip, port, bind=False)
            emitter = PeriodicSnapshot(
                reg,
                lambda snap: pub.send(Protocol.Telemetry, snap),
                interval_s=cfg.telemetry_interval_s,
            )
            self._telem = (reg, pub, emitter)
        reg, pub, emitter = self._telem
        reg.counter("supervisor-restarts").set_total(
            sum(c.restarts for c in self.children)
        )
        reg.counter("supervisor-exhausted").set_total(
            sum(1 for c in self.children if c.exhausted)
        )
        reg.gauge("supervisor-children-alive").set(
            sum(1 for c in self.children if c.proc.is_alive())
        )
        if self.chaos is not None:
            reg.counter("chaos-process-kills").set_total(self.chaos.n_kills)
            reg.counter("chaos-process-stops").set_total(self.chaos.n_stops)
        if force:
            emitter.maybe_emit(now=float("inf"))
        else:
            emitter.maybe_emit()

    # ---------------------------------------------------------------- stop
    def stop(self, timeout: float = 10.0) -> None:
        self.stop_event.set()
        deadline = time.time() + timeout
        for c in self.children:
            c.proc.join(max(0.1, deadline - time.time()))
        for c in self.children:
            if c.proc.is_alive():
                c.proc.terminate()
        for c in self.children:
            c.proc.join(2)
            if c.proc.is_alive():
                c.proc.kill()
        if self._telem is not None:
            self._telem[1].close()
            self._telem = None

    def install_signal_handlers(self) -> None:
        """SIGINT/SIGTERM -> cooperative stop (reference ``main.py:493-502``)."""

        def handler(signum, frame):
            self.stop_event.set()

        signal.signal(signal.SIGINT, handler)
        signal.signal(signal.SIGTERM, handler)


# --------------------------------------------------------------------- roles
def learner_role(
    cfg: Config,
    machines: MachinesConfig,
    supervisor: Supervisor | None = None,
    max_updates: int | None = None,
    publish_interval: int = 1,
    seed: int = 0,
) -> Supervisor:
    """Spawn LearnerStorage + LearnerService sharing shm (reference
    ``learner_sub_process``, ``main.py:301-414``)."""
    from tpu_rl.runtime.learner_service import learner_main
    from tpu_rl.runtime.storage import storage_main

    sup = supervisor or Supervisor.from_config(cfg)
    # Supervisor restart/chaos counters ride the stat channel the storage
    # child SUB-binds on this host (same path as the learner's snapshots).
    sup.enable_telemetry(cfg, "127.0.0.1", machines.learner_port)
    layout = BatchLayout.from_config(cfg)
    from tpu_rl.config import is_off_policy

    capacity = cfg.buffer_size if is_off_policy(cfg.algo) else cfg.batch_size
    handles = alloc_handles(layout, capacity, ctx=sup.ctx)
    stat_array = sup.ctx.Array("f", STAT_SLOTS, lock=False)

    sup.spawn(
        "storage", storage_main, cfg, handles, machines.learner_port, stat_array
    )
    # Inference fleet port plan (collision-checked): replica 0 lives inside
    # the learner process (zero-staleness swaps); replicas 1..N-1 are
    # supervised children below.
    inference_ports = (
        machines.inference_ports(cfg) if cfg.act_mode == "remote" else None
    )
    sup.spawn(
        "learner",
        functools.partial(
            learner_main,
            max_updates=max_updates,
            publish_interval=publish_interval,
            seed=seed,
            # The centralized-inference ROUTER (act_mode="remote") binds in
            # the learner process; the service itself gates on act_mode.
            inference_port=(
                inference_ports[0] if inference_ports is not None else None
            ),
            # The stat channel storage SUB-binds: the learner's Telemetry
            # snapshots ship there (LearnerService gates on telemetry_enabled).
            stat_port=machines.learner_port,
        ),
        cfg,
        handles,
        machines.model_port,
        stat_array,
        # "auto": the learner owns the accelerator. "cpu": force the CPU
        # backend (CI, or when another process holds the chip).
        cpu_only=(cfg.learner_device == "cpu"),
    )
    if inference_ports is not None and cfg.inference_replicas > 1:
        from tpu_rl.fleet import replica_main

        for i in range(1, cfg.inference_replicas):
            # Child names follow the chaos plane's prefix convention:
            # ``kill:inference-1@t+8s`` targets exactly these processes.
            sup.spawn(
                f"inference-{i}",
                functools.partial(replica_main, seed=seed),
                cfg,
                i,
                inference_ports[i],
                "127.0.0.1",  # learner (model PUB) is on this host
                machines.model_port,
                machines.learner_port,
                cpu_only=(cfg.learner_device == "cpu"),
            )
    return sup


def worker_role(
    cfg: Config,
    machines: MachinesConfig,
    machine_idx: int = 0,
    supervisor: Supervisor | None = None,
    seed: int = 0,
) -> Supervisor:
    """Spawn num_p actor processes (reference ``worker_sub_process``,
    ``main.py:244-299``)."""
    from tpu_rl.runtime.worker import worker_main

    sup = supervisor or Supervisor.from_config(cfg)
    sup.enable_telemetry(cfg, machines.learner_ip, machines.learner_port)
    m = machines.workers[machine_idx]
    # Warm-start every worker from the newest checkpoint when one exists
    # (reference ``main.py:247-252``: the newest saved model is loaded into
    # each worker before spawn). Loaded once here, shared by all num_p
    # children; workers without a checkpoint start from random init and catch
    # the learner's first broadcast.
    initial_params = None
    if cfg.model_dir:
        from tpu_rl.checkpoint import restore_actor_params

        initial_params = restore_actor_params(cfg.model_dir, cfg.algo)
    for i in range(m.num_p):
        sup.spawn(
            f"worker-{machine_idx}-{i}",
            functools.partial(
                worker_main,
                seed=seed * 1000 + machine_idx * 100 + i,
                initial_params=initial_params,
                # A fleet (N > 1) hands workers the full endpoint list so
                # FleetClient can balance/hedge; a single service keeps the
                # scalar port and the plain InferenceClient.
                inference_port=(
                    None if cfg.act_mode != "remote"
                    else machines.inference_ports(cfg)
                    if cfg.inference_replicas > 1
                    else machines.inference_port
                ),
            ),
            cfg,
            i,
            m.manager_ip,
            m.port,
            machines.learner_ip,
            machines.model_port,
        )
    return sup


def manager_role(
    cfg: Config,
    machines: MachinesConfig,
    machine_idx: int = 0,
    supervisor: Supervisor | None = None,
) -> Supervisor:
    """Spawn the relay (reference ``manager_sub_process``, ``main.py:228-242``)."""
    from tpu_rl.runtime.manager import manager_main

    sup = supervisor or Supervisor.from_config(cfg)
    sup.enable_telemetry(cfg, machines.learner_ip, machines.learner_port)
    m = machines.workers[machine_idx]
    sup.spawn(
        f"manager-{machine_idx}",
        manager_main,
        cfg,
        m.port,
        machines.learner_ip,
        machines.learner_port,
    )
    return sup


def colocated_role(
    cfg: Config,
    machines: MachinesConfig | None = None,
    supervisor: Supervisor | None = None,
    max_updates: int | None = None,
    seed: int = 0,
) -> Supervisor:
    """Spawn the colocated-mode loop (``runtime/colocated.py``): envs live
    on the accelerator inside the jitted train program, so this host's
    whole deployment is ONE supervised child — no storage, manager or
    workers. Routing: ``cfg.sebulba_split > 0`` spawns the split
    actor/learner-group loop (``runtime/sebulba.py``), otherwise the fused
    Anakin program; ``cfg.multihost`` is honored either way — the child
    joins the jax.distributed runtime exactly like the learner role, one
    ``colocated_role`` invocation per pod host. ``machines`` is accepted
    (and ignored) so the CLI can dispatch every role through one
    signature."""
    del machines  # colocated mode has no fleet topology
    if cfg.sebulba_split > 0:
        from tpu_rl.runtime.sebulba import sebulba_main as child_main
    else:
        from tpu_rl.runtime.colocated import colocated_main as child_main

    sup = supervisor or Supervisor.from_config(cfg)
    sup.spawn(
        "colocated",
        functools.partial(child_main, max_updates=max_updates, seed=seed),
        cfg,
        # "auto": the fused program owns the accelerator. "cpu": force the
        # CPU backend (CI, or when another process holds the chip).
        cpu_only=(cfg.learner_device == "cpu"),
    )
    return sup


def local_cluster(
    cfg: Config,
    machines: MachinesConfig | None = None,
    max_updates: int | None = None,
    publish_interval: int = 1,
    seed: int = 0,
) -> Supervisor:
    """Everything on one host: learner + storage + manager + workers under a
    single supervisor. The smallest real deployment and the integration-test
    harness. In colocated mode the "cluster" collapses to the single fused
    child (``colocated_role``) — same entry point, same supervisor contract."""
    machines = machines or MachinesConfig()
    sup = Supervisor.from_config(cfg)
    if cfg.env_mode == "colocated":
        return colocated_role(
            cfg, machines, supervisor=sup, max_updates=max_updates, seed=seed
        )
    learner_role(
        cfg,
        machines,
        supervisor=sup,
        max_updates=max_updates,
        publish_interval=publish_interval,
        seed=seed,
    )
    manager_role(cfg, machines, supervisor=sup)
    worker_role(cfg, machines, supervisor=sup, seed=seed)
    return sup


def population_role(
    cfg: Config,
    machines: MachinesConfig | None = None,
    max_updates: int | None = None,
):
    """Build the PBT controller (``population/controller.py``). Unlike the
    other roles this returns the controller, not a Supervisor: the
    controller IS the orchestrator and runs in the calling process, owning
    its own supervisor whose children are the K ``member-<k>`` runs."""
    from tpu_rl.population import PopulationController

    return PopulationController(cfg, machines=machines, max_updates=max_updates)


def autopilot_role(
    cfg: Config,
    machines: MachinesConfig | None = None,
    manage_all: bool = False,
    seed: int = 0,
):
    """Build the fleet autopilot (``autopilot/controller.py``). Same
    controller-as-orchestrator shape as ``population_role``: the returned
    controller runs in the calling process and owns its own supervisor,
    whose children are the elastic ``inference-<i>`` replicas (and any
    autopilot-managed workers) it scales in response to the fleet's SLO
    burn rates, goodput and straggler scores."""
    from tpu_rl.autopilot import AutopilotController

    return AutopilotController(
        cfg, machines=machines, manage_all=manage_all, seed=seed
    )
