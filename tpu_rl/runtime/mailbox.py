"""Stat-mailbox slot layout: the one shm contract between storage and learner.

The mailbox is a lock-free ``mp.Array("f", STAT_SLOTS)`` created by the
runner (reference ``main.py:324-326``): storage writes fleet aggregates,
the learner reads them at its loss-log tick. The slot indices used to be
magic numbers duplicated at both ends (``storage._relay_stat`` and
``learner_service._log_fleet_stat``) — they live here now so the two sides
cannot drift.

Write protocol: storage fills the data slots FIRST and flips
``SLOT_ACTIVATE`` last; the learner checks the flag, reads, and clears it.
The float array has no torn reads per-slot, and the activate ordering keeps
the learner from logging a half-updated window.

The first 7 slots are the REFERENCE-PARITY path (the first three slots are
the reference's 3-float mailbox). The telemetry plane (``tpu_rl.obs``)
supersedes it in expressiveness but rides beside it, never replaces it.

Two durability slots (PR 9) ride outside the windowed-write protocol, each
with a single steady-state writer:

- ``SLOT_JOIN_REQ``: storage sets 1.0 when a NEW worker joins the
  membership table; the learner polls it, publishes current weights+ver
  immediately, and clears it. (Both sides write the one flag in opposite
  directions; the benign race — storage setting while the learner clears —
  loses one join nudge, which ``rebroadcast_idle_s`` covers anyway.)
- ``SLOT_RUN_EPOCH``: the learner writes ``epoch + 1.0`` once at startup
  (0.0 = unknown, so a zeroed fresh array reads as "no epoch yet"); storage
  ratchets its stale-frame fence from it. The mp.Array outlives child
  respawns, so a restarted storage re-learns the current epoch instantly —
  before any new-epoch frame could reach it — which is what makes
  stale-epoch rejection deterministic rather than a broadcast race.
"""

from __future__ import annotations

SLOT_GAME_COUNT = 0  # fleet global episode count
SLOT_MEAN_REW = 1  # windowed (STAT_WINDOW-episode) mean reward
SLOT_ACTIVATE = 2  # storage sets 1.0 after a write; learner clears
SLOT_REJECTED = 3  # corrupt-frame drops across every transport hop
SLOT_MODEL_LOADS = 4  # fleet total worker model reloads
SLOT_RELAY_DROPPED = 5  # manager drop-oldest evictions
SLOT_FORWARD_BYTES = 6  # manager -> storage forwarded wire bytes
SLOT_JOIN_REQ = 7  # storage: new member joined -> learner: push weights now
SLOT_RUN_EPOCH = 8  # learner's run epoch + 1 (0 = unknown); storage reads

STAT_SLOTS = 9
