"""Stat-mailbox slot layout: the one shm contract between storage and learner.

The mailbox is a lock-free ``mp.Array("f", STAT_SLOTS)`` created by the
runner (reference ``main.py:324-326``): storage writes fleet aggregates,
the learner reads them at its loss-log tick. The slot indices used to be
magic numbers duplicated at both ends (``storage._relay_stat`` and
``learner_service._log_fleet_stat``) — they live here now so the two sides
cannot drift.

Write protocol: storage fills the data slots FIRST and flips
``SLOT_ACTIVATE`` last; the learner checks the flag, reads, and clears it.
The float array has no torn reads per-slot, and the activate ordering keeps
the learner from logging a half-updated window.

The 7-slot mailbox is the REFERENCE-PARITY path (the first three slots are
the reference's 3-float mailbox). The telemetry plane (``tpu_rl.obs``)
supersedes it in expressiveness but rides beside it, never replaces it.
"""

from __future__ import annotations

SLOT_GAME_COUNT = 0  # fleet global episode count
SLOT_MEAN_REW = 1  # windowed (STAT_WINDOW-episode) mean reward
SLOT_ACTIVATE = 2  # storage sets 1.0 after a write; learner clears
SLOT_REJECTED = 3  # corrupt-frame drops across every transport hop
SLOT_MODEL_LOADS = 4  # fleet total worker model reloads
SLOT_RELAY_DROPPED = 5  # manager drop-oldest evictions
SLOT_FORWARD_BYTES = 6  # manager -> storage forwarded wire bytes

STAT_SLOTS = 7
