"""Loader for the native C++ codec (``native/codec.cpp``).

Builds the shared library on demand with ``g++ -O3`` (cached next to the
source), binds it through ctypes — which releases the GIL for the duration of
each call, so compression overlaps the Python event loop — and exposes
``compress``/``decompress``/``crc32``. When no toolchain is available the
module still imports and ``LIB`` is None; the protocol layer falls back to
zlib (the reference's equivalent native dep is c-blosc2,
``/root/reference/utils/utils.py:244-249``).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SRC = os.path.join(_REPO, "native", "codec.cpp")
_BUILD_DIR = os.path.join(_REPO, "native", "build")

_lock = threading.Lock()

# Escape hatch + A/B switch: TPU_RL_NATIVE=0 disables the native codec
# entirely (pure-Python zlib/LZ4 fallback everywhere). CI runs the relay and
# protocol suites under both values so the fallback path can't rot.
_DISABLED = os.environ.get("TPU_RL_NATIVE", "1") == "0"

# The exact command _build() runs; surfaced in the one-time fallback warning
# so an operator can reproduce the failure by hand.
BUILD_CMD = "g++ -O3 -shared -fPIC -std=c++17 -o <out.so> " + SRC

_warned_fallback = False


def _warn_fallback(reason: str) -> None:
    """Warn ONCE that the native codec is unavailable, naming the exact
    compile command. The previous behavior — silently falling back to zlib —
    hid both missing toolchains and stale-binary rebuild failures, so a fleet
    could quietly run the slow path for weeks."""
    global _warned_fallback
    if _warned_fallback or _DISABLED:
        return
    _warned_fallback = True
    print(
        f"tpu_rl.native: falling back to pure-Python codec ({reason}); "
        f"to build the native library run: {BUILD_CMD}",
        file=sys.stderr,
    )


def _build() -> str | None:
    """Build from source, caching by source hash: the artifact name embeds
    the sha256 of codec.cpp, so a binary built from an OLDER source can never
    shadow the current .cpp (the previous mtime comparison trusted whatever
    a checkout happened to produce, e.g. a committed prebuilt .so). This is
    a staleness guard, not tamper-proofing — build/ must stay writable only
    by the deploy user, and is untracked/.gitignored."""
    if not os.path.exists(SRC):
        _warn_fallback(f"source missing: {SRC}")
        return None
    with open(SRC, "rb") as f:
        src_hash = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_BUILD_DIR, f"libtpurl_codec_{src_hash}.so")
    if os.path.exists(so):
        return so
    # Prune artifacts of older sources (each codec.cpp edit would otherwise
    # leave an orphaned .so behind forever).
    try:
        for name in os.listdir(_BUILD_DIR):
            if name.startswith("libtpurl_codec_") and name.endswith(".so"):
                os.unlink(os.path.join(_BUILD_DIR, name))
    except OSError:
        pass
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # Atomic build: compile to a temp name, rename into place (concurrent
    # role processes may race to build at first launch).
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, so)
        return so
    except subprocess.CalledProcessError as e:
        stderr = (e.stderr or b"").decode(errors="replace").strip()
        _warn_fallback(f"compile failed: {stderr.splitlines()[-1] if stderr else e}")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    except (subprocess.SubprocessError, OSError) as e:
        _warn_fallback(f"build failed: {type(e).__name__}: {e}")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load() -> ctypes.CDLL | None:
    if _DISABLED:
        return None
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        _warn_fallback(f"dlopen failed: {e}")
        return None
    i64, u32, buf = ctypes.c_int64, ctypes.c_uint32, ctypes.c_char_p
    lib.tpurl_compress_bound.restype = i64
    lib.tpurl_compress_bound.argtypes = [i64]
    lib.tpurl_compress.restype = i64
    lib.tpurl_compress.argtypes = [buf, i64, ctypes.c_void_p, i64]
    lib.tpurl_decompress.restype = i64
    lib.tpurl_decompress.argtypes = [buf, i64, ctypes.c_void_p, i64]
    lib.tpurl_crc32.restype = u32
    lib.tpurl_crc32.argtypes = [buf, i64, u32]
    pp = ctypes.POINTER(ctypes.c_char_p)
    batch_args = [
        pp,                               # parts (flattened pointers)
        ctypes.POINTER(i64),              # lens
        ctypes.POINTER(ctypes.c_int32),   # nparts
        i64,                              # n_frames
        u32,                              # trace_kinds bitmask
        ctypes.c_uint8,                   # max_proto
        ctypes.POINTER(ctypes.c_uint8),   # out verdicts
    ]
    lib.tpurl_validate_batch.restype = i64
    lib.tpurl_validate_batch.argtypes = batch_args
    lib.tpurl_validate_batch_crc.restype = i64
    lib.tpurl_validate_batch_crc.argtypes = batch_args
    return lib


with _lock:
    LIB = _load()


def available() -> bool:
    return LIB is not None


def compress(data: bytes) -> bytes:
    assert LIB is not None
    bound = LIB.tpurl_compress_bound(len(data))
    out = ctypes.create_string_buffer(bound)
    n = LIB.tpurl_compress(data, len(data), out, bound)
    if n < 0:
        raise RuntimeError(f"native compress failed: {n}")
    return out.raw[:n]


def decompress(data: bytes, raw_size: int) -> bytes:
    assert LIB is not None
    out = ctypes.create_string_buffer(raw_size) if raw_size else b""
    if raw_size == 0:
        return b""
    n = LIB.tpurl_decompress(data, len(data), out, raw_size)
    if n != raw_size:
        raise RuntimeError(f"native decompress failed: {n} != {raw_size}")
    return out.raw[:n]


def crc32(data: bytes, seed: int = 0) -> int:
    assert LIB is not None
    return int(LIB.tpurl_crc32(data, len(data), seed))


def validate_batch(
    frames: list[list[bytes]],
    trace_kinds_mask: int,
    max_proto: int,
    check_crc: bool = False,
) -> list[int]:
    """Validate N multipart frames in ONE native call (GIL released for the
    whole batch). ``frames`` is a list of part-lists as drained off a Sub;
    returns one verdict per frame, 0 = valid (see Verdict in codec.cpp).
    Frames whose part count exceeds the native cap (16) are rejected without
    entering the library. With ``check_crc`` the body crc32 is verified too —
    the storage-edge variant; without it this is relay-grade ``peek``."""
    assert LIB is not None
    n = len(frames)
    if n == 0:
        return []
    flat: list[bytes] = []
    nparts = (ctypes.c_int32 * n)()
    for i, parts in enumerate(frames):
        nparts[i] = len(parts)
        if 0 < len(parts) <= 16:
            flat.extend(parts)
    total = len(flat)
    # c_char_p arrays alias the bytes objects' buffers directly (no copy);
    # `flat` keeps them alive across the call.
    ptrs = (ctypes.c_char_p * total)(*flat) if total else (ctypes.c_char_p * 1)()
    lens = (ctypes.c_int64 * max(total, 1))(*[len(p) for p in flat])
    out = (ctypes.c_uint8 * n)()
    fn = LIB.tpurl_validate_batch_crc if check_crc else LIB.tpurl_validate_batch
    rc = fn(
        ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_char_p)),
        lens,
        nparts,
        n,
        trace_kinds_mask & 0xFFFFFFFF,
        max_proto & 0xFF,
        out,
    )
    if rc < 0:
        raise RuntimeError(f"native validate_batch failed: {rc}")
    return list(out)
