"""Loader for the native C++ codec (``native/codec.cpp``).

Builds the shared library on demand with ``g++ -O3`` (cached next to the
source), binds it through ctypes — which releases the GIL for the duration of
each call, so compression overlaps the Python event loop — and exposes
``compress``/``decompress``/``crc32``. When no toolchain is available the
module still imports and ``LIB`` is None; the protocol layer falls back to
zlib (the reference's equivalent native dep is c-blosc2,
``/root/reference/utils/utils.py:244-249``).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SRC = os.path.join(_REPO, "native", "codec.cpp")
_BUILD_DIR = os.path.join(_REPO, "native", "build")

_lock = threading.Lock()


def _build() -> str | None:
    """Build from source, caching by source hash: the artifact name embeds
    the sha256 of codec.cpp, so a binary built from an OLDER source can never
    shadow the current .cpp (the previous mtime comparison trusted whatever
    a checkout happened to produce, e.g. a committed prebuilt .so). This is
    a staleness guard, not tamper-proofing — build/ must stay writable only
    by the deploy user, and is untracked/.gitignored."""
    if not os.path.exists(SRC):
        return None
    with open(SRC, "rb") as f:
        src_hash = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_BUILD_DIR, f"libtpurl_codec_{src_hash}.so")
    if os.path.exists(so):
        return so
    # Prune artifacts of older sources (each codec.cpp edit would otherwise
    # leave an orphaned .so behind forever).
    try:
        for name in os.listdir(_BUILD_DIR):
            if name.startswith("libtpurl_codec_") and name.endswith(".so"):
                os.unlink(os.path.join(_BUILD_DIR, name))
    except OSError:
        pass
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # Atomic build: compile to a temp name, rename into place (concurrent
    # role processes may race to build at first launch).
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, so)
        return so
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load() -> ctypes.CDLL | None:
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    i64, u32, buf = ctypes.c_int64, ctypes.c_uint32, ctypes.c_char_p
    lib.tpurl_compress_bound.restype = i64
    lib.tpurl_compress_bound.argtypes = [i64]
    lib.tpurl_compress.restype = i64
    lib.tpurl_compress.argtypes = [buf, i64, ctypes.c_void_p, i64]
    lib.tpurl_decompress.restype = i64
    lib.tpurl_decompress.argtypes = [buf, i64, ctypes.c_void_p, i64]
    lib.tpurl_crc32.restype = u32
    lib.tpurl_crc32.argtypes = [buf, i64, u32]
    return lib


with _lock:
    LIB = _load()


def available() -> bool:
    return LIB is not None


def compress(data: bytes) -> bytes:
    assert LIB is not None
    bound = LIB.tpurl_compress_bound(len(data))
    out = ctypes.create_string_buffer(bound)
    n = LIB.tpurl_compress(data, len(data), out, bound)
    if n < 0:
        raise RuntimeError(f"native compress failed: {n}")
    return out.raw[:n]


def decompress(data: bytes, raw_size: int) -> bytes:
    assert LIB is not None
    out = ctypes.create_string_buffer(raw_size) if raw_size else b""
    if raw_size == 0:
        return b""
    n = LIB.tpurl_decompress(data, len(data), out, raw_size)
    if n != raw_size:
        raise RuntimeError(f"native decompress failed: {n} != {raw_size}")
    return out.raw[:n]


def crc32(data: bytes, seed: int = 0) -> int:
    assert LIB is not None
    return int(LIB.tpurl_crc32(data, len(data), seed))
