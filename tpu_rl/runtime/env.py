"""Environment adapter.

Parity with the reference's ``EnvBase``
(``/root/reference/agents/worker_module/env_maker.py:6-31``): gymnasium env
with float32 flattened observations, ``terminated or truncated`` collapsed to
one done flag, and continuous actions adapted between the policy's flat vector
and the env's Box space. The conv/image path the reference carries disabled
(``utils/utils.py:201-226``) is represented by the same config flags but
implemented as a plain resize+gray transform when enabled.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from tpu_rl.config import Config


def probe_spaces(cfg: Config) -> Config:
    """Fill runtime-derived obs/action-space fields by probing the env once
    (reference ``main.py:82-95``).

    Colocated mode reads the spaces off the jittable env spec instead —
    no ``gym.make``, no gymnasium import at all: the spec IS the env, so
    constructing a throwaway host env just to read its spaces would be
    pure overhead (and a hard dependency colocated deployments don't need).
    """
    if cfg.env_mode == "colocated":
        from tpu_rl.envs import get_spec

        spec = get_spec(cfg.env)
        return cfg.replace(
            obs_shape=spec.obs_shape,
            action_space=spec.action_space,
            is_continuous=spec.is_continuous,
        )
    import gymnasium as gym

    env = gym.make(cfg.env)
    obs_space = env.observation_space
    act_space = env.action_space
    env.close()
    if hasattr(act_space, "n"):  # Discrete
        action_space, continuous = int(act_space.n), False
    else:  # Box
        action_space, continuous = int(np.prod(act_space.shape)), True
    if cfg.need_conv:
        # The adapter resizes to (height, width[, c]) then flattens; obs_shape
        # must describe the PREPROCESSED observation the models consume.
        channels = 1 if cfg.is_gray else (
            obs_space.shape[-1] if len(obs_space.shape) == 3 else 1
        )
        obs_shape: tuple[int, ...] = (cfg.height * cfg.width * channels,)
    else:
        obs_shape = tuple(int(s) for s in obs_space.shape)
    return cfg.replace(
        obs_shape=obs_shape,
        action_space=action_space,
        is_continuous=continuous,
    )


class EnvAdapter:
    """Reset/step with preprocessed observations and a single done flag."""

    def __init__(self, cfg: Config, seed: int | None = None):
        import gymnasium as gym

        self.cfg = cfg
        self.env = gym.make(cfg.env)
        self._seed = seed
        self._continuous = cfg.is_continuous
        self._act_space = self.env.action_space

    def _preprocess(self, obs: Any) -> np.ndarray:
        arr = np.asarray(obs, np.float32)
        if self.cfg.need_conv:
            arr = self._conv_preprocess(arr)
        # Models consume flat vectors; preprocessed obs always flatten.
        return arr.reshape(-1) if arr.ndim > 1 else arr

    def _conv_preprocess(self, arr: np.ndarray) -> np.ndarray:
        """Resize (+optional grayscale) image observations — the capability the
        reference gates behind ``need_conv`` but leaves disabled."""
        h, w = self.cfg.height, self.cfg.width
        if self.cfg.is_gray and arr.ndim == 3 and arr.shape[-1] == 3:
            arr = arr @ np.asarray([0.299, 0.587, 0.114], np.float32)
        # Nearest-neighbor resize without cv2 (not in the image).
        ys = (np.linspace(0, arr.shape[0] - 1, h)).astype(np.int64)
        xs = (np.linspace(0, arr.shape[1] - 1, w)).astype(np.int64)
        return arr[np.ix_(ys, xs)].astype(np.float32) / 255.0

    def reset(self) -> np.ndarray:
        if self._seed is not None:
            obs, _ = self.env.reset(seed=self._seed)
            self._seed = None  # gymnasium: seed once, then evolve
        else:
            obs, _ = self.env.reset()
        return self._preprocess(obs)

    def step(self, action: np.ndarray) -> tuple[np.ndarray, float, bool]:
        """action: policy-side float vector — (1,) index for discrete, (A,)
        for continuous (reference ``action_preprocess``,
        ``env_maker.py:15-26``).

        ``cfg.action_repeat > 1`` holds each policy action for k underlying
        env steps (frame-skip), summing rewards and stopping early on done —
        the policy's MDP is the wrapped env, so everything downstream stays
        exactly on-policy. Standard practice (Atari frame-skip); on
        sparse-goal continuous-control envs it also makes per-step
        exploration noise piecewise-constant, which is what lets a Gaussian
        policy find MountainCarContinuous's goal at all (measured: iid
        noise 0/20 episodes reach the goal; the same noise held 8 steps,
        16/20)."""
        if self._continuous:
            env_action = np.asarray(action, np.float32).reshape(
                self._act_space.shape
            )
        else:
            env_action = int(np.asarray(action).reshape(-1)[0])
        total_rew, done = 0.0, False
        for _ in range(self.cfg.action_repeat):
            obs, rew, term, trunc, _info = self.env.step(env_action)
            total_rew += float(rew)
            if term or trunc:
                done = True
                break
        return self._preprocess(obs), total_rew, done

    def close(self) -> None:
        self.env.close()
