"""Zero-dependency live fleet dashboard: ``python -m tpu_rl.obs.top``.

Polls the storage (or colocated) telemetry HTTP server — ``/metrics``
(Prometheus text), ``/goodput`` (ledger breakdown + straggler top-k) and
``/slo`` (verdicts), plus ``/autopilot`` when a pilot is wired — and
renders a terminal view on stdlib curses: per-role goodput bars, bucket
breakdowns, throughput/MFU, the LEARN panel (entropy/KL/ESS update-math
diagnostics with the ESS-vs-staleness curve from the
``learner-diag-by-stale-*`` families), the straggler list, autopilot
replica/worker counts with recent actions and per-rule cooldown status,
and SLO verdicts. When the run-history plane is on, each panel gains a
unicode-block sparkline fed from ``GET /query`` (blank when the plane
is off). Nothing beyond the standard library; point it at
any fleet with the plane on::

    python -m tpu_rl.obs.top --url http://learner-host:9090/metrics

``--once`` renders a single frame to stdout without curses (no tty
needed) — the shape ``make goodput-smoke`` and CI drive. ``q`` quits the
live view. The frame builder is a pure function over the fetched
documents (``build_frame``), so the render is golden-testable with a
mocked terminal.
"""

from __future__ import annotations

import argparse
import json
import re
import urllib.error
import urllib.request
from urllib.parse import quote

from tpu_rl.obs.goodput import BUCKETS

DEFAULT_URL = "http://127.0.0.1:9090/metrics"
_SAMPLE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


# ------------------------------------------------------------------ fetch
def fetch(url: str, timeout: float = 2.0):
    """GET → (status, body str). An HTTPError with a body (the 503 /slo
    failing-verdict case) is a real answer, not a transport failure."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()
    except OSError as e:
        return None, str(e)


def fetch_json(url: str, timeout: float = 2.0):
    status, body = fetch(url, timeout)
    if status is None:
        return None
    try:
        return json.loads(body)
    except ValueError:
        return None


# ------------------------------------------------------------------ parse
def parse_prometheus(text: str) -> list:
    """Exposition text → [(name, labels dict, value)] (comments skipped)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        m = _SAMPLE.match(head)
        if m is None:
            continue
        try:
            value = float(val)
        except ValueError:
            continue
        labels = dict(_LABEL.findall(m.group(3) or ""))
        out.append((m.group(1), labels, value))
    return out


def _source_key(role: str, labels: dict) -> str:
    wid = labels.get("wid")
    return f"{role} wid={wid}" if wid is not None else role


def goodput_rows(samples: list) -> dict:
    """Per-source goodput view from the ``*_goodput_ratio`` /
    ``*_time_*_ratio`` gauge families → {display key: {goodput, buckets}}."""
    rows: dict = {}
    bucket_names = {b.replace("-", "_"): b for b in BUCKETS}
    for name, labels, value in samples:
        if name.endswith("_goodput_ratio"):
            role = name[: -len("_goodput_ratio")]
            key = _source_key(role, labels)
            rows.setdefault(key, {"goodput": 0.0, "buckets": {}})
            rows[key]["goodput"] = value
        elif name.endswith("_ratio") and "_time_" in name:
            role, _, rest = name.partition("_time_")
            bucket = bucket_names.get(rest[: -len("_ratio")])
            if bucket is None:
                continue
            key = _source_key(role, labels)
            rows.setdefault(key, {"goodput": 0.0, "buckets": {}})
            rows[key]["buckets"][bucket] = value
    return rows


def _scalar(samples: list, name: str):
    vals = [v for n, _l, v in samples if n == name]
    return max(vals) if vals else None


_DIAG_GLOBAL_PREFIX = "learner_diag_"
_DIAG_BUCKET_PREFIX = "learner_diag_by_stale_"


def learn_rows(samples: list) -> tuple[dict, dict]:
    """Learning-dynamics view from the ``learner_diag_*`` gauge families →
    (global {metric: value}, per-staleness {bucket label: {metric: value}}).
    Histogram families (``*_hist_*``) are skipped — the panel shows the
    current gauge values, not the distribution."""
    glob: dict = {}
    buckets: dict = {}
    for name, labels, value in samples:
        if "_hist" in name:
            continue
        if name.startswith(_DIAG_BUCKET_PREFIX):
            label = labels.get("stale_bucket")
            if label is None:
                continue
            metric = name[len(_DIAG_BUCKET_PREFIX):]
            buckets.setdefault(label, {})[metric] = value
        elif name.startswith(_DIAG_GLOBAL_PREFIX):
            glob[name[len(_DIAG_GLOBAL_PREFIX):]] = value
    return glob, buckets


def _stale_sort_key(label: str) -> float:
    head = label.split("-")[0].rstrip("+")
    try:
        return float(head)
    except ValueError:
        return float("inf")


def bar(frac: float, width: int = 20) -> str:
    frac = min(1.0, max(0.0, frac))
    filled = round(frac * width)
    return "#" * filled + "-" * (width - filled)


# --------------------------------------------------------------- sparklines
SPARK_BLOCKS = "▁▂▃▄▅▆▇█"

# History-channel tails worth a sparkline (suffix match against the
# ``/query`` series listing; labeled per-wid channels are skipped — the
# panel shows role-level trends, the straggler list covers outliers).
SPARK_SUFFIXES = (
    "-env-steps-per-s",
    "-throughput",
    "-updates-per-s",
    "-mfu",
    "-goodput-ratio",
    "-mean-episode-return",
    "-diag-ess",
)
_SPARK_FETCH_CAP = 12  # bound the per-frame /query fan-out
_SPARK_WIDTH = 24


def sparkline(values: list, width: int = _SPARK_WIDTH) -> str:
    """Values -> a fixed-width unicode-block trend line (empty string on
    no data). Longer series are bucket-mean compressed to ``width``; a
    flat series renders mid-height, not empty."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    if len(vals) > width:
        per = len(vals) / width
        vals = [
            sum(chunk) / len(chunk)
            for chunk in (
                vals[int(i * per): max(int(i * per) + 1, int((i + 1) * per))]
                for i in range(width)
            )
        ]
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK_BLOCKS[3] * len(vals)
    scale = (len(SPARK_BLOCKS) - 1) / (hi - lo)
    return "".join(SPARK_BLOCKS[int((v - lo) * scale)] for v in vals)


def collect_history(
    base: str, timeout: float = 2.0, fetch_json_fn=fetch_json
) -> dict | None:
    """Poll the run-history plane once: the ``/query`` series listing,
    then raw points for every spark-worthy channel -> ``{channel tail:
    [values]}``. Returns None when the plane is off (the endpoint 404s
    with an error body, or the server predates it) — every panel then
    renders without its trend line, never an error."""
    listing = fetch_json_fn(base + "/query", timeout)
    if not isinstance(listing, dict) or "series" not in listing:
        return None
    out: dict = {}
    for row in listing.get("series", ()):
        name = (row or {}).get("name")
        if not isinstance(name, str) or "{" in name:
            continue
        tail = name.rpartition("/")[2]
        if tail in out or not tail.endswith(SPARK_SUFFIXES):
            continue
        if len(out) >= _SPARK_FETCH_CAP:
            break
        doc = fetch_json_fn(
            base + "/query?metric=" + quote(name, safe=""), timeout
        )
        points = (doc or {}).get("points") if isinstance(doc, dict) else None
        if points:
            out[tail] = [p[1] for p in points if isinstance(p, list)]
    return out


# ------------------------------------------------------------------ frame
_HOT_METRICS = (
    ("learner tps", "learner_throughput", "{:,.0f}"),
    ("colocated tps", "colocated_env_steps_per_s", "{:,.0f}"),
    ("mfu", "learner_mfu", "{:.2%}"),
    ("colocated mfu", "colocated_mfu", "{:.2%}"),
    ("recompiles", "learner_xla_recompiles", "{:.0f}"),
)


def _spark(history: dict | None, tail: str) -> str:
    vals = (history or {}).get(tail)
    return sparkline(vals) if vals else ""


def build_frame(
    samples: list,
    goodput_doc: dict | None,
    slo_doc: dict | None,
    url: str = DEFAULT_URL,
    width: int = 100,
    autopilot_doc: dict | None = None,
    history: dict | None = None,
) -> list:
    """The whole dashboard as a list of text lines (pure; golden-tested).
    ``history`` is the :func:`collect_history` channel-tail dict; None
    (plane off) renders every panel without its trend line."""
    lines = [f"tpu_rl top — {url}  (q quits)", ""]
    rows = goodput_rows(samples)
    lines.append("GOODPUT (compute share of wall time, per role)")
    if not rows:
        lines.append("  no goodput gauges yet (ledger warming up?)")
    for key in sorted(rows):
        row = rows[key]
        g = row["goodput"]
        spark = _spark(history, f"{key}-goodput-ratio")
        tail = f"  {spark}" if spark else ""
        lines.append(f"  {key:<16} [{bar(g)}] {g * 100:5.1f}%{tail}")
        top = sorted(
            row["buckets"].items(), key=lambda kv: -kv[1]
        )[:4]
        detail = "  ".join(f"{b} {v * 100:.0f}%" for b, v in top if v > 0)
        if detail:
            lines.append(f"  {'':<16} {detail}")
    lines.append("")

    hot = []
    for label, metric, fmt in _HOT_METRICS:
        v = _scalar(samples, metric)
        if v is not None:
            hot.append(f"{label} {fmt.format(v)}")
    if hot:
        lines.append("THROUGHPUT  " + "   ".join(hot))
        for label, metric, _fmt in _HOT_METRICS:
            spark = _spark(history, metric.replace("_", "-"))
            if spark:
                lines.append(f"  {label:<14} {spark}")
        lines.append("")

    diag, diag_buckets = learn_rows(samples)
    if diag or diag_buckets:
        lines.append("LEARN (update-math diagnostics; learner-diag-* gauges)")
        head = []
        for label, metric, fmt in (
            ("entropy", "entropy", "{:.3f}"),
            ("kl", "approx_kl", "{:.4f}"),
            ("ess", "ess", "{:.2f}"),
            ("clip", "clip_frac", "{:.2f}"),
            ("ev", "explained_variance", "{:.2f}"),
            ("upd-ratio", "update_ratio", "{:.2e}"),
        ):
            v = diag.get(metric)
            if v is not None:
                head.append(f"{label} {fmt.format(v)}")
        if head:
            lines.append("  " + "   ".join(head))
        grads = [
            f"{g} {diag[f'grad_norm_{g}']:.2e}"
            for g in ("torso", "cell", "heads")
            if f"grad_norm_{g}" in diag
        ]
        if grads:
            lines.append("  grad-norm  " + "   ".join(grads))
        # ESS vs staleness: THE off-policy health curve (collapse at high
        # lag is the signal the update:data controller will regulate on).
        for label in sorted(diag_buckets, key=_stale_sort_key):
            b = diag_buckets[label]
            ess = b.get("ess")
            if ess is None:
                continue
            rows = b.get("rows")
            tail = f"  ({rows:.0f} rows)" if rows is not None else ""
            lines.append(
                f"  stale {label:>5}  [{bar(ess)}] ess {ess:.2f}{tail}"
            )
        lines.append("")

    lines.append("STRAGGLERS (robust z vs fleet median; report-only)")
    stragglers = (goodput_doc or {}).get("stragglers") or []
    if not stragglers:
        lines.append("  none")
    for e in stragglers:
        sig = e.get("signals", {})
        rate = sig.get("frame-rate")
        stale = sig.get("staleness")
        rtt = sig.get("rtt")
        lines.append(
            f"  wid {e.get('wid')}: score {e.get('score', 0):.1f}"
            f"  rate {rate if rate is not None else '—'}/s"
            f"  staleness {stale if stale is not None else '—'}"
            f"  rtt {rtt if rtt is not None else '—'}"
        )
    lines.append("")

    if autopilot_doc is not None:
        lines.append(
            f"AUTOPILOT  replicas {autopilot_doc.get('replicas', '—')}"
            f"/{autopilot_doc.get('replica_capacity', '—')}"
            f"  workers {autopilot_doc.get('workers', '—')}"
            f"  actions {(autopilot_doc.get('counts') or {}).get('actions', 0)}"
        )
        actions = autopilot_doc.get("actions") or []
        if not actions:
            lines.append("  no actions yet")
        for a in actions[-5:]:
            lines.append(
                f"  {a.get('action', '?'):<10} {a.get('target', '?'):<9}"
                f" {a.get('from', '?')}->{a.get('to', '?')}"
                f"  {a.get('reason', '')}"
            )
        cooldowns = autopilot_doc.get("cooldowns") or {}
        for rule, remaining in sorted(cooldowns.items()):
            state = "armed" if remaining <= 0 else f"cooldown {remaining:.1f}s"
            lines.append(f"  [{state:>14}] {rule}")
        lines.append("")

    if slo_doc is not None:
        ok = slo_doc.get("ok")
        verdict = "PASS" if ok else ("no data" if ok is None else "FAIL")
        lines.append(f"SLO  {verdict}")
        for rule in slo_doc.get("rules", []):
            if not isinstance(rule, dict):
                lines.append(f"  {rule}")
                continue
            spec = rule.get("rule") or rule.get("spec") or "?"
            r_ok = rule.get("ok")
            mark = "ok " if r_ok else ("?? " if r_ok is None else "FAIL")
            val = rule.get("value")
            tail = f"  (value {val})" if val is not None else ""
            lines.append(f"  [{mark}] {spec}{tail}")
    else:
        lines.append("SLO  (no /slo endpoint — no slo_spec configured)")
    return [ln[:width] for ln in lines]


def collect(url: str, timeout: float = 2.0):
    """Fetch all five endpoints once → (samples, goodput, slo, autopilot,
    history, ok). ``/autopilot`` is None on fleets without the pilot
    wired (the endpoint 404s with a JSON error body — filtered here);
    ``history`` is None on fleets without the run-history plane."""
    base = url.rsplit("/", 1)[0] if url.endswith("/metrics") else url
    status, body = fetch(url, timeout)
    ok = status == 200
    samples = parse_prometheus(body) if ok else []
    goodput_doc = fetch_json(base + "/goodput", timeout)
    slo_doc = fetch_json(base + "/slo", timeout)
    autopilot_doc = fetch_json(base + "/autopilot", timeout)
    if isinstance(autopilot_doc, dict) and "error" in autopilot_doc:
        autopilot_doc = None
    history = collect_history(base, timeout)
    return samples, goodput_doc, slo_doc, autopilot_doc, history, ok


# ----------------------------------------------------------------- curses
def draw(stdscr, lines: list) -> None:
    import curses

    stdscr.erase()
    h, w = stdscr.getmaxyx()
    for y, line in enumerate(lines[: max(0, h - 1)]):
        try:
            stdscr.addnstr(y, 0, line, max(1, w - 1))
        except curses.error:
            pass  # terminal shrank mid-draw: clip, don't crash
    stdscr.refresh()


def _loop(stdscr, args) -> int:
    import curses

    try:
        curses.curs_set(0)
    except curses.error:
        pass
    stdscr.timeout(int(args.interval * 1000))
    while True:
        samples, goodput_doc, slo_doc, ap_doc, history, ok = collect(
            args.url, args.timeout
        )
        lines = build_frame(
            samples, goodput_doc, slo_doc, url=args.url,
            autopilot_doc=ap_doc, history=history,
        )
        if not ok:
            lines.insert(1, f"  !! /metrics unreachable at {args.url}")
        draw(stdscr, lines)
        ch = stdscr.getch()
        if ch in (ord("q"), ord("Q")):
            return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_rl.obs.top",
        description="live fleet dashboard over /metrics + /goodput + /slo",
    )
    ap.add_argument("--url", default=DEFAULT_URL, help="metrics endpoint")
    ap.add_argument("--interval", type=float, default=2.0, help="poll seconds")
    ap.add_argument("--timeout", type=float, default=2.0, help="fetch timeout")
    ap.add_argument(
        "--once", action="store_true",
        help="render one frame to stdout (no curses, no tty) and exit",
    )
    args = ap.parse_args(argv)

    if args.once:
        samples, goodput_doc, slo_doc, ap_doc, history, ok = collect(
            args.url, args.timeout
        )
        frame = build_frame(
            samples, goodput_doc, slo_doc, url=args.url,
            autopilot_doc=ap_doc, history=history,
        )
        print("\n".join(frame))
        return 0 if ok else 1

    import curses

    return curses.wrapper(_loop, args)


if __name__ == "__main__":
    raise SystemExit(main())
