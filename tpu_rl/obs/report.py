"""Post-hoc run reports off the history store + audit jsonl streams.

``python -m tpu_rl.obs.report <result_dir>`` renders three artifacts next
to the run's history directory:

- ``report.json`` — the machine-readable summary (channel stats + event
  timeline) the report tests schema-pin and other tooling can consume;
- ``report.md`` — the human summary: one stats row per charted channel,
  one timeline row per fleet event;
- ``report.html`` — self-contained (inline SVG, no JS, no external
  assets): one sparkline chart per channel with chaos / rollback /
  resume / population / autopilot events overlaid as vertical rules.

Events come from the unified :mod:`tpu_rl.obs.audit` jsonl streams; a
stream that does not exist contributes nothing (a run without chaos has
no chaos events — that is data, not an error). Channels default to the
fleet-health set every prior plane publishes (throughput, MFU, goodput
ratios, staleness quantiles, learn-diag ESS, episode return) and can be
overridden with ``--channels`` fnmatch patterns.
"""

from __future__ import annotations

import argparse
import fnmatch
import html
import json
import os
import sys
import time

from tpu_rl.obs.history import HistoryReader, downsample

# The audit streams overlaid as report events: filename -> event kind.
EVENT_STREAMS = (
    ("chaos.jsonl", "chaos"),
    ("learner_rollback.jsonl", "rollback"),
    ("learner_resume.jsonl", "resume"),
    ("population.jsonl", "population"),
    ("autopilot.jsonl", "autopilot"),
)

# Default charted channels — the cross-plane health set (fnmatch, matched
# against ``role/metric`` channel names).
DEFAULT_CHANNELS = (
    "*-env-steps-per-s",
    "*-updates-per-s",
    "*-mean-episode-return",
    "*-mfu",
    "*-goodput-ratio",
    "*/policy-staleness-updates-p99",
    "*/learner-diag-ess*",
    "*/learner-update-index",
)

_EVENT_COLORS = {
    "chaos": "#d62728",
    "rollback": "#ff7f0e",
    "resume": "#2ca02c",
    "population": "#9467bd",
    "autopilot": "#1f77b4",
}
_SVG_W, _SVG_H, _SVG_PAD = 640, 120, 4
_MAX_POINTS = 240  # downsample target per chart


def _event_label(kind: str, rec: dict) -> str:
    """Best-effort one-liner from whatever keys the stream's schema has."""
    for key in ("action", "kind", "event", "rule", "reason"):
        v = rec.get(key)
        if isinstance(v, str) and v:
            detail = rec.get("target") or rec.get("name") or rec.get("member")
            return f"{v}:{detail}" if detail else v
    if "idx" in rec:
        tail = f"@e{rec['epoch']}" if "epoch" in rec else ""
        return f"idx={rec['idx']}{tail}"
    return kind


def load_events(result_dir: str) -> list[dict]:
    """All audit-stream events as ``{"t", "kind", "label"}``, time-sorted.
    Torn tail lines and unstamped records are skipped, mirroring the
    history reader's crash discipline."""
    events: list[dict] = []
    for fname, kind in EVENT_STREAMS:
        path = os.path.join(result_dir, fname)
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "t" not in rec:
                continue
            events.append({
                "t": float(rec["t"]),
                "kind": kind,
                "label": _event_label(kind, rec),
            })
    events.sort(key=lambda e: e["t"])
    return events


def select_channels(
    series: dict[str, str], patterns=DEFAULT_CHANNELS
) -> list[str]:
    return sorted(
        ch for ch in series
        if any(fnmatch.fnmatch(ch, p) for p in patterns)
    )


def build_report(
    result_dir: str,
    history_dir: str | None = None,
    patterns=DEFAULT_CHANNELS,
) -> dict:
    """The ``report.json`` document: per-channel stats over the full run
    span + the event timeline. Raises FileNotFoundError when the run has
    no history store (nothing to report on is an error, not an empty
    report — a silent blank would read as a healthy-but-idle run)."""
    hdir = history_dir or os.path.join(result_dir, "history")
    reader = HistoryReader(hdir)
    if not reader.exists():
        raise FileNotFoundError(f"no history store under {hdir}")
    series = reader.series()
    channels = []
    for ch in select_channels(series, patterns):
        pts = reader.points(ch)
        if not pts:
            continue
        values = [v for _, v in pts]
        channels.append({
            "name": ch,
            "kind": series.get(ch, "unknown"),
            "n": len(pts),
            "t0": pts[0][0],
            "t1": pts[-1][0],
            "mean": sum(values) / len(values),
            "min": min(values),
            "max": max(values),
            "last": values[-1],
        })
    return {
        "result_dir": os.path.abspath(result_dir),
        "history_dir": os.path.abspath(hdir),
        "generated_at": time.time(),
        "n_series": len(series),
        "channels": channels,
        "events": load_events(result_dir),
    }


# ------------------------------------------------------------------ markdown
def render_markdown(doc: dict) -> str:
    lines = [
        f"# Run report — `{doc['result_dir']}`",
        "",
        f"{len(doc['channels'])} charted channels of {doc['n_series']} "
        f"recorded series; {len(doc['events'])} fleet events.",
        "",
        "## Channels",
        "",
        "| Channel | Kind | Samples | Mean | Min | Max | Last |",
        "| --- | --- | ---: | ---: | ---: | ---: | ---: |",
    ]
    for ch in doc["channels"]:
        lines.append(
            f"| `{ch['name']}` | {ch['kind']} | {ch['n']} "
            f"| {ch['mean']:.4g} | {ch['min']:.4g} | {ch['max']:.4g} "
            f"| {ch['last']:.4g} |"
        )
    lines += ["", "## Events", ""]
    if doc["events"]:
        lines += ["| t | Kind | Event |", "| --- | --- | --- |"]
        t_base = doc["events"][0]["t"]
        for ev in doc["events"]:
            lines.append(
                f"| +{ev['t'] - t_base:.1f}s | {ev['kind']} "
                f"| {ev['label']} |"
            )
    else:
        lines.append("(none recorded)")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------- html
def _svg_chart(
    pts: list[tuple[float, float]],
    t0: float,
    t1: float,
    events: list[dict],
) -> str:
    """One inline SVG: the channel polyline over [t0, t1] plus a vertical
    rule per event inside the span."""
    span = max(t1 - t0, 1e-9)
    if len(pts) > _MAX_POINTS:
        pts = [
            (b["t"], b["mean"])
            for b in downsample(pts, span / _MAX_POINTS, start=t0)
        ]
    lo = min(v for _, v in pts)
    hi = max(v for _, v in pts)
    vspan = max(hi - lo, 1e-9)
    inner_w = _SVG_W - 2 * _SVG_PAD
    inner_h = _SVG_H - 2 * _SVG_PAD

    def xy(t, v):
        x = _SVG_PAD + (t - t0) / span * inner_w
        y = _SVG_PAD + (1.0 - (v - lo) / vspan) * inner_h
        return f"{x:.1f},{y:.1f}"

    parts = [
        f'<svg viewBox="0 0 {_SVG_W} {_SVG_H}" width="{_SVG_W}" '
        f'height="{_SVG_H}" role="img">',
        f'<rect width="{_SVG_W}" height="{_SVG_H}" fill="#fafafa" '
        'stroke="#ddd"/>',
    ]
    for ev in events:
        if not (t0 <= ev["t"] <= t1):
            continue
        x = _SVG_PAD + (ev["t"] - t0) / span * inner_w
        color = _EVENT_COLORS.get(ev["kind"], "#666")
        title = html.escape(f"{ev['kind']}: {ev['label']}")
        parts.append(
            f'<line x1="{x:.1f}" y1="0" x2="{x:.1f}" y2="{_SVG_H}" '
            f'stroke="{color}" stroke-dasharray="3,3">'
            f"<title>{title}</title></line>"
        )
    points = " ".join(xy(t, v) for t, v in pts)
    parts.append(
        f'<polyline points="{points}" fill="none" stroke="#1f77b4" '
        'stroke-width="1.5"/>'
    )
    parts.append(
        f'<text x="{_SVG_PAD + 2}" y="12" font-size="10" fill="#888">'
        f"max {hi:.4g}</text>"
        f'<text x="{_SVG_PAD + 2}" y="{_SVG_H - 6}" font-size="10" '
        f'fill="#888">min {lo:.4g}</text>'
    )
    parts.append("</svg>")
    return "".join(parts)


def render_html(doc: dict, reader: HistoryReader) -> str:
    rows = []
    for ch in doc["channels"]:
        pts = reader.points(ch["name"])
        if not pts:
            continue
        rows.append(
            f"<h3><code>{html.escape(ch['name'])}</code> "
            f"<small>({ch['kind']}, n={ch['n']}, mean={ch['mean']:.4g}, "
            f"last={ch['last']:.4g})</small></h3>"
            + _svg_chart(pts, ch["t0"], ch["t1"], doc["events"])
        )
    legend = " ".join(
        f'<span style="color:{color}">&#9475; {kind}</span>'
        for kind, color in _EVENT_COLORS.items()
    )
    ev_rows = "".join(
        f"<tr><td>{ev['t']:.3f}</td><td>{ev['kind']}</td>"
        f"<td>{html.escape(ev['label'])}</td></tr>"
        for ev in doc["events"]
    )
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>run report — {html.escape(doc['result_dir'])}</title>"
        "<style>body{font-family:sans-serif;max-width:700px;margin:2em auto}"
        "table{border-collapse:collapse}td,th{border:1px solid #ddd;"
        "padding:2px 8px;font-size:12px}</style></head><body>"
        f"<h1>Run report</h1><p><code>{html.escape(doc['result_dir'])}"
        f"</code></p><p>{legend}</p>"
        + "".join(rows)
        + "<h2>Events</h2><table><tr><th>t</th><th>kind</th><th>event</th>"
        f"</tr>{ev_rows}</table>"
        "</body></html>"
    )


# ---------------------------------------------------------------------- CLI
def write_report(
    result_dir: str,
    out_dir: str | None = None,
    history_dir: str | None = None,
    patterns=DEFAULT_CHANNELS,
) -> dict[str, str]:
    """Build + write all three artifacts; returns {format: path}."""
    doc = build_report(result_dir, history_dir=history_dir, patterns=patterns)
    reader = HistoryReader(doc["history_dir"])
    out_dir = out_dir or result_dir
    os.makedirs(out_dir, exist_ok=True)
    paths = {}
    for name, content in (
        ("report.json", json.dumps(doc, indent=1) + "\n"),
        ("report.md", render_markdown(doc)),
        ("report.html", render_html(doc, reader)),
    ):
        path = os.path.join(out_dir, name)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(content)
        os.replace(tmp, path)
        paths[name] = path
    return paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_rl.obs.report",
        description="Render a post-hoc run report from the history store.",
    )
    ap.add_argument("result_dir", help="run result_dir (history/ inside)")
    ap.add_argument(
        "--history-dir", default=None,
        help="history store location when not result_dir/history",
    )
    ap.add_argument(
        "--out", default=None, help="output directory (default: result_dir)"
    )
    ap.add_argument(
        "--channels", nargs="*", default=None,
        help="fnmatch patterns over role/metric channel names "
        "(default: the fleet-health set)",
    )
    args = ap.parse_args(argv)
    patterns = tuple(args.channels) if args.channels else DEFAULT_CHANNELS
    try:
        paths = write_report(
            args.result_dir, out_dir=args.out,
            history_dir=args.history_dir, patterns=patterns,
        )
    except FileNotFoundError as e:
        print(f"report: {e}", file=sys.stderr)
        return 2
    for name in sorted(paths):
        print(f"report: wrote {paths[name]}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
