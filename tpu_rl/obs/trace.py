"""Span tracing for the learner batch timeline.

The ExecutionTimer answers "how long does X take on average"; it cannot
answer "where did THIS batch's time go" — whether queue-wait happened
because the feeder was assembling, blocked on shm, or idle. A
:class:`TraceRecorder` is the missing instrument: a bounded ring of
complete spans (name, start, duration, thread lane) covering
assemble -> queue-wait -> H2D -> train_step -> broadcast, exported as
Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto "X" phase
format) so the learner's pipeline overlap is visible on a real timeline.

Cost model: recording a span is a ``perf_counter`` pair + one deque append
under a lock — safe from the feeder thread and the hot loop concurrently,
and bounded by ``capacity`` spans of memory. When tracing is disabled the
recorder is never constructed (``LearnerService`` guards on ``is None``),
so the hot loop carries no per-update cost.

The deep-dive companion is the XLA profiler window that already exists
(``Config.profile_dir`` / ``profile_start`` / ``profile_steps``): this ring
shows the host-side pipeline shape continuously; the profiler hook captures
device internals for a configured update window on top.
"""

from __future__ import annotations

import contextlib
import json
import socket
import threading
import time
from collections import deque


class TraceRecorder:
    """Ring buffer of completed spans, exportable as Chrome trace events."""

    def __init__(
        self,
        capacity: int = 4096,
        pid: int = 0,
        role: str = "",
        host: str | None = None,
    ):
        self.capacity = int(capacity)
        self.pid = int(pid)
        self.role = role
        self.host = socket.gethostname() if host is None else host
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.n_recorded = 0
        # One shared epoch so timestamps from every thread share an axis —
        # paired with a wall-clock anchor taken at the same instant so dumps
        # from different processes can be merged onto ONE fleet axis
        # (tpu_rl.obs.merge): a span's wall time is wall_anchor_ns + rel.
        self._t0 = time.perf_counter()
        self.wall_anchor_ns = time.time_ns()

    # ---------------------------------------------------------------- record
    def add(
        self,
        name: str,
        start: float,
        dur: float,
        tid: str = "main",
        args: dict | None = None,
    ) -> None:
        """One completed span; ``start`` is a ``perf_counter`` reading."""
        with self._lock:
            self._events.append((name, start - self._t0, dur, tid, args))
            self.n_recorded += 1

    @contextlib.contextmanager
    def span(self, name: str, tid: str = "main", args: dict | None = None):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.add(name, t0, time.perf_counter() - t0, tid=tid, args=args)

    def __len__(self) -> int:
        return len(self._events)

    # ---------------------------------------------------------------- export
    def to_chrome(self, extra_meta: dict | None = None) -> dict:
        """Chrome trace-event JSON object format: complete ("X") events with
        microsecond timestamps, one named lane per recording thread. The
        top-level ``meta`` block (role/pid/host + the wall-clock anchor of
        the perf_counter epoch) is what makes dumps from different processes
        mergeable in principle — without it a ring's timestamps are an
        offset-unknown local axis."""
        with self._lock:
            events = list(self._events)
        trace_events: list[dict] = []
        tids: dict[str, int] = {}
        for name, rel, dur, tid, args in events:
            tid_i = tids.setdefault(tid, len(tids))
            ev = {
                "name": name,
                "ph": "X",
                "ts": rel * 1e6,
                "dur": dur * 1e6,
                "pid": self.pid,
                "tid": tid_i,
            }
            if args:
                ev["args"] = args
            trace_events.append(ev)
        if self.role:
            trace_events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": self.pid,
                    "tid": 0,
                    "args": {"name": f"{self.role} {self.host}/{self.pid}"},
                }
            )
        # Thread-name metadata so the viewer shows "main"/"feeder" lanes.
        for tname, tid_i in tids.items():
            trace_events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self.pid,
                    "tid": tid_i,
                    "args": {"name": tname},
                }
            )
        meta = {
            "role": self.role,
            "pid": self.pid,
            "host": self.host,
            "wall_anchor_ns": self.wall_anchor_ns,
        }
        if extra_meta:
            meta.update(extra_meta)
        return {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "meta": meta,
        }

    def dump(self, path: str, extra_meta: dict | None = None) -> None:
        """Atomic write (tmp + rename) so a viewer never loads a torn file."""
        import os

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome(extra_meta), f)
        os.replace(tmp, path)
