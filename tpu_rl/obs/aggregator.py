"""Learner-side collection point for the fleet's Telemetry snapshots.

The aggregator runs in the storage process — the learner-side edge of the
stat channel, the one hop every role already reaches: workers' snapshots
arrive via the manager relay, the manager's own snapshots ride its PUB, and
the learner process publishes its snapshots on a tiny PUB connected to the
same port (``LearnerService``). Storage's own registry is folded in
in-process. The exporters (:mod:`tpu_rl.obs.exporters`) read everything
from here.

Responsibilities:

- keep the latest snapshot per source ``(role, host, pid[, wid])`` with its
  arrival time — staleness per source is what ``/healthz`` reports;
- **policy-staleness tracking**: every ``RolloutBatch`` frame echoes the
  policy version (the learner's update index, tagged onto ``Model``
  broadcasts and inference replies) it was acted with.
  :meth:`observe_staleness` compares that echo against the newest version
  the aggregator has seen anywhere — learner snapshots carry the
  authoritative ``learner-update-index`` gauge, and the echoes themselves
  ratchet the bound — and records ``current - acted`` into a per-worker
  ``policy-staleness-updates`` histogram (IMPALA's policy-lag signal,
  PAPERS.md 1802.01561);
- stay O(sources) in memory and O(1) per ingest: snapshots replace, they
  never accumulate.

When telemetry is disabled the aggregator is simply never constructed —
:func:`maybe_aggregator` returns None and every call site guards on that,
so the disabled path allocates nothing per frame (pinned by
``tests/test_obs.py::test_disabled_telemetry_allocates_nothing``).
"""

from __future__ import annotations

import time
from typing import Callable

from tpu_rl.obs.registry import MetricsRegistry

# A source whose last snapshot is older than this is reported dead by
# /healthz. Generous vs the default 5 s emit interval: one lost frame on the
# best-effort PUB/SUB fabric must not flap liveness.
DEFAULT_STALE_AFTER_S = 30.0

LEARNER_VERSION_GAUGE = "learner-update-index"
STALENESS_HIST = "policy-staleness-updates"


class TelemetryAggregator:
    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        stale_after_s: float = DEFAULT_STALE_AFTER_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        # The aggregator's own registry: the storage role's metrics plus the
        # per-worker staleness histograms (storage is where rollout frames
        # are decoded, so the version echoes surface here).
        self.registry = registry or MetricsRegistry(role="storage")
        self.stale_after_s = float(stale_after_s)
        self._clock = clock
        # (role, host, pid, wid) -> {"snap": dict, "at": monotonic}
        self.sources: dict[tuple, dict] = {}
        self.n_ingested = 0
        self.n_rejected = 0
        self._max_version = -1

    # ----------------------------------------------------------------- ingest
    def ingest(self, snap: dict, now: float | None = None) -> bool:
        """One Telemetry payload off the wire. Returns False (and counts)
        for frames that decoded fine but are not snapshot-shaped — a foreign
        publisher on the stat channel must not poison the plane."""
        if (
            not isinstance(snap, dict)
            or not isinstance(snap.get("role"), str)
            or not isinstance(snap.get("pid"), int)
        ):
            self.n_rejected += 1
            return False
        now = self._clock() if now is None else now
        key = (
            snap["role"],
            str(snap.get("host", "?")),
            snap["pid"],
            str(snap.get("wid", "")),
        )
        self.sources[key] = {"snap": snap, "at": now}
        self.n_ingested += 1
        if snap["role"] == "learner":
            for name, _labels, value in snap.get("gauges", ()):
                if name == LEARNER_VERSION_GAUGE:
                    self._max_version = max(self._max_version, int(value))
        return True

    # -------------------------------------------------------------- staleness
    @property
    def max_version(self) -> int:
        """Newest policy version seen anywhere (learner gauge or rollout
        echo); -1 until the first versioned frame arrives."""
        return self._max_version

    def observe_staleness(self, wid: int, version: int) -> None:
        """One rollout frame acted with policy ``version`` by worker
        ``wid``. The staleness, in learner updates, is the gap to the newest
        version known fleet-wide; the echoes themselves ratchet that bound,
        so the metric works even before the learner's first snapshot lands
        (it then under-reports by at most the broadcast in flight)."""
        if version < 0:
            return  # unversioned frame (pre-upgrade worker): nothing to say
        if version > self._max_version:
            self._max_version = version
        self.registry.histogram(
            STALENESS_HIST, labels={"wid": str(wid)}
        ).observe(self._max_version - version)

    # ---------------------------------------------------------------- reading
    def all_snapshots(self, now: float | None = None) -> list[tuple[dict, float]]:
        """Every known snapshot with its age in seconds — the fleet sources
        plus the aggregator's own registry (age 0, it lives here)."""
        now = self._clock() if now is None else now
        out = [(e["snap"], now - e["at"]) for e in self.sources.values()]
        out.append((self.registry.snapshot(), 0.0))
        return out

    def role_health(self, now: float | None = None) -> dict[str, dict]:
        """Per-role liveness: a role is alive while ANY of its sources
        emitted within ``stale_after_s``. The aggregator's own role is
        always alive (it is answering)."""
        now = self._clock() if now is None else now
        roles: dict[str, dict] = {
            self.registry.role: {"sources": 1, "age_s": 0.0, "alive": True}
        }
        for (role, _host, _pid, _wid), entry in self.sources.items():
            age = now - entry["at"]
            r = roles.setdefault(
                role, {"sources": 0, "age_s": age, "alive": False}
            )
            r["sources"] += 1
            r["age_s"] = min(r["age_s"], age) if r["sources"] > 1 else age
            r["alive"] = r["alive"] or age <= self.stale_after_s
        return roles

    def healthy(self, now: float | None = None) -> bool:
        return all(r["alive"] for r in self.role_health(now).values())


def maybe_aggregator(cfg) -> TelemetryAggregator | None:
    """The single gate for the whole plane: an aggregator exists iff
    telemetry has somewhere to go (``cfg.telemetry_enabled``)."""
    return TelemetryAggregator() if cfg.telemetry_enabled else None
