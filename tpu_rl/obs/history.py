"""Run-history plane: an embedded, crash-atomic, chunked time-series store.

Every observability surface before this one shows *now* — ``/metrics``,
``telemetry.json``, ``/slo``, ``/goodput`` and the dashboard are all
point-in-time snapshots. The history store is the read-side they were
writing toward: on the exporter cadence the owning role flattens its
:class:`~tpu_rl.obs.aggregator.TelemetryAggregator` into one row of
``{channel: value}`` samples (every gauge, every counter, p50/p99 of
every histogram) and appends it to a chunked jsonl log under
``result_dir/history/``. Zero new ports, zero new member-side protocol:
the fleet's snapshots already ride the stat channel to storage, and the
self-served roles (colocated/sebulba/autopilot) record their own
aggregator the same way.

Durability model (the repo-wide torn-write discipline, applied to an
append log):

- one JSON line per record tick — O_APPEND-style whole-line writes, so a
  crash mid-write tears at most the LAST line of the active chunk, and
  the reader skips unparseable lines: a torn chunk is invisible on
  reload, never a poisoned one;
- chunks rotate every ``Config.history_chunk_s`` seconds (start time in
  the filename), and rotation garbage-collects chunks that fell out of
  ``Config.history_retention_s`` — disk is bounded by construction;
- the ``series.json`` channel index (name -> kind) is rewritten
  tmp+``os.replace`` atomically, like every other sidecar in the repo.

Channel names are ``role/metric`` (plus ``{label=value,...}`` for
labeled series, e.g. a worker's ``wid``); histogram-derived quantiles
append ``-p50``/``-p99``. Timestamps are wall-clock (``time.time()``)
because the readers — :mod:`tpu_rl.obs.report`,
:mod:`tpu_rl.obs.compare` — run post-hoc and across runs.

When the plane is off (:func:`maybe_history` returns None) nothing is
constructed and every hot-path hook reduces to one ``is None`` check —
the same cost contract as the telemetry plane itself, pinned by the
``TPU_RL_BENCH_HISTORY`` tracemalloc bench.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Iterable

from tpu_rl.obs.registry import hist_quantile

CHUNK_PREFIX = "chunk-"
CHUNK_SUFFIX = ".jsonl"
SERIES_FILE = "series.json"

# Source-identity labels already encoded in the channel's role prefix —
# folding them into the label tail would split one logical series per
# process restart (pid churn).
_IDENTITY_LABELS = ("role", "host", "pid")

# Histogram-derived quantile channels recorded per hist family. p50 is
# the level, p99 the tail — the pair every SLO rule in the repo reads.
_HIST_QUANTILES = ((0.5, "-p50"), (0.99, "-p99"))


def channel_name(role: str, name: str, labels: dict | None = None) -> str:
    """``role/metric`` (+ ``{k=v,...}`` for non-identity labels)."""
    extra = {
        k: v for k, v in (labels or {}).items() if k not in _IDENTITY_LABELS
    }
    if not extra:
        return f"{role}/{name}"
    tail = ",".join(f"{k}={v}" for k, v in sorted(extra.items()))
    return f"{role}/{name}{{{tail}}}"


def flatten_snapshots(
    snaps: Iterable[tuple[dict, float]],
) -> tuple[dict[str, float], dict[str, str]]:
    """Aggregator ``all_snapshots()`` -> (``{channel: value}``,
    ``{channel: kind}``). Gauges last-write-wins, counters sum across
    sources sharing a channel (same role+name+labels from two pids is the
    restart case — the totals are what monitoring wants), histograms
    contribute interpolated p50/p99 (``hist_quantile``; empty hists
    contribute nothing — no-data stays explicit)."""
    samples: dict[str, float] = {}
    kinds: dict[str, str] = {}
    for snap, _age in snaps:
        role = str(snap.get("role", "?"))
        for name, labels, value in snap.get("gauges", ()):
            ch = channel_name(role, name, labels)
            samples[ch] = float(value)
            kinds[ch] = "gauge"
        for name, labels, value in snap.get("counters", ()):
            ch = channel_name(role, name, labels)
            if kinds.get(ch) == "counter":
                samples[ch] += float(value)
            else:
                samples[ch] = float(value)
                kinds[ch] = "counter"
        for name, labels, counts, _total, _count in snap.get("hists", ()):
            for q, suffix in _HIST_QUANTILES:
                v = hist_quantile(counts, q)
                if v is None:
                    continue
                ch = channel_name(role, name + suffix, labels)
                samples[ch] = float(v)
                kinds[ch] = "quantile"
    return samples, kinds


def downsample(
    points: list[tuple[float, float]], step: float, start: float | None = None
) -> list[dict]:
    """Fixed-width buckets over a sorted point list -> one row per
    non-empty bucket: ``{"t": bucket start, "n", "min", "max", "mean",
    "last"}``. Buckets align to ``start`` (default: the first point), so
    identical (start, step) queries over overlapping ranges agree."""
    if not points:
        return []
    step = float(step)
    assert step > 0, step
    t0 = float(points[0][0] if start is None else start)
    out: list[dict] = []
    cur_idx: int | None = None
    cur: dict | None = None
    for t, v in points:
        idx = int((t - t0) // step)
        if idx != cur_idx:
            if cur is not None:
                cur["mean"] = cur["_sum"] / cur["n"]
                del cur["_sum"]
                out.append(cur)
            cur_idx = idx
            cur = {
                "t": t0 + idx * step, "n": 0, "min": v, "max": v,
                "last": v, "_sum": 0.0,
            }
        cur["n"] += 1
        cur["min"] = min(cur["min"], v)
        cur["max"] = max(cur["max"], v)
        cur["last"] = v
        cur["_sum"] += v
    if cur is not None:
        cur["mean"] = cur["_sum"] / cur["n"]
        del cur["_sum"]
        out.append(cur)
    return out


def _chunk_start_ms(fname: str) -> int | None:
    if not (fname.startswith(CHUNK_PREFIX) and fname.endswith(CHUNK_SUFFIX)):
        return None
    try:
        return int(fname[len(CHUNK_PREFIX):-len(CHUNK_SUFFIX)])
    except ValueError:
        return None


class HistoryReader:
    """Read side over a history directory — shared by the live ``/query``
    endpoint, the offline report/compare CLIs, and autopilot rehydration.
    Stateless per call: every read re-lists chunks, so a reader opened on
    a LIVE directory (the HTTP endpoint) always sees the newest flushed
    rows, and torn tail lines are skipped, never raised."""

    def __init__(self, path: str):
        self.path = path

    def exists(self) -> bool:
        return os.path.isdir(self.path) and bool(self._chunks())

    def _chunks(self) -> list[tuple[int, str]]:
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        out = []
        for fname in names:
            start_ms = _chunk_start_ms(fname)
            if start_ms is not None:
                out.append((start_ms, os.path.join(self.path, fname)))
        out.sort()
        return out

    def series(self) -> dict[str, str]:
        """Channel -> kind. From the ``series.json`` index when present;
        a scan of the chunks otherwise (an index torn away by a crash
        degrades to a slower listing, never to silence)."""
        try:
            with open(os.path.join(self.path, SERIES_FILE)) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and isinstance(doc.get("series"), dict):
                return dict(doc["series"])
        except (OSError, ValueError):
            pass
        names: dict[str, str] = {}
        for row in self._rows():
            for ch in row["s"]:
                names.setdefault(ch, "unknown")
        return names

    def _chunk_s_hint(self) -> float | None:
        """The writer's rotation period, from the series index. Lets the
        reader bound every chunk's coverage window without assuming a
        single writer (two stores sharing a dir interleave chunks)."""
        try:
            with open(os.path.join(self.path, SERIES_FILE)) as f:
                doc = json.load(f)
            v = float(doc["chunk_s"])
            return v if v > 0 else None
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _rows(
        self, start: float | None = None, end: float | None = None
    ) -> Iterable[dict]:
        chunks = self._chunks()
        chunk_s = self._chunk_s_hint() if start is not None else None
        for start_ms, path in chunks:
            # Rows in a chunk are never earlier than its filename start,
            # and (when the rotation period is known) never later than
            # start + chunk_s — chunks outside the query range are skipped
            # without opening them.
            if end is not None and start_ms / 1000.0 > end:
                continue
            if (
                start is not None
                and chunk_s is not None
                and start_ms / 1000.0 + chunk_s < start
            ):
                continue
            try:
                with open(path) as f:
                    lines = f.readlines()
            except OSError:
                continue
            for line in lines:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue  # torn tail line: invisible by design
                if not isinstance(row, dict) or "t" not in row:
                    continue
                t = float(row["t"])
                if start is not None and t < start:
                    continue
                if end is not None and t > end:
                    continue
                if isinstance(row.get("s"), dict):
                    yield row

    def points(
        self,
        metric: str,
        start: float | None = None,
        end: float | None = None,
    ) -> list[tuple[float, float]]:
        out = []
        for row in self._rows(start, end):
            v = row["s"].get(metric)
            if v is not None:
                out.append((float(row["t"]), float(v)))
        out.sort(key=lambda p: p[0])
        return out

    def span(self) -> tuple[float, float] | None:
        """(first t, last t) across all rows; None on an empty store."""
        t0 = t1 = None
        for row in self._rows():
            t = float(row["t"])
            t0 = t if t0 is None else min(t0, t)
            t1 = t if t1 is None else max(t1, t)
        return None if t0 is None else (t0, t1)

    # ------------------------------------------------------------ HTTP query
    def http_query(self, params: dict) -> tuple[int, dict]:
        """The ``GET /query`` contract: without ``metric``, the series
        listing; with it, raw ``[t, v]`` points (``step`` absent/0) or
        min/max/mean/last downsampled rows. Returns (status, payload)."""
        metric = params.get("metric")
        if not metric:
            series = self.series()
            return 200, {
                "series": [
                    {"name": name, "kind": kind}
                    for name, kind in sorted(series.items())
                ],
            }
        try:
            start = float(params["start"]) if params.get("start") else None
            end = float(params["end"]) if params.get("end") else None
            step = float(params.get("step") or 0.0)
        except ValueError:
            return 400, {"error": "start/end/step must be numbers"}
        if step < 0:
            return 400, {"error": "step must be >= 0"}
        pts = self.points(metric, start, end)
        payload: dict = {
            "metric": metric, "start": start, "end": end, "n": len(pts),
        }
        if step > 0:
            payload["step"] = step
            payload["buckets"] = downsample(pts, step, start=start)
        else:
            payload["points"] = [[t, v] for t, v in pts]
        return 200, payload


class TimeSeriesStore(HistoryReader):
    """The write side: an open append handle on the active chunk plus the
    rotation/retention/series-index machinery. Inherits every read path
    from :class:`HistoryReader` (the live ``/query`` endpoint is the
    same code the offline CLIs run)."""

    def __init__(
        self,
        path: str,
        chunk_s: float = 60.0,
        retention_s: float = 3600.0,
        anomaly=None,
        clock: Callable[[], float] = time.time,
    ):
        super().__init__(path)
        assert chunk_s > 0 and retention_s > 0, (chunk_s, retention_s)
        self.chunk_s = float(chunk_s)
        self.retention_s = float(retention_s)
        self.anomaly = anomaly
        self._clock = clock
        self._f = None
        self._chunk_start: float | None = None
        self._kinds: dict[str, str] = {}
        self.n_rows = 0
        self.n_rotated = 0
        self.n_gc = 0
        os.makedirs(path, exist_ok=True)
        # Resume: inherit the prior run's channel index so /query's series
        # listing covers pre-restart chunks still inside retention.
        self._kinds.update(HistoryReader.series(self))

    # ------------------------------------------------------------------ write
    def record(
        self,
        agg,
        now: float | None = None,
        extra: dict[str, float] | None = None,
    ) -> dict[str, float]:
        """One exporter-cadence tick: flatten the aggregator, append the
        row, feed the anomaly detector, publish the store's own counters
        into the aggregator's registry. ``extra`` merges caller-supplied
        channels into the same row (kind ``signal`` — the autopilot
        persists its scraped signal windows this way). Returns the
        flattened samples."""
        samples, kinds = flatten_snapshots(agg.all_snapshots())
        if extra:
            for ch, v in extra.items():
                samples[ch] = float(v)
                kinds.setdefault(ch, "signal")
        self.append(samples, kinds=kinds, t=now)
        if self.anomaly is not None:
            self.anomaly.observe(samples, kinds, registry=agg.registry)
        reg = agg.registry
        reg.counter("history-rows").set_total(self.n_rows)
        reg.counter("history-chunks-rotated").set_total(self.n_rotated)
        reg.counter("history-chunks-gc").set_total(self.n_gc)
        return samples

    def append(
        self,
        samples: dict[str, float],
        kinds: dict[str, str] | None = None,
        t: float | None = None,
    ) -> None:
        t = self._clock() if t is None else float(t)
        self._rotate_if_due(t)
        line = json.dumps({"t": t, "s": samples}, separators=(",", ":"))
        self._f.write(line + "\n")
        self._f.flush()
        self.n_rows += 1
        if kinds and not (kinds.keys() <= self._kinds.keys()):
            self._kinds.update(kinds)
            self._write_series_index()

    def _rotate_if_due(self, t: float) -> None:
        if self._f is not None and t - self._chunk_start < self.chunk_s:
            return
        if self._f is not None:
            self._f.close()
            self.n_rotated += 1
        self._chunk_start = t
        fname = f"{CHUNK_PREFIX}{int(t * 1000):015d}{CHUNK_SUFFIX}"
        self._f = open(os.path.join(self.path, fname), "a")
        self._gc(t)

    def _gc(self, now: float) -> None:
        """Drop chunks wholly older than the retention horizon. A chunk's
        coverage ends ``chunk_s`` past its filename start; the active
        chunk is never eligible (its start is ``now``)."""
        horizon = now - self.retention_s
        for start_ms, path in self._chunks():
            if start_ms / 1000.0 + self.chunk_s < horizon:
                try:
                    os.remove(path)
                    self.n_gc += 1
                except OSError:
                    pass  # already gone (a sibling store GC'd it)

    def series(self) -> dict[str, str]:
        return dict(self._kinds)  # the live index; no disk walk

    def _write_series_index(self) -> None:
        path = os.path.join(self.path, SERIES_FILE)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"series": self._kinds, "chunk_s": self.chunk_s}, f)
            os.replace(tmp, path)
        except OSError:
            pass  # index is a cache; chunks remain the source of truth

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


# ------------------------------------------------------------------ gating
def history_path(cfg) -> str | None:
    """Where this config's history lives: ``Config.history_dir`` when
    set, else ``result_dir/history``, else nowhere (None)."""
    if getattr(cfg, "history_dir", None):
        return cfg.history_dir
    if cfg.result_dir is not None:
        return os.path.join(cfg.result_dir, "history")
    return None


def maybe_history(cfg) -> TimeSeriesStore | None:
    """The plane's single gate (the ``maybe_aggregator`` discipline): a
    store exists iff telemetry is on AND the history has a disk home.
    Off = None everywhere = one ``is None`` check on the hot path."""
    path = history_path(cfg) if cfg.telemetry_enabled else None
    if path is None:
        return None
    from tpu_rl.obs.anomaly import AnomalyDetector

    return TimeSeriesStore(
        path,
        chunk_s=cfg.history_chunk_s,
        retention_s=cfg.history_retention_s,
        anomaly=AnomalyDetector(),
    )
