"""Declarative SLO rules over the telemetry plane.

One spec string (``Config.slo_spec``, chaos-grammar style: parsed once at
config validation, consumed only in resolved form) turns the aggregator's
snapshots into pass/fail/burn-rate verdicts — the always-on form of the
questions today answered by eyeballing ``/metrics``: is inference p99 under
budget, is the learner's MFU above floor, are frames being rejected.

Grammar (comma-separated clauses)::

    spec      := clause ("," clause)*
    clause    := kind ":" metric op value ("@" qualifier)*
    kind      := p50 | p90 | p99 | p999   (histogram quantile)
               | gauge                     (instantaneous gauge value)
               | counter                   (cumulative counter total)
               | rate                      (counter delta per second)
    op        := "<" | "<=" | ">" | ">=" | "=="
    value     := float [unit]   unit := "us" | "ms" | "s" | "/s"
    qualifier := "window=<seconds>s"       (default 60s)

Examples::

    p99:inference-rtt<5ms@window=30s   # worker-observed RTT quantile
    gauge:learner-mfu>0.002            # utilization floor
    rate:transport-rejected-frames<1/s # fleet-wide corruption budget

Semantics — all worst-case/fleet-wide, so a rule passes only when every
source satisfies it:

- quantile kinds merge same-named histograms across all sources
  (elementwise slot add — the shared :data:`~tpu_rl.obs.registry
  .HIST_BUCKETS` layout is what makes that legal) and interpolate with
  :func:`~tpu_rl.obs.registry.hist_quantile`. Duration units (``ms``/
  ``us``) convert to seconds, the unit timers record in.
- ``gauge`` takes the worst value across sources for the comparison
  direction (max for ``<``-style rules, min for ``>``).
- ``counter`` and ``rate`` sum across sources (a rejected frame anywhere
  burns the fleet budget); ``rate`` differentiates that sum over the
  rule's window.
- a rule with no matching data is ``ok=None`` (no-data): it neither
  passes nor burns — silence is surfaced, not scored.

``burn_rate`` is the fraction of evaluations inside the rule's window that
violated (0.0 healthy, 1.0 hard-down) — the error-budget-burn view that
distinguishes a blip from a sustained breach. Each rule also carries
``burn_history``, the last :data:`BURN_HISTORY_LEN` ``[t, burn]`` points
(one per evaluate pass), so sustain/hysteresis consumers — the autopilot's
decision engine, the dashboard — read the exact series the verdicts were
scored on instead of re-deriving it from scrapes.

Pure stdlib + registry math, so ``Config.validate()`` can parse-check specs
without importing jax, and golden-fixture tests are exactly reproducible.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

from tpu_rl.obs.registry import hist_quantile

KINDS = frozenset({"p50", "p90", "p99", "p999", "gauge", "counter", "rate"})
_QUANTILES = {"p50": 0.50, "p90": 0.90, "p99": 0.99, "p999": 0.999}
# Longest-first so "<=" wins over "<". "==" is for exact invariants over
# counters (e.g. counter:learner-nonfinite-updates==0 — any nonfinite
# update anywhere in the fleet is a violation, not a budget).
_OPS: tuple[tuple[str, Callable[[float, float], bool]], ...] = (
    ("<=", lambda v, t: v <= t),
    (">=", lambda v, t: v >= t),
    ("==", lambda v, t: v == t),
    ("<", lambda v, t: v < t),
    (">", lambda v, t: v > t),
)
_UNITS = {"us": 1e-6, "ms": 1e-3, "s": 1.0, "/s": 1.0}
DEFAULT_WINDOW_S = 60.0
# Burn-rate points kept per rule for the /slo payload's burn_history —
# the same series the autopilot's sustain/hysteresis windows and the
# dashboard read (one point per evaluate tick, so 120 covers two minutes
# at the storage 1 Hz cadence).
BURN_HISTORY_LEN = 120


@dataclass(frozen=True)
class SloRule:
    """One resolved rule clause."""

    raw: str
    kind: str
    metric: str
    op: str
    threshold: float
    window_s: float = DEFAULT_WINDOW_S

    def check(self, value: float) -> bool:
        for sym, fn in _OPS:
            if sym == self.op:
                return fn(value, self.threshold)
        raise ValueError(f"slo rule {self.raw!r}: unknown op {self.op!r}")

    @property
    def upper_bound(self) -> bool:
        """True for ``<``-style rules (threshold is a ceiling). ``==``
        counts as a ceiling: exact invariants are worst-cased by the
        largest source value."""
        return self.op.startswith("<") or self.op == "=="


def _parse_value(clause: str, text: str) -> float:
    text = text.strip()
    for unit, scale in sorted(_UNITS.items(), key=lambda kv: -len(kv[0])):
        if text.endswith(unit):
            num = text[: -len(unit)]
            try:
                return float(num) * scale
            except ValueError:
                break
    try:
        return float(text)
    except ValueError:
        raise ValueError(
            f"slo clause {clause!r}: bad threshold {text!r} "
            "(expected float with optional us/ms/s//s unit)"
        ) from None


def _parse_clause(clause: str) -> SloRule:
    head, sep, tail = clause.partition(":")
    kind = head.strip()
    if not sep or kind not in KINDS:
        raise ValueError(
            f"slo clause {clause!r}: unknown kind {kind!r} "
            f"(expected one of {sorted(KINDS)})"
        )
    body, *quals = tail.split("@")
    for sym, _fn in _OPS:
        metric, sep, value = body.partition(sym)
        if sep:
            op = sym
            break
    else:
        raise ValueError(
            f"slo clause {clause!r}: no comparison (expected < <= > >= ==)"
        )
    metric = metric.strip()
    if not metric:
        raise ValueError(f"slo clause {clause!r}: empty metric name")
    threshold = _parse_value(clause, value)
    window_s = DEFAULT_WINDOW_S
    for qual in quals:
        qual = qual.strip()
        if qual.startswith("window=") and qual.endswith("s"):
            try:
                window_s = float(qual[len("window="):-1])
            except ValueError:
                window_s = -1.0
            if window_s > 0:
                continue
        raise ValueError(
            f"slo clause {clause!r}: unknown qualifier {qual!r} "
            "(expected 'window=<seconds>s')"
        )
    return SloRule(
        raw=clause.strip(), kind=kind, metric=metric, op=op,
        threshold=threshold, window_s=window_s,
    )


def parse_slo_spec(spec: str) -> list[SloRule]:
    """Parse a full spec; raises ``ValueError`` with the offending clause.
    Empty/whitespace spec -> no rules."""
    rules = []
    for clause in spec.split(","):
        clause = clause.strip()
        if clause:
            rules.append(_parse_clause(clause))
    return rules


# ---------------------------------------------------------------- evaluation
def _iter_snaps(source, now):
    if hasattr(source, "all_snapshots"):
        return [snap for snap, _age in source.all_snapshots(now)]
    return list(source)  # golden fixtures: a plain list of snapshot dicts


def _rule_value(rule: SloRule, snaps: list[dict]) -> float | None:
    """Extract the rule's observable from a set of snapshots (worst-case /
    fleet-wide per the module semantics); None = no data."""
    if rule.kind in _QUANTILES:
        merged: list[float] | None = None
        for snap in snaps:
            for name, _labels, counts, _total, _count in snap.get("hists", ()):
                if name != rule.metric:
                    continue
                if merged is None:
                    merged = [float(c) for c in counts]
                else:
                    merged = [a + b for a, b in zip(merged, counts)]
        if merged is None:
            return None
        return hist_quantile(merged, _QUANTILES[rule.kind])
    if rule.kind == "gauge":
        values = [
            float(value)
            for snap in snaps
            for name, _labels, value in snap.get("gauges", ())
            if name == rule.metric
        ]
        if not values:
            return None
        return max(values) if rule.upper_bound else min(values)
    # counter / rate: fleet-wide sum of cumulative totals
    values = [
        float(value)
        for snap in snaps
        for name, _labels, value in snap.get("counters", ())
        if name == rule.metric
    ]
    return sum(values) if values else None


class SloEngine:
    """Stateful evaluator: call :meth:`evaluate` on a fixed cadence (the
    storage/colocated telemetry tick); serve :meth:`report` from the
    ``/slo`` endpoint so scrapes read the last verdict instead of injecting
    extra samples into the burn-rate history."""

    def __init__(
        self,
        spec_or_rules: str | list[SloRule],
        clock: Callable[[], float] = time.monotonic,
    ):
        if isinstance(spec_or_rules, str):
            self.rules = parse_slo_spec(spec_or_rules)
        else:
            self.rules = list(spec_or_rules)
        self._clock = clock
        # Per rule: (t, violated) verdict samples inside the window.
        self._verdicts: list[deque] = [deque() for _ in self.rules]
        # Per rate-rule: (t, cumulative total) for differentiation.
        self._totals: list[deque] = [deque() for _ in self.rules]
        # Per rule: (t, burn_rate) — one point per evaluate pass, served
        # in the /slo payload so sustain/hysteresis consumers (autopilot,
        # dashboard) read the exact series the engine decided on.
        self._burn_hist: list[deque] = [
            deque(maxlen=BURN_HISTORY_LEN) for _ in self.rules
        ]
        self._last: dict | None = None

    def evaluate(self, source, now: float | None = None) -> dict:
        """One evaluation pass over an aggregator (or a plain snapshot
        list, for fixtures). Deterministic given (snapshots, now)."""
        now = self._clock() if now is None else now
        snaps = _iter_snaps(source, now)
        results = []
        for i, rule in enumerate(self.rules):
            value = _rule_value(rule, snaps)
            if rule.kind == "rate" and value is not None:
                totals = self._totals[i]
                totals.append((now, value))
                while totals and now - totals[0][0] > rule.window_s:
                    totals.popleft()
                if len(totals) >= 2 and totals[-1][0] > totals[0][0]:
                    value = (totals[-1][1] - totals[0][1]) / (
                        totals[-1][0] - totals[0][0]
                    )
                else:
                    value = None  # one sample: no rate yet
            ok = None if value is None else rule.check(value)
            verdicts = self._verdicts[i]
            if ok is not None:
                verdicts.append((now, not ok))
            while verdicts and now - verdicts[0][0] > rule.window_s:
                verdicts.popleft()
            burn = (
                sum(1 for _t, bad in verdicts if bad) / len(verdicts)
                if verdicts
                else 0.0
            )
            self._burn_hist[i].append((now, round(burn, 6)))
            results.append(
                {
                    "rule": rule.raw,
                    "kind": rule.kind,
                    "metric": rule.metric,
                    "op": rule.op,
                    "threshold": rule.threshold,
                    "window_s": rule.window_s,
                    "value": value,
                    "ok": ok,
                    "burn_rate": round(burn, 6),
                    "samples": len(verdicts),
                    "burn_history": [
                        [round(t, 3), b] for t, b in self._burn_hist[i]
                    ],
                }
            )
        self._last = {
            "ok": all(r["ok"] is not False for r in results),
            "failing": sum(1 for r in results if r["ok"] is False),
            "no_data": sum(1 for r in results if r["ok"] is None),
            "rules": results,
        }
        return self._last

    def report(self) -> dict:
        """Last verdict (evaluating nothing); skeleton before first pass."""
        if self._last is not None:
            return self._last
        return {
            "ok": True,
            "failing": 0,
            "no_data": len(self.rules),
            "rules": [
                {
                    "rule": r.raw,
                    "ok": None,
                    "value": None,
                    "burn_rate": 0.0,
                    "burn_history": [],
                }
                for r in self.rules
            ],
        }

    @property
    def failed(self) -> bool:
        """True when the latest verdict has any hard-failing rule — the
        fail-the-run exit gate for smokes (``Config.slo_fail_run``)."""
        return self._last is not None and not self._last["ok"]


def maybe_slo_engine(cfg) -> SloEngine | None:
    """Role-side constructor: an engine iff ``Config.slo_spec`` is set."""
    spec = getattr(cfg, "slo_spec", None)
    if not spec:
        return None
    return SloEngine(spec)
