"""Cross-run regression verdicts over two history stores.

``python -m tpu_rl.obs.compare <baseline_dir> <candidate_dir>`` compares
every channel the two runs share (plus every channel either side is
missing) and exits nonzero on regression — the CI gate the bench
trajectory never had.

Verdict semantics, per channel:

- **warmup trim**: the first ``warmup_frac`` (default 20%) of each run's
  span is dropped before statistics — cold caches, compile time and
  ramp-up are not the steady state under comparison.
- **tolerance band**: candidate median vs baseline median, with the band
  ``max(mad_k * MAD_baseline * 1.4826, rel_tol * |median_baseline|)`` —
  robust to outliers (MAD, not stddev) and never degenerate on quiet
  channels (the relative floor).
- **direction**: throughput-like channels (``*-per-s``, goodput ratios,
  MFU, ESS, returns) regress downward; latency-like channels
  (staleness, rtt, queue-wait) regress upward; everything else is
  direction-neutral — an out-of-band move is reported as ``shifted``
  but gates nothing (a changed config knob is not a regression).
- **no-data is explicit**: a channel present in the baseline but absent
  (or empty after trim) in the candidate is verdict ``no-data`` and
  FAILS the gate. A silently dropped metric is exactly the regression
  class a comparison layer exists to catch. Channels new in the
  candidate are reported (``new``) but do not gate, and a channel too
  sparse on BOTH sides is ``skipped`` (nothing stopped recording —
  self-compare is green by construction).
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys

from tpu_rl.obs.history import HistoryReader

# Channel-name patterns fixing regression direction. First match wins;
# matched against the bare ``role/metric`` channel name.
HIGHER_BETTER = (
    "*-per-s",
    "*-per-secs",
    "*-goodput-ratio",
    "*-mfu",
    "*-ess*",
    "*-mean-episode-return",
    "*-achieved-flops",
    "*-best-fitness",
)
LOWER_BETTER = (
    "*staleness*",
    "*-rtt*",
    "*-latency*",
    "*queue-wait*",
    "*-queue-depth",
    "*anomaly-*",
)

MAD_K = 5.0  # band half-width in (scaled) MADs
REL_TOL = 0.10  # relative floor on the band
WARMUP_FRAC = 0.2
MIN_SAMPLES = 3  # fewer post-trim samples than this = no-data

GATING = ("regressed", "no-data")


def direction(channel: str) -> str:
    """'up' (higher is better), 'down' (lower is better) or 'neutral'."""
    for pat in HIGHER_BETTER:
        if fnmatch.fnmatch(channel, pat):
            return "up"
    for pat in LOWER_BETTER:
        if fnmatch.fnmatch(channel, pat):
            return "down"
    return "neutral"


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def robust_stats(values: list[float]) -> tuple[float, float]:
    """(median, scaled MAD): MAD * 1.4826 estimates sigma under
    normality, so the band math reads in sigma units."""
    med = _median(values)
    mad = _median([abs(v - med) for v in values])
    return med, mad * 1.4826


def trim_warmup(
    points: list[tuple[float, float]], frac: float = WARMUP_FRAC
) -> list[float]:
    """Drop the first ``frac`` of the run's SPAN (time-based, not
    count-based — a slow-sampling channel still loses its ramp-up)."""
    if not points:
        return []
    t0, t1 = points[0][0], points[-1][0]
    cut = t0 + frac * (t1 - t0)
    return [v for t, v in points if t >= cut]


def compare_channel(
    base: list[float] | None,
    cand: list[float] | None,
    channel: str,
    mad_k: float = MAD_K,
    rel_tol: float = REL_TOL,
) -> dict:
    """One channel's verdict row. ``base``/``cand`` are post-trim value
    lists (None = channel absent from that run entirely)."""
    row: dict = {"channel": channel, "direction": direction(channel)}
    if base is None or len(base) < MIN_SAMPLES:
        if cand is None or len(cand) < MIN_SAMPLES:
            # Empty on BOTH sides (e.g. a channel indexed but too sparse
            # to survive the warmup trim in either run): nothing stopped
            # recording, so this never gates — self-compare stays green.
            row.update(verdict="skipped", detail="absent from both runs")
        else:
            # New in candidate: informational, never gates — a freshly
            # added metric is not a regression of the baseline.
            row.update(
                verdict="new", candidate_median=_median(cand),
                detail="channel absent from baseline",
            )
        return row
    if cand is None or len(cand) < MIN_SAMPLES:
        row.update(
            verdict="no-data", baseline_median=_median(base),
            detail="channel present in baseline but missing/empty in "
            "candidate",
        )
        return row
    med_b, sigma_b = robust_stats(base)
    med_c, _ = robust_stats(cand)
    band = max(mad_k * sigma_b, rel_tol * abs(med_b))
    delta = med_c - med_b
    row.update(
        baseline_median=med_b, candidate_median=med_c,
        delta=delta, band=band,
        n_baseline=len(base), n_candidate=len(cand),
    )
    if abs(delta) <= band:
        row["verdict"] = "ok"
        return row
    d = row["direction"]
    if d == "neutral":
        row["verdict"] = "shifted"
    elif (d == "up") == (delta > 0):
        row["verdict"] = "improved"
    else:
        row["verdict"] = "regressed"
    return row


def compare_runs(
    baseline_dir: str,
    candidate_dir: str,
    patterns: tuple[str, ...] = ("*",),
    warmup_frac: float = WARMUP_FRAC,
    mad_k: float = MAD_K,
    rel_tol: float = REL_TOL,
) -> dict:
    """Full comparison document. ``ok`` is False iff any channel's
    verdict is gating (regressed / no-data)."""
    b = HistoryReader(baseline_dir)
    c = HistoryReader(candidate_dir)
    if not b.exists():
        raise FileNotFoundError(f"no history store under {baseline_dir}")
    if not c.exists():
        raise FileNotFoundError(f"no history store under {candidate_dir}")
    b_series, c_series = b.series(), c.series()
    channels = sorted(
        ch for ch in set(b_series) | set(c_series)
        if any(fnmatch.fnmatch(ch, p) for p in patterns)
    )
    rows = []
    for ch in channels:
        base = (
            trim_warmup(b.points(ch), warmup_frac)
            if ch in b_series else None
        )
        cand = (
            trim_warmup(c.points(ch), warmup_frac)
            if ch in c_series else None
        )
        rows.append(
            compare_channel(base, cand, ch, mad_k=mad_k, rel_tol=rel_tol)
        )
    counts: dict[str, int] = {}
    for row in rows:
        counts[row["verdict"]] = counts.get(row["verdict"], 0) + 1
    return {
        "baseline_dir": baseline_dir,
        "candidate_dir": candidate_dir,
        "warmup_frac": warmup_frac,
        "mad_k": mad_k,
        "rel_tol": rel_tol,
        "counts": counts,
        "ok": not any(row["verdict"] in GATING for row in rows),
        "rows": rows,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_rl.obs.compare",
        description="Per-channel regression verdicts between two runs' "
        "history stores; exits nonzero on regression or missing data.",
    )
    ap.add_argument("baseline_dir", help="baseline history dir "
                    "(or result_dir containing history/)")
    ap.add_argument("candidate_dir", help="candidate history dir "
                    "(or result_dir containing history/)")
    ap.add_argument("--channels", nargs="*", default=["*"],
                    help="fnmatch patterns to compare (default: all)")
    ap.add_argument("--warmup-frac", type=float, default=WARMUP_FRAC)
    ap.add_argument("--mad-k", type=float, default=MAD_K)
    ap.add_argument("--rel-tol", type=float, default=REL_TOL)
    ap.add_argument("--json", dest="json_out", default=None,
                    help="also write the full document to this path")
    args = ap.parse_args(argv)

    def resolve(d):
        sub = os.path.join(d, "history")
        return sub if os.path.isdir(sub) else d

    try:
        doc = compare_runs(
            resolve(args.baseline_dir), resolve(args.candidate_dir),
            patterns=tuple(args.channels), warmup_frac=args.warmup_frac,
            mad_k=args.mad_k, rel_tol=args.rel_tol,
        )
    except FileNotFoundError as e:
        print(f"compare: {e}", file=sys.stderr)
        return 2
    for row in doc["rows"]:
        if row["verdict"] == "ok":
            continue
        med_b = row.get("baseline_median")
        med_c = row.get("candidate_median")
        detail = row.get(
            "detail",
            f"baseline {med_b:.4g} -> candidate {med_c:.4g} "
            f"(band {row.get('band', 0.0):.4g})"
            if med_b is not None and med_c is not None else "",
        )
        print(f"compare: {row['verdict']:>9} {row['channel']}  {detail}")
    summary = ", ".join(f"{k}={v}" for k, v in sorted(doc["counts"].items()))
    print(f"compare: {summary} -> {'OK' if doc['ok'] else 'FAIL'}")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(doc, f, indent=1)
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
