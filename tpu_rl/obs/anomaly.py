"""Online anomaly detection over the history plane's channel stream.

One EWMA mean/variance tracker per channel, fed each exporter-cadence
row by :meth:`TimeSeriesStore.record`. Two trip conditions, both
published as labeled counters (``anomaly-spikes`` / ``anomaly-level-shifts``
with ``{"channel": name}``) so the SLO engine and autopilot can rule on
them (`counter:anomaly-spikes` — the SignalScraper sums across labels):

- **spike**: one sample beyond ``z_spike`` sigma. The sample is folded
  into the baseline *clamped* to the spike threshold so a single outlier
  cannot drag the mean toward itself and mask a follow-up.
- **level shift**: ``sustain`` consecutive samples beyond ``z_level``
  sigma on the same side, opened by a sigma-scale first-difference (a
  *step*). While the candidate streak runs the baseline is frozen —
  folding would chase the new level and dissolve the streak before
  sustain. On trip the baseline re-centers on the new level (one event
  per shift, not one per sample forever after).

Slow drift — per-sample deltas small against the tracked sigma — never
clears the streak-opening jump gate, so the EWMA mean keeps folding
along with the signal and neither condition trips (pinned by test). Counter-kind channels are skipped: a healthy counter
is monotone by construction and every increment would z-trip.
"""

from __future__ import annotations

import math

# EWMA horizon ~1/alpha samples: at the default 10s exporter cadence,
# alpha=0.05 tracks a ~3-minute baseline.
_ALPHA = 0.05
_WARMUP = 8  # samples before the variance estimate is trustworthy
_Z_SPIKE = 8.0
_Z_LEVEL = 3.0
_SUSTAIN = 5
# Floor on sigma relative to the mean's magnitude: a channel that sat
# bit-identical through warmup (constant gauge) has var=0 and any
# sub-ppm wobble would otherwise z-trip.
_REL_FLOOR = 1e-3

ANOMALY_SPIKES_METRIC = "anomaly-spikes"
ANOMALY_LEVEL_SHIFTS_METRIC = "anomaly-level-shifts"


class _Channel:
    __slots__ = ("mean", "var", "n", "streak", "prev")

    def __init__(self):
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self.streak = 0  # signed run length of same-side z_level excursions
        self.prev = 0.0  # last raw sample (for the step-vs-ramp jump gate)


class AnomalyDetector:
    def __init__(
        self,
        alpha: float = _ALPHA,
        warmup: int = _WARMUP,
        z_spike: float = _Z_SPIKE,
        z_level: float = _Z_LEVEL,
        sustain: int = _SUSTAIN,
    ):
        assert 0 < alpha < 1 and warmup >= 2 and sustain >= 1
        assert z_spike > z_level > 0
        self.alpha = alpha
        self.warmup = warmup
        self.z_spike = z_spike
        self.z_level = z_level
        self.sustain = sustain
        self._channels: dict[str, _Channel] = {}
        self.spikes: dict[str, int] = {}
        self.level_shifts: dict[str, int] = {}

    def observe(
        self,
        samples: dict[str, float],
        kinds: dict[str, str],
        registry=None,
    ) -> list[tuple[str, str]]:
        """Feed one row; returns [(channel, "spike"|"level-shift"), ...]
        for the events this row tripped. With ``registry``, publishes the
        running totals as labeled counters."""
        events: list[tuple[str, str]] = []
        for ch, value in samples.items():
            if kinds.get(ch) == "counter":
                continue
            ev = self._observe_one(ch, float(value))
            if ev is not None:
                events.append((ch, ev))
        if registry is not None:
            for ch, n in self.spikes.items():
                registry.counter(
                    ANOMALY_SPIKES_METRIC, {"channel": ch}
                ).set_total(n)
            for ch, n in self.level_shifts.items():
                registry.counter(
                    ANOMALY_LEVEL_SHIFTS_METRIC, {"channel": ch}
                ).set_total(n)
        return events

    def _observe_one(self, ch: str, x: float) -> str | None:
        st = self._channels.get(ch)
        if st is None:
            st = self._channels[ch] = _Channel()
        if st.n == 0:
            st.mean = x
            st.prev = x
        st.n += 1
        if st.n <= self.warmup:
            self._fold(st, x)
            st.prev = x
            return None
        sigma = max(math.sqrt(st.var), _REL_FLOOR * abs(st.mean), 1e-12)
        z = (x - st.mean) / sigma
        jump = abs(x - st.prev) / sigma
        st.prev = x
        if abs(z) >= self.z_spike:
            self.spikes[ch] = self.spikes.get(ch, 0) + 1
            # fold clamped: the baseline absorbs at most z_spike sigma
            self._fold(st, st.mean + math.copysign(self.z_spike * sigma, z))
            st.streak = 0
            return "spike"
        if abs(z) >= self.z_level:
            side = 1 if z > 0 else -1
            if st.streak * side > 0:
                st.streak += side
            elif jump >= self.z_level:
                # A step, not a ramp: only a sigma-scale first-difference
                # opens a candidate shift. A slow drift reaches z_level
                # through sub-sigma increments and keeps folding below.
                st.streak = side
            else:
                st.streak = 0
                self._fold(st, x)
                return None
            if abs(st.streak) >= self.sustain:
                self.level_shifts[ch] = self.level_shifts.get(ch, 0) + 1
                # re-center on the new level; variance restarts its EWMA
                st.mean = x
                st.streak = 0
                return "level-shift"
            # Baseline frozen while the candidate shift accumulates
            # evidence: folding here would chase the new level and
            # dissolve the streak before sustain is ever reached.
            return None
        st.streak = 0
        self._fold(st, x)
        return None

    def _fold(self, st: _Channel, x: float) -> None:
        d = x - st.mean
        st.mean += self.alpha * d
        st.var = (1.0 - self.alpha) * (st.var + self.alpha * d * d)
