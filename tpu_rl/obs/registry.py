"""Per-role metrics registry: the fleet-wide replacement for ad-hoc stat dicts.

Every role process (worker, manager, storage, inference service, learner)
owns one :class:`MetricsRegistry`, registers counters / gauges / histograms
into it, and periodically emits ``registry.snapshot()`` as a
``Protocol.Telemetry`` frame riding the existing stat ZMQ channel
(worker PUB -> manager -> storage SUB). The storage-side
:class:`~tpu_rl.obs.aggregator.TelemetryAggregator` collects the snapshots
and the exporters (:mod:`tpu_rl.obs.exporters`) serve them as Prometheus
text, a rolling JSON file, and tensorboard scalars.

Design constraints:

- **wire-safe snapshots**: ``snapshot()`` returns only the closed type set
  the wire protocol packs (str-keyed dicts, lists, str, int, float) — a
  snapshot IS a Telemetry payload, no adapter layer;
- **fixed log-scale histogram buckets** (:data:`HIST_BUCKETS`): every
  histogram in the fleet shares one bucket layout, so snapshots merge by
  elementwise addition and the Prometheus exposition needs no per-metric
  schema. The 2^-14 .. 2^20 span covers microsecond timings and
  million-update policy lags alike;
- **cheap when idle**: metric updates are a lock + a float add. Roles that
  run with telemetry disabled simply never construct a registry — the hot
  paths guard on ``is None``, not on a config read.

Metric names follow the repo's dash convention (``learner-queue-depth``);
the Prometheus exporter sanitizes to underscores at exposition time.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from bisect import bisect_left
from typing import Callable

# One fixed log-scale bucket layout for every histogram in the fleet
# (Prometheus ``le`` upper bounds; an implicit +Inf overflow slot follows).
# Shared buckets are what make snapshot merge a plain elementwise sum.
HIST_BUCKETS: tuple[float, ...] = tuple(2.0**e for e in range(-14, 21))


def _label_key(labels: dict[str, str] | None) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((labels or {}).items()))


class Counter:
    """Monotonic cumulative count."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self.value += delta

    def set_total(self, total: float) -> None:
        """Mirror an externally-maintained monotonic count (e.g. a transport
        socket's ``n_rejected``) — the total never moves backwards."""
        with self._lock:
            if total > self.value:
                self.value = total


class Gauge:
    """Last-written instantaneous value."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)


def hist_quantile(counts: list[int] | tuple[int, ...], q: float) -> float | None:
    """Quantile estimate over a :data:`HIST_BUCKETS`-shaped slot-count list
    (the wire form snapshots carry), geometric interpolation inside buckets.

    Every bucket spans exactly one octave (``hi = 2 * lo``, including the
    synthetic ``(2^-15, 2^-14]`` floor for the first slot and the capped
    ``(2^20, 2^21]`` overflow slot), so the interpolated value is
    ``lo * 2**frac`` where ``frac`` is the rank's position within the
    bucket. Log-linear interpolation matches the log-scale layout: the
    estimate is exact when observations are log-uniform within a bucket and
    never leaves the bucket's bounds. Returns ``None`` on an empty
    histogram — callers (SLO engine, exporter) must treat no-data
    explicitly, not as 0.
    """
    total = sum(counts)
    if total <= 0:
        return None
    q = min(1.0, max(0.0, float(q)))
    rank = q * total  # fractional rank in (0, total]
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= rank:
            hi = HIST_BUCKETS[i] if i < len(HIST_BUCKETS) else HIST_BUCKETS[-1] * 2.0
            lo = hi / 2.0
            frac = (rank - cum) / c
            return lo * (2.0**frac)
        cum += c
    hi = HIST_BUCKETS[-1] * 2.0  # unreachable unless counts drifted negative
    return hi


class Histogram:
    """Fixed-bucket distribution (:data:`HIST_BUCKETS` + overflow slot).
    ``counts`` are per-slot (non-cumulative); the Prometheus exporter
    renders the cumulative ``le`` form."""

    __slots__ = ("counts", "sum", "count", "_lock")

    def __init__(self, lock: threading.Lock):
        self.counts = [0] * (len(HIST_BUCKETS) + 1)
        self.sum = 0.0
        self.count = 0
        self._lock = lock

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.counts[bisect_left(HIST_BUCKETS, v)] += 1
            self.sum += v
            self.count += 1

    def observe_n(self, value: float, n: int) -> None:
        """Bulk-observe ``n`` identical samples — delta replay of an
        externally-counted event stream (e.g. the inference service's
        per-bucket flush counts) without n lock round-trips."""
        if n <= 0:
            return
        v = float(value)
        with self._lock:
            self.counts[bisect_left(HIST_BUCKETS, v)] += n
            self.sum += v * n
            self.count += n

    def quantile(self, q: float) -> float | None:
        """p50/p90/p99/p999 estimate (see :func:`hist_quantile`)."""
        with self._lock:
            counts = list(self.counts)
        return hist_quantile(counts, q)


class MetricsRegistry:
    """One process-role's metric namespace, labeled ``(role, host, pid)``
    plus any extra constant labels (e.g. a worker's ``wid``)."""

    def __init__(
        self,
        role: str,
        labels: dict[str, str] | None = None,
        host: str | None = None,
        pid: int | None = None,
    ):
        self.role = role
        self.host = host if host is not None else socket.gethostname()
        self.pid = int(pid if pid is not None else os.getpid())
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._counters: dict[tuple, Counter] = {}
        self._gauges: dict[tuple, Gauge] = {}
        self._hists: dict[tuple, Histogram] = {}
        self._seq = 0

    # ----------------------------------------------------------- metric access
    def counter(self, name: str, labels: dict[str, str] | None = None) -> Counter:
        return self._get(self._counters, Counter, name, labels)

    def gauge(self, name: str, labels: dict[str, str] | None = None) -> Gauge:
        return self._get(self._gauges, Gauge, name, labels)

    def histogram(self, name: str, labels: dict[str, str] | None = None) -> Histogram:
        return self._get(self._hists, Histogram, name, labels)

    def _get(self, table: dict, cls, name: str, labels: dict[str, str] | None):
        key = (name, _label_key(labels))
        with self._lock:
            m = table.get(key)
            if m is None:
                m = table[key] = cls(self._lock)
            return m

    # -------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """Wire-safe dump of every metric: the ``Protocol.Telemetry``
        payload. Labels are the registry's constant labels merged with the
        metric's own (metric labels win on collision)."""
        with self._lock:
            self._seq += 1
            snap = {
                "role": self.role,
                "host": self.host,
                "pid": self.pid,
                "seq": self._seq,
                "ts": time.time(),
                "counters": [
                    [name, self._merged_labels(lk), c.value]
                    for (name, lk), c in self._counters.items()
                ],
                "gauges": [
                    [name, self._merged_labels(lk), g.value]
                    for (name, lk), g in self._gauges.items()
                ],
                "hists": [
                    [name, self._merged_labels(lk), list(h.counts), h.sum, h.count]
                    for (name, lk), h in self._hists.items()
                ],
            }
        return snap

    def _merged_labels(self, label_key: tuple) -> dict[str, str]:
        return {**self.labels, **dict(label_key)}


# --------------------------------------------------------------- snapshot ops
def _series_key(entry: list) -> tuple:
    name, labels = entry[0], entry[1]
    return (name, tuple(sorted(labels.items())))


def merge_snapshots(a: dict, b: dict) -> dict:
    """Elementwise combine two snapshots into one (counters and histogram
    slots add; gauges: the newer snapshot — by ``ts`` — wins). Metadata is
    kept from ``a`` except ``ts`` (max). Inputs are not mutated."""
    newer_b = float(b.get("ts", 0.0)) >= float(a.get("ts", 0.0))
    out = {
        k: a.get(k)
        for k in ("role", "host", "pid", "seq")
    }
    out["ts"] = max(float(a.get("ts", 0.0)), float(b.get("ts", 0.0)))

    counters: dict[tuple, list] = {}
    for src in (a, b):
        for name, labels, value in src.get("counters", ()):
            key = (name, tuple(sorted(labels.items())))
            if key in counters:
                counters[key][2] += value
            else:
                counters[key] = [name, dict(labels), float(value)]
    out["counters"] = list(counters.values())

    gauges: dict[tuple, list] = {}
    first, second = (a, b) if newer_b else (b, a)
    for src in (first, second):  # second (newer) overwrites
        for name, labels, value in src.get("gauges", ()):
            key = (name, tuple(sorted(labels.items())))
            gauges[key] = [name, dict(labels), float(value)]
    out["gauges"] = list(gauges.values())

    hists: dict[tuple, list] = {}
    for src in (a, b):
        for name, labels, counts, total, count in src.get("hists", ()):
            key = (name, tuple(sorted(labels.items())))
            if key in hists:
                h = hists[key]
                h[2] = [x + y for x, y in zip(h[2], counts, strict=True)]
                h[3] += total
                h[4] += count
            else:
                hists[key] = [name, dict(labels), list(counts), float(total), int(count)]
    out["hists"] = list(hists.values())
    return out


def diff_snapshots(cur: dict, prev: dict) -> dict:
    """Per-interval deltas: counters and histogram slots subtract (floored
    at zero, so a restarted source never yields negative rates); gauges pass
    through from ``cur``. The inverse of :func:`merge_snapshots` over the
    additive fields."""
    prev_counters = {_series_key(e): e[2] for e in prev.get("counters", ())}
    prev_hists = {_series_key(e): e for e in prev.get("hists", ())}
    out = {k: cur.get(k) for k in ("role", "host", "pid", "seq", "ts")}
    out["counters"] = [
        [name, dict(labels), max(0.0, value - prev_counters.get(_series_key([name, labels]), 0.0))]
        for name, labels, value in cur.get("counters", ())
    ]
    out["gauges"] = [list(e) for e in cur.get("gauges", ())]
    hists = []
    for name, labels, counts, total, count in cur.get("hists", ()):
        p = prev_hists.get(_series_key([name, labels]))
        if p is None:
            hists.append([name, dict(labels), list(counts), float(total), int(count)])
        else:
            hists.append(
                [
                    name,
                    dict(labels),
                    [max(0, x - y) for x, y in zip(counts, p[2], strict=True)],
                    max(0.0, total - p[3]),
                    max(0, count - p[4]),
                ]
            )
    out["hists"] = hists
    return out


class PeriodicSnapshot:
    """Wall-clock-gated snapshot emitter: call :meth:`maybe_emit` from a
    role's loop; every ``interval_s`` it ships ``registry.snapshot()``
    through the supplied ``send`` callable (transport-agnostic — the roles
    bind it to their existing PUB). This is what makes idle/stuck roles
    visible: emission is on the clock, not on episode completion."""

    def __init__(
        self,
        registry: MetricsRegistry,
        send: Callable[[dict], None],
        interval_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.registry = registry
        self._send = send
        self.interval_s = float(interval_s)
        self._clock = clock
        self._last = float("-inf")
        self.n_emitted = 0

    def due(self, now: float | None = None) -> bool:
        """Would :meth:`maybe_emit` emit right now? Lets roles refresh
        emit-cadence-only metrics (``/proc/self`` reads, fd counts) just
        before the snapshot they'll ride, without paying for them every
        tick."""
        now = self._clock() if now is None else now
        return now - self._last >= self.interval_s

    def maybe_emit(self, now: float | None = None) -> bool:
        now = self._clock() if now is None else now
        if now - self._last < self.interval_s:
            return False
        self._last = now
        self._send(self.registry.snapshot())
        self.n_emitted += 1
        return True
