"""One append-one-json-line audit helper for every role.

Four call sites grew the same copy-pasted writer (learner rollback /
resume, colocated resume, population decisions) before this module
unified them. The semantics every caller relies on are preserved exactly:

- the directory is created on demand (``makedirs(exist_ok=True)``);
- one ``json.dumps(record) + "\\n"`` appended per call — O_APPEND writes
  of one short line, so concurrent writers interleave whole lines;
- ``OSError`` is swallowed: audit is best-effort, the action being
  audited already happened and a full disk must never take the run down.

``stamp=True`` (default) adds the wall-clock ``"t"`` key the original
writers all carried, without clobbering one the caller set itself.
"""

from __future__ import annotations

import json
import os
import time


def append_jsonl(
    result_dir: str | None, filename: str, record: dict, stamp: bool = True
) -> bool:
    """Append one JSON line to ``result_dir/filename``; True if written."""
    if result_dir is None:
        return False
    if stamp and "t" not in record:
        record = {**record, "t": time.time()}
    try:
        os.makedirs(result_dir, exist_ok=True)
        with open(os.path.join(result_dir, filename), "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        return False  # audit is best-effort; the action already happened
    return True


def append_resume(result_dir: str | None, idx: int, epoch: int) -> bool:
    """The ONE resume-audit schema (``learner_resume.jsonl``) — the
    distributed learner and the colocated loop must emit identical records
    (pinned by test), so the record shape lives here, not at either site."""
    return append_jsonl(
        result_dir, "learner_resume.jsonl", {"idx": int(idx), "epoch": int(epoch)}
    )
