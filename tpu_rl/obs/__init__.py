"""Fleet-wide observability: metrics registries, the telemetry aggregator,
exporters (Prometheus / JSON / tensorboard), span tracing, the live
performance plane (MFU/FLOPs/recompiles/device memory + profiler capture),
the SLO engine, the learning-dynamics plane (in-jit algorithm
diagnostics with staleness-conditioned attribution — ``tpu_rl.obs.learn``),
and the run-history plane (embedded time-series store + ``/query`` +
anomaly detection — ``tpu_rl.obs.history``/``anomaly``, with the offline
``tpu_rl.obs.report`` / ``tpu_rl.obs.compare`` CLIs reading it back).

See ``docs/ARCHITECTURE.md`` ("Observability") for the data flow.
"""

from tpu_rl.obs.aggregator import (
    DEFAULT_STALE_AFTER_S,
    LEARNER_VERSION_GAUGE,
    STALENESS_HIST,
    TelemetryAggregator,
    maybe_aggregator,
)
from tpu_rl.obs.anomaly import (
    ANOMALY_LEVEL_SHIFTS_METRIC,
    ANOMALY_SPIKES_METRIC,
    AnomalyDetector,
)
from tpu_rl.obs.audit import append_jsonl, append_resume
from tpu_rl.obs.clocksync import ClockEstimate, ClockSync
from tpu_rl.obs.exporters import (
    JsonExporter,
    TelemetryHTTPServer,
    TensorboardExporter,
    render_healthz,
    render_prometheus,
)
from tpu_rl.obs.flightrec import FlightRecorder
from tpu_rl.obs.goodput import (
    BUCKETS,
    STRAGGLER_GAUGE,
    GoodputLedger,
    maybe_ledger,
    robust_z,
    straggler_report,
)
from tpu_rl.obs.history import (
    HistoryReader,
    TimeSeriesStore,
    channel_name,
    downsample,
    flatten_snapshots,
    history_path,
    maybe_history,
)
from tpu_rl.obs.learn import (
    BUCKET_GAUGE_PREFIX,
    GAUGE_PREFIX,
    N_STALE_BUCKETS,
    STALE_BUCKET_LABELS,
    DiagAccumulator,
    derive,
    ess_normalized,
    explained_variance,
    host_stale_rows,
    learn_record,
    publish,
    stale_bucket_index,
)
from tpu_rl.obs.merge import merge_result_dir, merge_traces
from tpu_rl.obs.perf import (
    PEAK_FLOPS,
    PerfTracker,
    ProfilerCapture,
    device_memory_bytes,
    device_peak_flops,
    maybe_perf_tracker,
    process_self_stats,
)
from tpu_rl.obs.registry import (
    HIST_BUCKETS,
    MetricsRegistry,
    PeriodicSnapshot,
    diff_snapshots,
    hist_quantile,
    merge_snapshots,
)
from tpu_rl.obs.slo import SloEngine, SloRule, maybe_slo_engine, parse_slo_spec
from tpu_rl.obs.trace import TraceRecorder

__all__ = [
    "ANOMALY_LEVEL_SHIFTS_METRIC",
    "ANOMALY_SPIKES_METRIC",
    "AnomalyDetector",
    "BUCKETS",
    "BUCKET_GAUGE_PREFIX",
    "ClockEstimate",
    "ClockSync",
    "DEFAULT_STALE_AFTER_S",
    "DiagAccumulator",
    "FlightRecorder",
    "GAUGE_PREFIX",
    "GoodputLedger",
    "HIST_BUCKETS",
    "HistoryReader",
    "JsonExporter",
    "LEARNER_VERSION_GAUGE",
    "MetricsRegistry",
    "N_STALE_BUCKETS",
    "PEAK_FLOPS",
    "PerfTracker",
    "PeriodicSnapshot",
    "ProfilerCapture",
    "STALENESS_HIST",
    "STALE_BUCKET_LABELS",
    "STRAGGLER_GAUGE",
    "SloEngine",
    "SloRule",
    "TelemetryAggregator",
    "TelemetryHTTPServer",
    "TensorboardExporter",
    "TimeSeriesStore",
    "TraceRecorder",
    "append_jsonl",
    "append_resume",
    "channel_name",
    "derive",
    "device_memory_bytes",
    "device_peak_flops",
    "diff_snapshots",
    "downsample",
    "ess_normalized",
    "explained_variance",
    "flatten_snapshots",
    "hist_quantile",
    "history_path",
    "host_stale_rows",
    "learn_record",
    "maybe_aggregator",
    "maybe_history",
    "maybe_ledger",
    "maybe_perf_tracker",
    "maybe_slo_engine",
    "merge_result_dir",
    "merge_snapshots",
    "merge_traces",
    "parse_slo_spec",
    "process_self_stats",
    "publish",
    "render_healthz",
    "render_prometheus",
    "robust_z",
    "stale_bucket_index",
    "straggler_report",
]
