"""Fleet-wide observability: metrics registries, the telemetry aggregator,
exporters (Prometheus / JSON / tensorboard), and span tracing.

See ``docs/ARCHITECTURE.md`` ("Observability") for the data flow.
"""

from tpu_rl.obs.aggregator import (
    DEFAULT_STALE_AFTER_S,
    LEARNER_VERSION_GAUGE,
    STALENESS_HIST,
    TelemetryAggregator,
    maybe_aggregator,
)
from tpu_rl.obs.clocksync import ClockEstimate, ClockSync
from tpu_rl.obs.exporters import (
    JsonExporter,
    TelemetryHTTPServer,
    TensorboardExporter,
    render_healthz,
    render_prometheus,
)
from tpu_rl.obs.flightrec import FlightRecorder
from tpu_rl.obs.merge import merge_result_dir, merge_traces
from tpu_rl.obs.registry import (
    HIST_BUCKETS,
    MetricsRegistry,
    PeriodicSnapshot,
    diff_snapshots,
    merge_snapshots,
)
from tpu_rl.obs.trace import TraceRecorder

__all__ = [
    "ClockEstimate",
    "ClockSync",
    "DEFAULT_STALE_AFTER_S",
    "FlightRecorder",
    "HIST_BUCKETS",
    "JsonExporter",
    "LEARNER_VERSION_GAUGE",
    "MetricsRegistry",
    "PeriodicSnapshot",
    "STALENESS_HIST",
    "TelemetryAggregator",
    "TelemetryHTTPServer",
    "TensorboardExporter",
    "TraceRecorder",
    "diff_snapshots",
    "maybe_aggregator",
    "merge_result_dir",
    "merge_snapshots",
    "merge_traces",
    "render_healthz",
    "render_prometheus",
]
