"""Merge per-role ``TraceRecorder`` dumps into one clock-corrected fleet
trace.

Every role dumps its own span ring (``trace.json`` for the learner,
``trace-<role>-<pid>.json`` for the others) with a ``meta`` block carrying
role/pid/host and the wall-clock anchor of its ``perf_counter`` epoch. This
module folds those rings onto ONE timeline:

1. **Clock correction** — the storage dump embeds ``meta.clock``, the
   :class:`~tpu_rl.obs.clocksync.ClockSync` snapshot keyed ``role/host/pid``
   (offsets are remote-minus-reference, reference = the storage/learner
   host). Each ring's anchor is shifted by its source's offset; rings
   without an estimate (storage and learner themselves, or a source the
   estimator never saw) pass through unshifted.
2. **Flow synthesis** — spans tagged ``args.trace_id`` by the wire hops
   (worker tick, manager in/out, storage ingest, window close) are chained
   per trace id in corrected-time order and joined with Chrome flow events
   (``ph: s/t/f``), which Perfetto renders as linked arrows. The learner
   hop is synthesized: the shm data plane carries no per-window metadata,
   so the chain is closed onto the first ``train-step`` span that begins
   after the chain's ``window-close`` (flagged ``synthesized: true`` in the
   flow args — it is a plausible consumer, not a measured identity).

Run standalone (``python -m tpu_rl.obs.merge result_dir/``) or let the
storage edge auto-merge at shutdown; both write ``fleet_trace.json`` next to
the inputs, atomically.
"""

from __future__ import annotations

import glob
import json
import os
import sys

MERGED_NAME = "fleet_trace.json"
# Spans that participate in a rollout's lineage chain, in hop order — used
# only for tie-breaking events at equal corrected timestamps.
_HOP_ORDER = {
    "worker-tick": 0,
    "relay-in": 1,
    "relay-out": 2,
    "storage-ingest": 3,
    "window-close": 4,
    "train-step": 5,
}


def load_trace(path: str) -> dict | None:
    """One TraceRecorder dump, or None when unreadable/not a trace doc."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return None
    return doc


def _doc_key(doc: dict) -> str:
    meta = doc.get("meta") or {}
    return f"{meta.get('role', '?')}/{meta.get('host', '?')}/{meta.get('pid', '?')}"


def merge_traces(docs: list[dict]) -> dict:
    """Merge loaded trace docs; see the module docstring for semantics."""
    # The reference clock map comes from whichever doc carries one (the
    # storage dump); later docs win, which is harmless — there is one
    # storage process per result_dir.
    clock: dict[str, dict] = {}
    for doc in docs:
        meta = doc.get("meta") or {}
        if isinstance(meta.get("clock"), dict):
            clock.update(meta["clock"])

    events: list[dict] = []
    roles: list[str] = []
    # (corrected_ts_us, hop_rank, pid, tid, name, dur_us) per lineage span
    chains: dict[int, list[tuple]] = {}
    train_steps: list[tuple] = []  # (corrected_ts_us, pid, tid, dur_us)

    for i, doc in enumerate(docs):
        meta = doc.get("meta") or {}
        role = str(meta.get("role") or "?")
        anchor_ns = meta.get("wall_anchor_ns")
        if not isinstance(anchor_ns, int):
            continue  # pre-anchor dump: no shared axis to place it on
        est = clock.get(_doc_key(doc))
        offset_ns = int(est.get("offset_ns", 0)) if isinstance(est, dict) else 0
        # Corrected wall microseconds of the ring's epoch: local anchor
        # pulled back onto the reference clock (remote = reference + offset).
        base_us = (anchor_ns - offset_ns) / 1e3
        roles.append(role)
        # pid collisions across hosts would fold two processes into one
        # Perfetto track — remap each doc to its own pid lane.
        pid = i
        for ev in doc.get("traceEvents", ()):
            if not isinstance(ev, dict):
                continue
            out = dict(ev)
            out["pid"] = pid
            if ev.get("ph") == "X":
                ts = base_us + float(ev.get("ts", 0.0))
                out["ts"] = ts
                args = ev.get("args")
                tid = ev.get("tid", 0)
                dur = float(ev.get("dur", 0.0))
                name = str(ev.get("name", ""))
                if isinstance(args, dict) and "trace_id" in args:
                    try:
                        trace_id = int(args["trace_id"])
                    except (TypeError, ValueError):
                        trace_id = None
                    if trace_id is not None:
                        chains.setdefault(trace_id, []).append(
                            (ts, _HOP_ORDER.get(name, 9), pid, tid, name, dur)
                        )
                if name == "train-step":
                    train_steps.append((ts, pid, tid, dur))
            events.append(out)

    if not events:
        return {
            "traceEvents": [],
            "displayTimeUnit": "ms",
            "meta": {"roles": [], "flows": 0, "clock": clock},
        }

    # Close each chain onto a plausible learner consumer: the first
    # train-step beginning at or after the chain's last measured hop.
    train_steps.sort()
    for hops in chains.values():
        hops.sort()
        if not train_steps or hops[-1][4] == "train-step":
            continue
        t_last = hops[-1][0]
        nxt = next((t for t in train_steps if t[0] >= t_last), None)
        if nxt is not None:
            ts, pid, tid, dur = nxt
            hops.append((ts, _HOP_ORDER["train-step"], pid, tid, "train-step", dur))

    # Normalize the axis so the merged trace starts near zero.
    t0 = min(ev["ts"] for ev in events if ev.get("ph") == "X")
    for ev in events:
        if ev.get("ph") == "X":
            ev["ts"] -= t0

    # Flow events: one s -> t... -> f arrow chain per trace id. Each step
    # binds to its hop's slice (same pid/tid, ts inside the slice).
    flows: list[dict] = []
    n_flows = 0
    for trace_id, hops in sorted(chains.items()):
        if len(hops) < 2:
            continue
        n_flows += 1
        last = len(hops) - 1
        for j, (ts, _rank, pid, tid, name, dur) in enumerate(hops):
            ph = "s" if j == 0 else ("f" if j == last else "t")
            ev = {
                "name": "rollout-lineage",
                "cat": "lineage",
                "ph": ph,
                # Bind inside the slice: the start anchors at the slice end
                # (the frame leaves the hop), later steps at the slice start.
                "ts": (ts - t0) + (dur if j == 0 else 0.0),
                "pid": pid,
                "tid": tid,
                "id": f"0x{trace_id:x}",
                "args": {
                    "trace_id": trace_id,
                    "hop": name,
                    "synthesized": name == "train-step",
                },
            }
            if ph == "f":
                ev["bp"] = "e"  # bind to the enclosing slice, not the next
            flows.append(ev)
    events.extend(flows)

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "meta": {
            "roles": sorted(set(roles)),
            "flows": n_flows,
            "clock": clock,
        },
    }


def find_trace_files(result_dir: str) -> list[str]:
    files = sorted(
        set(glob.glob(os.path.join(result_dir, "trace.json")))
        | set(glob.glob(os.path.join(result_dir, "trace-*.json")))
    )
    return [f for f in files if os.path.basename(f) != MERGED_NAME]


def merge_result_dir(result_dir: str, out_path: str | None = None) -> dict:
    """Merge every trace dump under ``result_dir`` -> ``fleet_trace.json``.
    Returns a summary dict (also useful to asserting callers)."""
    files = find_trace_files(result_dir)
    docs = [d for d in (load_trace(f) for f in files) if d is not None]
    merged = merge_traces(docs)
    out = out_path or os.path.join(result_dir, MERGED_NAME)
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out)
    return {
        "out": out,
        "n_files": len(docs),
        "n_events": len(merged["traceEvents"]),
        "roles": merged["meta"]["roles"],
        "flows": merged["meta"]["flows"],
    }


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m tpu_rl.obs.merge <result_dir>", file=sys.stderr)
        return 2
    result_dir = argv[0]
    if not os.path.isdir(result_dir):
        print(f"not a directory: {result_dir}", file=sys.stderr)
        return 2
    summary = merge_result_dir(result_dir)
    if summary["n_files"] == 0:
        print(f"no trace dumps found under {result_dir}", file=sys.stderr)
        return 1
    print(
        f"merged {summary['n_files']} trace file(s), "
        f"{summary['n_events']} events, {summary['flows']} linked flow(s), "
        f"roles={summary['roles']} -> {summary['out']}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
