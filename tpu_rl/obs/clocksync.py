"""NTP-style clock-offset estimation from timestamp echoes on existing frames.

Every role's ``TraceRecorder`` anchors its spans to the local ``time.time_ns``
wall clock — but fleet hosts' wall clocks disagree by milliseconds (or worse),
which is the same order as the transport latencies the fleet trace is supposed
to show. This estimator recovers each remote process's offset against the
storage process's clock WITHOUT new ports or probe traffic, from timestamps
already riding the fleet's frames:

- ``t0``: the learner stamps ``t_tx`` onto every Model broadcast;
- ``t1``: the worker notes its receive time for the newest broadcast;
- ``t2``: the worker stamps its Telemetry snapshot at send (``clk`` field,
  echoing t0/t1);
- ``t3``: the storage edge notes the snapshot's ingest time.

Learner and storage are colocated by construction (they share a shm store),
so t0 and t3 are readings of the SAME reference clock and the four stamps
form a full NTP round trip through the worker:

    offset = ((t1 - t0) + (t2 - t3)) / 2        (remote minus reference)
    delay  = (t3 - t0) - (t2 - t1)

with the classic bound |error| <= delay/2, which holds under arbitrarily
asymmetric path latencies — that worst case is exactly what the uncertainty
must cover, so it is reported, never assumed away. Samples are filtered
NTP-style: the estimate comes from the minimum-delay sample in a sliding
window (least queueing noise), and its uncertainty grows with sample age at a
generous crystal-drift allowance.

Managers have no return path on existing frames (their snapshots flow one
way), so they get a one-way estimate: each ``t_rx - t_tx`` observation is
``delay - offset`` shifted, making ``max(t_tx - t_rx)`` a lower bound on the
offset that tightens with the minimum-delay frame. These estimates are
flagged ``kind="one-way"`` so the merger and dashboards can show them as
bounds, not truths.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass

# Crystal-oscillator drift allowance: uncertainty grows by this much per
# second since the sample was taken. 200 ppm is far beyond typical server
# crystals (~10-50 ppm) — generous on purpose, the bound must hold.
DRIFT_PPM = 200.0
# Uncertainty floor: even a zero-delay sample can't beat timestamp
# granularity + interrupt jitter.
MIN_UNCERTAINTY_NS = 1_000
# One-way estimates can't bound the path delay at all; give them a wide
# floor so nobody mistakes them for a calibrated offset.
ONE_WAY_FLOOR_NS = 1_000_000


@dataclass
class ClockEstimate:
    """Offset of a remote process's clock relative to the reference clock
    (``remote = reference + offset_ns``), with an uncertainty the true
    offset is guaranteed to lie within (NTP delay bound + drift allowance)."""

    offset_ns: int
    uncertainty_ns: int
    n_samples: int
    kind: str  # "rtt" (full round trip) or "one-way" (lower bound)
    age_s: float  # age of the winning sample when the estimate was made


class _Sample:
    __slots__ = ("t_local_ns", "offset_ns", "delay_ns", "kind")

    def __init__(self, t_local_ns: int, offset_ns: int, delay_ns: int, kind: str):
        self.t_local_ns = t_local_ns
        self.offset_ns = offset_ns
        self.delay_ns = delay_ns
        self.kind = kind


class ClockSync:
    """Per-source sliding-window offset estimator. Keys are opaque strings
    (the telemetry plane uses ``"role/host/pid"``)."""

    def __init__(self, window: int = 64, clock=time.time_ns):
        self.window = int(window)
        self.clock = clock
        self._samples: dict[str, deque] = {}
        self.n_samples = 0

    # ---------------------------------------------------------------- ingest
    def add_round_trip(
        self, key: str, t0: int, t1: int, t2: int, t3: int
    ) -> None:
        """One full NTP exchange: reference-send t0, remote-recv t1,
        remote-send t2, reference-recv t3 (all ``time_ns`` readings)."""
        delay = (t3 - t0) - (t2 - t1)
        if delay < 0:
            # Physically impossible ordering — a re-used echo or a stepped
            # clock mid-exchange. Clamp rather than drop: the offset sample
            # is still the best available, just with no delay credit.
            delay = 0
        offset = ((t1 - t0) + (t2 - t3)) // 2
        self._push(key, offset, delay, "rtt")

    def add_one_way(self, key: str, t_tx: int, t_rx: int) -> None:
        """One remote-send / reference-recv pair (no return path). The
        sample ``t_tx - t_rx = offset - delay`` lower-bounds the offset."""
        self._push(key, t_tx - t_rx, 0, "one-way")

    def _push(self, key: str, offset: int, delay: int, kind: str) -> None:
        dq = self._samples.get(key)
        if dq is None:
            dq = self._samples[key] = deque(maxlen=self.window)
        dq.append(_Sample(self.clock(), offset, delay, kind))
        self.n_samples += 1

    # -------------------------------------------------------------- estimate
    def estimate(self, key: str) -> ClockEstimate | None:
        dq = self._samples.get(key)
        if not dq:
            return None
        now = self.clock()
        rtts = [s for s in dq if s.kind == "rtt"]
        if rtts:
            # NTP clock filter: the minimum-delay sample saw the least
            # queueing, so its delay/2 bound is the tightest available.
            best = min(rtts, key=lambda s: s.delay_ns)
            offsets = [s.offset_ns for s in rtts]
            # Jitter term: the window's own spread catches a clock that
            # stepped between samples, which the single best sample can't.
            jitter = (max(offsets) - min(offsets)) // 2
            age_s = max(0.0, (now - best.t_local_ns) / 1e9)
            unc = (
                best.delay_ns // 2
                + jitter
                + int(DRIFT_PPM * 1e3 * age_s)
                + MIN_UNCERTAINTY_NS
            )
            return ClockEstimate(
                offset_ns=best.offset_ns,
                uncertainty_ns=unc,
                n_samples=len(rtts),
                kind="rtt",
                age_s=age_s,
            )
        # One-way only: every sample under-estimates by its (unknown) delay,
        # so take the max (minimum-delay frame) and report a wide bound —
        # the spread plus a floor, because the residual delay is unbounded
        # from this side.
        best = max(dq, key=lambda s: s.offset_ns)
        offsets = [s.offset_ns for s in dq]
        age_s = max(0.0, (now - best.t_local_ns) / 1e9)
        unc = (
            (max(offsets) - min(offsets))
            + int(DRIFT_PPM * 1e3 * age_s)
            + ONE_WAY_FLOOR_NS
        )
        return ClockEstimate(
            offset_ns=best.offset_ns,
            uncertainty_ns=unc,
            n_samples=len(dq),
            kind="one-way",
            age_s=age_s,
        )

    def snapshot(self) -> dict[str, dict]:
        """JSON-ready map of every source's current estimate — embedded into
        the storage trace dump's ``meta.clock`` for the merger."""
        out = {}
        for key in self._samples:
            est = self.estimate(key)
            if est is None:
                continue
            out[key] = {
                "offset_ns": est.offset_ns,
                "uncertainty_ns": est.uncertainty_ns,
                "n_samples": est.n_samples,
                "kind": est.kind,
                "age_s": round(est.age_s, 3),
            }
        return out
