"""Goodput ledger: exhaustive, non-overlapping wall-clock attribution.

Every role's main loop owns one ``GoodputLedger`` (telemetry-gated — the
plane-off path is one ``is None`` check) and attributes each span of loop
wall time to exactly one bucket via ``add(BUCKET, secs)``. The taxonomy is
closed::

    compute / h2d / queue-wait / wire / ckpt / rollback / recompile /
    idle / overhead

``snapshot()`` turns the accumulators into an exhaustive breakdown: any
elapsed time the loop did not explicitly attribute spills into ``overhead``
(so the buckets sum to elapsed wall time by construction), while attributed
time EXCEEDING elapsed — the double-count failure mode, e.g. a feeder
thread's spans leaking into the main lane — surfaces as ``overcommit``
instead of being silently normalized away. The invariant the tests and
``make goodput-smoke`` pin is ``overcommit_ratio <= 1%``: buckets sum to
elapsed wall time within 1%, nothing counted twice.

Ledger rules (documented in ARCHITECTURE.md §Goodput):

- one ledger per loop THREAD — work done on other lanes (the learner's
  prefetch feeder, the async checkpoint writer, the async weight
  publisher) is never added; it overlaps the main lane and would
  double-count. The synchronous remnants (sync-feed h2d, the device-side
  checkpoint snapshot) ARE main-lane time and are attributed.
- ``goodput`` is the compute share: the fraction of wall time the role
  spent on the work it exists for (train steps, acting math, ingest).

Gauges published on the telemetry cadence (``publish``): the per-role
family ``{role}-goodput-ratio`` plus one ``{role}-time-{bucket}-ratio``
per bucket and ``{role}-time-overcommit-ratio`` — they ride the existing
registry → aggregator → Prometheus path, so SLO rules like
``gauge:learner-goodput-ratio>0.6`` need no engine change.

Straggler analytics (storage-side, report-only): per-wid frame-rate /
policy-staleness / rtt robust z-scores against the fleet median, rolled
into a ``worker-straggler-score`` gauge and a top-k report on
``GET /goodput``. Quarantine (the heal plane) stays the enforcement arm.
"""

from __future__ import annotations

import time

# The closed bucket taxonomy. Order is the accumulator layout; the integer
# aliases below are what hot loops pass to ``add`` (STRICT hot-path tier:
# no per-call string hashing, no literals).
BUCKETS = (
    "compute",
    "h2d",
    "queue-wait",
    "wire",
    "ckpt",
    "rollback",
    "recompile",
    "idle",
    "overhead",
)
(
    COMPUTE,
    H2D,
    QUEUE_WAIT,
    WIRE,
    CKPT,
    ROLLBACK,
    RECOMPILE,
    IDLE,
    OVERHEAD,
) = range(len(BUCKETS))

# Gauge-name families (role-prefixed at ledger construction). The constants
# carry the family suffixes so the drift checker sees the documented
# ``*-goodput-ratio`` / ``*-time-*-ratio`` wildcard rows matched in code.
GOODPUT_RATIO_GAUGE = "-goodput-ratio"
TIME_RATIO_GAUGE = "-time-%s-ratio"
STRAGGLER_GAUGE = "worker-straggler-score"

_OVERCOMMIT = "overcommit"


class GoodputLedger:
    """Per-loop wall-clock attribution into the closed bucket taxonomy."""

    __slots__ = ("role", "_clock", "_t0", "_acc", "_goodput_name", "_names")

    def __init__(self, role: str, clock=time.perf_counter):
        self.role = role
        self._clock = clock
        self._t0 = clock()
        self._acc = [0.0] * len(BUCKETS)
        self._goodput_name = role + GOODPUT_RATIO_GAUGE
        names = [role + (TIME_RATIO_GAUGE % b) for b in BUCKETS]
        names.append(role + (TIME_RATIO_GAUGE % _OVERCOMMIT))
        self._names = tuple(names)

    # ------------------------------------------------------------- hot path
    def add(self, bucket: int, secs: float) -> None:
        """Attribute ``secs`` of main-lane wall time to one bucket.

        STRICT hot-path tier (tools/analysis manifest): one float add,
        no allocation beyond float boxing.
        """
        if secs > 0.0:
            self._acc[bucket] += secs

    def now(self) -> float:
        return self._clock()

    # ------------------------------------------------------------ snapshots
    def elapsed(self) -> float:
        return self._clock() - self._t0

    def snapshot(self) -> dict:
        """Exhaustive breakdown: buckets sum to max(elapsed, attributed).

        Unattributed time spills into ``overhead``; attributed time past
        elapsed (double-count) is reported as ``overcommit_s`` /
        ``overcommit_ratio`` rather than hidden by normalization.
        """
        elapsed = self.elapsed()
        explicit = sum(self._acc)
        spill = elapsed - explicit
        buckets = dict(zip(BUCKETS, self._acc, strict=True))
        if spill > 0.0:
            buckets["overhead"] += spill
            total, overcommit = elapsed, 0.0
        else:
            total, overcommit = explicit, -spill
        denom = total if total > 0.0 else 1.0
        ratios = {b: v / denom for b, v in buckets.items()}
        return {
            "role": self.role,
            "elapsed_s": elapsed,
            "buckets": buckets,
            "ratios": ratios,
            "goodput": ratios["compute"],
            "overcommit_s": overcommit,
            "overcommit_ratio": overcommit / denom,
        }

    def publish(self, registry) -> dict:
        """Set the per-role gauges from a fresh snapshot; returns it."""
        snap = self.snapshot()
        registry.gauge(self._goodput_name).set(snap["goodput"])
        for i, b in enumerate(BUCKETS):
            registry.gauge(self._names[i]).set(snap["ratios"][b])
        registry.gauge(self._names[len(BUCKETS)]).set(snap["overcommit_ratio"])
        return snap


def maybe_ledger(role: str, enabled: bool) -> GoodputLedger | None:
    """The plane gate: None when telemetry is off (hot loops pay one
    ``is None`` check, same discipline as every other obs subsystem)."""
    return GoodputLedger(role) if enabled else None


# ------------------------------------------------------------- stragglers
def robust_z(values: dict, floor: float = 0.0) -> dict:
    """Robust z-score per key: (x - median) / scale, where scale is the
    scaled MAD floored at 5% of |median| (a uniform fleet with measurement
    noise must NOT produce stragglers — MAD alone collapses to ~0 there
    and would amplify jitter into false positives). ``floor`` is an
    absolute scale floor in the signal's own units, for signals whose
    healthy median is exactly 0 (staleness): without it one lagging member
    divides by ~0 and the score loses all magnitude meaning."""
    if not values:
        return {}
    xs = sorted(values.values())
    med = _median(xs)
    mad = _median(sorted(abs(x - med) for x in xs))
    scale = max(1.4826 * mad, 0.05 * abs(med), floor, 1e-9)
    return {k: (v - med) / scale for k, v in values.items()}


def _median(xs: list) -> float:
    n = len(xs)
    mid = n // 2
    if n % 2:
        return float(xs[mid])
    return (xs[mid - 1] + xs[mid]) / 2.0


def straggler_report(
    frame_rate: dict | None = None,
    staleness: dict | None = None,
    rtt: dict | None = None,
    k: int = 5,
) -> tuple[dict, list]:
    """Per-wid straggler scores + the top-k report.

    Signals are oriented so positive = straggling: a frame rate BELOW the
    fleet median (negated z), staleness or rtt ABOVE it (raw z). The score
    is the worst oriented z across available signals, floored at 0 — any
    single bad signal marks the wid; a wid missing a signal (e.g. no rtt
    estimate yet) is judged on what it has. Returns ``(scores_by_wid,
    top_k_entries)`` with entries shaped for ``GET /goodput``::

        {"wid": 1, "score": 20.1, "signals": {"frame-rate": 0.0, ...},
         "z": {"frame-rate": 20.1, ...}}

    Report-only by design: quarantine (PR 13) is the enforcement arm.
    """
    frame_rate = frame_rate or {}
    staleness = staleness or {}
    rtt = rtt or {}
    oriented = {
        "frame-rate": {w: -z for w, z in robust_z(frame_rate).items()},
        # Absolute scale floors: a healthy fleet sits at staleness ~0 and
        # sub-ms rtt jitter, so z is "excess over one update" / "excess
        # over 1 ms" there rather than a division by ~0.
        "staleness": robust_z(staleness, floor=1.0),
        "rtt": robust_z(rtt, floor=1e-3),
    }
    raw = {"frame-rate": frame_rate, "staleness": staleness, "rtt": rtt}
    wids = set(frame_rate) | set(staleness) | set(rtt)
    scores: dict = {}
    entries = []
    for wid in wids:
        zs = {s: d[wid] for s, d in oriented.items() if wid in d}
        score = max(0.0, max(zs.values()))
        scores[wid] = score
        entries.append(
            {
                "wid": wid,
                "score": round(score, 3),
                "signals": {
                    s: round(d[wid], 6) for s, d in raw.items() if wid in d
                },
                "z": {s: round(z, 3) for s, z in zs.items()},
            }
        )
    entries.sort(key=lambda e: (-e["score"], str(e["wid"])))
    return scores, entries[:k]
