"""Live performance plane: MFU/FLOPs, recompiles, device memory, profiler.

``bench.py`` already knows how to turn ``compiled.cost_analysis()`` into
FLOPs-per-step and MFU — but only offline, one workload at a time. This
module promotes those instruments into the running fleet so every role with
telemetry on reports them continuously:

- :class:`PerfTracker` — attach to a jitted entry point (learner
  ``train_step``, the colocated fused program, the inference ``act`` step).
  On first sight of a callable it does a ONE-TIME AOT ``lower().compile().
  cost_analysis()`` to capture analytical FLOPs per dispatched call (the AOT
  executable is separate from the call cache, so this costs one extra
  compile — acceptable one-time, and only when telemetry is on), then
  derives achieved FLOPs/s and MFU from a rolling window of dispatch
  intervals. Recompiles are counted from the callable's jit cache size
  (``_cache_size()``): after warmup the cache holds exactly one entry per
  seen signature, so ``cache_size - 1`` IS the number of shape-drift
  retraces — a far sharper signal than process-wide compile events, which
  fire several times per trace. Rebinding a rebuilt callable (the learner's
  anneal switch) freezes the old count and restarts the baseline, so
  expected rebuilds don't masquerade as drift.
- :func:`device_peak_flops` / :data:`PEAK_FLOPS` — the single source of
  truth for bf16 peak by device kind; ``bench.py`` imports these from here
  so live and offline MFU can never disagree on the denominator.
  ``TPU_RL_PEAK_FLOPS`` (env, FLOPs/s per device) overrides for backends
  with no table entry — it's what lets CPU smokes exercise the MFU path.
- :func:`device_memory_bytes` — in-use/peak watermarks from
  ``device.memory_stats()``; backends that report none (CPU) fall back to
  process RSS with a module-tracked high-water mark.
- :func:`process_self_stats` — RSS + open-fd count from ``/proc/self``
  (no psutil), cheap enough to refresh on the telemetry emit cadence.
- :class:`ProfilerCapture` — the one gate every profiler path goes
  through: the learner's config window, ``/prof?ms=N`` on the telemetry
  HTTP server, and ``SIGUSR2`` (mirroring the flight recorder's SIGUSR1).
  Captures are serialized (an overlapping request is refused, HTTP 409),
  bounded, land under ``result_dir``, and ``stop_trace()`` is guaranteed on
  fatal exceptions via the flight-recorder crash hook.

jax imports are lazy: constructing registries/aggregators must not drag the
backend into processes that don't own one.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from tpu_rl.obs import flightrec

# bf16 peak FLOPs/s per chip by device kind (public spec sheets). MFU is
# reported against bf16 peak regardless of compute dtype (standard MFU
# convention); unknown kinds (e.g. CPU test runs) -> None -> mfu omitted.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6": 918e12,  # Trillium
}


def device_peak_flops(device=None) -> float | None:
    """Peak bf16 FLOPs/s for one device, or None when unknown. The
    ``TPU_RL_PEAK_FLOPS`` env var (float, per-device) wins over the table —
    set it to give CPU runs a denominator for smoke-testing the MFU path."""
    env = os.environ.get("TPU_RL_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = device.device_kind
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k) or k in kind:
            return v
    return None


def compiled_flops(compiled) -> float:
    """Analytical FLOPs of an AOT-compiled program (0.0 when the backend
    reports none). XLA counts a scan/while body ONCE regardless of trip
    count, so a chained learner program's count already IS per-dispatch."""
    try:
        cost = compiled.cost_analysis() or {}
    except Exception:  # noqa: BLE001 — backends may not implement it
        return 0.0
    if isinstance(cost, (list, tuple)):  # some versions return [dict]
        cost = cost[0] if cost else {}
    return float(cost.get("flops", 0.0) or 0.0)


# ------------------------------------------------------------ process stats
_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
_rss_peak = 0.0  # fallback high-water mark for backends without memory_stats


def process_self_stats() -> tuple[float, int]:
    """(RSS bytes, open fd count) from ``/proc/self`` — no psutil. Returns
    (0.0, 0) where /proc is absent; callers still set the gauges so the
    series exists."""
    rss = 0.0
    try:
        with open("/proc/self/statm") as f:
            rss = float(int(f.read().split()[1])) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        pass
    try:
        n_fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        n_fds = 0
    return rss, n_fds


def device_memory_bytes(device=None) -> tuple[float, float]:
    """(bytes in use, peak bytes) for the role's first device. Backends
    whose ``memory_stats()`` is None/absent (CPU) fall back to process RSS,
    with the peak tracked as a module-level high-water mark so the
    watermark semantics survive the fallback."""
    global _rss_peak
    if device is None:
        import jax

        device = jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — not part of the stable device API
        stats = None
    if stats:
        in_use = float(stats.get("bytes_in_use", 0.0))
        peak = float(stats.get("peak_bytes_in_use", in_use))
        return in_use, peak
    rss, _ = process_self_stats()
    _rss_peak = max(_rss_peak, rss)
    return rss, _rss_peak


# ------------------------------------------------------------- perf tracker
class _JitWatch:
    """Recompile counter for one jitted callable, from its jit cache size.
    ``_cache_size()`` is private API — hasattr-gated; without it the count
    degrades to 0 rather than lying."""

    def __init__(self, fn):
        self.fn = fn
        self._offset = 0  # recompiles frozen from earlier bindings

    def _current(self) -> int:
        size = getattr(self.fn, "_cache_size", None)
        if size is None:
            return 0
        try:
            return max(0, int(size()) - 1)  # first entry is the warmup trace
        except Exception:  # noqa: BLE001 — private API, fail to zero
            return 0

    def rebind(self, fn) -> None:
        """Point at a rebuilt callable (expected recompile, e.g. the
        learner's anneal switch): freeze the old binding's drift count,
        restart the baseline."""
        if fn is self.fn:
            return
        self._offset += self._current()
        self.fn = fn

    @property
    def recompiles(self) -> int:
        return self._offset + self._current()


class PerfTracker:
    """Live FLOPs/MFU/recompile accounting for ONE jitted entry point.

    Loop protocol (all telemetry-gated — the tracker is simply ``None``
    when the plane is off, one ``is None`` check on the hot path):

    - ``capture(fn, *args)`` each iteration before dispatch: an identity
      check when nothing changed; first sight of a (new) callable runs the
      one-time AOT cost analysis and (re)binds the recompile watch.
    - ``note(dt)`` with the wall-clock dispatch interval. Donated buffers
      serialize consecutive dispatches, so in steady state the interval
      converges to true device step time — the same quantity ``bench.py``
      measures with an explicit sync over many iters.
    - read ``flops_per_call`` / ``achieved_flops_per_s()`` / ``mfu()`` /
      ``recompiles`` at emit cadence.
    """

    def __init__(
        self,
        n_devices: int | None = None,
        peak_flops: float | None = None,
        window: int = 100,
    ):
        if n_devices is None:
            import jax

            n_devices = len(jax.devices())
        self.n_devices = int(n_devices)
        self.peak = peak_flops if peak_flops is not None else device_peak_flops()
        self.flops_per_call = 0.0
        self._dts: deque[float] = deque(maxlen=int(window))
        self._watch: _JitWatch | None = None

    def capture(self, fn, *args, **kwargs) -> bool:
        """Bind ``fn`` (idempotent per callable); on a new binding, run the
        one-time cost analysis against the given example args. Returns True
        when a capture actually ran."""
        if self._watch is not None:
            if self._watch.fn is fn:
                return False
            self._watch.rebind(fn)
        else:
            self._watch = _JitWatch(fn)
        try:
            self.flops_per_call = compiled_flops(
                fn.lower(*args, **kwargs).compile()
            )
        except Exception:  # noqa: BLE001 — accounting must never kill a role
            self.flops_per_call = 0.0
        return True

    def note(self, dt_s: float) -> None:
        if dt_s > 0:
            self._dts.append(float(dt_s))

    @property
    def recompiles(self) -> int:
        return self._watch.recompiles if self._watch is not None else 0

    def achieved_flops_per_s(self) -> float | None:
        if not self._dts or self.flops_per_call <= 0:
            return None
        total = sum(self._dts)
        if total <= 0:
            return None
        return self.flops_per_call * len(self._dts) / total

    def mfu(self) -> float | None:
        achieved = self.achieved_flops_per_s()
        if achieved is None or not self.peak:
            return None
        return achieved / (self.peak * self.n_devices)


def maybe_perf_tracker(cfg) -> PerfTracker | None:
    """The role-side constructor: a tracker when the telemetry plane is on,
    else None (hot paths guard on ``is None``, never on a config read)."""
    if not getattr(cfg, "telemetry_enabled", False):
        return None
    return PerfTracker()


# --------------------------------------------------------- profiler capture
class ProfilerCapture:
    """Serialized ``jax.profiler`` trace capture into ``out_dir``.

    One instance per role process gates every capture path — the learner's
    config window (``start()``/``stop()``), HTTP ``/prof?ms=N``
    (:meth:`capture_async`), and SIGUSR2 — so traces never interleave. A
    request while one is in flight is refused (the HTTP layer maps that to
    409). A crash hook registered with the flight recorder guarantees
    ``stop_trace()`` runs on fatal exceptions, so the capture that was
    meant to explain the crash survives it.
    """

    def __init__(self, out_dir: str, default_ms: int = 500):
        self.out_dir = out_dir
        self.default_ms = int(default_ms)
        self._lock = threading.Lock()
        self._active: str | None = None  # trace dir while capturing
        self.n_captures = 0
        flightrec.add_crash_hook(self._crash_stop)

    @property
    def active(self) -> bool:
        return self._active is not None

    def start(self, tag: str = "window") -> str | None:
        """Begin an unbounded capture (caller stops it); None if busy."""
        import jax

        with self._lock:
            if self._active is not None:
                return None
            path = os.path.join(
                self.out_dir, f"prof-{tag}-{time.strftime('%Y%m%d-%H%M%S')}"
            )
            os.makedirs(path, exist_ok=True)
            try:
                jax.profiler.start_trace(path)
            except Exception:  # noqa: BLE001 — profiling is best-effort
                return None
            self._active = path
        return path

    def stop(self) -> str | None:
        """Flush and end the in-flight capture; None when idle. Never
        raises — this runs on crash paths."""
        import jax

        with self._lock:
            if self._active is None:
                return None
            path = self._active
            try:
                jax.profiler.stop_trace()
                self.n_captures += 1
            except Exception:  # noqa: BLE001
                path = None
            finally:
                # Cleared last: unlocked ``active`` readers must never see
                # False while the trace is still flushing / uncounted.
                self._active = None
        return path

    def capture_async(self, ms: int | None = None) -> tuple[bool, str]:
        """Bounded background capture: (True, trace dir) when started,
        (False, reason) when one is already in flight. Powers ``/prof``
        and SIGUSR2."""
        ms = self.default_ms if ms is None else max(1, int(ms))
        path = self.start(tag=f"{ms}ms")
        if path is None:
            return False, "capture in progress"

        def _run():
            time.sleep(ms / 1000.0)
            self.stop()

        threading.Thread(target=_run, name="prof-capture", daemon=True).start()
        return True, path

    def _crash_stop(self) -> None:
        self.stop()

    def close(self) -> None:
        """Stop any in-flight capture and unhook from the crash path."""
        self.stop()
        flightrec.remove_crash_hook(self._crash_stop)

    def install_sigusr2(self) -> bool:
        """Mirror the flight recorder's SIGUSR1: ``kill -USR2 <pid>`` grabs
        a bounded capture from a live process. Main-thread-only (Python's
        signal API); returns whether the handler landed."""
        if threading.current_thread() is not threading.main_thread():
            return False
        import signal

        def _on_signal(signum, frame):
            self.capture_async()

        try:
            signal.signal(signal.SIGUSR2, _on_signal)
        except (ValueError, OSError, AttributeError):
            return False
        return True
