"""Learning-dynamics diagnostics: watch the learning, not just the machines.

Every observability layer before this one (telemetry, tracing, perf/SLO,
goodput) watches the *system*; this module watches the *update math*. The
seven ``make_train_step`` loops (``tpu_rl/algos``) additionally return an
in-jit ``diag`` pytree — per-row moment sums of policy entropy, approx-KL,
clip rates, importance weights, advantages and value errors, plus per-update
scalars (per-module grad norms, update/param norm, SAC alpha + target-Q,
V-MPO eta) — and the learner folds each dispatch's ``diag`` into an
on-device accumulator **bucketed by the batch's policy staleness** (the
learner-version delta that rides every RolloutBatch). Host readback happens
only on the existing loss-log cadence (the PR 13 nonfinite-counter pattern:
zero extra per-step syncs), where :func:`derive` turns the raw moment sums
into the published curves — ``learner-diag-*`` gauges, the per-staleness
``learner-diag-by-stale-*`` gauge families, and ``result_dir/learn.jsonl``.

The staleness-conditioned ESS/KL curves are exactly the inputs the
IMPACT-style adaptive update:data controller (ROADMAP item 1) regulates
against; until that lands they are SLO-able for free
(``gauge:learner-diag-approx-kl<0.5``-style rules need no engine change).

Contracts:

- **bit-identity**: the diag pytree is derived from existing intermediates
  and never feeds back into the update — params/opt-state with
  ``Config.learn_diag`` on are bitwise equal to off (pinned per algo);
- **row channels are per-row means**: every entry in ``diag["rows"]`` is a
  ``(R,)`` array of per-row means over that row's elements, so bucket
  aggregation needs no element-count bookkeeping — pooled first/second
  moments weight rows equally, which is exact here because every row spans
  the same ``(seq_len - 1) * width`` region;
- **the accumulator is pure sums**: ``accumulate`` is a single jitted
  scatter-add (one-hot matmul over the bucket axis); all division happens
  host-side in :func:`derive`.
"""

from __future__ import annotations

import math
import time
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

# Power-of-two staleness buckets: 0 (fresh / colocated), 1, 2-3, 4-7, ...
# 64+. Eight buckets cover the update:data ratios the IMPACT controller
# will sweep (2^6 updates of lag is already deep off-policy for the
# on-policy families) while keeping the one-hot scatter tiny.
N_STALE_BUCKETS = 8
STALE_BUCKET_LABELS: tuple[str, ...] = (
    "0", "1", "2-3", "4-7", "8-15", "16-31", "32-63", "64+",
)

GAUGE_PREFIX = "learner-diag-"
BUCKET_GAUGE_PREFIX = "learner-diag-by-stale-"

# Headline series of the two families (drift-checked against
# docs/ARCHITECTURE.md; the full set is GAUGE_PREFIX/BUCKET_GAUGE_PREFIX +
# derived channel name — channels an algo doesn't emit don't appear).
ENTROPY_GAUGE = "learner-diag-entropy"
APPROX_KL_GAUGE = "learner-diag-approx-kl"
ESS_GAUGE = "learner-diag-ess"
BY_STALE_ESS_GAUGE = "learner-diag-by-stale-ess"
APPROX_KL_HIST = "learner-diag-approx-kl-hist"
ESS_HIST = "learner-diag-ess-hist"

_EPS = 1e-12


# --------------------------------------------------------------- in-jit math
def rows_mean(x: jax.Array) -> jax.Array:
    """Per-row mean over all non-batch axes: (R, ...) -> (R,). The canonical
    ``diag["rows"]`` channel producer (see module contract)."""
    return jnp.mean(x.reshape(x.shape[0], -1), axis=1)


def module_grad_norms(grads: Any) -> dict[str, jax.Array]:
    """Global grad norm split by module group — ``torso`` (any path part
    containing "body": the shared MLP/conv torsos, SAC's obs/act bodies),
    ``cell`` (the recurrent core), ``heads`` (everything else: output heads,
    dual variables like log_eta/log_alpha). Static path walk, so this is
    free to call under jit."""
    sq = {"torso": 0.0, "cell": 0.0, "heads": 0.0}
    for path, leaf in jax.tree_util.tree_leaves_with_path(grads):
        group = "heads"
        for part in path:
            key = getattr(part, "key", None)
            if not isinstance(key, str):
                continue
            if "body" in key:
                group = "torso"
                break
            if key == "cell":
                group = "cell"
                break
        sq[group] = sq[group] + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return {k: jnp.sqrt(v) for k, v in sq.items()}


def tree_delta_norm(new: Any, old: Any) -> jax.Array:
    """Global norm of ``new - old`` over a param pytree (the applied update's
    magnitude; exactly 0 when a guard skipped the update)."""
    import optax

    return optax.global_norm(
        jax.tree.map(lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32), new, old)
    )


def tree_norm(tree: Any) -> jax.Array:
    import optax

    return optax.global_norm(tree)


def stale_bucket_index(stale: jax.Array) -> jax.Array:
    """Map per-row staleness (updates of policy lag, any numeric dtype) to a
    bucket index in ``[0, N_STALE_BUCKETS)``: 0 for <=0, else
    ``min(1 + floor(log2(s)), K-1)`` — the power-of-two layout above."""
    s = jnp.maximum(stale.astype(jnp.float32), 1.0)
    idx = 1 + jnp.floor(jnp.log2(s)).astype(jnp.int32)
    idx = jnp.minimum(idx, N_STALE_BUCKETS - 1)
    return jnp.where(stale.astype(jnp.float32) <= 0.0, 0, idx)


def host_stale_rows(idx: int, vers: Any, n_rows: int) -> np.ndarray:
    """Per-row policy staleness for one dispatch: ``max(0, idx - ver)`` where
    the version sidecar is known, 0 elsewhere. ``vers`` is the per-row
    learner-version array the store read out of its per-slot sidecar (a
    chained dispatch concatenates its K raws' sidecars, matching the
    flattened row channels); None or a size mismatch degrades to all-fresh
    rather than misattributing rows to the wrong bucket."""
    if vers is None:
        return np.zeros(n_rows, np.float32)
    v = np.asarray(vers).reshape(-1)
    if v.size != n_rows:
        return np.zeros(n_rows, np.float32)
    return np.where(
        v >= 0, np.maximum(np.float64(idx) - v, 0.0), 0.0
    ).astype(np.float32)


def init_acc(diag: Mapping[str, Any]) -> dict:
    """Zero accumulator matching a ``diag`` pytree's channel set (the set is
    static per algo+config, so the jitted :func:`accumulate` traces once)."""
    k = N_STALE_BUCKETS
    return {
        "n-updates": jnp.zeros((), jnp.float32),
        "rows-n": jnp.zeros((k,), jnp.float32),
        "rows": {n: jnp.zeros((k,), jnp.float32) for n in diag["rows"]},
        "scalars": {n: jnp.zeros((), jnp.float32) for n in diag["scalars"]},
    }


def accumulate(acc: dict, diag: Mapping[str, Any], stale: jax.Array) -> dict:
    """Fold one dispatch's ``diag`` into the accumulator: per-row channels
    scatter-add into their staleness bucket (one-hot matmul — no host sync,
    no dynamic shapes), scalars and counts add. ``stale`` is ``(R,)``
    aligned with the row channels; chained dispatch pre-flattens both
    (``parallel.dp``) and carries the update count in ``diag["n-updates"]``."""
    onehot = jax.nn.one_hot(
        stale_bucket_index(stale), N_STALE_BUCKETS, dtype=jnp.float32
    )  # (R, K)
    n_up = diag.get("n-updates", 1.0)
    return {
        "n-updates": acc["n-updates"] + n_up,
        "rows-n": acc["rows-n"] + jnp.sum(onehot, axis=0),
        "rows": {
            n: acc["rows"][n] + onehot.T @ v.astype(jnp.float32)
            for n, v in diag["rows"].items()
        },
        "scalars": {
            n: acc["scalars"][n] + v.astype(jnp.float32)
            for n, v in diag["scalars"].items()
        },
    }


def make_accumulate():
    """The jitted accumulator program (donates the running accumulator, so
    steady state allocates nothing new)."""
    return jax.jit(accumulate, donate_argnums=(0,))


# ---------------------------------------------------- host-side derived math
def ess_normalized(w_mean: float, w2_mean: float) -> float:
    """Normalized importance-weight effective sample size
    ``(Σw)² / (N·Σw²) = E[w]²/E[w²]`` in (0, 1]: 1 for uniform weights,
    ``1/N`` when one element carries all the mass. 0 on no data."""
    if w2_mean <= _EPS:
        return 0.0
    return min(1.0, (w_mean * w_mean) / w2_mean)


def explained_variance(
    ret_mean: float, ret2_mean: float, err_mean: float, err2_mean: float
) -> float:
    """Value explained-variance ``1 - Var(err)/Var(ret)`` from pooled first
    and second moments (``err = target - value``). A constant predictor
    scores 0, a perfect one 1; degenerate targets (Var(ret)=0) score 0."""
    var_ret = max(0.0, ret2_mean - ret_mean * ret_mean)
    var_err = max(0.0, err2_mean - err_mean * err_mean)
    if var_ret <= _EPS:
        return 0.0
    return 1.0 - var_err / var_ret


# Row-channel pairs -> derived metric names. Channels an algo doesn't emit
# simply don't appear (SAC has no "clip"; PPO has no "rho-clip").
_MEAN_CHANNELS = {
    "ent": "entropy",
    "kl": "approx-kl",
    "clip": "clip-frac",
    "rho-clip": "rho-clip-rate",
    "c-clip": "c-clip-rate",
    "adv": "adv-mean",
    "tq": "target-q-mean",
}


def _derive_channels(sums: Mapping[str, float], n_rows: float) -> dict:
    """Derived metrics for one pool (a staleness bucket or the global sum)
    from per-row-mean sums and the pooled row count."""
    if n_rows <= 0:
        return {}
    m = {k: v / n_rows for k, v in sums.items()}
    out = {
        name: m[ch] for ch, name in _MEAN_CHANNELS.items() if ch in m
    }
    if "w" in m and "w2" in m:
        out["ess"] = ess_normalized(m["w"], m["w2"])
    if "adv" in m and "adv2" in m:
        out["adv-std"] = math.sqrt(max(0.0, m["adv2"] - m["adv"] ** 2))
    if "tq" in m and "tq2" in m:
        out["target-q-std"] = math.sqrt(max(0.0, m["tq2"] - m["tq"] ** 2))
    if all(ch in m for ch in ("ret", "ret2", "err", "err2")):
        out["explained-variance"] = explained_variance(
            m["ret"], m["ret2"], m["err"], m["err2"]
        )
    return out


def derive(acc: Mapping[str, Any]) -> dict:
    """Turn a host copy of the accumulator (``jax.device_get``) into the
    published document: ``{"n_updates", "global": {...}, "buckets":
    {label: {..., "rows": n}}}`` — global pools every bucket; only nonempty
    buckets appear."""
    n_up = float(acc["n-updates"])
    rows_n = [float(x) for x in acc["rows-n"]]
    sums = {k: [float(x) for x in v] for k, v in acc["rows"].items()}

    glob = _derive_channels(
        {k: sum(v) for k, v in sums.items()}, sum(rows_n)
    )
    if n_up > 0:
        for name, v in acc["scalars"].items():
            glob[name] = float(v) / n_up
        if glob.get("param-norm", 0.0) > _EPS:
            glob["update-ratio"] = glob.get("update-norm", 0.0) / glob["param-norm"]
    buckets = {}
    for b, label in enumerate(STALE_BUCKET_LABELS):
        if rows_n[b] <= 0:
            continue
        d = _derive_channels({k: v[b] for k, v in sums.items()}, rows_n[b])
        d["rows"] = rows_n[b]
        buckets[label] = d
    return {"n_updates": n_up, "global": glob, "buckets": buckets}


def publish(reg, derived: Mapping[str, Any]) -> None:
    """Export one derived document into a MetricsRegistry: global curves as
    ``learner-diag-<name>`` gauges (the SLO-able series), per-staleness
    families as ``learner-diag-by-stale-<name>`` gauges labeled
    ``stale_bucket`` (a distinct family so a sparsely-populated bucket can
    never trip a worst-case-over-samples SLO rule on the global name), and
    approx-KL/ESS additionally as histograms for distribution-over-time."""
    for name, val in derived["global"].items():
        reg.gauge(GAUGE_PREFIX + name).set(val)
        if name in ("approx-kl", "ess"):
            reg.histogram(GAUGE_PREFIX + name + "-hist").observe(float(val))
    for label, vals in derived["buckets"].items():
        for name, val in vals.items():
            reg.gauge(
                BUCKET_GAUGE_PREFIX + name, labels={"stale_bucket": label}
            ).set(val)


def learn_record(idx: int, derived: Mapping[str, Any]) -> dict:
    """One ``learn.jsonl`` line: the derived document stamped with the
    update index and wall clock (obs/audit.py writer shape)."""
    return {
        "ts": time.time(),
        "idx": int(idx),
        "n_updates": derived["n_updates"],
        **derived["global"],
        "buckets": derived["buckets"],
    }


class DiagAccumulator:
    """Host-side wrapper owning the device accumulator and its jitted fold:
    ``add(diag, stale)`` per dispatch (lazy — one extra device program, no
    sync), ``drain(idx)`` at the log cadence (the only readback) returning
    the derived document and resetting the sums. Constructed only when
    ``Config.learn_diag`` is on and the algo emitted a ``diag`` — callers
    guard on ``is None`` like every other plane."""

    def __init__(self):
        self._acc = None
        self._fold = make_accumulate()

    def add(self, diag: Mapping[str, Any], stale: jax.Array) -> None:
        if self._acc is None:
            self._acc = init_acc(diag)
        self._acc = self._fold(self._acc, diag, stale)

    def drain(self, idx: int) -> dict | None:
        """Block on + read back the accumulated sums, derive, reset. Returns
        None when nothing was accumulated since the last drain."""
        if self._acc is None:
            return None
        host = jax.device_get(self._acc)
        if float(host["n-updates"]) <= 0:
            return None
        self._acc = init_acc(host)
        return derive(host)
