"""Crash flight recorder: postmortem state capture for role processes.

A role that dies today leaves a traceback file (``utils.errlog``) and nothing
else — no timeline of what the process was doing in its final seconds, no
config identity to match the corpse against a deployment. The flight recorder
closes that gap: every role registers one per process, holding

- the role's bounded span ring (its ``TraceRecorder``, when tracing is on),
- the last error seen (fatal or noted by the role itself),
- a config fingerprint (sha256 over the sorted config dict) so a dump is
  attributable to an exact configuration,
- an optional role-supplied ``extra`` callable for live counters
  (queue depths, assembler stats) captured at dump time.

Dumps are atomic (tmp + rename) to
``result_dir/flightrec-<role>-<pid>.json`` and fire on:

- ``SIGUSR1`` — poke a live-but-suspect process from the shell
  (``kill -USR1 <pid>``) without stopping it;
- fatal exception — ``utils.errlog.role_entry`` calls :func:`dump_on_crash`
  before re-raising, so the recorder lands next to the crash log.

The signal handler is only installed when running on the process's main
thread (Python's signal API requires it; tests run roles as threads) — the
crash-dump path works regardless.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import socket
import threading
import time
import traceback

# One recorder per process: the crash hook in utils.errlog has no handle on
# the role object, so the installed recorder is reachable module-globally.
_CURRENT: "FlightRecorder | None" = None

# Cleanup callbacks that must fire on a fatal exception BEFORE the dump —
# e.g. ``jax.profiler.stop_trace()`` so an in-flight capture is flushed to
# disk instead of dying with the process. Kept module-global (like
# ``_CURRENT``) and run even when no recorder is installed: crash cleanup
# must not depend on result_dir being set.
_CRASH_HOOKS: list = []


def add_crash_hook(fn) -> None:
    """Register ``fn()`` to run at crash time (idempotent per callable)."""
    if fn not in _CRASH_HOOKS:
        _CRASH_HOOKS.append(fn)


def remove_crash_hook(fn) -> None:
    if fn in _CRASH_HOOKS:
        _CRASH_HOOKS.remove(fn)


def _run_crash_hooks() -> None:
    for fn in list(_CRASH_HOOKS):
        try:
            fn()
        except Exception:  # noqa: BLE001 — cleanup must not mask the crash
            pass


def config_fingerprint(cfg) -> str | None:
    """Stable short hash of a config's JSON-able dict — enough to tell two
    dumps apart by configuration without shipping the whole config."""
    try:
        d = cfg.to_dict() if hasattr(cfg, "to_dict") else dict(vars(cfg))
        blob = json.dumps(d, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return None
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class FlightRecorder:
    def __init__(
        self,
        role: str,
        result_dir: str | None,
        tracer=None,
        cfg=None,
        extra=None,
    ):
        self.role = role
        self.result_dir = result_dir
        self.tracer = tracer
        self.fingerprint = config_fingerprint(cfg) if cfg is not None else None
        self.extra = extra  # callable -> dict, evaluated at dump time
        self.last_error: str | None = None
        self.n_dumps = 0

    # ---------------------------------------------------------------- wiring
    def install(self) -> "FlightRecorder":
        global _CURRENT
        _CURRENT = self
        if threading.current_thread() is threading.main_thread():
            try:
                signal.signal(signal.SIGUSR1, self._on_signal)
            except (ValueError, OSError, AttributeError):
                pass  # exotic platform / nested handler: crash path still works
        return self

    def _on_signal(self, signum, frame) -> None:
        try:
            self.dump("SIGUSR1")
        except OSError:
            pass  # a poked process must never die of its own postmortem

    def note_error(self, exc: BaseException) -> None:
        self.last_error = "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)
        )

    # ------------------------------------------------------------------ dump
    def snapshot(self, reason: str = "snapshot") -> dict:
        doc = {
            "role": self.role,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "ts_ns": time.time_ns(),
            "reason": reason,
            "config_fingerprint": self.fingerprint,
            "last_error": self.last_error,
            "trace": (
                self.tracer.to_chrome() if self.tracer is not None else None
            ),
        }
        if self.extra is not None:
            try:
                doc["extra"] = self.extra()
            except Exception as e:  # noqa: BLE001 — extra() runs role code
                doc["extra"] = {"error": repr(e)}
        return doc

    def dump(self, reason: str = "snapshot") -> str | None:
        """Atomic write; returns the path, or None without a result_dir."""
        if self.result_dir is None:
            return None
        os.makedirs(self.result_dir, exist_ok=True)
        path = os.path.join(
            self.result_dir, f"flightrec-{self.role}-{os.getpid()}.json"
        )
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(reason), f)
        os.replace(tmp, path)
        self.n_dumps += 1
        return path


def install(
    role: str, result_dir: str | None, tracer=None, cfg=None, extra=None
) -> FlightRecorder:
    """Create + register the process's recorder (latest install wins)."""
    return FlightRecorder(role, result_dir, tracer, cfg, extra).install()


def current() -> FlightRecorder | None:
    return _CURRENT


def dump_on_crash(exc: BaseException) -> str | None:
    """Crash hook for ``utils.errlog.role_entry``: run registered cleanup
    hooks (profiler stop etc.), then record the fatal error into the
    installed recorder (if any) and dump it. Never raises."""
    _run_crash_hooks()
    fr = _CURRENT
    if fr is None:
        return None
    try:
        fr.note_error(exc)
        return fr.dump("fatal-exception")
    except Exception:  # noqa: BLE001 — postmortem must not mask the crash
        return None
