"""Exporters off the :class:`~tpu_rl.obs.aggregator.TelemetryAggregator`.

Three sinks, all stdlib-only (the container has no prometheus_client):

- :func:`render_prometheus` — Prometheus text exposition (format 0.0.4) over
  every source's snapshot, each sample labeled ``role``/``host``/``pid`` plus
  the metric's own labels. Served by :class:`TelemetryHTTPServer` at
  ``/metrics`` together with a staleness-aware ``/healthz``, on
  ``Config.telemetry_port`` (0 = no server, no socket);
- :class:`JsonExporter` — rolling atomic snapshot of the whole plane at
  ``result_dir/telemetry.json`` (tmp + rename, so a scraper never reads a
  torn file);
- :class:`TensorboardExporter` — folds fleet counters/gauges into the same
  event-file machinery the learner already uses (``utils.metrics.make_writer``),
  so fleet health lands next to the loss curves without a new viewer.

Everything here reads aggregator state; nothing writes it — exporters can be
added or dropped without touching the collection path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl

from tpu_rl.obs.aggregator import TelemetryAggregator
from tpu_rl.obs.registry import HIST_BUCKETS, hist_quantile

_NAME_OK = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def sanitize_name(name: str) -> str:
    """Repo metric names (dash convention) -> Prometheus identifiers."""
    out = "".join(ch if ch in _NAME_OK else "_" for ch in name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(value: float) -> str:
    f = float(value)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{sanitize_name(str(k))}="{_escape_label(str(v))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_prometheus(agg: TelemetryAggregator, now: float | None = None) -> str:
    """Deterministic exposition: samples sorted by (name, labels) within
    each metric family, one ``# TYPE`` line per family. Counters keep their
    registered names (no ``_total`` rewrite) so dashboards match the
    tensorboard scalar names one-to-one."""
    counters: dict[str, list] = {}
    gauges: dict[str, list] = {}
    hists: dict[str, list] = {}
    for snap, _age in agg.all_snapshots(now):
        base = {
            "role": str(snap.get("role", "?")),
            "host": str(snap.get("host", "?")),
            "pid": str(snap.get("pid", "?")),
        }
        for name, labels, value in snap.get("counters", ()):
            counters.setdefault(name, []).append(({**base, **labels}, value))
        for name, labels, value in snap.get("gauges", ()):
            gauges.setdefault(name, []).append(({**base, **labels}, value))
        for name, labels, counts, total, count in snap.get("hists", ()):
            hists.setdefault(name, []).append(({**base, **labels}, counts, total, count))

    lines: list[str] = []
    for kind, table in (("counter", counters), ("gauge", gauges)):
        for name in sorted(table):
            pname = sanitize_name(name)
            lines.append(f"# TYPE {pname} {kind}")
            for labels, value in sorted(table[name], key=lambda s: _labels_str(s[0])):
                lines.append(f"{pname}{_labels_str(labels)} {_fmt(value)}")
    for name in sorted(hists):
        pname = sanitize_name(name)
        lines.append(f"# TYPE {pname} histogram")
        for labels, counts, total, count in sorted(
            hists[name], key=lambda s: _labels_str(s[0])
        ):
            cum = 0
            # counts carries one extra overflow slot past the last bound; the
            # +Inf line below renders it, so the truncating zip is deliberate.
            for bound, c in zip(HIST_BUCKETS, counts, strict=False):
                cum += c
                le = {**labels, "le": repr(bound)}
                lines.append(f"{pname}_bucket{_labels_str(le)} {cum}")
            le = {**labels, "le": "+Inf"}
            lines.append(f"{pname}_bucket{_labels_str(le)} {count}")
            lines.append(f"{pname}_sum{_labels_str(labels)} {_fmt(total)}")
            lines.append(f"{pname}_count{_labels_str(labels)} {count}")
            # Pre-interpolated tail quantile (registry.hist_quantile) so
            # dashboards without PromQL histogram_quantile() — and the bare
            # curl in the README — still read a p99 directly.
            p99 = hist_quantile(counts, 0.99)
            if p99 is not None:
                lines.append(f"{pname}_p99{_labels_str(labels)} {_fmt(p99)}")
    return "\n".join(lines) + "\n"


def render_healthz(
    agg: TelemetryAggregator, now: float | None = None
) -> tuple[int, dict]:
    """(HTTP status, body): 200 while every known role has a fresh source,
    503 once any goes silent past the aggregator's staleness window. Roles
    never seen simply aren't listed — liveness is about sources that exist."""
    roles = agg.role_health(now)
    ok = all(r["alive"] for r in roles.values())
    body = {
        "status": "ok" if ok else "stale",
        "stale_after_s": agg.stale_after_s,
        "roles": {
            role: {
                "alive": bool(r["alive"]),
                "sources": int(r["sources"]),
                "age_s": round(float(r["age_s"]), 3),
            }
            for role, r in sorted(roles.items())
        },
    }
    return (200 if ok else 503), body


class TelemetryHTTPServer:
    """stdlib HTTP thread serving ``/metrics`` (Prometheus text),
    ``/healthz`` (JSON liveness) and — when the owner wires the matching
    callable — ``/tracez`` (the role's live span ring + clock estimates),
    ``/slo`` (last SLO verdict: 200 while every rule holds, 503 on any hard
    failure, so probes can alert off the status line alone), ``/goodput``
    (wall-clock attribution breakdown + straggler top-k), ``/autopilot``
    (the autopilot controller's live status: counts, recent actions with
    reasons, per-rule cooldowns), ``/query?metric=&start=&end=&step=``
    (range queries over the run-history store when the owner wires a
    ``query`` callable — raw points, or min/max/mean/last buckets when
    ``step`` is set; without ``metric``, the series listing) and
    ``/prof?ms=N``
    (bounded on-demand ``jax.profiler`` capture; an overlapping request is
    refused with 409). Daemonized: it must never hold the storage process
    open at shutdown, and :meth:`close` is idempotent and bounded so cluster
    e2e tests can tear servers down back-to-back without leaking the
    socket."""

    def __init__(
        self,
        agg: TelemetryAggregator,
        port: int,
        host: str = "",
        tracez=None,
        slo=None,
        prof=None,
        goodput=None,
        autopilot=None,
        query=None,
    ):
        self.agg = agg
        self.tracez = tracez  # callable -> JSON-able dict, or None
        self.slo = slo  # callable -> SLO report dict, or None
        self.prof = prof  # callable (ms|None) -> (started, path|reason)
        self.goodput = goodput  # callable -> goodput/straggler doc, or None
        self.autopilot = autopilot  # callable -> autopilot status doc, or None
        self.query = query  # callable (params dict) -> (status, doc), or None

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    body = render_prometheus(outer.agg).encode()
                    ctype, status = "text/plain; version=0.0.4", 200
                elif path == "/healthz":
                    status, payload = render_healthz(outer.agg)
                    body = (json.dumps(payload, indent=1) + "\n").encode()
                    ctype = "application/json"
                elif path == "/tracez":
                    payload = (
                        outer.tracez() if outer.tracez is not None
                        else {"trace": None}
                    )
                    body = (json.dumps(payload) + "\n").encode()
                    ctype, status = "application/json", 200
                elif path == "/slo":
                    if outer.slo is None:
                        payload, status = {"error": "no slo rules configured"}, 404
                    else:
                        payload = outer.slo()
                        status = 200 if payload.get("ok", True) else 503
                    body = (json.dumps(payload, indent=1) + "\n").encode()
                    ctype = "application/json"
                elif path == "/goodput":
                    if outer.goodput is None:
                        payload, status = {"error": "goodput ledger not wired"}, 404
                    else:
                        payload, status = outer.goodput(), 200
                    body = (json.dumps(payload, indent=1) + "\n").encode()
                    ctype = "application/json"
                elif path == "/autopilot":
                    if outer.autopilot is None:
                        payload, status = {"error": "no autopilot wired"}, 404
                    else:
                        payload, status = outer.autopilot(), 200
                    body = (json.dumps(payload, indent=1) + "\n").encode()
                    ctype = "application/json"
                elif path == "/query":
                    if outer.query is None:
                        payload = {"error": "history store not wired"}
                        status = 404
                    else:
                        status, payload = outer.query(
                            dict(parse_qsl(query))
                        )
                    body = (json.dumps(payload) + "\n").encode()
                    ctype = "application/json"
                elif path == "/prof":
                    status, payload = outer._handle_prof(query)
                    body = (json.dumps(payload) + "\n").encode()
                    ctype = "application/json"
                else:
                    body, ctype, status = b"not found\n", "text/plain", 404
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                # One request per connection: a keep-alive scraper must not
                # pin a handler thread across the server's close().
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam role stdout
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        # Don't let server_close() block on a wedged in-flight handler —
        # handlers are daemon threads, shutdown already stopped the accept
        # loop, and close() promises to return promptly.
        self._httpd.block_on_close = False
        self.port = self._httpd.server_address[1]  # resolved when port=0
        self._closed = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="telemetry-http",
            daemon=True,
        )
        self._thread.start()

    def _handle_prof(self, query: str) -> tuple[int, dict]:
        if self.prof is None:
            return 404, {"error": "profiler capture not wired"}
        ms = None
        for part in query.split("&"):
            key, sep, value = part.partition("=")
            if key == "ms" and sep:
                try:
                    ms = int(value)
                except ValueError:
                    return 400, {"error": f"bad ms value {value!r}"}
                if ms <= 0:
                    return 400, {"error": "ms must be positive"}
        started, detail = self.prof(ms)
        if not started:
            return 409, {"error": detail}
        return 200, {"started": True, "trace_dir": detail, "ms": ms}

    def close(self) -> None:
        """Stop accepting, release the listening socket, reap the serve
        thread. Safe to call more than once (role finallys may overlap)."""
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


class JsonExporter:
    """Rolling ``telemetry.json``: the whole plane as one JSON document,
    rewritten atomically every ``interval_s``. Cheap enough to leave on —
    a few KB per write at fleet scale."""

    def __init__(self, agg: TelemetryAggregator, path: str, interval_s: float = 2.0):
        self.agg = agg
        self.path = path
        self.interval_s = float(interval_s)
        self._last = float("-inf")
        self.n_written = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def maybe_export(self, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        if now - self._last < self.interval_s:
            return False
        self._last = now
        status, health = render_healthz(self.agg)
        doc = {
            "ts": time.time(),
            "healthz": health,
            "sources": [
                {**snap, "age_s": round(age, 3)}
                for snap, age in self.agg.all_snapshots()
            ],
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)  # readers never see a torn file
        self.n_written += 1
        return True


class TensorboardExporter:
    """Fleet counters/gauges -> tensorboard scalars under ``telemetry/``,
    via the same writer factory the learner logger uses. Histograms export
    their running mean (sum/count) — the full distribution lives in
    Prometheus. Steps are the per-source snapshot ``seq`` so each series
    advances monotonically regardless of wall clock."""

    def __init__(self, writer):
        self.w = writer

    def export(self, agg: TelemetryAggregator) -> None:
        for snap, _age in agg.all_snapshots():
            role = snap.get("role", "?")
            step = int(snap.get("seq", 0))
            for name, labels, value in snap.get("counters", ()):
                self.w.add_scalar(self._tag(role, name, labels), float(value), step)
            for name, labels, value in snap.get("gauges", ()):
                self.w.add_scalar(self._tag(role, name, labels), float(value), step)
            for name, labels, _counts, total, count in snap.get("hists", ()):
                if count:
                    self.w.add_scalar(
                        self._tag(role, name + "-mean", labels), total / count, step
                    )
        self.w.flush()

    @staticmethod
    def _tag(role: str, name: str, labels: dict[str, str]) -> str:
        wid = labels.get("wid")
        suffix = f"/w{wid}" if wid not in (None, "") else ""
        return f"telemetry/{role}/{name}{suffix}"

    def close(self) -> None:
        self.w.close()
