"""Fused act-step kernel (Pallas/TPU) — the serving fast path's compute layer.

The serving hot path (``InferenceService._step_fn`` and the worker-local act)
runs ``DiscreteActorCritic.act`` as four separate XLA ops per flush: torso
Dense+relu, LSTM-cell step, logits head, log-softmax — each a kernel launch
that round-trips its (rows, H) activations through HBM. At serving batch
sizes (a bucket of 8..256 rows) those intermediates are tiny and the
launches + HBM hops dominate. This kernel fuses the whole act step into ONE
Pallas program: every weight matrix and every intermediate lives in VMEM,
the three matmuls feed the MXU back to back, and only (obs, h, c) in and
(log-softmax logits, h', c') out touch HBM.

Scope: the discrete LSTM actor-critic family only (PPO/IMPALA/V-MPO with the
MLP backbone) at float32 compute — exactly the family whose act step the
fleet benches. Everything else falls back to ``family.act``
(:func:`make_fused_act` returns None); the value head is skipped entirely
because the act contract discards it.

Dispatch honors :func:`tpu_rl.models.cells.set_pallas_mode`: ``"interpret"``
runs the kernel in the Pallas interpreter (CPU equivalence tests — the
parity pin in tests/test_pallas_act.py), ``"off"`` disables it, ``"auto"``/
``"force"`` use the compiled kernel on single-device TPU backends when the
working set fits VMEM. Multi-device GSPMD programs (``InferenceReplica``
with ``inference_mesh_data > 1``) always fall back: the Mosaic custom call
has no automatic SPMD partitioning rule (same constraint as
``pallas_lstm``'s shard_map gating).

Sampling and the carry-reset mask stay OUTSIDE the kernel, shared with the
XLA path, so a given (params, obs, key) produces the identical action from
either implementation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpu_rl.ops.pallas_lstm import _VMEM_BUDGET_BYTES, _compiler_params


def act_fits_vmem(rows: int, obs_dim: int, hidden: int, n_actions: int) -> bool:
    """Whole act step in one VMEM-resident program? (No grid: the serving
    batch is one tile.) Weights + activations, counted once; Mosaic's
    scoped-VMEM ceiling is raised by ``_compiler_params`` as in the LSTM
    kernel."""
    weights = obs_dim * hidden + hidden * 4 * hidden * 2 + hidden * n_actions
    acts = rows * (obs_dim + hidden * 8 + n_actions * 2)
    return (weights + acts) * 4 <= _VMEM_BUDGET_BYTES


def _act_kernel(
    obs_ref, wb_ref, bb_ref, wx_ref, bx_ref, wh_ref, wl_ref, bl_ref,
    h_ref, c_ref, logits_ref, h2_ref, c2_ref,
):
    """obs (B,D); torso wb (D,H) + bb (1,H); LSTM wx (H,4H) + bx (1,4H) +
    wh (H,4H); logits head wl (H,A) + bl (1,A); carry h/c (B,H).
    Outputs: log-softmax logits (B,A), h2/c2 (B,H). Biases are 2-D (1,·):
    sublane/lane-shaped operands, broadcast over rows inside the kernel."""
    H = wh_ref.shape[0]
    x = jnp.maximum(
        jnp.dot(obs_ref[:], wb_ref[:], preferred_element_type=jnp.float32)
        + bb_ref[:],
        0.0,
    )
    z = (
        jnp.dot(x, wx_ref[:], preferred_element_type=jnp.float32)
        + bx_ref[:]
        + jnp.dot(h_ref[:], wh_ref[:], preferred_element_type=jnp.float32)
    )
    i = jax.nn.sigmoid(z[:, :H])
    f = jax.nn.sigmoid(z[:, H : 2 * H])
    g = jnp.tanh(z[:, 2 * H : 3 * H])
    o = jax.nn.sigmoid(z[:, 3 * H :])
    c2 = f * c_ref[:] + i * g
    h2 = o * jnp.tanh(c2)
    raw = (
        jnp.dot(h2, wl_ref[:], preferred_element_type=jnp.float32) + bl_ref[:]
    )
    # log-softmax, fused: one max + one exp-sum per row, all in VMEM.
    m = jnp.max(raw, axis=-1, keepdims=True)
    logits_ref[:] = raw - (m + jnp.log(jnp.sum(jnp.exp(raw - m), axis=-1, keepdims=True)))
    h2_ref[:] = h2
    c2_ref[:] = c2


def fused_act_step(actor_params, obs, h, c, interpret: bool):
    """Run the fused kernel on an (already dequantized, f32) actor param
    tree. Returns (log-softmax logits, h2, c2) — the same triple
    ``DiscreteActorCritic.act`` produces, minus the discarded value."""
    p = actor_params["params"]
    wb, bb = p["body"]["kernel"], p["body"]["bias"]
    wx, bx = p["cell"]["x_proj"]["kernel"], p["cell"]["x_proj"]["bias"]
    wh = p["cell"]["recurrent_kernel"]
    wl, bl = p["logits"]["kernel"], p["logits"]["bias"]
    B = obs.shape[0]
    H = wh.shape[0]
    A = wl.shape[1]
    out_shape = (
        jax.ShapeDtypeStruct((B, A), jnp.float32),  # log-softmax logits
        jax.ShapeDtypeStruct((B, H), jnp.float32),  # h2
        jax.ShapeDtypeStruct((B, H), jnp.float32),  # c2
    )
    f32 = lambda a: jnp.asarray(a, jnp.float32)
    return pl.pallas_call(
        _act_kernel,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(
        f32(obs), f32(wb), f32(bb)[None, :], f32(wx), f32(bx)[None, :],
        f32(wh), f32(wl), f32(bl)[None, :], f32(h), f32(c),
    )


def _kernel_choice(rows: int, obs_dim: int, hidden: int, n_actions: int):
    """-> (use_kernel, interpret), read at TRACE time (the serving step is
    traced once per bucket at warmup, after any set_pallas_mode call)."""
    from tpu_rl.models.cells import _PALLAS_MODE

    if _PALLAS_MODE == "off":
        return False, False
    if _PALLAS_MODE == "interpret":
        return True, True
    if jax.default_backend() != "tpu" or len(jax.devices()) != 1:
        return False, False
    if not act_fits_vmem(rows, obs_dim, hidden, n_actions):
        return False, False
    return True, False


def make_fused_act(family):
    """Fused replacement for ``family.act`` with the identical signature and
    return contract, or None when the family is out of scope (non-discrete,
    transformer, bf16-compute LSTM — the fused kernel is f32-only, like the
    pallas_lstm unroll)."""
    from tpu_rl.models.policies import DiscreteActorCritic
    from tpu_rl.ops import distributions as D

    actor = family.actor
    if not isinstance(actor, DiscreteActorCritic) or actor.dtype is not None:
        return None

    def act(params, obs, h, c, key):
        use, interpret = _kernel_choice(
            obs.shape[0], obs.shape[1], family.hidden, family.n_actions
        )
        if not use:
            return family.act(params, obs, h, c, key)
        logits, h2, c2 = fused_act_step(params["actor"], obs, h, c, interpret)
        a = D.categorical_sample(key, logits)
        log_prob = D.categorical_log_prob(logits, a)
        return a[..., None].astype(jnp.float32), logits, log_prob[..., None], h2, c2

    return act
