"""Target-network updates."""

from __future__ import annotations

import jax


def polyak_update(source_params, target_params, tau: float = 0.005):
    """Soft (Polyak) target update: ``t <- (1 - tau) * t + tau * s``.

    Functional equivalent of the reference's in-place ``soft_update``
    (``/root/reference/agents/learner_module/compute_loss.py:69-71``) — and,
    unlike the reference, it acts on a genuinely separate target tree (the
    reference's ``target_critic`` aliases ``critic`` via ``.to()`` returning
    self, ``agents/learner.py:355-358``, making its soft update a no-op;
    documented divergence / bug fix).
    """
    return jax.tree_util.tree_map(
        lambda s, t: (1.0 - tau) * t + tau * s, source_params, target_params
    )
