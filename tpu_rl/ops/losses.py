"""Loss primitives."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_rl.ops.distributions import categorical_kl  # re-export  # noqa: F401


def smooth_l1(pred: jax.Array, target: jax.Array, beta: float = 1.0) -> jax.Array:
    """Elementwise smooth-L1 (Huber) loss, mean-reduced — semantics of
    ``F.smooth_l1_loss(...)`` as used by every reference update loop
    (e.g. ``/root/reference/agents/learner_module/ppo/learning.py:74``)."""
    diff = jnp.abs(pred - target)
    loss = jnp.where(diff < beta, 0.5 * diff * diff / beta, diff - 0.5 * beta)
    return jnp.mean(loss)


def clip_subtree_by_global_norm(grads, max_norm: float, subtree: str | None = None):
    """Clip gradients by global norm, optionally only a named top-level subtree.

    The reference clips only the model parameters, not auxiliary scalars like
    V-MPO's Lagrange temperatures (``v_mpo/learning.py:111-114`` clips
    ``model.actor.parameters()`` while ``log_eta``/``log_alpha`` share the
    optimizer, ``learner.py:331-338``). ``subtree=None`` clips everything.
    """
    if subtree is None:
        tree = grads
    else:
        tree = grads[subtree]
    leaves = jax.tree_util.tree_leaves(tree)
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
    scale = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))
    clipped = jax.tree_util.tree_map(lambda g: g * scale, tree)
    if subtree is None:
        return clipped, gnorm
    out = dict(grads)
    out[subtree] = clipped
    return out, gnorm
