"""Pure-JAX numerical ops: returns (GAE, V-trace), distribution math, losses,
and target-network updates. Everything here is functional, shape-static, and
jit/scan-friendly — the TPU-native replacement for the reference's Python
reverse-time loops (``/root/reference/agents/learner_module/compute_loss.py``)
and ``torch.distributions`` usage."""

from tpu_rl.ops.returns import gae, vtrace  # noqa: F401
from tpu_rl.ops.losses import smooth_l1, categorical_kl  # noqa: F401
from tpu_rl.ops.target import polyak_update  # noqa: F401
from tpu_rl.ops import distributions  # noqa: F401
