"""Fused LSTM sequence kernel (Pallas/TPU).

The hot op of the reference model zoo is the LSTM unroll — a Python loop of
``nn.LSTMCell`` launches in torch (``/root/reference/networks/models.py:71-75``),
a ``lax.scan`` here. This kernel fuses the whole sequence into ONE Pallas
program per batch tile: the recurrent weights live in VMEM for the entire
sequence (zero re-fetch from HBM between timesteps), the per-step work is a
single (Bt, H) x (H, 4H) MXU matmul plus VPU gate math, and the input
projection for all timesteps is one big batched matmul done OUTSIDE the
kernel where the MXU is happiest.

Differentiation: ``lstm_unroll`` is a ``jax.custom_vjp`` — forward runs the
Pallas kernel and saves the gate activations + cell states; backward is the
analytic LSTM backprop as a reverse ``lax.scan`` (elementwise + two small
matmuls per step), no recomputation.

Episode resets: the carry is multiplied by ``keep = 1 - firsts[t]`` before
each step, matching ``models.policies.scan_lstm`` semantics exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Max VMEM footprint for one batch tile before we refuse. ~3/8 of a TPU
# v5e/v4 core's 128 MB VMEM: fits_vmem counts each buffer once, while Mosaic
# double-buffers the streamed blocks (xp/hs/cs/acts) across grid steps, so
# the true high-water mark is < 2x this budget. Wide-hidden workloads (e.g.
# H=1024: 16 MB of recurrent weights alone) tile their batch via
# ``batch_tile`` instead of falling back to the scan.
_VMEM_BUDGET_BYTES = 48 * 1024 * 1024


def _compiler_params(interpret: bool):
    """Mosaic params shared by the forward and backward kernels: raise the
    scoped-VMEM ceiling above the default (~16 MB), which is below one
    wide-hidden tile's working set (wh alone is 16 MB at H=1024). fits_vmem
    counts each buffer once; with double-buffered streaming the true
    high-water is < 2x budget + weights, well under the 128 MB core VMEM."""
    if interpret:
        return None
    cp_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cp_cls is None:
        return None
    return cp_cls(vmem_limit_bytes=int(2.2 * _VMEM_BUDGET_BYTES))


def _make_kernel(save_acts: bool):
    def kernel(xp_ref, wh_ref, h0_ref, c0_ref, keep_ref, hs_ref, cs_ref, *rest):
        """One batch tile, full sequence, TIME-MAJOR layouts (the sequence
        index is the untiled leading axis, so the dynamic per-step index never
        touches a tiled sublane/lane dimension — a Mosaic requirement).

        xp   : (S, Bt, 4H) precomputed input projection (+bias)
        wh   : (H, 4H) recurrent weights (VMEM-resident all S steps)
        h0,c0: (Bt, H) initial carry
        keep : (S, Bt, 1) carry-keep mask (0 at episode-first steps)
        hs,cs: (S, Bt, H) per-step hidden / cell states (outputs)
        acts : (S, Bt, 4H) post-activation gates i,f,g,o — only in the
               differentiated path (VJP residuals); the primal skips the
               stores entirely (XLA cannot DCE an opaque custom call).
        """
        acts_ref = rest[0] if save_acts else None
        S = xp_ref.shape[0]
        H = wh_ref.shape[0]
        wh = wh_ref[:]

        def step(t, carry):
            h, c = carry
            keep = keep_ref[t]  # (Bt, 1)
            h = h * keep
            c = c * keep
            z = xp_ref[t] + jnp.dot(h, wh, preferred_element_type=jnp.float32)
            i = jax.nn.sigmoid(z[:, :H])
            f = jax.nn.sigmoid(z[:, H : 2 * H])
            g = jnp.tanh(z[:, 2 * H : 3 * H])
            o = jax.nn.sigmoid(z[:, 3 * H :])
            c2 = f * c + i * g
            h2 = o * jnp.tanh(c2)
            hs_ref[t] = h2
            cs_ref[t] = c2
            if acts_ref is not None:
                # one full-width store (no partial-lane writes)
                acts_ref[t] = jnp.concatenate([i, f, g, o], axis=-1)
            return h2, c2

        jax.lax.fori_loop(0, S, step, (h0_ref[:], c0_ref[:]))

    return kernel


def _pallas_forward(xp, wh, h0, c0, keep, interpret: bool, save_acts: bool):
    """xp (B,S,4H), keep (B,S) -> (hs, cs[, acts]) in batch-major layout
    (the kernel runs time-major internally).

    The batch dimension is tiled over a 1-D Pallas grid: each grid step
    unrolls the full sequence for one VMEM-sized batch tile while Mosaic
    streams the next tile's input projection HBM->VMEM behind it. The
    recurrent weights block is the same for every tile (index_map pins it),
    so it stays VMEM-resident across the whole grid."""
    B, S, H4 = xp.shape
    H = H4 // 4
    bt = batch_tile(B, S, H)
    if bt is None:
        raise ValueError(
            f"no VMEM-fitting batch tile for (B={B}, S={S}, H={H}); "
            "caller should use the scan path"
        )
    grid = (B // bt,)
    out_shapes = [
        jax.ShapeDtypeStruct((S, B, H), jnp.float32),  # hs
        jax.ShapeDtypeStruct((S, B, H), jnp.float32),  # cs
    ]
    out_specs = [
        pl.BlockSpec((S, bt, H), lambda b: (0, b, 0)),
        pl.BlockSpec((S, bt, H), lambda b: (0, b, 0)),
    ]
    if save_acts:
        out_shapes.append(jax.ShapeDtypeStruct((S, B, H4), jnp.float32))
        out_specs.append(pl.BlockSpec((S, bt, H4), lambda b: (0, b, 0)))
    in_specs = [
        pl.BlockSpec((S, bt, H4), lambda b: (0, b, 0)),  # xp
        pl.BlockSpec((H, H4), lambda b: (0, 0)),  # wh (every tile)
        pl.BlockSpec((bt, H), lambda b: (b, 0)),  # h0
        pl.BlockSpec((bt, H), lambda b: (b, 0)),  # c0
        pl.BlockSpec((S, bt, 1), lambda b: (0, b, 0)),  # keep
    ]
    outs = pl.pallas_call(
        _make_kernel(save_acts),
        grid=grid,
        out_shape=tuple(out_shapes),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(
        jnp.moveaxis(xp, 1, 0),
        wh,
        h0,
        c0,
        jnp.moveaxis(keep, 1, 0)[..., None],
    )
    return tuple(jnp.moveaxis(o, 0, 1) for o in outs)


def fits_vmem(batch: int, seq: int, hidden: int) -> bool:
    """Does ONE batch tile of this size fit the per-tile VMEM budget?"""
    # xp + acts dominate: 2 * B*S*4H floats, plus hs/cs and weights.
    floats = batch * seq * hidden * (4 + 4 + 1 + 1) + hidden * 4 * hidden
    return floats * 4 <= _VMEM_BUDGET_BYTES


def _best_tile(batch: int, fits) -> int | None:
    """Largest divisor of ``batch`` accepted by ``fits``, restricted to
    sublane multiples of 8 (or the whole batch when it both fits and is
    small): a degenerate few-row tile would serialize the batch over the grid
    at a fraction of VPU width — strictly worse than the ``lax.scan``
    fallback — so shapes with only tiny fitting divisors return None."""
    divs = [d for d in range(1, batch + 1) if batch % d == 0 and fits(d)]
    if not divs:
        return None
    mult8 = [d for d in divs if d % 8 == 0]
    if mult8:
        return max(mult8)
    return batch if batch in divs else None


def batch_tile(batch: int, seq: int, hidden: int) -> int | None:
    """Forward-kernel batch tile, or None when no tiling fits VMEM (very
    long seq x wide hidden: the caller falls back to the scan; long-context
    training is the transformer's job)."""
    return _best_tile(batch, lambda d: fits_vmem(d, seq, hidden))


def bwd_batch_tile(batch: int, seq: int, hidden: int) -> int | None:
    """Backward-kernel batch tile. The backward working set per row is
    acts + cs + dhs + dcs + dxp ~ 11 H-floats per step, plus the wh block."""

    def fits(d: int) -> bool:
        floats = d * seq * hidden * 11 + hidden * 4 * hidden
        return floats * 4 <= _VMEM_BUDGET_BYTES

    return _best_tile(batch, fits)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def mixed_dot(a, b, dtype=jnp.bfloat16):
    """``a @ b`` with BOTH passes at reduced-precision MXU rate, f32 out.

    A plain ``dot(a.astype(bf16), b.astype(bf16), preferred f32)`` only
    accelerates the FORWARD: its AD transpose receives an f32 cotangent, so
    both backward matmuls are mixed f32 x bf16 dots that XLA runs at f32
    rate — measured as the round-4 "bf16 gave nothing" wide-LSTM row
    (10.25 ms bf16 vs 10.16 f32; the backward holds ~2/3 of the matmul
    FLOPs). This VJP casts the cotangent to ``dtype`` too — standard
    mixed-precision practice; gradients pick up one bf16 rounding, while
    accumulation (``preferred_element_type``) and all results stay f32.

    2-D operands only: the backward's ``.T``-transposed dots assume plain
    matrices, and batched/1-D operands would silently compute the wrong
    gradient contraction rather than fail. Reshape to 2-D at the call site
    (every LSTM use is ``(rows, features) @ (features, cols)``)."""
    if a.ndim != 2 or b.ndim != 2:
        raise ValueError(
            "mixed_dot requires 2-D operands (its custom VJP transposes "
            f"with .T); got a.ndim={a.ndim}, b.ndim={b.ndim}. Reshape to "
            "matrices before calling."
        )
    return jnp.dot(
        a.astype(dtype), b.astype(dtype), preferred_element_type=jnp.float32
    )


def _mixed_dot_fwd(a, b, dtype):
    # Residuals saved PRE-cast to ``dtype``: identical backward numerics
    # (the cast is idempotent), half the stacked-residual bytes under a
    # scan, and no per-step re-cast of the loop-invariant weights.
    return mixed_dot(a, b, dtype), (a.astype(dtype), b.astype(dtype))


def _mixed_dot_bwd(dtype, res, g):
    ad, bd = res
    gd = g.astype(dtype)
    da = jnp.dot(gd, bd.T, preferred_element_type=jnp.float32)
    db = jnp.dot(ad.T, gd, preferred_element_type=jnp.float32)
    return da, db


mixed_dot.defvjp(_mixed_dot_fwd, _mixed_dot_bwd)


def _scan_forward(xp, wh, h0, c0, keep, matmul_dtype=None, want_cs=False):
    """Plain ``lax.scan`` forward over the precomputed input projection —
    the measured winner for UNdifferentiated unrolls (the fused kernel is
    0.82-0.99x the scan on forward-only at every benched shape,
    bench_lstm_kernel.json; it wins only when the fused backward is in
    play).

    ``matmul_dtype`` (e.g. ``jnp.bfloat16``) runs the recurrent matmul
    through :func:`mixed_dot` — MXU-rate compute in BOTH passes with f32
    accumulation; the carry, gate math, and outputs stay float32.
    None = pure float32 (bit-identical to the fused kernel).

    Returns ``(hs, (h_last, c_last))`` by default; ``want_cs=True`` stacks
    the full per-step cell state and returns ``(hs, cs)`` instead — only
    the ``lstm_unroll`` primal needs that (its custom_vjp output contract
    is (B,S,H) pairs); every other caller consumes just the final carry,
    and stacking cs for them would write an extra (B,S,H) buffer per
    forward (~64 MB at the wide bench shape)."""
    def step(carry, xs):
        h, c = carry
        xp_t, keep_t = xs
        kp = keep_t[:, None]
        h = h * kp
        c = c * kp
        rec = (
            jnp.dot(h, wh, preferred_element_type=jnp.float32)
            if matmul_dtype is None
            else mixed_dot(h, wh, matmul_dtype)
        )
        z = xp_t.astype(jnp.float32) + rec
        H = wh.shape[0]
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return (h2, c2), ((h2, c2) if want_cs else h2)

    (h_last, c_last), out = jax.lax.scan(
        step, (h0, c0), (jnp.moveaxis(xp, 1, 0), jnp.moveaxis(keep, 1, 0))
    )
    if want_cs:
        hs, cs = out
        return jnp.moveaxis(hs, 0, 1), jnp.moveaxis(cs, 0, 1)
    return jnp.moveaxis(out, 0, 1), (h_last, c_last)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def lstm_unroll(xp, wh, h0, c0, keep, interpret=False):
    """Fused LSTM over a sequence.

    xp (B,S,4H) input projection incl. bias; wh (H,4H); h0/c0 (B,H);
    keep (B,S) carry-keep mask. Returns (hs, cs), each (B,S,H).

    Measured-win dispatch (bench_lstm_kernel.json): this primal body runs
    only when the call is NOT differentiated (custom_vjp routes traced-for-AD
    calls through ``_fwd``), and forward-only is where the kernel loses
    (0.82-0.99x the scan at every shape) — so the undifferentiated path
    always scans. ``interpret`` (CPU equivalence tests) and the cells
    module's "force" benchmark mode still run the kernel so tests and the
    gate-deriving benchmark can never silently degrade into scan-vs-scan."""
    from tpu_rl.models.cells import _PALLAS_MODE

    if interpret or _PALLAS_MODE == "force":
        hs, cs = _pallas_forward(
            xp, wh, h0, c0, keep, interpret, save_acts=False
        )
        return hs, cs
    return _scan_forward(xp, wh, h0, c0, keep, want_cs=True)


def _fwd(xp, wh, h0, c0, keep, interpret):
    hs, cs, acts = _pallas_forward(
        xp, wh, h0, c0, keep, interpret, save_acts=True
    )
    return (hs, cs), (xp, wh, h0, c0, keep, hs, cs, acts)


def _bwd_kernel(
    acts_ref, cs_ref, h0_ref, c0_ref, keep_ref, dhs_ref, dcs_ref,
    wh_ref, dxp_ref, dh0_ref, dc0_ref,
):
    """Analytic LSTM backprop for one batch tile, full sequence, reverse
    time — the fused mirror of the forward kernel: per step, the elementwise
    gate-gradient math plus ONE (Bt, 4H) x (4H, H) MXU matmul for the carry
    gradient, with wh VMEM-resident across the grid. The weight gradient is
    NOT accumulated here: dwh = sum_t h_prev_used[t]^T dz[t] contracts over
    batch x time, so it is one big MXU matmul over the kernel's dxp output,
    done outside where the contraction is (B*S)-deep instead of Bt-deep."""
    S = acts_ref.shape[0]
    H = wh_ref.shape[0]
    wh = wh_ref[:]

    def step(idx, carry):
        dh, dc = carry
        t = S - 1 - idx
        act = acts_ref[t]
        i = act[:, :H]
        f = act[:, H : 2 * H]
        g = act[:, 2 * H : 3 * H]
        o = act[:, 3 * H :]
        kp = keep_ref[t]  # (Bt, 1)
        tm1 = jnp.maximum(t - 1, 0)
        cp = jnp.where(t > 0, cs_ref[tm1], c0_ref[:])
        cp_used = cp * kp
        dh_t = dhs_ref[t] + dh
        t_c2 = jnp.tanh(cs_ref[t])
        do = dh_t * t_c2
        dc_t = dcs_ref[t] + dc + dh_t * o * (1.0 - t_c2 * t_c2)
        di = dc_t * g
        dg = dc_t * i
        df = dc_t * cp_used
        dz = jnp.concatenate(
            [
                di * i * (1.0 - i),
                df * f * (1.0 - f),
                dg * (1.0 - g * g),
                do * o * (1.0 - o),
            ],
            axis=-1,
        )  # (Bt, 4H)
        dxp_ref[t] = dz
        dh_prev = jnp.dot(dz, wh.T, preferred_element_type=jnp.float32) * kp
        dc_prev = dc_t * f * kp
        return dh_prev, dc_prev

    dh, dc = jax.lax.fori_loop(
        0, S, step, (jnp.zeros_like(h0_ref[:]), jnp.zeros_like(c0_ref[:]))
    )
    dh0_ref[:] = dh
    dc0_ref[:] = dc


def _pallas_backward(wh, h0, c0, keep, hs, cs, acts, dhs, dcs, interpret):
    """Batch-tiled fused backward; same grid scheme as the forward. Returns
    (dxp, dh0, dc0); the weight gradient is computed by the caller from dxp
    (one batch*time-deep MXU matmul)."""
    B, S, H = hs.shape
    H4 = 4 * H
    # The interpreter has no VMEM: an untileable shape still runs (whole
    # batch, grid 1) so tests always exercise the kernel.
    bt = bwd_batch_tile(B, S, H) or (B if interpret else None)
    assert bt is not None  # caller gates on bwd_batch_tile
    grid = (B // bt,)
    tm = lambda a: jnp.moveaxis(a, 1, 0)
    seq_spec = lambda w: pl.BlockSpec((S, bt, w), lambda b: (0, b, 0))
    row_spec = pl.BlockSpec((bt, H), lambda b: (b, 0))
    wh_spec = pl.BlockSpec((H, H4), lambda b: (0, 0))
    dxp, dh0, dc0 = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        out_shape=(
            jax.ShapeDtypeStruct((S, B, H4), jnp.float32),  # dxp (= dz)
            jax.ShapeDtypeStruct((B, H), jnp.float32),  # dh0
            jax.ShapeDtypeStruct((B, H), jnp.float32),  # dc0
        ),
        in_specs=[
            seq_spec(H4),  # acts
            seq_spec(H),  # cs
            row_spec,  # h0
            row_spec,  # c0
            seq_spec(1),  # keep
            seq_spec(H),  # dhs
            seq_spec(H),  # dcs
            wh_spec,  # wh
        ],
        out_specs=(seq_spec(H4), row_spec, row_spec),
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(
        tm(acts), tm(cs), h0, c0, tm(keep)[..., None], tm(dhs),
        tm(dcs), wh,
    )
    return jnp.moveaxis(dxp, 0, 1), dh0, dc0


def _bwd(interpret, res, ct):
    xp, wh, h0, c0, keep, hs, cs, acts = res
    dhs, dcs = ct
    B, S, H = hs.shape

    # Fused backward kernel only when the WHOLE batch fits one tile: with a
    # multi-tile grid each sequential step's carry matmul contracts over just
    # Bt rows, starving the MXU — measured 0.73x the scan at B1024/H1024 —
    # while at grid 1 the fusion wins (1.2x at the reference quantum). Wide
    # multi-tile shapes keep the scan backward, whose per-step matmuls see
    # the full batch. (lstm_unroll is only reached when the cell chose the
    # kernel for the forward.) The cells "force" benchmark mode overrides
    # this gate too (any fitting tile), so force-mode fwd+grad rows time the
    # genuinely fused kernel pair, not kernel-fwd + scan-bwd.
    from tpu_rl.models.cells import _PALLAS_MODE

    bwd_tile = bwd_batch_tile(B, S, H)
    if interpret or (
        jax.default_backend() == "tpu"
        and (
            bwd_tile == B
            or (_PALLAS_MODE == "force" and bwd_tile is not None)
        )
    ):
        dxp, dh0, dc0 = _pallas_backward(
            wh, h0, c0, keep, hs, cs, acts, dhs, dcs, interpret
        )
        # Weight gradient as one (H, B*S) x (B*S, 4H) MXU matmul — the
        # batch*time-deep contraction the per-tile kernel cannot express
        # efficiently (a Bt-deep contraction starves the systolic array).
        h_prev = jnp.concatenate([h0[:, None], hs[:, :-1]], axis=1)
        dwh = jnp.einsum(
            "bth,btz->hz",
            h_prev * keep[..., None],
            dxp,
            preferred_element_type=jnp.float32,
        )
        return dxp, dwh, dh0, dc0, None

    h_prev = jnp.concatenate([h0[:, None], hs[:, :-1]], axis=1)  # (B,S,H)
    c_prev = jnp.concatenate([c0[:, None], cs[:, :-1]], axis=1)

    def step(carry, xs):
        dh, dc, dwh = carry
        # per-step slices, time-reversed
        dh_out, dc_out, act, hp, cp, c_t, kp = xs
        kp = kp[:, None]
        i, f, g, o = jnp.split(act, 4, axis=-1)
        hp_used = hp * kp
        cp_used = cp * kp
        dh_t = dh_out + dh
        t_c2 = jnp.tanh(c_t)  # tanh of the saved cell state
        do = dh_t * t_c2
        dc_t = dc_out + dc + dh_t * o * (1.0 - t_c2 * t_c2)
        di = dc_t * g
        dg = dc_t * i
        df = dc_t * cp_used
        dz = jnp.concatenate(
            [
                di * i * (1.0 - i),
                df * f * (1.0 - f),
                dg * (1.0 - g * g),
                do * o * (1.0 - o),
            ],
            axis=-1,
        )  # (B, 4H)
        dwh = dwh + hp_used.T @ dz
        dh_prev = (dz @ wh.T) * kp
        dc_prev = dc_t * f * kp
        return (dh_prev, dc_prev, dwh), dz

    xs = (
        jnp.moveaxis(dhs, 1, 0)[::-1],
        jnp.moveaxis(dcs, 1, 0)[::-1],
        jnp.moveaxis(acts, 1, 0)[::-1],
        jnp.moveaxis(h_prev, 1, 0)[::-1],
        jnp.moveaxis(c_prev, 1, 0)[::-1],
        jnp.moveaxis(cs, 1, 0)[::-1],
        jnp.moveaxis(keep, 1, 0)[::-1],
    )
    zero = jnp.zeros((B, H), jnp.float32)
    (dh0, dc0, dwh), dz_rev = jax.lax.scan(
        step, (zero, zero, jnp.zeros_like(wh)), xs
    )
    dxp = jnp.moveaxis(dz_rev[::-1], 0, 1)  # (B, S, 4H)
    return dxp, dwh, dh0, dc0, None


lstm_unroll.defvjp(_fwd, _bwd)
