"""Return / advantage estimators as reverse-time ``lax.scan``s.

TPU-native re-implementations of the reference's Python reverse loops:
- GAE: ``/root/reference/agents/learner_module/compute_loss.py:7-19``
- V-trace: ``/root/reference/agents/learner_module/compute_loss.py:22-66``

Semantics match the reference exactly (including its non-standard rho lower
clip ``min=0.1`` at ``compute_loss.py:37`` and the ``(1 - is_fir[t+1])``
bootstrap masking), but the recursion is a single fused scan over the time
axis instead of a per-step Python loop — one XLA program, no per-step kernel
launches, differentiable end-to-end if needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _reverse_scan(f, init, xs_time_major):
    """Run ``lax.scan`` backwards over the leading (time) axis."""
    carry, ys = jax.lax.scan(f, init, xs_time_major, reverse=True)
    return carry, ys


def gae(deltas: jax.Array, gamma: float, lmbda: float) -> jax.Array:
    """Generalized advantage estimation over the time axis (axis 1).

    ``deltas``: (B, T, ...) TD errors. Returns (B, T, ...) advantages with
    ``adv[t] = delta[t] + gamma * lmbda * adv[t+1]`` (reference
    ``compute_loss.py:12-17``; note the reference applies no done-masking
    inside the recursion — masking happens in the deltas via is_fir).
    """
    deltas_t = jnp.moveaxis(deltas, 1, 0)  # (T, B, ...)

    def step(carry, d):
        adv = d + gamma * lmbda * carry
        return adv, adv

    _, advs = _reverse_scan(step, jnp.zeros_like(deltas_t[0]), deltas_t)
    return jnp.moveaxis(advs, 0, 1)


def vtrace(
    behav_log_probs: jax.Array,
    target_log_probs: jax.Array,
    is_fir: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    gamma: float,
    rho_bar: float = 0.8,
    rho_min: float = 0.1,
    c_bar: float = 1.0,
    v_min: float | None = None,
    v_max: float | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """V-trace off-policy corrections (IMPALA).

    All inputs are (B, S, 1); time is axis 1. Returns
    ``(rho_clipped (B,S-1,1), advantages (B,S-1,1), values_target (B,S,1))``
    with the reference's exact recursion (``compute_loss.py:22-66``):

        rho   = clip(exp(target_lp - behav_lp), rho_min, rho_bar)
        c     = clip(exp(target_lp - behav_lp), max=c_bar)
        delta[t] = rho[t] * (r[t] + g*(1-fir[t+1])*V[t+1] - V[t])
        dv[t] = delta[t] + c[t] * g*(1-fir[t+1]) * dv[t+1],  dv[S-1] = 0
        vs    = V + dv
        adv[t] = rho[t] * (r[t] + g*(1-fir[t+1])*vs[t+1] - V[t])

    ``v_min``/``v_max`` (default None = reference parity) clamp the critic
    values entering the recursion AND the resulting targets to the env's
    achievable discounted-return range. Under async policy lag the reference
    clips (rho <= rho_bar < 1) damp the corrections that would pull a
    drifting critic back, and bootstrapped drift compounds — measured on
    the cluster deployment: mean V exceeded the discounted cap, advantages
    went persistently negative, entropy collapsed (CLUSTER_LEARNING.md).
    For bounded-return envs the bound is known by construction, so
    hallucination above it is clamped at the source; values inside the
    bound are untouched.
    """
    if v_min is not None or v_max is not None:
        values = jnp.clip(values, v_min, v_max)
    log_ratio = target_log_probs[:, :-1] - behav_log_probs[:, :-1]
    ratio = jnp.exp(log_ratio)
    rho_clipped = jnp.clip(ratio, rho_min, rho_bar)
    c_clipped = jnp.minimum(ratio, c_bar)

    not_fir_next = 1.0 - is_fir[:, 1:]  # (B, S-1, 1)
    disc = gamma * not_fir_next

    td_target = rewards[:, :-1] + disc * values[:, 1:]
    deltas = rho_clipped * (td_target - values[:, :-1])

    # dv[t] = deltas[t] + c[t] * disc[t] * dv[t+1]   (reverse scan, T = S-1)
    def step(carry, xs):
        d, c_disc = xs
        dv = d + c_disc * carry
        return dv, dv

    xs = (jnp.moveaxis(deltas, 1, 0), jnp.moveaxis(c_clipped * disc, 1, 0))
    _, dvs = _reverse_scan(step, jnp.zeros_like(deltas[:, 0]), xs)
    dv = jnp.moveaxis(dvs, 0, 1)  # (B, S-1, 1)

    # vs = V + dv, with dv[S-1] = 0 at the boundary (reference zero-inits the
    # full (B, S, 1) buffer, compute_loss.py:48).
    dv_full = jnp.concatenate([dv, jnp.zeros_like(dv[:, :1])], axis=1)
    values_target = values + dv_full
    if v_min is not None or v_max is not None:
        # The corrected targets are returns too: same achievable range.
        values_target = jnp.clip(values_target, v_min, v_max)

    advantages = rho_clipped * (
        rewards[:, :-1] + disc * values_target[:, 1:] - values[:, :-1]
    )
    return rho_clipped, advantages, values_target
