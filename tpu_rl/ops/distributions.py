"""Distribution math as pure functions with explicit RNG keys.

Replaces the reference's ``torch.distributions`` usage
(``/root/reference/networks/models.py:58-61,114-118,199-214``) with jit-safe
primitives. Conventions kept for behavior parity:

- "logits" stored in trajectories are **log-softmax** values, matching torch's
  ``Categorical(probs).logits`` (``models.py:46-49``).
- Normal log-probs are **per-dimension** (not summed), matching
  ``dist.log_prob`` on a (..., A) event (``models.py:86``).
- Tanh-squash correction uses ``log(1 - tanh(x)^2 + 1e-7)`` per dimension
  (``models.py:205-214``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

_LOG_2PI = math.log(2.0 * math.pi)


# ---------------------------------------------------------------- categorical
def categorical_sample(key: jax.Array, logits: jax.Array) -> jax.Array:
    """Sample action indices from (unnormalized or log-softmax) logits."""
    return jax.random.categorical(key, logits, axis=-1)


def categorical_log_prob(logits: jax.Array, actions: jax.Array) -> jax.Array:
    """log pi(a) for integer ``actions`` (..., ) given logits (..., A)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, actions[..., None].astype(jnp.int32), axis=-1)[
        ..., 0
    ]


def categorical_entropy(logits: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(logp)
    return -jnp.sum(p * logp, axis=-1)


def categorical_kl(logits_p: jax.Array, logits_q: jax.Array) -> jax.Array:
    """KL(p || q) over the last axis (reference ``compute_loss.py:74-77``)."""
    logp = jax.nn.log_softmax(logits_p, axis=-1)
    logq = jax.nn.log_softmax(logits_q, axis=-1)
    p = jnp.exp(logp)
    return jnp.sum(p * (logp - logq), axis=-1)


# ------------------------------------------------------------------- gaussian
def normal_sample(key: jax.Array, mu: jax.Array, std: jax.Array) -> jax.Array:
    return mu + std * jax.random.normal(key, mu.shape, mu.dtype)


def normal_log_prob(mu: jax.Array, std: jax.Array, x: jax.Array) -> jax.Array:
    """Per-dimension Normal log-density (torch ``Normal.log_prob`` parity)."""
    var = std * std
    return -0.5 * (jnp.square(x - mu) / var + 2.0 * jnp.log(std) + _LOG_2PI)


def normal_entropy(std: jax.Array) -> jax.Array:
    """Per-dimension Normal entropy."""
    return 0.5 * (1.0 + _LOG_2PI) + jnp.log(std)


# ---------------------------------------------------------------- tanh-normal
def tanh_normal_sample(
    key: jax.Array, mu: jax.Array, std: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Reparameterized tanh-squashed Gaussian sample and per-dim log-prob.

    Matches the reference SAC-continuous actor (``models.py:205-214``):
    ``a = tanh(x), x ~ N(mu, std)``;
    ``log_prob = logN(x) - log(1 - a^2 + 1e-7)`` per dimension.
    """
    x = normal_sample(key, mu, std)
    action = jnp.tanh(x)
    log_prob = normal_log_prob(mu, std, x) - jnp.log(1.0 - jnp.square(action) + 1e-7)
    return action, log_prob
